"""Sharding-aware plan optimizer: placement as an optimizer *decision*.

PR 8's static front-end made placement a checked, priced property: every
stage boundary carries a `PartitionSpec`, implicit reshards are priced
as boundary all-to-alls (KP601/KP603), and memory is modeled per device
(KP600). This module is the decision back-end — KeystoneML's thesis
(PAPER §4) applied to placement: the optimizer, not the user, chooses
each stage's physical layout from a small legal menu, prices every
candidate with the SAME cost model the lints use
(`parallel.mesh.collective_cost`), and hands the winning assignment to
the execution layer for enforcement.

The model:

  - **menu** — per stage boundary, the legal placement *families*:
    data-sharded leading axis (`FAMILY_DATA`), model-sharded feature
    axis (`FAMILY_MODEL`), 2-D data×model (`FAMILY_DATA_MODEL`), and
    replicated (`FAMILY_REPLICATED`). A family is legal for a stage only
    when the mesh has the axes and every element leaf's feature dim
    divides the model-axis size — the same divisibility contract
    `data.dataset.leaf_sharding` enforces at runtime.
  - **cost** — a boundary where producer and consumer families differ
    prices an all-to-all of the producer's bytes (plus a fixed
    per-reshard penalty, so fewer moves win byte ties); an operator
    `abstract_sharding` demand (`fit_sharding_demands` — solver fits
    want row-sharded inputs) unmet by the producer's family prices the
    same all-to-all the KP601 lint would report; a provably-host
    consumer of sharded data prices the KP603 all-gather; a replicated
    stage above the KP602 threshold with a shardable axis prices a
    broadcast. Per-device residency over the KP600 budget makes a
    family INFEASIBLE (pruned), the memory-safe-compilation discipline
    of arXiv 2206.14148.
  - **solver** — min-cost DP over the fan-out-free chain structure of
    the lowered plan: exact on chains (each link's table carries the
    best cost per family with backpointers), greedy frontier merge at
    gather diamonds and fan-in (parents are frozen at their own best
    assignment — demand- and gather-aware — before the consumer
    chooses).

The planner NEVER loses to the default: the chosen assignment and the
PR-8 default placement are scored by the same function, and when the
optimum fails to strictly beat the default the plan degrades to the
default assignment (``improved=False``, nothing is enforced) — so
``KEYSTONE_SHARDING_PLANNER`` only ever removes priced boundary bytes.

Everything here is pure spec arithmetic — no data moves, no device
allocates. Enforcement lives in `workflow.optimizer.ShardingPlannerRule`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as meshlib
from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .propagate import _label, toposort
from .sharding import (
    DEFAULT_REPLICATED_THRESHOLD,
    DEMAND_DATA_SHARDED,
    DEMAND_REPLICATED,
    PartitionRule,
    ShardedValue,
    ShardingResult,
    _is_host_stage,
    _shardable_axis,
    per_device_bytes,
    sharding_pass,
    spec_str,
)
from .specs import DataSpec, element_nbytes, is_known

#: the placement menu: every family the planner may assign to a stage.
FAMILY_DATA = "data"
FAMILY_DATA_MODEL = "data_model"
FAMILY_MODEL = "model"
FAMILY_REPLICATED = "replicated"
MENU: Tuple[str, ...] = (
    FAMILY_DATA, FAMILY_DATA_MODEL, FAMILY_MODEL, FAMILY_REPLICATED)

#: fixed per-boundary-move penalty (bytes): every reshard costs a
#: collective launch + layout change on top of its payload, so
#: assignments with fewer moves win byte ties (the "reshard count
#: penalty" term of the objective).
RESHARD_PENALTY_BYTES = 64 << 10

_INF = float("inf")


# ------------------------------------------------------------------ families


def _family_leaf_spec(family: str, leaf, mesh, kind: str) -> Optional[P]:
    """Batch-level PartitionSpec ``family`` gives one element leaf, or
    None when the leaf cannot take it (no model axis, indivisible
    feature dim, rank-0 leaf for a feature-axis family)."""
    shape = tuple(getattr(leaf, "shape", ()))
    if kind != "dataset":
        return None
    if family == FAMILY_DATA:
        return P(meshlib.DATA_AXIS)
    if family == FAMILY_REPLICATED:
        return P()
    model = int(mesh.shape.get(meshlib.MODEL_AXIS, 1))
    if model <= 1 or not shape or int(shape[0]) % model != 0:
        return None
    if family == FAMILY_MODEL:
        return P(None, meshlib.MODEL_AXIS)
    if family == FAMILY_DATA_MODEL:
        return P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)
    raise ValueError(f"unknown placement family {family!r}")


def realize_family(family: str, spec: DataSpec, mesh) -> Optional[ShardedValue]:
    """The `ShardedValue` ``family`` assigns to a stage's value, or None
    when any element leaf cannot take the family (the family is then not
    on this stage's menu)."""
    leaves = jax.tree_util.tree_leaves(spec.element)
    leaf_specs = [_family_leaf_spec(family, l, mesh, spec.kind)
                  for l in leaves]
    if any(s is None for s in leaf_specs):
        return None
    specs = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(spec.element), leaf_specs)
    return ShardedValue(specs, kind=spec.kind)


def family_of(sv: Optional[ShardedValue], mesh) -> Optional[str]:
    """Classify a propagated `ShardedValue` back into a menu family, or
    None when it matches no family (mixed per-leaf placements, exotic
    axes) — such stages are left out of the planner's choice set."""
    if sv is None or sv.kind != "dataset":
        return None
    fams = set()
    for lspec in sv.leaf_specs():
        axes = meshlib.spec_axes(lspec)
        entries = tuple(lspec)
        lead = entries[0] if entries else None
        if isinstance(lead, (tuple, list)):
            lead = lead[0] if lead else None
        if not axes:
            fams.add(FAMILY_REPLICATED)
        elif lead == meshlib.DATA_AXIS and meshlib.MODEL_AXIS in axes:
            fams.add(FAMILY_DATA_MODEL)
        elif lead == meshlib.DATA_AXIS:
            fams.add(FAMILY_DATA)
        elif meshlib.MODEL_AXIS in axes and meshlib.DATA_AXIS not in axes:
            fams.add(FAMILY_MODEL)
        else:
            return None
    if len(fams) != 1:
        return None
    return fams.pop()


def family_shards(family: Optional[str], mesh) -> int:
    data = int(mesh.shape.get(meshlib.DATA_AXIS, 1))
    model = int(mesh.shape.get(meshlib.MODEL_AXIS, 1))
    return {
        FAMILY_DATA: data,
        FAMILY_MODEL: model,
        FAMILY_DATA_MODEL: data * model,
        FAMILY_REPLICATED: 1,
        None: 1,
    }[family]


# --------------------------------------------------------------------- costs


def _effective_input_family(v_fam: str, u_spec, mesh) -> str:
    """The layout a consumer choosing ``v_fam`` actually needs its
    *input* in. A feature-axis family that cannot apply to the input's
    element (rank-0 leaves, indivisible widths) demands only its data
    component: computing a model-sharded output from a value with no
    shardable feature axis needs that value row-aligned, not feature-
    split — so a data-sharded scalar-label input feeding a data×model
    one-hot output is collective-free, while a feature-sharded matrix
    feeding a data-only consumer really does pay the model-axis
    gather."""
    if v_fam in (FAMILY_DATA, FAMILY_REPLICATED):
        return v_fam
    if isinstance(u_spec, DataSpec) and \
            realize_family(v_fam, u_spec, mesh) is not None:
        return v_fam
    return FAMILY_DATA if v_fam == FAMILY_DATA_MODEL else FAMILY_REPLICATED


def transition_cost(u_fam: Optional[str], v_fam: Optional[str],
                    nbytes: Optional[int], mesh, u_spec=None):
    """The `CollectiveCost` of relaying a producer's output from its
    family to the layout the consumer's family implies for it
    (`_effective_input_family`), or None when the boundary is free. A
    matching layout — and anything leaving a replicated producer, which
    every device already holds whole — is free; gathering into full
    replication is an all-gather; everything else is an all-to-all of
    the boundary bytes (`parallel.mesh.collective_cost`, the KP601
    formula). The byte planner reads ``.bytes_moved`` and the unified
    seconds model reads ``.seconds`` off the SAME object, so the two
    cost views can never diverge."""
    if u_fam is None or v_fam is None or not nbytes:
        return None
    eff = _effective_input_family(v_fam, u_spec, mesh)
    if u_fam == eff:
        return None
    if u_fam == FAMILY_REPLICATED:
        return None  # local slicing: each device holds the full value
    if eff == FAMILY_REPLICATED:
        return meshlib.collective_cost(
            "all_gather", nbytes, shards=family_shards(u_fam, mesh),
            mesh=mesh)
    return meshlib.collective_cost(
        "all_to_all", nbytes,
        shards=max(family_shards(u_fam, mesh),
                   family_shards(eff, mesh)),
        mesh=mesh)


def _transition_bytes(u_fam: Optional[str], v_fam: Optional[str],
                      nbytes: Optional[int], mesh,
                      u_spec=None) -> float:
    """Pure collective bytes of `transition_cost` — the per-reshard
    penalty is an OBJECTIVE term only (`_with_penalty`), never reported
    as bytes."""
    cost = transition_cost(u_fam, v_fam, nbytes, mesh, u_spec=u_spec)
    return float(cost.bytes_moved) if cost is not None else 0.0


def demand_cost(demand: Optional[str], fam: Optional[str],
                nbytes: Optional[int], mesh):
    """KP601's demand pricing as a `CollectiveCost` (or None when met):
    an `abstract_sharding` input demand unmet by the producer's family.
    A sharding demand costs an all-to-all between layouts; a
    replication demand gathers the whole value (the lint's own
    convention)."""
    if demand is None or fam is None or not nbytes:
        return None
    data = int(mesh.shape.get(meshlib.DATA_AXIS, 1))
    bad = (
        demand == DEMAND_DATA_SHARDED and data > 1
        and fam not in (FAMILY_DATA, FAMILY_DATA_MODEL)
    ) or (
        demand == DEMAND_REPLICATED and fam != FAMILY_REPLICATED
    )
    if not bad:
        return None
    if demand == DEMAND_REPLICATED:
        return meshlib.collective_cost(
            "all_gather", nbytes, shards=family_shards(fam, mesh),
            mesh=mesh)
    return meshlib.collective_cost(
        "all_to_all", nbytes,
        shards=max(data, family_shards(fam, mesh)), mesh=mesh)


def _demand_bytes(demand: Optional[str], fam: Optional[str],
                  nbytes: Optional[int], mesh) -> float:
    """Pure collective bytes of `demand_cost` — see `_transition_bytes`
    on the penalty split."""
    cost = demand_cost(demand, fam, nbytes, mesh)
    return float(cost.bytes_moved) if cost is not None else 0.0


def _with_penalty(move_bytes: float) -> float:
    """Objective contribution of one boundary move: its bytes plus the
    fixed per-reshard penalty (every move also costs a collective
    launch, so fewer moves win byte ties). Zero moves carry no
    penalty."""
    return move_bytes + RESHARD_PENALTY_BYTES if move_bytes else 0.0


def gather_cost(fam: Optional[str], nbytes: Optional[int], mesh):
    """KP603's pricing as a `CollectiveCost` (or None): a host consumer
    of device-sharded data all-gathers every shard."""
    if fam is None or fam == FAMILY_REPLICATED or not nbytes:
        return None
    return meshlib.collective_cost(
        "all_gather", nbytes, shards=family_shards(fam, mesh), mesh=mesh)


def _gather_bytes(fam: Optional[str], nbytes: Optional[int], mesh) -> float:
    cost = gather_cost(fam, nbytes, mesh)
    return float(cost.bytes_moved) if cost is not None else 0.0


class _CostModel:
    """The planner's priced view of one graph: per-vertex menus, node
    costs (KP600 budget feasibility, KP602 replication penalty), hook
    demands, and a shared assignment scorer — so the DP's choice and the
    default's score come from literally the same arithmetic."""

    def __init__(self, graph: Graph, specs: Dict[GraphId, Any], mesh,
                 hbm_budget_bytes: Optional[int],
                 replicated_threshold_bytes: int):
        self.graph = graph
        self.specs = specs
        self.mesh = mesh
        self.budget = hbm_budget_bytes
        self.threshold = replicated_threshold_bytes
        order, _ = toposort(graph)
        self.order = [v for v in order if not isinstance(v, SinkId)]
        # apply-path boundaries propagated from an unbound source carry
        # no example count; cost them at the graph's nominal count (the
        # largest known count — the fit side's — else a fixed stand-in)
        # so the per-example byte ratios that drive the decision still
        # rank correctly. Absolute feasibility (KP600) is only checked
        # where the count is real.
        known_counts = [
            s.count for s in specs.values()
            if isinstance(s, DataSpec) and s.kind == "dataset"
            and s.count
        ]
        self.nominal_count = max(known_counts, default=1024)
        #: vid -> {family: realized ShardedValue} for choosable vertices
        self.menus: Dict[GraphId, Dict[str, ShardedValue]] = {}
        for vid in self.order:
            spec = specs.get(vid)
            if not self._choosable_spec(spec):
                continue
            menu = {}
            for fam in MENU:
                sv = realize_family(fam, spec, mesh)
                if sv is not None:
                    menu[fam] = sv
            if menu:
                self.menus[vid] = menu
        self._demands: Dict[GraphId, Tuple[Optional[str], ...]] = {}
        self._host: Dict[GraphId, bool] = {}

    def _choosable_spec(self, spec) -> bool:
        if not isinstance(spec, DataSpec) or spec.kind != "dataset":
            return False
        if not spec.on_device or not is_known(spec.element):
            return False
        return self.vbytes(spec) is not None

    def vbytes(self, spec) -> Optional[int]:
        """Priced size of a boundary value: real bytes when the count is
        known, per-element bytes × the nominal count otherwise."""
        if not isinstance(spec, DataSpec):
            return None
        if spec.nbytes is not None:
            return spec.nbytes
        if spec.kind != "dataset":
            return None
        per = element_nbytes(spec.element)
        if per is None:
            return None
        return per * self.nominal_count

    def data_deps(self, vid) -> List[GraphId]:
        if isinstance(vid, (SourceId,)):
            return []
        deps = self.graph.get_dependencies(vid)
        return [d for d in deps if isinstance(self.specs.get(d), DataSpec)]

    def demands(self, vid, assignment) -> Tuple[Optional[str], ...]:
        """The operator's `abstract_sharding` input demands, evaluated
        once (fit demands are static; a raising hook contributes none —
        the lint's KP605 channel reports it)."""
        if vid in self._demands:
            return self._demands[vid]
        out: Tuple[Optional[str], ...] = ()
        if isinstance(vid, NodeId):
            op = self.graph.get_operator(vid)
            hook = getattr(op, "abstract_sharding", None)
            if hook is not None:
                deps = self.graph.get_dependencies(vid)
                in_shardings = [assignment.get(d) for d in deps]
                in_specs = [self.specs.get(d) for d in deps]
                try:
                    res = hook(in_shardings, in_specs)
                    if isinstance(res, ShardingResult):
                        out = tuple(res.demands)
                except Exception:
                    out = ()
        self._demands[vid] = out
        return out

    def is_host(self, vid) -> bool:
        got = self._host.get(vid)
        if got is None:
            got = isinstance(vid, NodeId) and _is_host_stage(
                self.graph, vid, self.specs)
            self._host[vid] = got
        return got

    def node_cost(self, vid, fam: str) -> float:
        """Per-vertex cost of holding this stage in ``fam``: INF when
        the per-device residency busts the KP600 budget (the menu entry
        is pruned), plus the KP602 broadcast penalty for oversized
        replication with a shardable axis."""
        spec = self.specs.get(vid)
        sv = self.menus[vid][fam]
        cost = 0.0
        if self.budget:
            pd = per_device_bytes(spec, sv, self.mesh)
            if pd is not None and pd > self.budget:
                return _INF
        if fam == FAMILY_REPLICATED and spec.nbytes \
                and spec.nbytes >= self.threshold \
                and _shardable_axis(spec, self.mesh) is not None:
            cost += float(meshlib.collective_cost(
                "broadcast", spec.nbytes,
                shards=int(self.mesh.devices.size),
                mesh=self.mesh).bytes_moved)
        return cost

    # ---------------------------------------------------------- scoring

    def score(self, families: Dict[GraphId, str]) -> Tuple[
            float, float, Dict[NodeId, int]]:
        """``(objective, bytes_total, boundary)`` of one complete
        assignment. ``boundary`` holds per-vertex PURE collective bytes
        (charged at the consumer, matching the lint's ``boundary_costs``
        semantics — no synthetic penalties); ``bytes_total`` is their
        sum; ``objective`` additionally carries the per-reshard penalty
        and INF for budget-infeasible assignments, and is what the
        solver compares. The SAME function scores the planner's optimum
        and the PR-8 default, so "planner ≤ default" is a property of
        the arithmetic, not of two models agreeing."""
        assignment = {
            vid: self.menus[vid][fam]
            for vid, fam in families.items() if vid in self.menus
        }
        objective = 0.0
        bytes_total = 0.0
        boundary: Dict[NodeId, int] = {}

        def charge(vid, move_bytes: float, penalized: bool = True) -> None:
            nonlocal objective, bytes_total
            if not move_bytes:
                return
            objective += (_with_penalty(move_bytes) if penalized
                          else move_bytes)
            if move_bytes != _INF:
                bytes_total += move_bytes
                if isinstance(vid, NodeId):
                    boundary[vid] = boundary.get(vid, 0) + int(move_bytes)

        for vid in self.order:
            fam_v = families.get(vid)
            if fam_v is not None and vid in self.menus:
                # node costs are either INF (budget) or real broadcast
                # bytes (KP602) — never a launch-penalty situation
                charge(vid, self.node_cost(vid, fam_v), penalized=False)
            deps = self.data_deps(vid)
            demands = self.demands(vid, assignment)
            all_deps = (list(self.graph.get_dependencies(vid))
                        if isinstance(vid, NodeId) else [])
            for d in deps:
                fam_u = families.get(d)
                u_spec = self.specs.get(d)
                nbytes = self.vbytes(u_spec)
                if self.is_host(vid):
                    charge(vid, _gather_bytes(fam_u, nbytes, self.mesh),
                           penalized=False)
                    continue
                demand = None
                if demands:
                    try:
                        i = all_deps.index(d)
                    except ValueError:
                        i = -1
                    if 0 <= i < len(demands):
                        demand = demands[i]
                if demand is not None:
                    charge(vid, _demand_bytes(
                        demand, fam_u, nbytes, self.mesh))
                elif fam_v is not None:
                    charge(vid, _transition_bytes(
                        fam_u, fam_v, nbytes, self.mesh, u_spec=u_spec))
        return objective, bytes_total, boundary


# ---------------------------------------------------------------------- plan


@dataclass
class ShardingPlan:
    """The planner's decision: chosen per-stage placements, the PR-8
    default they were scored against, and both priced totals. When
    ``improved`` is False the choices ARE the default assignment and
    nothing is enforced."""

    mesh: Any
    families: Dict[GraphId, str]
    default_families: Dict[GraphId, str]
    choices: Dict[GraphId, ShardedValue]
    default_shardings: Dict[GraphId, Optional[ShardedValue]]
    planned_cost_bytes: float
    default_cost_bytes: float
    planned_boundary: Dict[NodeId, int] = field(default_factory=dict)
    default_boundary: Dict[NodeId, int] = field(default_factory=dict)
    #: every complete assignment the solver actually scored, priced by
    #: the shared cost function: ``[{"entry", "objective", "cost_bytes"},
    #: ...]`` — the decision ledger's alternatives menu (the candidates
    #: used to be computed and thrown away; now they are the audit
    #: trail of what the chosen plan beat).
    scored_candidates: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def improved(self) -> bool:
        return self.planned_cost_bytes < self.default_cost_bytes

    @property
    def savings_bytes(self) -> int:
        return max(0, int(self.default_cost_bytes - self.planned_cost_bytes))

    def changed_vertices(self) -> List[GraphId]:
        return [vid for vid, fam in sorted(
                    self.families.items(),
                    key=lambda kv: getattr(kv[0], "id", -1))
                if self.default_families.get(vid) != fam]

    def spec_for(self, vid) -> Optional[P]:
        """The batch-level PartitionSpec the plan pins on ``vid``'s
        output (first leaf — enforcement constrains array outputs, which
        are single-leaf on every enforced path)."""
        sv = self.choices.get(vid)
        if sv is None:
            return None
        leaves = sv.leaf_specs()
        return leaves[0] if leaves else None

    def partition_rules(self, graph: Graph) -> List[PartitionRule]:
        """The chosen plan as declarative `PartitionRule`s — one
        anchor-exact rule per stage whose choice deviates from the
        default — the channel by which the decision feeds any
        rule-consuming surface (`validate(partition_rules=...)`)."""
        rules = []
        for vid in self.changed_vertices():
            if not isinstance(vid, NodeId):
                continue
            spec = self.spec_for(vid)
            if spec is None:
                continue
            anchor = f"{_label(graph, vid)}@{vid}"
            rules.append(PartitionRule(f"^{re.escape(anchor)}$", spec))
        return rules

    def rows(self, graph: Graph) -> List[Dict[str, Any]]:
        """Chosen-vs-default per-stage table (topo order), JSON-ready —
        the ``--explain-sharding --plan`` payload."""
        order, _ = toposort(graph)
        rows = []
        for vid in order:
            if not isinstance(vid, NodeId):
                continue
            chosen = self.choices.get(vid, self.default_shardings.get(vid))
            rows.append({
                "vertex": vid.id,
                "label": _label(graph, vid),
                "default_spec": spec_str(self.default_shardings.get(vid)),
                "chosen_spec": spec_str(chosen),
                "changed": vid in set(self.changed_vertices()),
                "default_boundary_bytes": self.default_boundary.get(vid, 0),
                "planned_boundary_bytes": self.planned_boundary.get(vid, 0),
            })
        return rows


def format_plan(rows: List[Dict[str, Any]]) -> str:
    lines = [f"{'stage':<38} {'default':<20} {'chosen':<20} {'Δbytes':>12}"]
    for r in rows:
        delta = r["default_boundary_bytes"] - r["planned_boundary_bytes"]
        mark = "*" if r["changed"] else " "
        name = f"{r['label']}@{r['vertex']}"
        col = f"{delta:+,d}" if delta else "—"
        lines.append(
            f"{name[:38]:<38} {r['default_spec'][:20]:<20} "
            f"{mark}{r['chosen_spec'][:19]:<19} {col:>12}")
    return "\n".join(lines)


# ------------------------------------------------------------------- solver


def plan_sharding(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    mesh=None,
    hbm_budget_bytes: Optional[int] = None,
    replicated_threshold_bytes: int = DEFAULT_REPLICATED_THRESHOLD,
) -> Optional[ShardingPlan]:
    """Choose a placement assignment minimizing priced boundary bytes.

    Returns None when there is nothing to decide (a 1-device mesh, or no
    stage with a known on-device dataset boundary). Otherwise the DP
    runs, both the optimum and the PR-8 default are scored with the same
    cost function, and the better one is returned — ``improved`` says
    whether the planner actually beat the default."""
    mesh = mesh or meshlib.current_mesh()
    if int(mesh.devices.size) <= 1:
        return None
    model = _CostModel(graph, specs, mesh, hbm_budget_bytes,
                       replicated_threshold_bytes)
    if not model.menus:
        return None

    # the PR-8 default placement, classified into families; stages whose
    # default placement matches no family are dropped from the choice
    # set entirely (the planner leaves what it cannot classify alone)
    default_shardings, _, _ = sharding_pass(graph, specs, mesh=mesh)
    default_families: Dict[GraphId, str] = {}
    for vid in list(model.menus):
        fam = family_of(default_shardings.get(vid), mesh)
        if fam is None or fam not in model.menus[vid]:
            del model.menus[vid]
        else:
            default_families[vid] = fam
    if not model.menus:
        return None

    graph_users = {vid: [u for u in graph.users_of(vid)
                         if not isinstance(u, SinkId)]
                   for vid in model.order}

    dp: Dict[GraphId, Dict[str, float]] = {}
    back: Dict[GraphId, Dict[str, Optional[str]]] = {}
    chain_parent: Dict[GraphId, GraphId] = {}
    frozen: Dict[GraphId, str] = {}

    def menu_rank(vid, fam) -> Tuple:
        # deterministic tie-break: prefer the default family, then menu
        # order — so a planner with nothing to win reproduces the
        # default assignment exactly
        return (0 if fam == default_families.get(vid) else 1,
                MENU.index(fam))

    def freeze(vid, extra=None) -> None:
        """Finalize ``vid``'s family (greedy frontier merge): pick the
        cheapest table entry — optionally biased by the freezing
        consumer's ``extra(family)`` cost — then walk the chain
        backpointers so every upstream link of the fan-out-free chain
        is assigned its matching optimal family."""
        if vid in frozen or vid not in dp:
            return
        table = dp[vid]
        best = min(
            table,
            key=lambda f: (table[f] + (extra(f) if extra else 0.0),)
            + menu_rank(vid, f))
        if table[best] == _INF:
            best = default_families[vid]  # every entry infeasible
        cur, fam = vid, best
        while cur is not None:
            frozen[cur] = fam
            parent = chain_parent.get(cur)
            fam = back.get(cur, {}).get(fam) if parent is not None else None
            if parent is not None and fam is None:
                # all-INF chain under a KP600 budget (every transition
                # priced infeasible, so no backpointer was recorded):
                # keep the default family rather than poisoning the
                # assignment with None — score() still prices it INF
                fam = default_families[parent]
            cur = parent

    for vid in model.order:
        deps = model.data_deps(vid)
        choosable_deps = [d for d in deps if d in model.menus]
        if vid in model.menus:
            chain = None
            if len(choosable_deps) == 1:
                (u,) = choosable_deps
                if len(graph_users.get(u, ())) == 1 and u in dp \
                        and u not in frozen:
                    chain = u
            # non-chain parents are frozen here (greedy frontier merge)
            for d in choosable_deps:
                if d is not chain:
                    freeze(d)
            table: Dict[str, float] = {}
            bptr: Dict[str, Optional[str]] = {}
            for fam in model.menus[vid]:
                node = model.node_cost(vid, fam)
                if chain is not None:
                    u_spec = model.specs.get(chain)
                    u_bytes = model.vbytes(u_spec)
                    best_g, best_cost = None, _INF
                    for g, gc in dp[chain].items():
                        c = gc + _with_penalty(_transition_bytes(
                            g, fam, u_bytes, mesh, u_spec=u_spec))
                        if c < best_cost or (
                                c == best_cost and best_g is not None
                                and menu_rank(chain, g)
                                < menu_rank(chain, best_g)):
                            best_g, best_cost = g, c
                    table[fam] = best_cost + node
                    bptr[fam] = best_g
                else:
                    base = 0.0
                    for d in choosable_deps:
                        d_spec = model.specs.get(d)
                        base += _with_penalty(_transition_bytes(
                            frozen.get(d), fam, model.vbytes(d_spec),
                            mesh, u_spec=d_spec))
                    table[fam] = base + node
                    bptr[fam] = None
            dp[vid] = table
            back[vid] = bptr
            if chain is not None:
                chain_parent[vid] = chain
        else:
            # a non-choice consumer terminates its producers' chains;
            # freezing is demand- and host-aware so a chain's last link
            # is chosen knowing what its consumer will charge
            demands = model.demands(vid, {})
            all_deps = (graph.get_dependencies(vid)
                        if isinstance(vid, NodeId) else ())
            for d in choosable_deps:
                d_bytes = model.vbytes(model.specs.get(d))
                if model.is_host(vid):
                    freeze(d, extra=lambda f, b=d_bytes:
                           _gather_bytes(f, b, mesh))
                elif demands:
                    try:
                        i = list(all_deps).index(d)
                    except ValueError:
                        i = -1
                    demand = demands[i] if 0 <= i < len(demands) else None
                    freeze(d, extra=lambda f, dm=demand, b=d_bytes:
                           _with_penalty(_demand_bytes(dm, f, b, mesh)))
                else:
                    freeze(d)

    for vid in model.order:
        if vid in dp and vid not in frozen:
            freeze(vid)  # chain tails feeding only sinks

    default_obj, default_bytes, default_boundary = model.score(
        default_families)

    # Greedy frontier merge can freeze a shared producer (the
    # train/apply input both chains hang off) before either consumer's
    # preference is known. Two cheap repairs, both scored by the same
    # function: the uniform data-parallel assignment as an alternative
    # seed, then a bounded coordinate-descent sweep (try each family at
    # each vertex, keep strict improvements) — chains stay exact via the
    # DP, diamonds get polished globally.
    def pick(fams_a, obj_a, fams_b):
        obj_b, _, _ = model.score(fams_b)
        return (fams_b, obj_b) if obj_b < obj_a else (fams_a, obj_a)

    best_fams = dict(frozen)
    best_obj, dp_bytes, _ = model.score(best_fams)
    uniform = {
        vid: (FAMILY_DATA if FAMILY_DATA in model.menus[vid]
              else default_families[vid])
        for vid in model.menus
    }
    uniform_obj, uniform_bytes, _ = model.score(uniform)
    # the scored-candidate menu the ledger exposes: every complete
    # assignment priced by the same function (the chosen plan's own
    # entry is appended after descent below)
    scored_candidates = [
        {"entry": "default", "objective": float(default_obj),
         "cost_bytes": float(default_bytes)},
        {"entry": "chain_dp", "objective": float(best_obj),
         "cost_bytes": float(dp_bytes)},
        {"entry": "uniform_data", "objective": float(uniform_obj),
         "cost_bytes": float(uniform_bytes)},
    ]
    best_fams, best_obj = pick(best_fams, best_obj, uniform)
    for _sweep in range(3):
        changed = False
        for vid in model.order:
            if vid not in model.menus:
                continue
            for fam in model.menus[vid]:
                if fam == best_fams.get(vid):
                    continue
                trial = dict(best_fams)
                trial[vid] = fam
                trial_obj, _, _ = model.score(trial)
                if trial_obj < best_obj:
                    best_fams, best_obj = trial, trial_obj
                    changed = True
        if not changed:
            break

    frozen = best_fams
    planned_obj, planned_bytes, planned_boundary = model.score(frozen)
    scored_candidates.append(
        {"entry": "local_descent", "objective": float(planned_obj),
         "cost_bytes": float(planned_bytes)})

    # the plan wins only when BOTH the full objective (bytes +
    # per-reshard penalties + feasibility) and the pure byte total are
    # strictly better — the reported savings are honest collective
    # bytes, and `improved` is exactly "frozen differs from default"
    if not (planned_obj < default_obj and planned_bytes < default_bytes):
        # the optimizer found no strict win: the plan IS the default
        frozen = dict(default_families)
        planned_bytes, planned_boundary = default_bytes, default_boundary

    choices = {vid: model.menus[vid][fam] for vid, fam in frozen.items()}
    return ShardingPlan(
        mesh=mesh,
        families=frozen,
        default_families=default_families,
        choices=choices,
        default_shardings=default_shardings,
        planned_cost_bytes=planned_bytes,
        default_cost_bytes=default_bytes,
        planned_boundary=planned_boundary,
        default_boundary=default_boundary,
        scored_candidates=scored_candidates,
    )
