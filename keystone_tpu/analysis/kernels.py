"""KP10xx static chain-kernel verification tier: prove every registered
chain-kernel lowering (`ops/chain_kernels.py` — the KP801 candidates
the unified planner's kernel axis prices) safe BEFORE any TPU time.

PR 16 lowered the KP801 candidates to hand-rolled Pallas megakernels,
but every safety property they rest on was validated only by
interpret-mode CPU tests — while live TPU windows are scarce and must
not be burned on avoidable Mosaic rejects or silent padded-row
corruption. This tier makes those runtime disciplines *checked static
properties* (the KP2xx/KP5xx/KP6xx/KP9xx pattern; the memory-safe-XLA
discipline of arXiv 2206.14148 applied to kernel geometry), from the
analyzer's propagated element specs, with no device and no tracing
beyond `jax.eval_shape` / `jax.make_jaxpr`:

- **KP1001** grid/index-map coverage: grid × block shape tiles the
  padded output exactly — every output element written exactly once
  (double-writes AND gaps both flagged).
- **KP1002** ragged-tail bounds: block reads stay inside the padded
  operand shapes for EVERY batch count the host batcher's PR-5 pad
  ladder can emit (checked against `utils/batching._pad_target`'s
  actual pad targets, not a convention).
- **KP1003** VMEM working-set proof: 2× double-buffered streamed
  blocks + single-buffered intermediates + closure params ≤ the
  budget, computed by the SAME arithmetic as `chain_feasible`'s
  runtime chooser (`ops.chain_kernels.chain_vmem_bytes` /
  `chain_block_rows` — one shared function, so the static proof and
  the runtime demotion can never diverge; the
  `collective_cost`/`live_set_walk` precedent).
- **KP1004** mask discipline: a `fuse_masks_output` stage inside a
  kernel body that does not consume the streamed mask operand at its
  original chain position is the padded-row corruption class —
  detected structurally from `stage_statics`, not by convention.
- **KP1005** abstract oracle equivalence: the per-block kernel body vs
  the pure-jnp reference oracle — shape/dtype agreement on every stage
  boundary, with the block's leading (batch) dim preserved end to end
  (a body that reduces or grows the batch axis inside a block cannot
  equal the batch oracle).

Surfaced in `validate(level="full")` (after the roofline pass — the
verifier consumes its KP801 candidate list), `python -m
keystone_tpu.analysis --audit-kernels [--json]` (gated in
scripts/lint.sh: every registered lowering verifies clean or carries a
named suppression), the unified planner (statically-refuted kernel
menu entries price INF instead of relying on the runtime canary), the
ledger's kernel records (`statically_verified`, reconciled by
`reconcile.reconcile_roofline`), and `scripts/kernel_live_check.py`
(statically-refuted geometries are skipped with the KP code printed,
so live TPU minutes only test what static analysis cannot prove).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from .diagnostics import Diagnostic, Severity

#: named suppressions for the --audit-kernels gate: (example, rule) or
#: (example, rule, stage-label-substring) → reason. Each entry states
#: WHY the lowering stays unproven and what would discharge it — the
#: `SUPPRESSED_STAGES`/`SERVING_SUPPRESSIONS` escape-hatch discipline.
#: Empty today: all registered lowerings verify clean.
KERNEL_SUPPRESSIONS: Dict[Tuple[str, str], str] = {}

#: smallest ragged probe the coverage proof re-runs at (exercises the
#: bn_e = min(bn, n) clamp the full-chunk probe cannot see)
_MIN_PROBE = 1


# ---------------------------------------------------------------------------
# Rule checkers — pure functions over explicit geometry, so the
# seeded-mutant tests can feed broken grids/blocks/recipes directly
# ---------------------------------------------------------------------------


def check_grid_coverage(grid, block_shape, index_map, out_shape) -> List[str]:
    """KP1001: prove ``grid`` × ``block_shape`` under ``index_map``
    tiles ``out_shape`` exactly — every output element written exactly
    once. Block origins are index-map outputs scaled by the block shape
    (Pallas `BlockSpec` semantics), so in-bounds distinct origins are
    disjoint by construction; a repeated origin is a double-write, a
    short union is a gap, an origin past the padded extent is an
    out-of-bounds write."""
    import itertools
    import math

    problems: List[str] = []
    origins = set()
    for idx in itertools.product(*(range(int(g)) for g in grid)):
        bi = tuple(index_map(*idx))
        if len(bi) != len(block_shape):
            return [f"index map returns rank {len(bi)} for block rank "
                    f"{len(block_shape)}"]
        origin = tuple(int(b) * int(s) for b, s in zip(bi, block_shape))
        for d, (o, s, full) in enumerate(
                zip(origin, block_shape, out_shape)):
            if o < 0 or o + s > full:
                problems.append(
                    f"grid point {idx}: writes [{o}, {o + s}) outside "
                    f"output dim {d} of size {full}")
        if origin in origins:
            problems.append(
                f"grid point {idx}: double-write — origin {origin} "
                f"already written by an earlier grid step")
        origins.add(origin)
    if problems:
        return problems
    covered = len(origins) * math.prod(int(s) for s in block_shape)
    total = math.prod(int(s) for s in out_shape)
    if covered != total:
        problems.append(
            f"coverage gap: {len(origins)} block(s) of "
            f"{tuple(block_shape)} write {covered} of {total} padded "
            f"output elements")
    return problems


def check_read_bounds(grid, block_shape, index_map, operand_shape,
                      name="operand") -> List[str]:
    """KP1002 (structural half): every block READ stays inside the
    padded operand — repeated reads (broadcast params) are legal, reads
    past the padded extent are not."""
    import itertools

    problems: List[str] = []
    for idx in itertools.product(*(range(int(g)) for g in grid)):
        bi = tuple(index_map(*idx))
        if len(bi) != len(block_shape):
            return [f"{name}: index map returns rank {len(bi)} for "
                    f"block rank {len(block_shape)}"]
        origin = tuple(int(b) * int(s) for b, s in zip(bi, block_shape))
        for d, (o, s, full) in enumerate(
                zip(origin, block_shape, operand_shape)):
            if o < 0 or o + s > full:
                problems.append(
                    f"{name}: grid point {idx} reads [{o}, {o + s}) "
                    f"outside padded dim {d} of size {full}")
                break
    return problems


def check_ragged_bounds(bn, counts, *, pad=None) -> List[str]:
    """KP1002 (pad-ladder half): for every batch count the host batcher
    can emit, the lowering's own padding recipe (``bn_e = min(bn, n)``,
    ``n_pad = round_up(n, bn_e)``, ``grid = n_pad // bn_e``) must cover
    every valid row and end the final block exactly at the padded row
    count. ``pad`` is injectable so the seeded-mutant tests can feed a
    floor-instead-of-ceil recipe."""
    if pad is None:
        from ..ops.pallas_kernels import _round_up as pad
    problems: List[str] = []
    for n_b in counts:
        n_b = int(n_b)
        if n_b <= 0:
            continue
        bn_e = min(int(bn), n_b)
        if bn_e <= 0:
            problems.append(f"count {n_b}: non-positive block {bn_e}")
            continue
        n_pad = int(pad(n_b, bn_e))
        if n_pad < n_b:
            problems.append(
                f"count {n_b}: padded row count {n_pad} drops "
                f"{n_b - n_pad} valid row(s)")
            continue
        grid = n_pad // bn_e
        if grid * bn_e != n_pad:
            problems.append(
                f"count {n_b}: grid {grid} × block {bn_e} covers "
                f"{grid * bn_e} of {n_pad} padded rows")
    return problems


def check_vmem_budget(bn, io_bytes, inter_bytes, param_bytes, ladder, *,
                      budget=None) -> List[str]:
    """KP1003: the chosen block's working set fits the VMEM budget AND
    the static choice is identical to the runtime chooser's — both
    computed by the ONE shared formula (`chain_vmem_bytes` /
    `chain_block_rows`), so a divergence here means the shared-function
    contract itself was broken."""
    from ..ops import chain_kernels as ck

    budget = ck._VMEM_BUDGET if budget is None else budget
    problems: List[str] = []
    if bn <= 0:
        problems.append("no feasible VMEM block at this geometry")
        return problems
    used = ck.chain_vmem_bytes(int(bn), io_bytes, inter_bytes, param_bytes)
    if used > budget:
        problems.append(
            f"block {bn}: working set {used} B (2×{io_bytes} streamed "
            f"+ {bn}×{inter_bytes} transient + {param_bytes} params) "
            f"exceeds the VMEM budget {budget} B")
    chooser = ck.chain_block_rows(io_bytes, inter_bytes, param_bytes,
                                  ladder=ladder, budget=budget)
    if chooser != bn:
        problems.append(
            f"chooser divergence: static proof holds block {bn} but "
            f"the runtime chooser picks {chooser} from the same parts")
    return problems


def check_mask_discipline(declared_positions, consumed_positions,
                          streams_mask) -> List[str]:
    """KP1004: every `fuse_masks_output` stage (declared via its
    `_stage_fuse` static's ``(key, "masked")`` wrapping) must re-zero
    padded rows at its ORIGINAL chain position inside the kernel body,
    from a streamed mask operand — a mask applied late, early, or not
    at all lets padded garbage flow through downstream reductions."""
    declared = [int(p) for p in declared_positions]
    consumed = [int(p) for p in consumed_positions]
    problems: List[str] = []
    if declared and not streams_mask:
        problems.append(
            f"stage position(s) {declared} declare fuse_masks_output "
            f"but the kernel streams no mask operand — padded rows are "
            f"never re-zeroed")
        return problems
    for p in declared:
        if p not in consumed:
            problems.append(
                f"stage {p} declares fuse_masks_output but the kernel "
                f"body does not consume the mask at position {p} — the "
                f"padded-row corruption class")
    for p in consumed:
        if p not in declared:
            problems.append(
                f"kernel body masks at position {p} where no stage "
                f"declares fuse_masks_output — the body diverges from "
                f"the node-by-node semantics")
    return problems


def check_oracle_boundaries(kernel_avals, oracle_avals, bn) -> List[str]:
    """KP1005: per-block kernel body vs pure-jnp reference oracle —
    shape/dtype agreement at every stage boundary, with the block's
    leading (batch) dim preserved: a body that reduces or concatenates
    over the batch axis inside a block cannot agree with the batch
    oracle even when per-boundary tails match."""
    problems: List[str] = []
    if len(kernel_avals) != len(oracle_avals):
        return [f"boundary count mismatch: kernel body traces "
                f"{len(kernel_avals)} boundaries, the oracle "
                f"{len(oracle_avals)}"]
    for i, (ka, oa) in enumerate(zip(kernel_avals, oracle_avals)):
        if str(ka.dtype) != str(oa.dtype):
            problems.append(
                f"boundary {i}: kernel dtype {ka.dtype} != oracle "
                f"dtype {oa.dtype}")
        if tuple(ka.shape[1:]) != tuple(oa.shape[1:]):
            problems.append(
                f"boundary {i}: kernel block tail {tuple(ka.shape[1:])} "
                f"!= oracle tail {tuple(oa.shape[1:])}")
        if ka.shape and int(ka.shape[0]) != int(bn):
            problems.append(
                f"boundary {i}: kernel block leading dim "
                f"{ka.shape[0]} != block rows {bn} — the body does not "
                f"preserve the batch axis within a block")
    return problems


# ---------------------------------------------------------------------------
# Pad-ladder enumeration (the KP1002 bucket set)
# ---------------------------------------------------------------------------


_PAD_TARGET_CACHE: Dict[int, List[int]] = {}


def batcher_pad_targets(chunk: Optional[int] = None) -> List[int]:
    """Every padded batch count `utils/batching`'s PR-5 pad ladder can
    emit at the resolved chunk size: full chunks, the pow-2 ladder for
    small buckets, and the tail counts of chunk-straddling buckets —
    enumerated from `_pad_target` itself, never re-derived."""
    from ..utils.batching import _pad_target
    from ..workflow.env import resolved_chunk_size

    if chunk is None:
        try:
            chunk = resolved_chunk_size()
        except Exception:
            chunk = None
    if not chunk:
        return [1]
    chunk = int(chunk)
    if chunk in _PAD_TARGET_CACHE:
        return _PAD_TARGET_CACHE[chunk]
    targets = {chunk}
    for n in range(1, chunk + 1):
        for bucket_n in (n, chunk + n):
            t = _pad_target(n, chunk, bucket_n)
            if t:
                targets.add(int(t))
    _PAD_TARGET_CACHE[chunk] = sorted(targets)
    return _PAD_TARGET_CACHE[chunk]


# ---------------------------------------------------------------------------
# Per-family abstract geometry (mirrors the pallas_call construction)
# ---------------------------------------------------------------------------


def _abstract_geometry(family, statics, params, item_shape, dtype, n):
    """The lowering's abstract launch geometry at batch count ``n``:
    grid, write spec, read specs, per-boundary avals (kernel block and
    batch oracle), the shared VMEM parts, and the mask positions —
    everything the KP1001–KP1005 checkers consume, built from the SAME
    published chooser/body helpers `ops/chain_kernels.py` dispatches
    through (`eval_shape` only, nothing compiles)."""
    import jax
    import jax.numpy as jnp

    from ..ops import chain_kernels as ck
    from ..ops.pallas_kernels import _round_up

    item_shape = tuple(int(d) for d in item_shape)
    geom: Dict[str, Any] = {"family": family, "item_shape": item_shape,
                            "dtype": jnp.dtype(dtype).name}
    if family == "rectify_pool_vectorize":
        if len(item_shape) != 3:
            geom["error"] = (f"expected (H, W, K) input, got "
                             f"{item_shape}")
            return geom
        inner, _ = ck._unwrap(statics[0])
        _, _, _, pool, stride = inner[:5]
        h, w, k = item_shape
        parts = ck._rectify_pool_vectorize_parts(h, w, k, pool, stride)
        if parts is None:
            geom["error"] = (f"empty pool grid at (h={h}, w={w}) with "
                             f"pool={pool}, stride={stride}")
            return geom
        geom["parts"] = parts
        bn = ck.chain_block_rows(parts[0], parts[1], parts[2],
                                 ladder=parts[3])
        geom["bn"] = bn
        if bn <= 0:
            return geom
        gy = (h - pool) // stride + 1
        gx = (w - pool) // stride + 1
        bn_e = min(bn, int(n))
        n_pad = _round_up(int(n), bn_e)
        geom["grid"] = (n_pad // bn_e,)
        geom["out_block"] = ((bn_e, gy, gx, 2 * k),
                             lambda i: (i, 0, 0, 0))
        geom["out_shape"] = (n_pad, gy, gx, 2 * k)
        geom["reads"] = [("x", (bn_e, h, w, k), lambda i: (i, 0, 0, 0),
                          (n_pad, h, w, k))]
        geom["streams_mask"] = False
        geom["mask_declared"] = [i for i, key in enumerate(statics)
                                 if ck._unwrap(key)[1]]
        geom["mask_consumed"] = []
        # kernel-side boundary avals come from the DECLARED launch
        # geometry (the BlockSpec shapes the kernel writes); the oracle
        # side re-derives them by eval_shape of the pure-jnp reference
        # — a gy/gx arithmetic bug shows up as a boundary mismatch
        x = jax.ShapeDtypeStruct((bn_e, h, w, k), jnp.dtype(dtype))
        geom["kernel_avals"] = [
            x,
            jax.ShapeDtypeStruct((bn_e, gy, gx, 2 * k), x.dtype),
            jax.ShapeDtypeStruct((bn_e, gy * gx * 2 * k), x.dtype)]
        pooled = jax.eval_shape(
            lambda xx: ck.rectify_pool_reference(xx, 0.25, 0.0, pool,
                                                 stride), x)
        flat = jax.eval_shape(
            lambda xx: ck.rectify_pool_vectorize_reference(
                xx, 0.25, 0.0, pool, stride), x)
        geom["oracle_avals"] = [x, pooled, flat]
        return geom

    # elementwise_chain
    bodies = ck._compile_bodies(statics)
    if bodies is None:
        geom["error"] = f"no elementwise lowering for {statics!r}"
        return geom
    ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
    probe = jax.ShapeDtypeStruct((8,) + item_shape, jnp.dtype(dtype))
    parts = ck._elementwise_parts(bodies, ops, probe)
    geom["parts"] = parts
    bn = ck.chain_block_rows(parts[0], parts[1], parts[2],
                             ladder=parts[3])
    geom["bn"] = bn
    if bn <= 0:
        return geom
    bn_e = min(bn, int(n))
    n_pad = _round_up(int(n), bn_e)
    block_probe = jax.ShapeDtypeStruct((bn_e,) + item_shape,
                                       jnp.dtype(dtype))
    avals = ck._elementwise_avals(bodies, ops, block_probe)
    out_tail = tuple(int(d) for d in avals[-1].shape[1:])
    geom["grid"] = (n_pad // bn_e,)
    geom["out_block"] = ((bn_e,) + out_tail,
                         lambda i, nd=len(out_tail) + 1:
                         (i,) + (0,) * (nd - 1))
    geom["out_shape"] = (n_pad,) + out_tail
    reads = [("x", (bn_e,) + item_shape,
              lambda i, nd=len(item_shape) + 1: (i,) + (0,) * (nd - 1),
              (n_pad,) + item_shape)]
    needs_mask = any(masked for masked, _, _ in bodies)
    if needs_mask:
        reads.append(("mask", (bn_e, 1), lambda i: (i, 0), (n_pad, 1)))
    for t, a in enumerate(x for stage in ops for x in stage):
        shape = tuple(int(d) for d in a.shape)
        reads.append((f"param{t}", shape,
                      lambda i, nd=len(shape): (0,) * nd, shape))
    geom["reads"] = reads
    geom["streams_mask"] = needs_mask
    geom["mask_declared"] = [i for i, key in enumerate(statics)
                             if ck._unwrap(key)[1]]
    geom["mask_consumed"] = [i for i, (masked, _, _) in enumerate(bodies)
                             if masked]
    geom["kernel_avals"] = avals
    # the batch oracle at a distinct probe count: tails must agree with
    # the block trace at EVERY boundary (a batch-axis reduce would not)
    oracle_probe = jax.ShapeDtypeStruct((max(2 * bn_e, 2),) + item_shape,
                                        jnp.dtype(dtype))
    oracle = ck._elementwise_avals(bodies, ops, oracle_probe)
    geom["oracle_avals"] = [
        jax.ShapeDtypeStruct((bn_e,) + tuple(a.shape[1:]), a.dtype)
        for a in oracle]
    return geom


# ---------------------------------------------------------------------------
# The per-lowering verifier
# ---------------------------------------------------------------------------


def verify_lowering(stages, item_shape, dtype=None, *, vertex=None,
                    label="", chunk=None) -> Tuple[Dict[str, Any],
                                                   List[Diagnostic]]:
    """Run every KP10xx rule over one candidate chain at its propagated
    element shape. Returns ``(proof, diagnostics)``:

    - ``proof["verified"]`` — True when every rule proved;
    - ``proof["refuted_by"]`` — the rule that refuted a geometry that
      can NEVER dispatch (VMEM-infeasible, chooser-agreeing) — an INFO
      fact, not an error: the planner prices it INF and the live check
      skips it;
    - ERROR diagnostics — genuine safety violations (a lowering the
      runtime WOULD dispatch whose geometry/mask/oracle proof failed).
    """
    import jax.numpy as jnp

    from ..nodes.util.fusion import _peephole, _stage_fuse
    from ..ops import chain_kernels as ck

    dtype = jnp.float32 if dtype is None else dtype
    proof: Dict[str, Any] = {
        "label": label, "vertex": vertex,
        "item_shape": tuple(int(d) for d in item_shape),
        "dtype": jnp.dtype(dtype).name, "family": None,
        "rules": {}, "verified": False, "refuted_by": None,
    }
    diags: List[Diagnostic] = []

    def err(rule, msg):
        diags.append(Diagnostic(rule, Severity.ERROR, msg,
                                vertex=vertex, label=label))
        proof["rules"][rule] = f"REFUTED: {msg}"

    try:
        fused = [_stage_fuse(s) for s in _peephole(list(stages))]
    except Exception as e:
        err("KP1005", f"stage decomposition failed: "
                      f"{type(e).__name__}: {e}")
        return proof, diags
    statics = tuple(f[0] for f in fused)
    params = [f[1] for f in fused]
    verdict = ck.lowerability(statics)
    proof["family"] = verdict.get("family")
    if not verdict["lowerable"]:
        proof["rules"]["lowerability"] = verdict["reason"]
        return proof, diags

    counts = batcher_pad_targets(chunk)
    try:
        geom = _abstract_geometry(verdict["family"], statics, params,
                                  item_shape, dtype, max(counts))
    except Exception as e:
        err("KP1005", f"abstract geometry probe failed: "
                      f"{type(e).__name__}: {e}")
        return proof, diags
    if geom.get("error"):
        # a geometry the family cannot express — the runtime chooser
        # refuses it identically (chain_feasible), so it never runs
        proof["rules"]["KP1003"] = f"refuted: {geom['error']}"
        proof["refuted_by"] = "KP1003"
        _assert_chooser_agreement(stages, item_shape, dtype, False,
                                  err)
        return proof, diags
    bn = geom["bn"]
    if bn <= 0:
        proof["rules"]["KP1003"] = (
            "refuted: no feasible VMEM block at item shape "
            f"{proof['item_shape']} (runtime chooser agrees — the "
            "planner prices this lowering INF, it never dispatches)")
        proof["refuted_by"] = "KP1003"
        _assert_chooser_agreement(stages, item_shape, dtype, False,
                                  err)
        return proof, diags

    # KP1001 — output write coverage at the flagship AND a ragged probe
    problems = check_grid_coverage(geom["grid"], geom["out_block"][0],
                                   geom["out_block"][1],
                                   geom["out_shape"])
    small = _abstract_geometry(verdict["family"], statics, params,
                               item_shape, dtype, _MIN_PROBE)
    if not small.get("error") and small.get("bn", 0) > 0:
        problems += check_grid_coverage(
            small["grid"], small["out_block"][0], small["out_block"][1],
            small["out_shape"])
    if problems:
        err("KP1001", "; ".join(sorted(set(problems))))
    else:
        proof["rules"]["KP1001"] = (
            f"proved: grid {geom['grid']} × block "
            f"{geom['out_block'][0]} tiles {geom['out_shape']} "
            f"exactly, every element written once")

    # KP1002 — read bounds + the full pad-ladder sweep
    problems = []
    for name, block, imap, oshape in geom["reads"]:
        problems += check_read_bounds(geom["grid"], block, imap, oshape,
                                      name=name)
    problems += check_ragged_bounds(bn, counts)
    if problems:
        err("KP1002", "; ".join(sorted(set(problems))))
    else:
        proof["rules"]["KP1002"] = (
            f"proved: all block reads in bounds; padding covers every "
            f"pad-ladder count in {counts}")

    # KP1003 — the shared-formula VMEM proof + chooser identity
    io_b, inter_b, param_b, ladder = geom["parts"]
    problems = check_vmem_budget(bn, io_b, inter_b, param_b, ladder)
    if problems:
        err("KP1003", "; ".join(problems))
    else:
        used = ck.chain_vmem_bytes(bn, io_b, inter_b, param_b)
        proof["rules"]["KP1003"] = (
            f"proved: block {bn} working set {used} B ≤ budget "
            f"{ck._VMEM_BUDGET} B (shared chain_vmem_bytes formula; "
            f"runtime chooser identical)")
        _assert_chooser_agreement(stages, item_shape, dtype, True, err)

    # KP1004 — mask discipline
    problems = check_mask_discipline(geom["mask_declared"],
                                     geom["mask_consumed"],
                                     geom["streams_mask"])
    if problems:
        err("KP1004", "; ".join(problems))
    else:
        proof["rules"]["KP1004"] = (
            f"proved: fuse_masks_output position(s) "
            f"{geom['mask_declared']} re-zero from the streamed mask "
            f"at their original chain position"
            if geom["mask_declared"] else
            "proved: no fuse_masks_output stage in the chain")

    # KP1005 — abstract oracle equivalence per boundary
    problems = check_oracle_boundaries(geom["kernel_avals"],
                                       geom["oracle_avals"],
                                       geom["kernel_avals"][0].shape[0])
    if problems:
        err("KP1005", "; ".join(problems))
    else:
        proof["rules"]["KP1005"] = (
            f"proved: kernel block trace agrees with the pure-jnp "
            f"oracle on shape/dtype at all "
            f"{len(geom['kernel_avals'])} stage boundaries")

    proof["verified"] = not any(d.severity >= Severity.ERROR
                                for d in diags)
    return proof, diags


def _assert_chooser_agreement(stages, item_shape, dtype, expect_ok, err):
    """The KP1003 identity half: `chain_feasible` (the runtime chooser
    the planner and dispatcher consult) must reach the same verdict as
    the static proof — both sit on `chain_vmem_bytes`, so a mismatch
    means the shared-function contract was broken."""
    from ..ops.chain_kernels import chain_feasible

    try:
        ok, reason = chain_feasible(list(stages), tuple(item_shape),
                                    dtype)
    except Exception as e:
        ok, reason = None, f"chain_feasible raised {type(e).__name__}"
    if ok is not None and bool(ok) != bool(expect_ok):
        err("KP1003",
            f"static proof says feasible={expect_ok} but "
            f"chain_feasible says feasible={ok} ({reason}) — the "
            f"shared VMEM formula diverged")


def statically_verified(stages, item_shape, dtype=None, *,
                        chunk=None) -> Optional[bool]:
    """Tri-state verdict for one candidate slice: True (every KP10xx
    rule proved), False (a rule refuted the lowering — the planner must
    price it INF), None (verification could not run — the runtime
    canary remains the only gate, as before this tier existed)."""
    try:
        proof, diags = verify_lowering(stages, item_shape, dtype,
                                       chunk=chunk)
    except Exception:
        return None
    if proof.get("family") is None:
        return None
    if any(d.severity >= Severity.ERROR for d in diags):
        return False
    if proof.get("refuted_by"):
        return False
    return bool(proof.get("verified"))


# ---------------------------------------------------------------------------
# Graph-level pass (validate(level="full")) and the registry-wide audit
# ---------------------------------------------------------------------------


def _element_at_slice(graph, specs, cand):
    """The propagated element aval entering a KP801 candidate's slice —
    the same data-dep + `eval_shape` stage walk
    `plan_ir._UnifiedModel._kernel_feasible` uses."""
    import jax

    from .specs import DataSpec

    vid = cand["vertices"][0]
    dep = None
    try:
        for d in graph.get_dependencies(vid):
            if isinstance(specs.get(d), DataSpec):
                dep = d
                break
    except Exception:
        return None
    spec = specs.get(dep)
    if spec is None or getattr(spec, "element", None) is None:
        return None
    elem = spec.element
    if cand.get("kind") == "fused_trail" and cand.get("stage_slice"):
        from ..nodes.util.fusion import _peephole
        from ..workflow.fusion_rule import FusedChainOperator

        op = graph.get_operator(vid)
        stage_list = (list(op.stage_specs)
                      if isinstance(op, FusedChainOperator)
                      else list(op.stages))
        stages = list(_peephole(stage_list))
        i, _ = cand["stage_slice"]
        for s in stages[:i]:
            elem = jax.eval_shape(
                lambda x, s=s: s.single_transform([x]), elem)
    return elem


def kernel_pass(graph, specs, roofline) -> Tuple[List[Dict[str, Any]],
                                                 List[Diagnostic]]:
    """Verify every lowerable KP801 candidate of one graph's roofline
    estimate. Returns (proofs, diagnostics); annotates each candidate
    dict with ``statically_verified`` in place (the ledger/planner
    thread). Never breaks validation — an internal failure downgrades
    to a WARNING naming the candidate (the `contract_pass` discipline:
    the audit must never break the analyzer that hosts it)."""
    proofs: List[Dict[str, Any]] = []
    diags: List[Diagnostic] = []
    if roofline is None:
        return proofs, diags
    from .roofline import _candidate_stage_objects

    for cand in getattr(roofline, "candidates", None) or []:
        verdict = cand.get("lowerable") or {}
        if not verdict.get("lowerable"):
            continue
        head = cand["vertices"][0]
        label = " >> ".join(str(s) for s in cand.get("stages", []))
        try:
            stages = _candidate_stage_objects(graph, cand)
            elem = _element_at_slice(graph, specs, cand)
            if stages is None or elem is None:
                continue
            proof, pdiags = verify_lowering(
                stages, tuple(elem.shape), elem.dtype, vertex=head,
                label=label)
        except Exception as e:
            diags.append(Diagnostic(
                "KP1005", Severity.WARNING,
                f"kernel verification could not run: "
                f"{type(e).__name__}: {e}", vertex=head, label=label))
            cand["statically_verified"] = None
            continue
        proof["vertices"] = list(cand["vertices"])
        proof["kind"] = cand.get("kind")
        cand["statically_verified"] = (
            False if (proof["refuted_by"] or not proof["verified"])
            else True)
        proofs.append(proof)
        diags.extend(pdiags)
        if proof["refuted_by"]:
            diags.append(Diagnostic(
                proof["refuted_by"], Severity.INFO,
                f"statically refuted: "
                f"{proof['rules'].get(proof['refuted_by'], '')} — the "
                f"unified planner prices this kernel INF and the live "
                f"check skips the geometry", vertex=head, label=label))
    return proofs, diags


def audit_kernels(names: Optional[Iterable[str]] = None,
                  chunk: Optional[int] = None):
    """Registry-wide chain-kernel verification sweep — the KP10xx twin
    of `contracts.audit_registry`: build every example pipeline,
    propagate specs, price the roofline, and verify every lowerable
    KP801 candidate. Returns ``(findings, stats)`` where findings is
    ``[(example, proof, Diagnostic)]`` (ERROR/WARNING only — named
    `KERNEL_SUPPRESSIONS` entries are dropped with their reason
    recorded) and stats carries the per-example proof records the
    --audit-kernels CLI renders."""
    from . import as_source_spec
    from .examples import EXAMPLES, build_example
    from .propagate import spec_pass
    from .roofline import roofline_pass

    names = sorted(EXAMPLES) if names is None else list(names)
    findings: List[Tuple[str, Dict[str, Any], Diagnostic]] = []
    stats: Dict[str, Any] = {"examples": 0, "lowerings": 0,
                             "verified": 0, "proofs": [],
                             "suppressed": [], "build_errors": {}}
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            graph = pipeline.graph
            specs, _ = spec_pass(
                graph, {pipeline.source: as_source_spec(source_spec)})
            est, _ = roofline_pass(graph, specs)
            proofs, diags = kernel_pass(graph, specs, est)
        except Exception as e:
            stats["build_errors"][name] = f"{type(e).__name__}: {e}"
            continue
        stats["examples"] += 1
        stats["lowerings"] += len(proofs)
        stats["verified"] += sum(1 for p in proofs if p["verified"])
        for p in proofs:
            stats["proofs"].append({"example": name, **{
                k: v for k, v in p.items() if k != "vertex"}})
        for d in diags:
            if d.severity < Severity.WARNING:
                continue
            reason = KERNEL_SUPPRESSIONS.get((name, d.rule))
            if reason is not None:
                stats["suppressed"].append(
                    {"example": name, "rule": d.rule, "reason": reason})
                continue
            proof = next((p for p in proofs
                          if p.get("label") == d.label), {})
            findings.append((name, proof, d))
    return findings, stats
