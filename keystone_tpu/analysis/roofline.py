"""Static roofline analyzer — jaxpr-level FLOP/byte pricing, a
time-domain cost model, and Pallas-candidate lints (the KP8xx tier).

KeystoneML's solver cost model already prices ``cpuWeight·flops +
memWeight·bytes`` (nodes/learning/cost_model.py, after
LeastSquaresEstimator.scala), but until this tier the FLOP term existed
only as hand-written per-solver formulas: every static tier (KP2xx
memory, KP6xx collectives, KP7xx precision) priced bytes alone, so the
optimizer literally could not see compute. This module closes that gap
with the same static-resource discipline arXiv 2206.14148 applies to
memory: walk the jaxpr of every stage body — traced from the analyzer's
already-propagated element specs via `jax.make_jaxpr`, zero data
movement — count FLOPs and HBM bytes moved, derive arithmetic
intensity, and classify each stage compute-bound vs bandwidth-bound
against the calibrated machine balance
(`nodes.learning.calibrate.machine_rates`, the same weights
`reconcile.drift_cost_weights` recalibrates from live spans).

The model:

  - **flops** — a per-primitive jaxpr walk (`jaxpr_counts`):
    `dot_general` 2·out·contraction, `conv_general_dilated`
    2·out·kernel·in_ch, FFT 5·n·log2 n, reductions/pool windows at
    input size, elementwise at one FLOP per output element,
    transcendentals deliberately flattened to the same (the MXU/VPU
    issue rate, not the op latency, is what the roofline prices).
    `lax.scan` bodies multiply by trip count; `while` counts one trip
    (an honest floor); `cond` takes the worst branch. Where the backend
    provides `Lowered.cost_analysis()`, `xla_cost_analysis` is the
    cross-check (tests pin 2× agreement on a GEMM stage) — the jaxpr
    walk stays the source of truth because the CPU backend's analysis
    is absent or partial for many ops.
  - **bytes** — the stage-at-a-time HBM model: under XLA's per-stage
    lowering every stage boundary round-trips through HBM, so a stage's
    traffic is its input element bytes plus its output element bytes
    (× the propagated example count). Pure data-movement primitives
    (transpose/reshape/gather/...) additionally accumulate
    ``movement_bytes`` — traffic that produces no FLOPs — which is what
    KP802 compares against compute.
  - **time** — ``stage_cost(flops, bytes) = max(flops/peak_flops,
    bytes/peak_bw)``: the roofline's time denominator, exported for the
    future unified plan optimizer (ROADMAP: ONE calibrated cost model).
  - **fitted applies** — a `_FitSlot` / `DelegatingOperator` body does
    not exist before the fit runs; it is *modeled* as a dense map
    (2·in·out FLOPs per item, ``flop_source="modeled"``) — exactly the
    y=xW family every `fusable_fit` estimator produces.

Lints (all advisory — the roofline informs, placement/precision decide):

  - **KP801** (INFO): a bandwidth-bound fan-out-free fused chain of ≥2
    stages is a Pallas megakernel candidate, priced with the boundary
    bytes the chain would stop round-tripping through HBM (each
    internal boundary is one write + one read at peak bandwidth) — the
    static selector for the ROADMAP's Pallas megakernel backend.
  - **KP802** (WARNING): a stage dominated by pure data movement —
    transpose/reshape/gather traffic at least the larger of its compute
    and its unavoidable boundary traffic — is paying for layout, not
    math (the file-level twin is jaxlint KJ013).
  - **KP803** (INFO): the whole plan re-priced in seconds; the per-stage
    ``predicted_seconds`` are embedded in trace metadata
    (``keystone.roofline``) so `analysis.reconcile` joins them against
    observed span timings (the flops-residual column of the drift
    report).
  - **KP804** (INFO): a megafused scan body whose per-trip compute is
    below the dispatch/loop overhead floor cannot amortize its trips —
    raise ``chunk_size``.
  - **KP805** (INFO): a KP801 candidate that actually LOWERS — its
    `_stage_fuse` statics match a chain-kernel family in
    `ops/chain_kernels.py` — and whose one-HBM-pass kernel pricing
    beats the XLA chain's predicted seconds; the unified planner's
    kernel axis prices the scored pair and records the decision.

Everything here is pure spec arithmetic over abstract values — no data
moves, no device allocates, no program compiles.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..workflow.graph import Graph, GraphId, NodeId, SinkId
from .diagnostics import Diagnostic, Severity
from .memory import _fmt_bytes, resolve_chunk_rows
from .propagate import _label, toposort
from .specs import (
    UNKNOWN,
    DataSpec,
    TransformerSpec,
    element_nbytes,
    is_known,
)

#: per-program dispatch / scan-trip bookkeeping floor the KP804 lint
#: amortizes against (~50 µs: the PERF.md round-4 tunnel-free dispatch
#: overhead order of magnitude; in-program scan trips are cheaper but
#: the same order once loop bookkeeping and donation checks are paid).
DISPATCH_OVERHEAD_S = 5e-5

# ------------------------------------------------------------ jaxpr walk

#: primitives that MOVE bytes but perform no arithmetic — the traffic
#: KP802 weighs against compute. `convert_element_type` belongs here:
#: a cast re-materializes every byte it touches for zero FLOPs.
_MOVEMENT_PRIMS = frozenset({
    "transpose", "reshape", "rev", "broadcast_in_dim", "squeeze",
    "expand_dims", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "pad", "gather", "scatter", "select_and_scatter_add",
    "convert_element_type", "bitcast_convert_type", "copy",
    "device_put", "split",
})

#: primitives that neither compute nor read (generators, annotations).
_FREE_PRIMS = frozenset({
    "iota", "stop_gradient", "broadcast", "create_token",
    "sharding_constraint", "constant",
})

#: reductions priced at INPUT size (every input element is touched once).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_precision", "cumsum", "cumprod", "cummax", "cummin",
    "cumlogsumexp",
})


def _aval_elems(v) -> int:
    shape = getattr(getattr(v, "aval", None), "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64))


def _aval_nbytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _eqn_cost(eqn) -> Tuple[float, float]:
    """``(flops, movement_bytes)`` of one first-order equation."""
    name = eqn.primitive.name
    out_elems = sum(_aval_elems(v) for v in eqn.outvars)
    if name in _FREE_PRIMS:
        return 0.0, 0.0
    if name in _MOVEMENT_PRIMS:
        nbytes = (sum(_aval_nbytes(v) for v in eqn.invars)
                  + sum(_aval_nbytes(v) for v in eqn.outvars))
        return 0.0, float(nbytes)
    if name == "dot_general":
        (lhs_contract, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
        contraction = int(np.prod(
            [lhs_shape[d] for d in lhs_contract], dtype=np.int64)) or 1
        return 2.0 * out_elems * contraction, 0.0
    if name == "conv_general_dilated":
        dnums = eqn.params["dimension_numbers"]
        kshape = getattr(eqn.invars[1].aval, "shape", ())
        rhs_spec = dnums.rhs_spec  # (out_ch, in_ch, *spatial)
        in_ch = kshape[rhs_spec[1]] if len(kshape) > rhs_spec[1] else 1
        spatial = int(np.prod(
            [kshape[d] for d in rhs_spec[2:]], dtype=np.int64)) or 1
        return 2.0 * out_elems * spatial * in_ch, 0.0
    if name == "fft":
        lengths = eqn.params.get("fft_lengths", ())
        n = int(np.prod(lengths, dtype=np.int64)) or 1
        in_elems = _aval_elems(eqn.invars[0]) or n
        batches = max(1, in_elems // n)
        return 5.0 * n * math.log2(max(2, n)) * batches, 0.0
    if name in _REDUCE_PRIMS:
        return float(sum(_aval_elems(v) for v in eqn.invars)), 0.0
    if name.startswith("reduce_window") or name == "select_and_scatter":
        window = eqn.params.get("window_dimensions", ())
        wsize = int(np.prod(window, dtype=np.int64)) or 1
        return float(out_elems * wsize), 0.0
    if name == "sort":
        in_elems = sum(_aval_elems(v) for v in eqn.invars)
        dim_shape = getattr(eqn.invars[0].aval, "shape", (2,))
        axis = eqn.params.get("dimension", len(dim_shape) - 1)
        n = dim_shape[axis] if dim_shape else 2
        return float(in_elems * math.log2(max(2, n))), 0.0
    if name.startswith("scatter"):
        # scatter-add and friends: one op per update element, plus the
        # operand copy counts as movement
        updates = _aval_elems(eqn.invars[-1])
        nbytes = _aval_nbytes(eqn.invars[0]) + sum(
            _aval_nbytes(v) for v in eqn.outvars)
        return float(updates), float(nbytes)
    # default: elementwise — one FLOP per output element (transcendental
    # flattening is deliberate; see module docstring)
    return float(out_elems), 0.0


def jaxpr_counts(jaxpr) -> Tuple[float, float]:
    """``(flops, movement_bytes)`` of a (Closed)Jaxpr, sub-jaxprs
    (pjit, scan × trip count, while ≥1 trip, cond worst-branch)
    included."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    flops = 0.0
    movement = 0.0
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if name == "scan":
            f, m = jaxpr_counts(eqn.params["jaxpr"])
            trips = int(eqn.params.get("length", 1) or 1)
            flops += f * trips
            movement += m * trips
            continue
        if name == "while":
            fc, mc = jaxpr_counts(eqn.params["cond_jaxpr"])
            fb, mb = jaxpr_counts(eqn.params["body_jaxpr"])
            flops += fc + fb  # one trip: an honest floor, documented
            movement += mc + mb
            continue
        if name == "cond":
            branches = eqn.params.get("branches", ())
            if branches:
                sub = [jaxpr_counts(b) for b in branches]
                flops += max(s[0] for s in sub)
                movement += max(s[1] for s in sub)
            continue
        recursed = False
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            sub = eqn.params.get(key) if eqn.params else None
            if sub is not None and hasattr(
                    getattr(sub, "jaxpr", sub), "eqns"):
                f, m = jaxpr_counts(sub)
                flops += f
                movement += m
                recursed = True
                break
        if recursed:
            continue
        f, m = _eqn_cost(eqn)
        flops += f
        movement += m
    return flops, movement


def body_counts(fn, elem) -> Optional[Tuple[float, float]]:
    """Per-item ``(flops, movement_bytes)`` of one stage body, traced
    abstractly over the propagated element spec (`jax.make_jaxpr` on a
    `ShapeDtypeStruct` pytree — zero data movement). None when the body
    is host code the tracer cannot enter."""
    if not is_known(elem):
        return None
    try:
        jx = jax.make_jaxpr(fn)(elem)
    except Exception:
        return None
    return jaxpr_counts(jx)


def xla_cost_analysis(fn, elem) -> Optional[Dict[str, Optional[float]]]:
    """Backend-reported ``{"flops", "bytes"}`` of one stage body via
    `Lowered.cost_analysis()` — the cross-check, NOT the source of
    truth: the CPU backend's analysis is absent or partial for many
    ops, so callers must treat None (or a non-positive flop count) as
    'backend cannot tell' and fall back to the jaxpr walk."""
    try:
        ca = jax.jit(fn).lower(elem).cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None or not np.isfinite(flops) or flops <= 0:
        return None
    nbytes = ca.get("bytes accessed")
    return {"flops": float(flops),
            "bytes": float(nbytes) if nbytes is not None else None}


# --------------------------------------------------------------- machine


@dataclass(frozen=True)
class Machine:
    """The roofline's two peak rates. ``balance`` (FLOP per byte) is
    the ridge point: a stage whose arithmetic intensity sits below it
    is bandwidth-bound."""

    peak_flops: float  # FLOP/s
    peak_bw: float     # HBM B/s

    @property
    def balance(self) -> float:
        return self.peak_flops / self.peak_bw


def default_machine() -> Machine:
    """Machine balance from the calibrated cost weights — the SAME
    numbers the solver cost model and every optimizer decision price
    with (`calibrate.machine_rates`: measured calibration when the
    platform matches, honest CPU-backend analytic peaks otherwise)."""
    from ..nodes.learning.calibrate import machine_rates

    peak_flops, peak_bw = machine_rates()
    return Machine(peak_flops, peak_bw)


def stage_cost(flops: Optional[float], nbytes: Optional[float],
               machine: Optional[Machine] = None) -> float:
    """``predicted_seconds = max(flops/peak_flops, bytes/peak_bw)`` —
    the roofline time model, exported for the future unified plan
    optimizer (each decision menu entry prices in these seconds)."""
    machine = machine or default_machine()
    return max(float(flops or 0.0) / machine.peak_flops,
               float(nbytes or 0.0) / machine.peak_bw)


# ------------------------------------------------------------ stage model


@dataclass
class StageRoofline:
    """One priced stage: FLOPs, stage-at-a-time HBM traffic, derived
    intensity/bound, and the predicted seconds. ``trail`` carries the
    per-internal-stage rows of a fused/megafused program body."""

    vertex: NodeId
    label: str
    flops: float
    hbm_bytes: int
    movement_bytes: float
    count: int
    flop_source: str  # "traced" | "modeled" | "mixed"
    intensity: float
    bound: str  # "compute" | "bandwidth"
    predicted_seconds: float
    trail: List[Dict[str, Any]] = field(default_factory=list)
    #: bytes of the stage's internal boundaries (fused trails only):
    #: what a Pallas megakernel would keep in VMEM
    internal_boundary_bytes: int = 0

    def as_row(self) -> Dict[str, Any]:
        return {
            "vertex": self.vertex.id,
            "label": self.label,
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "movement_bytes": self.movement_bytes,
            "count": self.count,
            "flop_source": self.flop_source,
            "intensity": self.intensity,
            "bound": self.bound,
            "predicted_seconds": self.predicted_seconds,
            "stages": list(self.trail),
        }


@dataclass
class RooflineEstimate:
    """The roofline picture of one graph: per-stage costs, the machine
    they were classified against, the plan total in seconds, and the
    KP801 Pallas-candidate chains."""

    stages: Dict[NodeId, StageRoofline] = field(default_factory=dict)
    machine: Machine = None
    plan_seconds: float = 0.0
    candidates: List[Dict[str, Any]] = field(default_factory=list)
    unknown_stages: int = 0

    def rows(self, graph: Graph) -> List[Dict[str, Any]]:
        order, _ = toposort(graph)
        return [self.stages[v].as_row() for v in order
                if isinstance(v, NodeId) and v in self.stages]

    def __repr__(self) -> str:
        return (f"RooflineEstimate({len(self.stages)} stage(s), "
                f"≈{self.plan_seconds:.3e}s predicted, "
                f"{len(self.candidates)} pallas candidate(s))")


def _fmt_rate(x: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(x) < 1000 or unit == "P":
            return f"{x:.1f}{unit}"
        x /= 1000.0
    return str(x)


def format_roofline(rows: List[Dict[str, Any]]) -> str:
    """Text table of `RooflineEstimate.rows` (the --explain-roofline
    rendering)."""
    lines = [f"{'stage':<40} {'flops':>10} {'bytes':>10} {'flop/B':>8} "
             f"{'bound':<10} {'pred s':>10}"]
    for r in rows:
        name = f"{r['label']}@{r['vertex']}"
        lines.append(
            f"{name[:40]:<40} {_fmt_rate(r['flops']):>10} "
            f"{_fmt_bytes(int(r['hbm_bytes'])):>10} "
            f"{r['intensity']:>8.2f} {r['bound']:<10} "
            f"{r['predicted_seconds']:>10.3e}")
    return "\n".join(lines)


# --------------------------------------------------------- trail walking


def _elem_count(spec: Any, nominal: int) -> int:
    if isinstance(spec, DataSpec) and spec.kind == "dataset":
        return int(spec.count) if spec.count else nominal
    return 1


def _modeled_dense_flops(in_elem, out_elem) -> Optional[float]:
    """Per-item FLOPs of a fitted apply modeled as a dense map in→out
    (2·in·out — the y = xW family every `fusable_fit` estimator
    produces). Refinement: when both sides are single-leaf 2-D arrays
    sharing a leading dim, the map is row-wise (each row independently
    projected — the PCA/whitening family) and prices 2·rows·d_in·d_out;
    the full in×out product would charge the rows against each other,
    a quadratic overprice the serving latency bound cannot afford."""
    in_leaves = jax.tree_util.tree_leaves(in_elem)
    out_leaves = jax.tree_util.tree_leaves(out_elem)
    if len(in_leaves) == 1 and len(out_leaves) == 1:
        a, b = in_leaves[0], out_leaves[0]
        if getattr(a, "ndim", 0) == 2 and getattr(b, "ndim", 0) == 2 \
                and a.shape[0] == b.shape[0]:
            return 2.0 * float(a.shape[0]) * float(a.shape[1]) \
                * float(b.shape[1])

    def elems(e) -> Optional[int]:
        total = 0
        for leaf in jax.tree_util.tree_leaves(e):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                return None
            total += int(np.prod(shape, dtype=np.int64))
        return total

    in_elems = elems(in_elem)
    out_elems = elems(out_elem)
    if in_elems is None or out_elems is None:
        return None
    return 2.0 * in_elems * out_elems


def _stage_trail(graph: Graph, vid: NodeId, op, specs: Dict[GraphId, Any]):
    """The per-internal-stage cost trail of one vertex:
    ``[(label, in_elem, out_elem, flops_per_item, movement_per_item,
    source)]``, or None when nothing can be priced.

    A `FusedChainOperator`/`MegafusedPlanOperator` walks its PEEPHOLED
    stage list (the list `_build_program` executes) with `_FitSlot`s
    modeled as dense maps; a `FusedBatchTransformer` walks its fitted
    ``stages`` the same way; a `DelegatingOperator` is one modeled
    dense map; a plain transformer with a traceable per-item body is
    one traced stage."""
    from ..nodes.util.fusion import FusedBatchTransformer
    from ..workflow.fusion_rule import FusedChainOperator, _FitSlot
    from ..workflow.operators import DelegatingOperator

    deps = graph.get_dependencies(vid)
    if not deps:
        return None

    if isinstance(op, (FusedChainOperator, FusedBatchTransformer)):
        from ..nodes.util.fusion import _peephole

        data_spec = specs.get(deps[-1])
        if not isinstance(data_spec, DataSpec) or not is_known(
                data_spec.element):
            return None
        t_specs = [specs.get(d) for d in deps[:-1]]
        elem = data_spec.element
        trail = []
        stage_list = (list(op.stage_specs)
                      if isinstance(op, FusedChainOperator)
                      else list(op.stages))
        # any unpriceable internal stage makes the WHOLE vertex
        # unpriced: a partial prefix silently recorded as the full
        # stage would undercount KP803 plan seconds, corrupt KP801
        # boundary bytes, and hand reconcile a prediction covering
        # less work than the span it joins (spurious residual)
        for s in _peephole(stage_list):
            if not is_known(elem):
                return None
            if isinstance(s, _FitSlot):
                ts = t_specs[s.index] if s.index < len(t_specs) else None
                out = (ts.apply_element(elem)
                       if isinstance(ts, TransformerSpec) else UNKNOWN)
                if not is_known(out):
                    return None
                flops = _modeled_dense_flops(elem, out)
                if flops is None:
                    return None
                trail.append((repr(s), elem, out, flops, 0.0, "modeled"))
            else:
                counts = body_counts(
                    lambda x, s=s: s.single_transform([x]), elem)
                try:
                    out = jax.eval_shape(
                        lambda x, s=s: s.single_transform([x]), elem)
                except Exception:
                    return None
                if counts is None or not is_known(out):
                    return None
                trail.append((s.label, elem, out, counts[0], counts[1],
                              "traced"))
            elem = trail[-1][2]
        return trail or None

    if isinstance(op, DelegatingOperator):
        if len(deps) < 2:
            return None
        data_spec = specs.get(deps[1])
        out_spec = specs.get(vid)
        if not isinstance(data_spec, DataSpec) \
                or not isinstance(out_spec, DataSpec) \
                or not is_known(data_spec.element) \
                or not is_known(out_spec.element):
            return None
        # the estimator may declare its encoder's honest flop order
        # (`abstract_apply_flops` — the FV family prices ~40× under
        # the generic dense map); the dense model is the fallback
        flops = None
        est_dep = deps[0]
        if isinstance(est_dep, NodeId):
            hook = getattr(graph.get_operator(est_dep),
                           "abstract_apply_flops", None)
            if hook is not None:
                try:
                    flops = hook(data_spec.element, out_spec.element)
                except Exception:
                    flops = None
        if flops is None:
            flops = _modeled_dense_flops(data_spec.element,
                                         out_spec.element)
        if flops is None:
            return None
        return [(_label(graph, vid), data_spec.element, out_spec.element,
                 float(flops), 0.0, "modeled")]

    fn = getattr(op, "single_transform", None)
    if fn is None:
        return None
    data_spec = specs.get(deps[0])
    if not isinstance(data_spec, DataSpec) or not is_known(
            data_spec.element):
        return None
    counts = body_counts(lambda x: fn([x]), data_spec.element)
    out_spec = specs.get(vid)
    out_elem = out_spec.element if isinstance(out_spec, DataSpec) else UNKNOWN
    if counts is None or not is_known(out_elem):
        return None
    return [(_label(graph, vid), data_spec.element, out_elem,
             counts[0], counts[1], "traced")]


# ------------------------------------------------------------------ pass


def roofline_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    machine: Optional[Machine] = None,
    chunk_rows: Optional[int] = None,
    only: Optional[Sequence[NodeId]] = None,
) -> Tuple[RooflineEstimate, List[Diagnostic]]:
    """Price every priceable stage of one graph on the roofline and
    emit the KP8xx lints. Pure spec arithmetic — never touches data or
    devices.

    ``only`` restricts pricing to the given vertices (the per-chain
    ledger path: jaxpr-tracing every stage of the graph to price one
    chain would be O(stages) per decision record). A restricted
    estimate skips the lints — KP801/KP803 are whole-plan statements."""
    from ..workflow.fusion_rule import MegafusedPlanOperator

    machine = machine or default_machine()
    chunk_rows = resolve_chunk_rows(chunk_rows)
    order, _ = toposort(graph)
    restrict = set(only) if only is not None else None
    est = RooflineEstimate(machine=machine)
    diags: List[Diagnostic] = []

    known_counts = [
        s.count for s in specs.values()
        if isinstance(s, DataSpec) and s.kind == "dataset" and s.count
    ]
    nominal = max(known_counts, default=1024)

    for vid in order:
        if not isinstance(vid, NodeId):
            continue
        if restrict is not None and vid not in restrict:
            continue
        op = graph.get_operator(vid)
        out_spec = specs.get(vid)
        if not isinstance(out_spec, DataSpec):
            continue  # estimators/transformer outputs: not a data stage
        trail = None
        try:
            trail = _stage_trail(graph, vid, op, specs)
        except Exception:
            trail = None
        if not trail:
            if graph.get_dependencies(vid):
                est.unknown_stages += 1
            continue
        count = _elem_count(out_spec, nominal)

        flops = 0.0
        movement = 0.0
        hbm = 0
        internal = 0
        trail_rows: List[Dict[str, Any]] = []
        sources = set()
        priced = True
        for i, (label, in_elem, out_elem, f_item, m_item, source) in \
                enumerate(trail):
            in_b = element_nbytes(in_elem)
            out_b = element_nbytes(out_elem)
            if in_b is None or out_b is None:
                priced = False
                break
            s_flops = f_item * count
            s_bytes = (in_b + out_b) * count
            s_move = m_item * count
            s_int = s_flops / s_bytes if s_bytes else 0.0
            s_bound = ("compute" if s_int >= machine.balance
                       else "bandwidth")
            trail_rows.append({
                "stage": label,
                "flops": s_flops,
                "hbm_bytes": s_bytes,
                "movement_bytes": s_move,
                "intensity": s_int,
                "bound": s_bound,
                "predicted_seconds": stage_cost(s_flops, s_bytes, machine),
                "flop_source": source,
            })
            flops += s_flops
            movement += s_move
            hbm += s_bytes
            if i < len(trail) - 1:
                internal += out_b * count
            sources.add(source)
        if not priced or not hbm:
            est.unknown_stages += 1
            continue

        intensity = flops / hbm
        bound = "compute" if intensity >= machine.balance else "bandwidth"
        seconds = stage_cost(flops, hbm, machine)
        est.stages[vid] = StageRoofline(
            vertex=vid,
            label=_label(graph, vid),
            flops=flops,
            hbm_bytes=hbm,
            movement_bytes=movement,
            count=count,
            flop_source=(sources.pop() if len(sources) == 1 else "mixed"),
            intensity=intensity,
            bound=bound,
            predicted_seconds=seconds,
            trail=trail_rows if len(trail_rows) > 1 else [],
            internal_boundary_bytes=internal,
        )

        # KP802: movement-dominated stage — pure layout traffic at least
        # the larger of its compute and its unavoidable boundary bytes
        st = est.stages[vid]
        if restrict is not None:
            continue  # restricted pricing: no lints
        if st.movement_bytes > max(st.flops, float(st.hbm_bytes)):
            diags.append(Diagnostic(
                "KP802", Severity.WARNING,
                f"data-movement-dominated stage: "
                f"{_fmt_bytes(int(st.movement_bytes))} of pure "
                f"transpose/reshape/gather traffic vs {_fmt_rate(st.flops)}"
                f" FLOPs over {_fmt_bytes(st.hbm_bytes)} of boundary "
                "bytes — the stage pays for layout, not math "
                "(see jaxlint KJ013 for the in-body pattern)",
                vertex=vid, label=st.label))

        # KP804: megafused scan body too small per trip
        if isinstance(op, MegafusedPlanOperator) and count:
            trip_cost = stage_cost(flops / count * chunk_rows,
                                   hbm / count * chunk_rows, machine)
            if trip_cost < DISPATCH_OVERHEAD_S:
                diags.append(Diagnostic(
                    "KP804", Severity.INFO,
                    f"megafused scan body predicts ≈{trip_cost:.1e}s per "
                    f"trip (chunk_rows={chunk_rows}) — below the "
                    f"≈{DISPATCH_OVERHEAD_S:.0e}s dispatch/loop overhead "
                    "floor; raise chunk_size so each trip amortizes its "
                    "bookkeeping",
                    vertex=vid, label=st.label))

    est.plan_seconds = sum(
        s.predicted_seconds for s in est.stages.values())
    if restrict is not None:
        return est, diags

    # ----------------------------------------------------------- KP801
    est.candidates = _pallas_candidates(graph, est, machine)
    for cand in est.candidates:
        head = cand["vertices"][0]
        diags.append(Diagnostic(
            "KP801", Severity.INFO,
            f"pallas-candidate: bandwidth-bound fan-out-free chain of "
            f"{cand['n_stages']} stage(s) "
            f"[{' >> '.join(cand['stages'])}]; one double-buffered "
            f"HBM→VMEM kernel stops "
            f"{_fmt_bytes(cand['boundary_bytes'])} of boundary "
            f"round-trips (≈{cand['seconds_saved']:.2e}s at "
            f"{_fmt_rate(machine.peak_bw)}B/s)",
            vertex=head, label=_label(graph, head)))
        # KP805: the candidate actually lowers, and the kernel's one
        # HBM pass beats the XLA chain's predicted seconds
        verdict = cand.get("lowerable") or {}
        if verdict.get("lowerable") \
                and cand["kernel_seconds"] < cand["chain_seconds"]:
            diags.append(Diagnostic(
                "KP805", Severity.INFO,
                f"chain-kernel-wins: lowers to ONE "
                f"{verdict['family']} Pallas kernel "
                f"(ops/chain_kernels) — predicted "
                f"≈{cand['kernel_seconds']:.2e}s vs the XLA chain's "
                f"≈{cand['chain_seconds']:.2e}s; the unified planner's "
                "kernel axis prices this pair",
                vertex=head, label=_label(graph, head)))

    if est.stages:
        diags.append(Diagnostic(
            "KP803", Severity.INFO,
            f"plan roofline: ≈{est.plan_seconds:.3e}s predicted over "
            f"{len(est.stages)} priced stage(s) (machine balance "
            f"{machine.balance:.1f} FLOP/B; peaks "
            f"{_fmt_rate(machine.peak_flops)}FLOP/s, "
            f"{_fmt_rate(machine.peak_bw)}B/s)"
            + (f"; {est.unknown_stages} stage(s) unpriced"
               if est.unknown_stages else ""),
            vertex=None, label="<plan>"))
    return est, diags


def _fusable_member(graph: Graph, vid: NodeId) -> bool:
    from ..workflow.fusion_rule import FusedChainOperator

    op = graph.get_operator(vid)
    return bool(getattr(op, "fusable", False)) \
        or isinstance(op, FusedChainOperator)


def _pallas_candidates(graph: Graph, est: RooflineEstimate,
                       machine: Machine) -> List[Dict[str, Any]]:
    """KP801 chains, two sources merged:

      - graph-level: maximal fan-out-free runs of ≥2 adjacent priced
        bandwidth-bound fusable stages (each member the sole consumer
        of its producer's data output) — what the fusion rules WILL
        collapse and a Pallas kernel could then swallow whole;
      - within one fused/megafused operator: a run of ≥2 consecutive
        bandwidth-bound trail stages — the already-fused chain whose
        internal boundaries still round-trip HBM under XLA's
        stage-at-a-time lowering.

    Each candidate is priced with the boundary bytes the kernel would
    keep in VMEM: every internal boundary is one write plus one read
    at peak bandwidth."""
    out: List[Dict[str, Any]] = []
    order, _ = toposort(graph)

    def bandwidth_bound(v) -> bool:
        s = est.stages.get(v)
        return s is not None and s.bound == "bandwidth"

    # graph-level chains
    visited: set = set()
    for vid in order:
        if not isinstance(vid, NodeId) or vid in visited:
            continue
        if not (bandwidth_bound(vid) and _fusable_member(graph, vid)):
            continue
        chain = [vid]
        cur = vid
        while True:
            users = [u for u in graph.users_of(cur)
                     if not isinstance(u, SinkId)]
            if len(users) != 1 or not isinstance(users[0], NodeId):
                break
            nxt = users[0]
            if nxt in visited or not (
                    bandwidth_bound(nxt) and _fusable_member(graph, nxt)):
                break
            chain.append(nxt)
            cur = nxt
        visited.update(chain)
        if len(chain) < 2:
            continue
        boundary = sum(_chain_boundary_bytes(est, v) for v in chain[:-1])
        chain_seconds = sum(est.stages[v].predicted_seconds for v in chain)
        cand = {
            "vertices": [v for v in chain],
            "stages": [est.stages[v].label for v in chain],
            "n_stages": len(chain),
            "boundary_bytes": int(boundary),
            "seconds_saved": 2.0 * boundary / machine.peak_bw,
            "chain_seconds": chain_seconds,
            "chain_flops": sum(est.stages[v].flops for v in chain),
            "chain_hbm_bytes": int(
                sum(est.stages[v].hbm_bytes for v in chain)),
            "stage_slice": None,
            "kind": "graph_chain",
        }
        _annotate_kernel_lowering(graph, cand, machine)
        out.append(cand)

    # fused-trail runs
    for vid, st in est.stages.items():
        if len(st.trail) < 2:
            continue
        i = 0
        while i < len(st.trail):
            if st.trail[i]["bound"] != "bandwidth":
                i += 1
                continue
            j = i
            while j < len(st.trail) and st.trail[j]["bound"] == "bandwidth":
                j += 1
            if j - i >= 2:
                # boundary between trail stages k and k+1 is stage k's
                # output: half of (in+out) is not recoverable from the
                # row, so re-derive from hbm − in: use the row's own
                # out-boundary share (hbm_bytes = (in+out)·count)
                boundary = 0
                for k in range(i, j - 1):
                    row = st.trail[k]
                    nxt = st.trail[k + 1]
                    # stage k's out bytes == stage k+1's in bytes ==
                    # (row_k.hbm + row_{k+1}.hbm − ends) /2 … simplest
                    # exact form: shared boundary = overlap of the two
                    # stage traffics
                    boundary += int(min(row["hbm_bytes"],
                                        nxt["hbm_bytes"]) // 2)
                seconds = sum(st.trail[k]["predicted_seconds"]
                              for k in range(i, j))
                cand = {
                    "vertices": [vid],
                    "stages": [st.trail[k]["stage"] for k in range(i, j)],
                    "n_stages": j - i,
                    "boundary_bytes": int(boundary),
                    "seconds_saved": 2.0 * boundary / machine.peak_bw,
                    "chain_seconds": seconds,
                    "chain_flops": sum(st.trail[k]["flops"]
                                       for k in range(i, j)),
                    "chain_hbm_bytes": int(
                        sum(st.trail[k]["hbm_bytes"] for k in range(i, j))),
                    "stage_slice": (i, j),
                    "kind": "fused_trail",
                }
                _annotate_kernel_lowering(graph, cand, machine)
                out.append(cand)
            i = j
    return out


def _candidate_stage_objects(graph: Graph, cand: Dict[str, Any]):
    """The actual stage objects a KP801 candidate's kernel would
    replace, or None when the chain has no static fuse bodies
    (`_FitSlot`s — the decomposition depends on a fit that has not
    happened). A fused_trail candidate slices the operator's PEEPHOLED
    stage list (the list `_build_program` executes, which the trail
    indices address); a graph_chain candidate concatenates its member
    stages — the list the fusion rules WILL collapse."""
    from ..nodes.util.fusion import FusedBatchTransformer, _peephole
    from ..workflow.fusion_rule import FusedChainOperator, _FitSlot

    stages: List[Any] = []
    if cand["kind"] == "fused_trail":
        op = graph.get_operator(cand["vertices"][0])
        stage_list = (list(op.stage_specs)
                      if isinstance(op, FusedChainOperator)
                      else list(op.stages))
        i, j = cand["stage_slice"]
        stages = list(_peephole(stage_list))[i:j]
    else:
        for vid in cand["vertices"]:
            op = graph.get_operator(vid)
            if isinstance(op, (FusedChainOperator, FusedBatchTransformer)):
                stages.extend(op.stage_specs
                              if isinstance(op, FusedChainOperator)
                              else op.stages)
            else:
                stages.append(op)
    if any(isinstance(s, _FitSlot) for s in stages) \
            or not all(hasattr(s, "fuse") for s in stages):
        return None
    return stages


def _annotate_kernel_lowering(graph: Graph, cand: Dict[str, Any],
                              machine: Machine) -> None:
    """Attach the chain-kernel verdict to one KP801 candidate:

    - ``lowerable``: the `ops.chain_kernels.lowerability` verdict on
      the candidate's `_stage_fuse` statics — family when it lowers,
      the blocking stages (and any NAMED suppression) when it doesn't;
    - ``kernel_seconds``: the kernel side of the planner's
      kernel-vs-XLA axis — ONE HBM pass of in+out bytes (the chain's
      traffic minus the 2× boundary round-trips the kernel keeps in
      VMEM) at the same calibrated roofline; INF when not lowerable,
      so the planner demotes cleanly instead of picking a kernel that
      cannot compile.
    """
    try:
        from ..ops.chain_kernels import lowerability, stage_statics

        stages = _candidate_stage_objects(graph, cand)
        if stages is None:
            verdict = {"lowerable": False, "family": None,
                       "reason": "fit-dependent stage: no static fuse "
                                 "body to lower"}
        else:
            verdict = lowerability(stage_statics(stages))
    except Exception as e:  # never let the verdict break the pass
        verdict = {"lowerable": False, "family": None,
                   "reason": f"fuse decomposition failed: {e}"}
    cand["lowerable"] = verdict
    if verdict.get("lowerable"):
        kernel_bytes = max(
            float(cand["chain_hbm_bytes"] - 2 * cand["boundary_bytes"]),
            0.0)
        cand["kernel_seconds"] = stage_cost(
            cand["chain_flops"], kernel_bytes, machine)
    else:
        cand["kernel_seconds"] = float("inf")


def _chain_boundary_bytes(est: RooflineEstimate, vid: NodeId) -> int:
    """The boundary a graph-chain member hands its consumer: its output
    element bytes × count — half its stage traffic minus the input
    side. Derived from the trail when present, else out = hbm − in is
    unavailable, so approximate with hbm/2 (exact for in == out)."""
    st = est.stages[vid]
    if st.trail:
        return int(st.trail[-1]["hbm_bytes"] // 2)
    return int(st.hbm_bytes // 2)


# --------------------------------------------------- optimizer plumbing


def chain_predicted_seconds(graph: Graph,
                            vertices: Sequence[NodeId]) -> Optional[float]:
    """Roofline seconds of one chain of vertices on a bound graph —
    the `predicted_seconds` a fusion/megafusion ledger record carries.
    None when nothing in the chain can be priced (unbound sources,
    host bodies). Never raises."""
    try:
        from .propagate import spec_pass

        specs, _ = spec_pass(graph, {})
        # price ONLY the chain's vertices: tracing every stage of the
        # graph per decision record would be O(stages) jaxpr walks per
        # fused chain
        est, _ = roofline_pass(graph, specs, only=list(vertices))
        vals = [est.stages[v].predicted_seconds for v in vertices
                if v in est.stages]
        return float(sum(vals)) if vals else None
    except Exception:
        return None
