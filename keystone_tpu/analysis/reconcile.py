"""Static-vs-observed memory reconciliation.

"Memory Safe Computations with XLA Compiler" (arxiv 2206.14148) builds
its case on compile-time memory estimates being *checked* against
observed peaks; our KP2xx lints (memory.py) emit the static side but
until now nothing validated them against a real run. The telemetry layer
closes the loop: when a trace is active, `GraphExecutor` embeds the
analyzer's per-node byte estimates in the trace metadata
(``keystone.static_memory``), and every node force records its observed
output bytes (``out_bytes`` span arg) plus the running live-set gauge.
This module diffs the two, producing the estimation-error table
`python -m keystone_tpu.telemetry <trace>` prints — the calibration data
for tightening KP201/KP202 budget lints.

Keys are ``"<vertex_id>:<label>"``: vertex ids are per-graph, so the
label disambiguates the common fit-graph/apply-graph id collisions; a
node forced in several executors under the same key keeps its largest
observed force (peak residency is what the static model predicts).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def node_key(vertex, label: str) -> str:
    return f"{vertex}:{label}"


def observed_node_bytes(trace: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """key → {label, vertex, bytes, forces} from ``cat="node"`` spans."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != "node":
            continue
        args = e.get("args", {})
        vertex = args.get("vertex")
        if vertex is None:
            continue
        label = e.get("name", "")
        if label.startswith("force "):
            label = label[len("force "):]
        key = node_key(vertex, label)
        rec = out.setdefault(key, {
            "label": label, "vertex": vertex, "bytes": 0.0, "forces": 0,
        })
        rec["forces"] += 1
        rec["bytes"] = max(rec["bytes"], float(args.get("out_bytes", 0.0) or 0.0))
    return out


def reconcile_trace(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Join the trace's static estimates against its observed bytes.

    Returns ``{"rows": [...], "static_peak_bytes", "observed_peak_bytes",
    "peak_rel_error", "static_per_device_peak_bytes"}`` where each row
    carries ``label``, ``vertex``, ``static_bytes``, ``observed_bytes``
    and ``rel_error`` (signed, relative to the observation: +1.0 means
    the model predicted double), plus — when the sharding tier ran — the
    propagated ``spec`` and ``static_per_device_bytes`` (one shard's
    predicted bytes; on a mesh this is what each chip's allocator sees,
    the number the KP600 budget lints against). Nodes with only one side
    known are reported with ``rel_error=None`` so coverage gaps stay
    visible instead of silently dropping."""
    ks = trace.get("keystone", {})
    static = (ks.get("static_memory") or {}).get("per_node", {})
    observed = observed_node_bytes(trace)
    rows: List[Dict[str, Any]] = []
    for key in sorted(set(static) | set(observed)):
        s = static.get(key)
        o = observed.get(key)
        static_b: Optional[float] = float(s["bytes"]) if s else None
        obs_b: Optional[float] = float(o["bytes"]) if o else None
        rel: Optional[float] = None
        if static_b is not None and obs_b:
            rel = (static_b - obs_b) / obs_b
        rows.append({
            "key": key,
            "label": (s or o)["label"],
            "vertex": (s or o).get("vertex", key.split(":", 1)[0]),
            "static_bytes": static_b,
            "observed_bytes": obs_b,
            "rel_error": rel,
            "spec": (s or {}).get("spec"),
            # the propagated boundary dtype — uint8/int32 loader stages
            # and precision-planner bf16 decisions are visible here, so
            # a dtype-blind estimate can no longer hide behind a byte
            # count that happens to match
            "dtype": (s or {}).get("dtype"),
            "static_per_device_bytes": (s or {}).get("per_device_bytes"),
        })
    # nodes with both sides first, largest observation first — the head
    # of the table is what calibration actually reads
    rows.sort(key=lambda r: (r["rel_error"] is None,
                             -(r["observed_bytes"] or 0.0)))
    static_peak = (ks.get("static_memory") or {}).get("peak_bytes")
    # per-run peak tracked on the tracer; the registry gauge is
    # cumulative across every run in the process, so it is only a
    # fallback for traces written before the per-run field existed
    observed_peak = ks.get("observed_live_peak_bytes") or (
        ks.get("metrics", {}).get("gauges", {})
        .get("executor.live_bytes", {}).get("max")
    )
    peak_rel = None
    if static_peak and observed_peak:
        peak_rel = (static_peak - observed_peak) / observed_peak
    return {
        "rows": rows,
        "static_peak_bytes": static_peak,
        "observed_peak_bytes": observed_peak,
        "peak_rel_error": peak_rel,
        "static_per_device_peak_bytes": (
            (ks.get("static_memory") or {}).get("per_device_peak_bytes")),
    }


def _fmt(n: Optional[float]) -> str:
    if n is None:
        return "—"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return str(n)


def format_reconciliation(rec: Dict[str, Any], top: int = 20) -> str:
    per_dev = any(r.get("static_per_device_bytes") is not None
                  for r in rec["rows"])
    dtyped = any(r.get("dtype") is not None for r in rec["rows"])
    lines = ["== static vs observed memory (KP2xx calibration) =="]
    head = f"{'node':<40} {'static':>10} {'observed':>10} {'err %':>8}"
    if dtyped:
        head += f" {'dtype':>9}"
    if per_dev:
        head += f" {'per-dev':>10}"
    lines.append(head)
    for r in rec["rows"][:top]:
        err = (f"{100 * r['rel_error']:+.1f}%"
               if r["rel_error"] is not None else "—")
        line = (
            f"{r['label'][:40]:<40} {_fmt(r['static_bytes']):>10} "
            f"{_fmt(r['observed_bytes']):>10} {err:>8}"
        )
        if dtyped:
            line += f" {(r.get('dtype') or '—')[:9]:>9}"
        if per_dev:
            line += f" {_fmt(r.get('static_per_device_bytes')):>10}"
        lines.append(line)
    sp, op_, pr = (rec["static_peak_bytes"], rec["observed_peak_bytes"],
                   rec["peak_rel_error"])
    if sp is not None or op_ is not None:
        err = f"{100 * pr:+.1f}%" if pr is not None else "—"
        line = (
            f"{'PEAK LIVE SET':<40} {_fmt(sp):>10} {_fmt(op_):>10} {err:>8}")
        if dtyped:
            line += f" {'—':>9}"
        if per_dev:
            line += f" {_fmt(rec.get('static_per_device_peak_bytes')):>10}"
        lines.append(line)
    return "\n".join(lines)
