"""Static-vs-observed reconciliation: memory bytes AND optimizer
decisions.

"Memory Safe Computations with XLA Compiler" (arxiv 2206.14148) builds
its case on compile-time memory estimates being *checked* against
observed peaks; our KP2xx lints (memory.py) emit the static side but
until now nothing validated them against a real run. The telemetry layer
closes the loop: when a trace is active, `GraphExecutor` embeds the
analyzer's per-node byte estimates in the trace metadata
(``keystone.static_memory``), and every node force records its observed
output bytes (``out_bytes`` span arg) plus the running live-set gauge.
This module diffs the two, producing the estimation-error table
`python -m keystone_tpu.telemetry <trace>` prints — the calibration data
for tightening KP201/KP202 budget lints.

Keys are ``"<vertex_id>:<label>"``: vertex ids are per-graph, so the
label disambiguates the common fit-graph/apply-graph id collisions; a
node forced in several executors under the same key keeps its largest
observed force (peak residency is what the static model predicts).

PR 11 widens the loop from memory bytes to the whole decision space
(`telemetry.ledger` records what the optimizer decided and predicted;
this module says what the run observably did):

  - `reconcile_decisions` joins a run's decision ledger against its
    trace — predicted vs observed programs-executed / programs-compiled
    / megafused programs / baked casts at the run level, and per
    decision the matching span forces and boundary bytes;
  - `cost_model_drift` recomputes the calibrated cost-weight residuals
    from observed span timings (seconds-per-byte over the run's node
    forces vs the `nodes.learning.cost_model` weights), the
    recalibration input the unified plan optimizer needs — and
    `drift_cost_weights` packages it as a
    `nodes.learning.calibrate.CostWeights`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def node_key(vertex, label: str) -> str:
    return f"{vertex}:{label}"


def observed_node_bytes(trace: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """key → {label, vertex, bytes, forces} from ``cat="node"`` spans."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != "node":
            continue
        args = e.get("args", {})
        vertex = args.get("vertex")
        if vertex is None:
            continue
        label = e.get("name", "")
        if label.startswith("force "):
            label = label[len("force "):]
        key = node_key(vertex, label)
        rec = out.setdefault(key, {
            "label": label, "vertex": vertex, "bytes": 0.0, "forces": 0,
        })
        rec["forces"] += 1
        rec["bytes"] = max(rec["bytes"], float(args.get("out_bytes", 0.0) or 0.0))
    return out


def reconcile_trace(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Join the trace's static estimates against its observed bytes.

    Returns ``{"rows": [...], "static_peak_bytes", "observed_peak_bytes",
    "peak_rel_error", "static_per_device_peak_bytes"}`` where each row
    carries ``label``, ``vertex``, ``static_bytes``, ``observed_bytes``
    and ``rel_error`` (signed, relative to the observation: +1.0 means
    the model predicted double), plus — when the sharding tier ran — the
    propagated ``spec`` and ``static_per_device_bytes`` (one shard's
    predicted bytes; on a mesh this is what each chip's allocator sees,
    the number the KP600 budget lints against). Nodes with only one side
    known are reported with ``rel_error=None`` so coverage gaps stay
    visible instead of silently dropping."""
    ks = trace.get("keystone", {})
    static = (ks.get("static_memory") or {}).get("per_node", {})
    observed = observed_node_bytes(trace)
    rows: List[Dict[str, Any]] = []
    for key in sorted(set(static) | set(observed)):
        s = static.get(key)
        o = observed.get(key)
        static_b: Optional[float] = float(s["bytes"]) if s else None
        obs_b: Optional[float] = float(o["bytes"]) if o else None
        rel: Optional[float] = None
        if static_b is not None and obs_b:
            rel = (static_b - obs_b) / obs_b
        rows.append({
            "key": key,
            "label": (s or o)["label"],
            "vertex": (s or o).get("vertex", key.split(":", 1)[0]),
            "static_bytes": static_b,
            "observed_bytes": obs_b,
            "rel_error": rel,
            "spec": (s or {}).get("spec"),
            # the propagated boundary dtype — uint8/int32 loader stages
            # and precision-planner bf16 decisions are visible here, so
            # a dtype-blind estimate can no longer hide behind a byte
            # count that happens to match
            "dtype": (s or {}).get("dtype"),
            "static_per_device_bytes": (s or {}).get("per_device_bytes"),
        })
    # nodes with both sides first, largest observation first — the head
    # of the table is what calibration actually reads
    rows.sort(key=lambda r: (r["rel_error"] is None,
                             -(r["observed_bytes"] or 0.0)))
    static_peak = (ks.get("static_memory") or {}).get("peak_bytes")
    # per-run peak tracked on the tracer; the registry gauge is
    # cumulative across every run in the process, so it is only a
    # fallback for traces written before the per-run field existed
    observed_peak = ks.get("observed_live_peak_bytes") or (
        ks.get("metrics", {}).get("gauges", {})
        .get("executor.live_bytes", {}).get("max")
    )
    peak_rel = None
    if static_peak and observed_peak:
        peak_rel = (static_peak - observed_peak) / observed_peak
    return {
        "rows": rows,
        "static_peak_bytes": static_peak,
        "observed_peak_bytes": observed_peak,
        "peak_rel_error": peak_rel,
        "static_per_device_peak_bytes": (
            (ks.get("static_memory") or {}).get("per_device_peak_bytes")),
    }


# ------------------------------------------------- decision reconciliation


def _node_spans_by_label(trace: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """label → {forces, out_bytes(max)} over ``cat="node"`` spans (the
    fit/apply vertex-id split collapsed — decisions key on labels)."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != "node":
            continue
        name = e.get("name", "")
        if name.startswith("force "):
            name = name[len("force "):]
        rec = out.setdefault(name, {"forces": 0, "out_bytes": 0.0})
        rec["forces"] += 1
        rec["out_bytes"] = max(
            rec["out_bytes"],
            float(e.get("args", {}).get("out_bytes", 0.0) or 0.0))
    return out


def _counter_value(trace: Dict[str, Any], name: str) -> Optional[float]:
    c = (trace.get("keystone", {}).get("metrics", {})
         .get("counters", {}).get(name))
    return float(c["value"]) if c and "value" in c else None


def reconcile_decisions(run: Dict[str, Any]) -> Dict[str, Any]:
    """Join a run's decision ledger (`telemetry.ledger.read_ledger`)
    against its trace: what was decided and predicted vs what the run
    observably did.

    Returns ``{"rows", "run_predicted", "run_observed", "residuals"}``:

      - ``rows`` — one row per decision: ``{seq, kind, labels,
        predicted, observed, residuals}``. Fusion/megafusion rows
        observe the fused program's span forces and output bytes
        (megafused programs via their ``megafused_program`` spans);
        placement rows observe the changed stages' boundary bytes and
        carry the predicted-minus-observed byte residual; precision
        rows observe their program's span bytes.
      - ``run_predicted`` / ``run_observed`` / ``residuals`` — the
        run-level predicted-vs-observed join: ``programs_executed``
        (sum of the megafusion decisions' chosen program counts — exact
        on a trace covering one apply run of a fully megafused plan,
        which is what the exactness tests pin), ``programs_compiled``
        (cold-compile upper bound vs the compile counter),
        ``megafused_programs``, ``casts_baked``, and
        ``boundary_bytes_saved`` (predicted only — the savings the
        placement/precision decisions priced).

    Registry counters in a trace are process-cumulative: reset the
    registry (or use a fresh process) when a run-exact join is needed —
    the bench child processes and the lint smoke both do."""
    from ..telemetry.ledger import decision_key

    trace = run.get("trace") or {}
    decisions = run.get("decisions") or []
    by_label = _node_spans_by_label(trace)
    mega_spans = [
        e for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("name") == "megafused_program"
    ]
    kernel_spans = [
        e for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("name") == "chain_kernel"
    ]
    request_spans = [
        e for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("cat") == "request"
    ]

    unique: Dict = {}
    for d in decisions:
        unique.setdefault(decision_key(d), d)

    rows: List[Dict[str, Any]] = []
    for d in decisions:
        pred = d.get("predicted") or {}
        observed: Dict[str, Any] = {}
        residuals: Dict[str, Any] = {}
        labels = d.get("labels") or []
        kind = d.get("kind")
        if kind == "megafusion":
            n = len(mega_spans)
            n_mega_decisions = sum(
                1 for k in unique if k[0] == "megafusion")
            observed["programs_executed"] = n
            if n and n_mega_decisions == 1 \
                    and "programs_per_apply" in pred:
                # exact only when the trace covers one apply run of the
                # one megafused program — the pinned-test shape; a
                # longer trace shows the positive residual honestly
                residuals["programs_per_apply"] = (
                    pred["programs_per_apply"] - n)
            trips = sum(
                float(e.get("args", {}).get("scan_trips", 0) or 0)
                for e in mega_spans)
            if trips:
                observed["scan_trips"] = int(trips)
        elif kind == "fusion":
            # the fused program's span label embeds its member labels
            hits = [v for lbl, v in by_label.items()
                    if labels and labels[0] in lbl]
            if hits:
                observed["forces"] = sum(h["forces"] for h in hits)
                observed["out_bytes"] = max(h["out_bytes"] for h in hits)
        elif kind == "placement":
            total = 0.0
            found = False
            for lbl in labels:
                for span_lbl, v in by_label.items():
                    if lbl and lbl in span_lbl:
                        total += v["out_bytes"]
                        found = True
                        break
            if found:
                observed["boundary_bytes"] = total
                if "boundary_bytes" in pred:
                    residuals["boundary_bytes"] = (
                        float(pred["boundary_bytes"]) - total)
        elif kind == "precision":
            hits = [v for lbl, v in by_label.items()
                    if labels and labels[0] in lbl]
            if hits:
                observed["out_bytes"] = max(h["out_bytes"] for h in hits)
        elif kind == "kernel":
            # the chain-kernel decision observes its own span: one
            # `chain_kernel` interval per kernel-bearing dispatch, with
            # the planner's predicted seconds riding as a span arg
            hits = []
            for e in kernel_spans:
                sl = str(e.get("args", {}).get("label", ""))
                if any(lbl and (lbl in sl or sl in lbl)
                       for lbl in labels):
                    hits.append(e)
            if hits:
                observed["kernel_dispatches"] = len(hits)
                obs_sec = max(float(e.get("dur", 0.0) or 0.0) / 1e6
                              for e in hits)
                if obs_sec:
                    observed["kernel_seconds"] = obs_sec
                    pred_k = sum(
                        float(k.get("kernel_seconds") or 0.0)
                        for k in ((d.get("chosen") or {})
                                  .get("kernels") or []))
                    if pred_k:
                        residuals["kernel_seconds"] = pred_k - obs_sec
        elif kind == "spill":
            # the spill decision observes the windowed reload machinery
            # it priced: `spill_window` spans (one per host→device
            # window trip), the spill byte counters, and the measured
            # reload-stall histogram — residual is the planner's
            # predicted reload seconds minus the observed stall total
            spill_spans = [
                e for e in trace.get("traceEvents", [])
                if e.get("ph") == "X" and e.get("name") == "spill_window"
            ]
            if spill_spans:
                observed["window_trips"] = len(spill_spans)
            for metric, cname in (("bytes_out", "spill.bytes_out"),
                                  ("bytes_in", "spill.bytes_in")):
                v = _counter_value(trace, cname)
                if v is not None:
                    observed[metric] = v
            hist = (trace.get("keystone", {}).get("metrics", {})
                    .get("histograms", {}).get("spill.reload_stall_s"))
            if hist and hist.get("count"):
                observed["reload_stall_s"] = float(hist["total"])
                if "reload_seconds" in pred and pred["reload_seconds"]:
                    residuals["reload_seconds"] = (
                        float(pred["reload_seconds"])
                        - float(hist["total"]))
        elif kind == "conformance":
            # the watchdog's breach record joins against the live
            # request spans at the SAME padded shape: observed is the
            # worst request the trace holds for that shape, residual is
            # certified bound minus observed (negative == breach held
            # up in the artifact, not only in the counter)
            chosen = d.get("chosen") or {}
            shape = chosen.get("chunk_shape")
            hits = [
                e for e in request_spans
                if shape is None
                or e.get("args", {}).get("chunk_shape") == shape
            ]
            if hits:
                observed["request_spans"] = len(hits)
                obs_sec = max(
                    float(e.get("dur", 0.0) or 0.0) / 1e6 for e in hits)
                observed["observed_seconds"] = obs_sec
                if "bound_seconds" in pred and pred["bound_seconds"]:
                    residuals["bound_seconds"] = (
                        float(pred["bound_seconds"]) - obs_sec)
            elif "observed_seconds" in chosen:
                # dump window may have rotated past the request span:
                # the record itself still carries the observation
                observed["observed_seconds"] = chosen["observed_seconds"]
                if "bound_seconds" in pred and pred["bound_seconds"]:
                    residuals["bound_seconds"] = (
                        float(pred["bound_seconds"])
                        - float(chosen["observed_seconds"]))
        rows.append({
            "seq": d.get("seq"),
            "kind": kind,
            "labels": labels,
            "predicted": pred,
            "observed": observed,
            "residuals": residuals,
        })

    run_predicted: Dict[str, Any] = {}
    mega_unique = [d for k, d in unique.items() if k[0] == "megafusion"]
    if mega_unique:
        run_predicted["programs_executed"] = sum(
            int((d.get("chosen") or {}).get("programs", 1))
            for d in mega_unique)
        run_predicted["megafused_programs"] = len(mega_unique)
    compile_max = sum(
        int((d.get("predicted") or {}).get("cold_compiles_max", 0))
        for k, d in unique.items() if k[0] in ("fusion", "megafusion"))
    if compile_max:
        run_predicted["programs_compiled_max"] = compile_max
    casts = sum(
        int((d.get("predicted") or {}).get("casts_baked", 0))
        for k, d in unique.items() if k[0] == "precision")
    if any(k[0] == "precision" for k in unique):
        run_predicted["casts_baked"] = casts
    saved = sum(
        int((d.get("predicted") or {}).get("boundary_bytes_saved", 0))
        + int((d.get("predicted") or {}).get("policy_bytes_saved", 0))
        for d in unique.values())
    if saved:
        run_predicted["boundary_bytes_saved"] = saved

    run_observed: Dict[str, Any] = {}
    for metric, counter_name in (
            ("programs_executed", "dispatch.programs_executed"),
            ("programs_compiled", "dispatch.programs_compiled"),
            ("megafused_programs", "megafusion.programs"),
            ("casts_baked", "precision.casts_baked")):
        v = _counter_value(trace, counter_name)
        if v is not None:
            run_observed[metric] = v

    residuals: Dict[str, Any] = {}
    for metric in set(run_predicted) & set(run_observed):
        residuals[metric] = run_predicted[metric] - run_observed[metric]
    if "programs_compiled_max" in run_predicted \
            and "programs_compiled" in run_observed:
        residuals["programs_compiled"] = (
            run_predicted["programs_compiled_max"]
            - run_observed["programs_compiled"])

    return {
        "rows": rows,
        "run_predicted": run_predicted,
        "run_observed": run_observed,
        "residuals": residuals,
    }


def format_decision_reconciliation(rec: Dict[str, Any]) -> str:
    lines = ["== decisions: predicted vs observed (run level) =="]
    keys = sorted(set(rec["run_predicted"]) | set(rec["run_observed"]))
    if not keys:
        lines.append("(no run-level quantities on both sides)")
    for k in keys:
        p = rec["run_predicted"].get(k)
        o = rec["run_observed"].get(k)
        r = rec["residuals"].get(k)
        lines.append(
            f"{k:<24} predicted={'—' if p is None else p:>12} "
            f"observed={'—' if o is None else o:>12} "
            f"residual={'—' if r is None else r}")
    return "\n".join(lines)


# ------------------------------------------------- roofline reconciliation


def observed_node_seconds(trace: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """key → {label, vertex, seconds(max over forces), forces} from
    ``cat="node"`` spans — the observed side of the roofline's time
    model. The roofline predicts ONE dataset pass per stage, and a
    fit+apply run forces the same vertex:label more than once, so
    seconds aggregate with **max** (the `observed_node_bytes`
    precedent) — summing would inflate the residual and the implied
    ``cpu_weight`` by the force count."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != "node":
            continue
        args = e.get("args", {})
        vertex = args.get("vertex")
        if vertex is None:
            continue
        label = e.get("name", "")
        if label.startswith("force "):
            label = label[len("force "):]
        key = node_key(vertex, label)
        rec = out.setdefault(key, {
            "label": label, "vertex": vertex, "seconds": 0.0, "forces": 0,
        })
        rec["forces"] += 1
        rec["seconds"] = max(rec["seconds"],
                             float(args.get("seconds", 0.0) or 0.0))
    return out


def reconcile_roofline(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Join the trace's embedded roofline predictions
    (``keystone.roofline`` — per-stage flops / bytes / predicted
    seconds, the KP803 metadata the executor records) against the
    observed per-node span seconds.

    Returns ``{"rows", "kernels", "predicted_seconds",
    "observed_seconds", "flops_residual_seconds", "stages_joined",
    "machine"}`` — ``kernels`` joins every ``chain_kernel`` span's
    planner-predicted seconds against its observed wall duration (the
    kernel-axis side of the drift report). Each stage
    row carries ``predicted_seconds``, ``observed_seconds``,
    ``residual`` (predicted − observed; positive means the model
    promised more time than the run took) and the static ``flops`` /
    ``bound``. Rows with only one side known are kept with
    ``residual=None`` so coverage gaps stay visible; a trace with no
    roofline metadata (or no spans) degrades to empty rows instead of
    raising — the --ledger drift report must render on partial
    artifacts."""
    ks = trace.get("keystone", {})
    roof = ks.get("roofline") or {}
    static = roof.get("per_node", {}) or {}
    observed = observed_node_seconds(trace)
    rows: List[Dict[str, Any]] = []
    pred_total = 0.0
    obs_total = 0.0
    joined = 0
    for key in sorted(set(static) | set(observed)):
        s = static.get(key)
        o = observed.get(key)
        pred: Optional[float] = (
            float(s["predicted_seconds"]) if s else None)
        obs: Optional[float] = (
            float(o["seconds"]) if o and o["seconds"] else None)
        residual = None
        if pred is not None and obs is not None:
            residual = pred - obs
            pred_total += pred
            obs_total += obs
            joined += 1
        rows.append({
            "key": key,
            "label": (s or o)["label"],
            "vertex": (s or o).get("vertex", key.split(":", 1)[0]),
            "flops": (s or {}).get("flops"),
            "bound": (s or {}).get("bound"),
            "predicted_seconds": pred,
            "observed_seconds": obs,
            "residual": residual,
        })
    rows.sort(key=lambda r: (r["residual"] is None,
                             -(r["observed_seconds"] or 0.0)))
    # chain-kernel spans carry their OWN predicted seconds (the unified
    # planner's kernel-axis price rides `predicted_seconds` on every
    # `chain_kernel` interval), so the kernel join needs no static
    # metadata: predicted vs the span's observed wall seconds, per
    # kernel-bearing dispatch
    kernel_rows: List[Dict[str, Any]] = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("name") != "chain_kernel":
            continue
        args = e.get("args", {})
        pred = args.get("predicted_seconds")
        obs = float(e.get("dur", 0.0) or 0.0) / 1e6
        kernel_rows.append({
            "label": args.get("label"),
            "family": args.get("family"),
            "predicted_seconds": (float(pred) if pred is not None
                                  else None),
            "observed_seconds": obs if obs else None,
            "residual": (float(pred) - obs
                         if pred is not None and obs else None),
            # the KP10xx static verifier's verdict for this lowering
            # (True proved / False refuted / None unverifiable), carried
            # on the span by the dispatcher
            "statically_verified": args.get("statically_verified"),
        })
    return {
        "rows": rows,
        "kernels": kernel_rows,
        "predicted_seconds": pred_total,
        "observed_seconds": obs_total,
        "flops_residual_seconds": (
            pred_total - obs_total if joined else None),
        "stages_joined": joined,
        "machine": {k: roof.get(k) for k in ("peak_flops", "peak_bw")
                    if roof.get(k) is not None} or None,
    }


def reconcile_serving(trace: Dict[str, Any],
                      observed: Optional[Any] = None) -> Dict[str, Any]:
    """Join the trace's embedded serving certificate
    (``keystone.serving`` — the per-ladder-shape certified latency
    bounds the KP9xx certifier issued, which the executor records when
    an envelope is armed) against observed per-shape serving
    percentiles from `scripts/serving_latency.py`.

    ``observed`` is the artifact's per-shape record list
    (``[{"batch", "chunk_shape", "p50_ms", ...}]``); when omitted it is
    read from ``keystone.serving_observed`` — the script embeds its
    measurements into the same trace it wrote, so one artifact carries
    both sides of the join. Each observed shape joins the certificate
    row whose ladder shape covers it (``chunk_shape`` when recorded,
    else the batch itself), and the certificate's claim is directional:
    the certified bound is an UPPER bound, so ``holds`` means
    ``predicted_bound ≥ observed p50``. The residual (bound − p50,
    always ≥ 0 while the claim holds) is the `BOUND_HEADROOM`
    recalibration feed: a persistently large residual means the
    headroom can shrink. Degrades to empty rows on partial artifacts —
    the drift report must render regardless."""
    ks = trace.get("keystone", {})
    cert = ks.get("serving") or {}
    if observed is None:
        observed = ks.get("serving_observed") or []
    by_shape: Dict[int, Dict[str, Any]] = {
        int(s["batch"]): s for s in cert.get("shapes", [])
        if s.get("batch") is not None
    }
    rows: List[Dict[str, Any]] = []
    joined = 0
    violations = 0
    residual_total = 0.0
    for o in observed:
        batch = o.get("batch")
        if batch is None:
            continue
        shape = int(o.get("chunk_shape") or batch)
        p50 = o.get("p50_ms")
        p50_s = float(p50) / 1e3 if p50 is not None else None
        c = by_shape.get(shape)
        bound = float(c["predicted_seconds"]) if c else None
        residual = holds = None
        if bound is not None and p50_s is not None:
            residual = bound - p50_s
            holds = bound >= p50_s
            joined += 1
            violations += 0 if holds else 1
            residual_total += residual
        rows.append({
            "batch": int(batch),
            "chunk_shape": shape,
            "predicted_bound_seconds": bound,
            "machine_seconds": (float(c["machine_seconds"])
                                if c and "machine_seconds" in c else None),
            "observed_p50_seconds": p50_s,
            "observed_p99_seconds": (float(o["p99_ms"]) / 1e3
                                     if o.get("p99_ms") is not None
                                     else None),
            "residual_seconds": residual,
            "holds": holds,
        })
    rows.sort(key=lambda r: (r["holds"] is None, r["batch"]))
    return {
        "rows": rows,
        "shapes_joined": joined,
        "violations": violations,
        "bound_holds": (violations == 0) if joined else None,
        "residual_seconds": residual_total if joined else None,
        "slo_seconds": cert.get("slo_seconds"),
        "certified": cert.get("certified"),
        "dominating_stage": cert.get("dominating_stage"),
    }


def format_serving_reconciliation(rec: Dict[str, Any]) -> str:
    """Text table of one serving join (the --serving rendering)."""
    lines = ["== serving reconciliation (certified bound vs observed "
             "percentiles) =="]
    if not rec["rows"]:
        lines.append("(no joined shapes — trace carries no "
                     "keystone.serving certificate or no observed "
                     "percentiles)")
        return "\n".join(lines)
    lines.append(f"{'batch':>6} {'shape':>6} {'bound':>12} {'p50':>10} "
                 f"{'residual':>10} verdict")
    for r in rec["rows"]:
        bound = (f"{r['predicted_bound_seconds'] * 1e3:9.2f} ms"
                 if r["predicted_bound_seconds"] is not None else "—")
        p50 = (f"{r['observed_p50_seconds'] * 1e3:7.2f} ms"
               if r["observed_p50_seconds"] is not None else "—")
        res = (f"{r['residual_seconds'] * 1e3:+7.2f} ms"
               if r["residual_seconds"] is not None else "—")
        verdict = ("holds" if r["holds"]
                   else "VIOLATED" if r["holds"] is not None else "unjoined")
        lines.append(f"{r['batch']:>6} {r['chunk_shape']:>6} {bound:>12} "
                     f"{p50:>10} {res:>10} {verdict}")
    verdict = ("bound holds over every joined shape" if rec["bound_holds"]
               else f"{rec['violations']} shape(s) VIOLATE the bound"
               if rec["bound_holds"] is not None else "nothing joined")
    lines.append(f"({rec['shapes_joined']} shape(s) joined — {verdict})")
    return "\n".join(lines)


# --------------------------------------------------- cost-model drift


def cost_model_drift(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Recompute the cost-weight residuals from observed span timings —
    the trace-recalibration input the unified plan optimizer's priced
    menus need (ROADMAP). Every optimizer decision since PR 8 is priced
    by ``cost = cpu_weight·flops + mem_weight·bytes +
    network_weight·collective_bytes``; a run's node spans carry
    ``seconds`` and ``out_bytes``, so the observed seconds-per-byte over
    the run bounds the effective ``mem_weight`` (HBM + transport) the
    plan actually experienced. When the trace additionally carries the
    static roofline metadata (``keystone.roofline``, PR 12), the
    per-stage FLOP counts join the same spans and imply a
    ``cpu_weight`` bound too — plus a flops-residual section
    (`reconcile_roofline`: predicted vs observed stage seconds under
    the time model). Collective bytes remain unobserved, so
    ``network_weight`` reports unmeasured and keeps its current value
    in the suggestion — a MULTICHIP run's collective spans can widen
    this later.

    Returns ``{"rows": [{weight, current, implied, ratio}],
    "suggested": {cpu_weight, mem_weight, network_weight},
    "observed_bytes", "observed_seconds", "observed_flops", "spans",
    "roofline"}`` — ``roofline`` is the flops-residual join (None when
    the trace carries no roofline metadata or no spans matched)."""
    from ..nodes.learning import cost_model

    total_b = 0.0
    total_s = 0.0
    n = 0
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("cat") != "node":
            continue
        args = e.get("args", {})
        b = float(args.get("out_bytes", 0.0) or 0.0)
        s = float(args.get("seconds", 0.0) or 0.0)
        if b > 0 and s > 0:
            total_b += b
            total_s += s
            n += 1
    implied_mem = (total_s / total_b) if total_b else None

    # flops side: the embedded roofline joins static per-stage FLOPs
    # against the same spans' seconds — the compute half of the
    # recalibration feed
    roof = reconcile_roofline(trace)
    total_f = 0.0
    flop_s = 0.0
    for r in roof["rows"]:
        if r["residual"] is not None and r["flops"]:
            total_f += float(r["flops"])
            flop_s += float(r["observed_seconds"])
    implied_cpu = (flop_s / total_f) if total_f else None
    roofline_section = None
    if roof["stages_joined"]:
        roofline_section = {
            "stages_joined": roof["stages_joined"],
            "predicted_seconds": roof["predicted_seconds"],
            "observed_seconds": roof["observed_seconds"],
            "flops_residual_seconds": roof["flops_residual_seconds"],
        }

    current = {
        "cpu_weight": float(cost_model.CPU_WEIGHT),
        "mem_weight": float(cost_model.MEM_WEIGHT),
        "network_weight": float(cost_model.NETWORK_WEIGHT),
    }
    rows = []
    for name, implied in (("cpu_weight", implied_cpu),
                          ("mem_weight", implied_mem),
                          ("network_weight", None)):
        rows.append({
            "weight": name,
            "current": current[name],
            "implied": implied,
            "ratio": (implied / current[name]) if implied else None,
        })
    suggested = dict(current)
    if implied_mem:
        suggested["mem_weight"] = implied_mem
    if implied_cpu:
        suggested["cpu_weight"] = implied_cpu
    return {
        "rows": rows,
        "suggested": suggested,
        "observed_bytes": total_b,
        "observed_seconds": total_s,
        "observed_flops": total_f,
        "spans": n,
        "roofline": roofline_section,
    }


def drift_cost_weights(trace: Dict[str, Any]):
    """The drift report as a `nodes.learning.calibrate.CostWeights` —
    the exact type `calibrate.calibrate_cost_weights` returns, so the
    recalibration feed is drop-in for every `CostModel.cost(...)`
    consumer."""
    from ..nodes.learning.calibrate import CostWeights

    s = cost_model_drift(trace)["suggested"]
    return CostWeights(s["cpu_weight"], s["mem_weight"],
                       s["network_weight"])


def format_drift(drift: Dict[str, Any]) -> str:
    lines = ["== cost-model drift (observed span timings vs calibrated "
             "weights) =="]
    for r in drift["rows"]:
        implied = (f"{r['implied']:.3e}" if r["implied"] else "unmeasured")
        ratio = (f"×{r['ratio']:.2f}" if r["ratio"] else "—")
        lines.append(
            f"{r['weight']:<16} current={r['current']:.3e} "
            f"implied={implied:>12} drift={ratio}")
    lines.append(
        f"({drift['spans']} span(s), {_fmt(drift['observed_bytes'])} over "
        f"{drift['observed_seconds']:.4f}s)")
    roof = drift.get("roofline")
    if roof is not None:
        # the flops-residual column: the roofline time model's promise
        # vs what the joined spans actually took
        lines.append(
            f"{'flops residual':<16} "
            f"predicted={roof['predicted_seconds']:.4f}s "
            f"observed={roof['observed_seconds']:.4f}s "
            f"Δ={roof['flops_residual_seconds']:+.4f}s "
            f"({roof['stages_joined']} stage(s) joined)")
    return "\n".join(lines)


def _fmt(n: Optional[float]) -> str:
    if n is None:
        return "—"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return str(n)


def format_reconciliation(rec: Dict[str, Any], top: int = 20) -> str:
    per_dev = any(r.get("static_per_device_bytes") is not None
                  for r in rec["rows"])
    dtyped = any(r.get("dtype") is not None for r in rec["rows"])
    lines = ["== static vs observed memory (KP2xx calibration) =="]
    head = f"{'node':<40} {'static':>10} {'observed':>10} {'err %':>8}"
    if dtyped:
        head += f" {'dtype':>9}"
    if per_dev:
        head += f" {'per-dev':>10}"
    lines.append(head)
    for r in rec["rows"][:top]:
        err = (f"{100 * r['rel_error']:+.1f}%"
               if r["rel_error"] is not None else "—")
        line = (
            f"{r['label'][:40]:<40} {_fmt(r['static_bytes']):>10} "
            f"{_fmt(r['observed_bytes']):>10} {err:>8}"
        )
        if dtyped:
            line += f" {(r.get('dtype') or '—')[:9]:>9}"
        if per_dev:
            line += f" {_fmt(r.get('static_per_device_bytes')):>10}"
        lines.append(line)
    sp, op_, pr = (rec["static_peak_bytes"], rec["observed_peak_bytes"],
                   rec["peak_rel_error"])
    if sp is not None or op_ is not None:
        err = f"{100 * pr:+.1f}%" if pr is not None else "—"
        line = (
            f"{'PEAK LIVE SET':<40} {_fmt(sp):>10} {_fmt(op_):>10} {err:>8}")
        if dtyped:
            line += f" {'—':>9}"
        if per_dev:
            line += f" {_fmt(rec.get('static_per_device_peak_bytes')):>10}"
        lines.append(line)
    return "\n".join(lines)
