"""Unified plan optimizer: ONE decision IR over the whole choice space.

PRs 4–10 built five *sequential greedy* passes — fuse, megafuse, place
(`analysis.planner`), retype (`analysis.precision`) — while
``chunk_size``, streaming-vs-materialization, and autocache placement
stayed manual knobs outside the optimizer entirely. Each pass wins its
axis locally and can still lose jointly: a bf16 policy halves the very
boundary bytes whose all-to-all price drove the placement choice, and a
chunk size that fixes KP804 underfilled scans can bust the KP600
per-device budget. This module is the ROADMAP's refactor-that-unlocks:
KeystoneML's cost-based whole-pipeline optimizer thesis (arXiv
1610.09451) fused with the memory-safe-XLA discipline of treating the
HBM budget as a hard constraint, not an afterthought (arXiv 2206.14148).

The IR: per choosable stage boundary a product menu

    {placement family (PR 9's MENU, legality = the `leaf_sharding`
     divisibility contract)
     × storage dtype (PR 10's policies, legality = `precision_tolerance`
       flowed through passthrough stages; inside fused programs the
       per-trail `plan_stage_precision` decision)
     × cache point (legality = `AutoCacheRule._candidates`: demanded
       more than once, not already cached)}

plus one plan-level axis, the chunk size from the PR-5 pow-2 ladder,
plus a per-fused-program kernel axis: lower a KP801 candidate's stage
sub-trail to ONE double-buffered Pallas chain megakernel
(`ops.chain_kernels`) or keep XLA's stage-at-a-time lowering. The
kernel side prices ONE HBM pass of in+out bytes (the chain's traffic
minus its 2× boundary round-trips); non-lowerable statics or a
VMEM-infeasible block geometry price INF and demote cleanly — a scored
demotion record, never a compile crash.

Every assignment is priced by ONE calibrated time model, in seconds:

  - per stage, ``roofline.stage_cost(flops, policy_nbytes)`` — the
    KP8xx jaxpr-walk FLOPs against the boundary bytes the chosen dtypes
    actually move (`precision.policy_nbytes`), on the calibrated
    machine (`calibrate.machine_rates`, or the
    `reconcile.drift_cost_weights`-recalibrated peaks when a trace
    artifact is supplied);
  - plus ``collective_cost`` seconds at placement-family flips, unmet
    `abstract_sharding` demands, and host gathers — literally the same
    `CollectiveCost` objects the KP601/KP603 lints and the byte planner
    read (`planner.transition_cost` / `demand_cost` / `gather_cost`);
  - plus a per-dispatch floor (`roofline.DISPATCH_OVERHEAD_S`) per
    chunk trip, which is what makes the chunk axis a real decision
    (KP804's underfilled-scan economics, priced instead of linted);
  - plus the cast seconds every storage flip costs
    (`precision.CAST_PENALTY_BYTES` over the machine's bandwidth);
  - each stage weighted by its recomputation count under the chosen
    cache points (`autocache.get_runs` — the reference's lazy
    re-execution semantics, the same model `AutoCacheRule` prices),
    which is what makes cache placement a priced decision instead of a
    profile-then-guess pass.

The KP600 per-device budget is a hard constraint: a family whose
per-device residency, a chunk whose in-flight rows, or a cache set
whose pinned bytes bust it price INFEASIBLE and are pruned — never
linted after the fact.

Solver: the existing chain-DP + frontier-merge shape generalized to the
product menu (states are (family, policy) pairs along fan-out-free
chains, greedy freeze at fan-in), then bounded local descent ACROSS
decision kinds — family/policy sweeps, program-trail toggles, the chunk
ladder, greedy cache additions — every candidate re-scored by the one
shared scorer. The sequential PR-13 composition (plan_sharding's
placement, the per-program precision trails, the config chunk, no
caches) is always scored as a candidate by the SAME function, so the
joint plan can never lose to it: ``improved`` is a strict win or the
plan IS the sequential assignment and nothing deviates.

Everything here is pure spec arithmetic — no data moves, no device
allocates. Enforcement lives in `workflow.optimizer.UnifiedPlannerRule`
(placement/precision tags, the `workflow.env.set_planned_chunk_size`
chunk override, `CacheMarker` insertion).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from ..parallel import mesh as meshlib
from ..workflow.graph import Graph, GraphId, NodeId, SinkId
from .planner import (
    FAMILY_REPLICATED,
    ShardingPlan,
    _CostModel,
    demand_cost,
    family_shards,
    gather_cost,
    plan_sharding,
    transition_cost,
)
from .precision import (
    CAST_PENALTY_BYTES,
    POLICY_F32,
    _STORAGE,
    _PrecisionModel,
    plan_precision,
    plan_stage_precision,
    policy_nbytes,
)
from .sharding import DEFAULT_REPLICATED_THRESHOLD
from .propagate import _label, toposort
from .roofline import (
    DISPATCH_OVERHEAD_S,
    Machine,
    default_machine,
    roofline_pass,
    stage_cost,
)
from .specs import DataSpec

_INF = float("inf")

#: the PR-5 pow-2 chunk ladder the chunk axis chooses from (the same
#: shape family `utils.batching._pad_target` pads into, so every chosen
#: chunk is a shape the pad-stable dispatcher already compiles).
CHUNK_LADDER: Tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048, 4096)


def machine_from_weights(weights) -> Machine:
    """The roofline `Machine` a `calibrate.CostWeights` implies — the
    recalibration seam: `reconcile.drift_cost_weights(trace)` feeds the
    trace-implied peaks straight into the unified scorer."""
    return Machine(float(weights.peak_flops), float(weights.peak_bw))


# ------------------------------------------------------------ assignment


@dataclass(frozen=True)
class Assignment:
    """One point in the joint decision space. ``families`` and
    ``policies`` are per-vertex; ``trails`` holds the per-fused-program
    bf16-trail on/off decisions; ``chunk`` is the plan-level chunk
    size; ``caches`` the chosen cache points."""

    families: Tuple[Tuple[Any, str], ...] = ()
    policies: Tuple[Tuple[Any, str], ...] = ()
    trails: Tuple[Tuple[Any, bool], ...] = ()
    chunk: int = 256
    caches: FrozenSet = frozenset()
    #: per-fused-program chain-megakernel on/off (the kernel-vs-XLA
    #: axis over the KP801 fused-trail candidates)
    kernels: Tuple[Tuple[Any, bool], ...] = ()
    #: cache points placed on the HOST (⊆ caches): the spill tier.
    #: A spilled cache pins window-residency on device instead of its
    #: full bytes and pays reload seconds (bytes over the calibrated
    #: host↔device bandwidth + the dispatch floor per window trip) —
    #: how a tight KP600 budget becomes satisfiable instead of pruning
    #: every cache entry to INF.
    spills: FrozenSet = frozenset()

    def fam(self) -> Dict[Any, str]:
        return dict(self.families)

    def pol(self) -> Dict[Any, str]:
        return dict(self.policies)

    def trl(self) -> Dict[Any, bool]:
        return dict(self.trails)

    def krn(self) -> Dict[Any, bool]:
        return dict(self.kernels)


def _assign(families: Dict, policies: Dict, trails: Dict, chunk: int,
            caches, kernels: Optional[Dict] = None,
            spills=frozenset()) -> Assignment:
    return Assignment(
        families=tuple(sorted(families.items(),
                              key=lambda kv: getattr(kv[0], "id", -1))),
        policies=tuple(sorted(policies.items(),
                              key=lambda kv: getattr(kv[0], "id", -1))),
        trails=tuple(sorted(trails.items(),
                            key=lambda kv: getattr(kv[0], "id", -1))),
        chunk=int(chunk),
        caches=frozenset(caches),
        kernels=tuple(sorted((kernels or {}).items(),
                             key=lambda kv: getattr(kv[0], "id", -1))),
        spills=frozenset(spills),
    )


# ------------------------------------------------------------- the model


class _UnifiedModel:
    """The priced joint view of one graph: the placement menus and
    collective formulas of `analysis.planner`, the dtype menus and byte
    model of `analysis.precision`, the roofline's per-stage FLOPs, the
    autocache candidate set — and ONE scorer that prices any complete
    assignment in seconds. The sequential composition and the joint
    optimum are scored by literally the same function."""

    def __init__(self, graph: Graph, specs: Dict[GraphId, Any], mesh,
                 hbm_budget_bytes: Optional[int], chunk_default: int,
                 machine: Machine,
                 include_boundary_policies: bool = True,
                 precision_floor_bytes: int = 0,
                 allow_spill: bool = False):
        from ..workflow.autocache import AutoCacheRule, get_runs

        self.graph = graph
        self.specs = specs
        self.mesh = mesh
        self.budget = hbm_budget_bytes
        self.chunk_default = int(chunk_default)
        self.machine = machine
        self.precision_floor_bytes = int(precision_floor_bytes)
        #: spill axis gate (KEYSTONE_OOC_SPILL): when False no spill
        #: toggle is ever scored, Assignment.spills stays empty, and the
        #: scorer's spill branches are dead — bit-for-bit the PR-19 plan
        self.allow_spill = bool(allow_spill)
        self._host_bw: Optional[float] = None
        self._get_runs = get_runs
        order, _ = toposort(graph)
        self.order = [v for v in order if not isinstance(v, SinkId)]

        # --- compute axis: the roofline's chunk-independent FLOPs and
        # reference bytes per stage (the time model's numerators)
        self.roof, _ = roofline_pass(graph, specs, machine=machine,
                                     chunk_rows=chunk_default)
        self.unpriced_stages = self.roof.unknown_stages

        # --- placement axis (multi-device meshes only)
        self.pmodel: Optional[_CostModel] = None
        self.splan: Optional[ShardingPlan] = None
        if int(mesh.devices.size) > 1:
            self.splan = plan_sharding(
                graph, specs, mesh=mesh,
                hbm_budget_bytes=hbm_budget_bytes)
            if self.splan is not None:
                self.pmodel = _CostModel(
                    graph, specs, mesh, hbm_budget_bytes,
                    replicated_threshold_bytes=DEFAULT_REPLICATED_THRESHOLD)
                # the choice set is exactly the sequential planner's —
                # vertices it dropped as unclassifiable stay dropped
                for vid in list(self.pmodel.menus):
                    if vid not in self.splan.families:
                        del self.pmodel.menus[vid]

        # --- dtype axis: graph-level boundary policies (CLI surfaces,
        # unenforced — mirroring --explain-precision) and per-program
        # trails (the enforced PR-10 mechanism)
        self.prmodel: Optional[_PrecisionModel] = None
        self.pplan = None
        if include_boundary_policies:
            self.pplan = plan_precision(graph, specs)
            if self.pplan is not None:
                self.prmodel = _PrecisionModel(
                    graph, specs, tolerances=self.pplan.tolerances)
        self.program_trails: Dict[Any, Tuple] = {}
        from ..nodes.util.fusion import FusedBatchTransformer
        from ..workflow.fusion_rule import FusedChainOperator

        for vid in self.order:
            if not isinstance(vid, NodeId):
                continue
            op = graph.get_operator(vid)
            if isinstance(op, (FusedChainOperator, FusedBatchTransformer)) \
                    and getattr(op, "planned_precision", None) is None:
                try:
                    decided = plan_stage_precision(graph, vid, op, specs)
                except Exception:
                    decided = None
                if decided is not None:
                    self.program_trails[vid] = decided

        # --- kernel axis: KP801 fused-trail candidates — the
        # chain-megakernel-vs-XLA choice per fused program. Every
        # candidate joins the menu (one per vertex, highest boundary
        # savings wins); non-lowerable statics or a VMEM-infeasible
        # block geometry price INF in the scorer, so the toggle is
        # scored-and-demoted with a ledger record instead of crashing
        # or silently vanishing.
        self.kernel_candidates: Dict[Any, Dict[str, Any]] = {}
        for cand in self.roof.candidates:
            if cand.get("kind") != "fused_trail" \
                    or not cand.get("stage_slice"):
                continue
            kvid = cand["vertices"][0]
            prev = self.kernel_candidates.get(kvid)
            if prev is None or cand["seconds_saved"] > prev["seconds_saved"]:
                self.kernel_candidates[kvid] = cand
        for kvid, cand in self.kernel_candidates.items():
            cand["vmem_feasible"] = self._kernel_feasible(kvid, cand)
            cand["statically_verified"] = self._kernel_verified(
                kvid, cand)

        # --- cache axis: the autocache candidate set, restricted to
        # boundaries whose residency the model can price
        self.cache_candidates: List[Any] = []
        self._cache_bytes: Dict[Any, int] = {}
        try:
            candidates = AutoCacheRule._candidates(graph)
        except Exception:
            candidates = []
        nominal = 1024
        counts = [s.count for s in specs.values()
                  if isinstance(s, DataSpec) and s.kind == "dataset"
                  and s.count]
        if counts:
            nominal = max(counts)
        self.nominal_count = nominal
        for vid in candidates:
            spec = specs.get(vid)
            nb = policy_nbytes(spec, POLICY_F32, nominal) \
                if isinstance(spec, DataSpec) else None
            if nb is not None and vid in self.roof.stages:
                self.cache_candidates.append(vid)
                self._cache_bytes[vid] = nb
        self._nbytes_cache: Dict[Tuple[Any, str], Optional[int]] = {}

    # ------------------------------------------------------------ pieces

    def host_bandwidth(self) -> float:
        """Calibrated host↔device bytes/second — the spill tier's
        reload price denominator. Resolved lazily (only when a spilled
        assignment is actually scored) so the KEYSTONE_OOC_SPILL=0
        path never touches the calibration machinery."""
        if self._host_bw is None:
            bw = 0.0
            try:
                from ..nodes.learning.calibrate import host_bandwidth
                bw = float(host_bandwidth())
            except Exception:
                bw = 0.0
            self._host_bw = bw if bw > 0 else 1.0e10
        return self._host_bw

    def vbytes(self, vid, policy: str) -> Optional[int]:
        key = (vid, policy)
        if key not in self._nbytes_cache:
            self._nbytes_cache[key] = policy_nbytes(
                self.specs.get(vid), policy, self.nominal_count)
        return self._nbytes_cache[key]

    def _count(self, vid) -> int:
        st = self.roof.stages.get(vid)
        if st is not None and st.count:
            return int(st.count)
        spec = self.specs.get(vid)
        if isinstance(spec, DataSpec) and spec.count:
            return int(spec.count)
        return self.nominal_count

    def _data_dep(self, vid):
        if not isinstance(vid, NodeId):
            return None
        for d in self.graph.get_dependencies(vid):
            if isinstance(self.specs.get(d), DataSpec):
                return d
        return None

    def _kernel_slice(self, vid, cand):
        """(slice stage objects, element aval entering the slice) for a
        fused-trail kernel candidate — the one walk both the VMEM
        feasibility probe and the KP10xx static verifier consume."""
        import jax

        from ..nodes.util.fusion import _peephole
        from ..workflow.fusion_rule import FusedChainOperator

        op = self.graph.get_operator(vid)
        stage_list = (list(op.stage_specs)
                      if isinstance(op, FusedChainOperator)
                      else list(op.stages))
        stages = list(_peephole(stage_list))
        i, j = cand["stage_slice"]
        dep = self._data_dep(vid)
        spec = self.specs.get(dep)
        elem = spec.element
        # walk the element to the slice's input shape
        for s in stages[:i]:
            elem = jax.eval_shape(
                lambda x, s=s: s.single_transform([x]), elem)
        return stages[i:j], elem

    def _kernel_feasible(self, vid, cand) -> Tuple[bool, str]:
        """Probe the candidate slice's block geometry against the VMEM
        budget at the ACTUAL propagated element shapes — the
        memory-safety side of the kernel axis (arXiv 2206.14148
        discipline): an infeasible geometry prices INF downstream, it
        never reaches a compiler."""
        try:
            from ..ops.chain_kernels import chain_feasible

            if not (cand.get("lowerable") or {}).get("lowerable"):
                return False, (cand.get("lowerable") or {}).get(
                    "reason", "not lowerable")
            stages, elem = self._kernel_slice(vid, cand)
            return chain_feasible(stages, tuple(elem.shape), elem.dtype)
        except Exception as e:
            return False, f"feasibility probe failed: {e}"

    def _kernel_verified(self, vid, cand):
        """The KP10xx static proof for the candidate slice
        (analysis/kernels.statically_verified): False prices the kernel
        toggle INF — a lowering the verifier refuted must never reach
        the runtime canary, let alone a chip. None (verifier could not
        run) keeps the pre-verifier behavior: the canary decides."""
        try:
            from .kernels import statically_verified

            if not (cand.get("lowerable") or {}).get("lowerable"):
                return None
            stages, elem = self._kernel_slice(vid, cand)
            return statically_verified(stages, tuple(elem.shape),
                                       elem.dtype)
        except Exception:
            return None

    # ------------------------------------------------------------ scorer

    def score(self, a: Assignment) -> float:
        """Predicted seconds of one complete assignment — the ONE
        objective every candidate (sequential composition included) is
        measured by. INF means a hard KP600 infeasibility (the
        assignment is pruned, never enforced-then-linted)."""
        families = a.fam()
        policies = a.pol()
        trails = a.trl()
        kernels = a.krn()
        chunk = max(1, a.chunk)
        runs = self._get_runs(self.graph, set(a.caches))
        total = 0.0
        bw = self.machine.peak_bw

        # cache residency is pinned for the whole run: it must fit the
        # per-device budget alongside the plan (hard constraint). A
        # HOST-placed cache (the spill tier) pins only its windowed
        # double-buffer residency — full bytes live in host RAM and
        # re-enter through the PR-1 overlap prefetcher — which is what
        # turns a busted budget into a satisfiable constraint.
        if self.budget:
            pinned = 0
            for vid in a.caches:
                shards = family_shards(families.get(vid), self.mesh)
                nb = (self.vbytes(vid, policies.get(vid, POLICY_F32))
                      or 0)
                if vid in a.spills:
                    count = max(1, self._count(vid))
                    nb = int(2 * (nb / count) * chunk)
                pinned += nb // max(1, shards)
            if pinned > self.budget:
                return _INF

        # spill reload seconds: each spilled cache pays one eviction
        # (device→host) plus, per consuming re-run, one full windowed
        # reload (host→device) over the calibrated host bandwidth and
        # the dispatch floor per window trip — the priced disadvantage
        # that keeps device placement winning whenever it fits.
        if a.spills:
            host_bw = self.host_bandwidth()
            for vid in a.spills:
                if vid not in a.caches:
                    continue
                nb = (self.vbytes(vid, policies.get(vid, POLICY_F32))
                      or 0)
                count = max(1, self._count(vid))
                trips = max(1, math.ceil(count / chunk))
                reruns = max(1, runs.get(vid, 1))
                total += nb / host_bw  # evict once
                total += reruns * (nb / host_bw
                                   + trips * DISPATCH_OVERHEAD_S)

        for vid, st in self.roof.stages.items():
            pol_v = policies.get(vid, POLICY_F32)
            dep = self._data_dep(vid)
            pol_u = policies.get(dep, POLICY_F32) if dep is not None \
                else POLICY_F32
            out_b = self.vbytes(vid, pol_v)
            in_b = self.vbytes(dep, pol_u) if dep is not None else None
            if out_b is not None and in_b is not None:
                nbytes = in_b + out_b
            elif out_b is not None:
                nbytes = 2 * out_b
            else:
                nbytes = st.hbm_bytes
            trail = self.program_trails.get(vid)
            if trail is not None and trails.get(vid):
                # the baked bf16 trail halves the program's INTERNAL
                # boundaries (each internal boundary is one write + one
                # read in the stage-at-a-time model) and costs its casts
                _, saved, _ = trail
                nbytes = max(0, nbytes - 2 * saved)
                casts = sum(1 for s in trail[0] if s is not None)
                total += casts * CAST_PENALTY_BYTES / bw
            kc = self.kernel_candidates.get(vid)
            if kc is not None and kernels.get(vid):
                # the chain megakernel: the slice's internal boundaries
                # never round-trip HBM (one streamed pass of in+out
                # bytes). Non-lowerable statics or a VMEM-infeasible
                # geometry make the WHOLE assignment infeasible — the
                # toggle demotes with a priced-INF record, it is never
                # enforced.
                if not kc["vmem_feasible"][0]:
                    return _INF
                if kc.get("statically_verified") is False:
                    # the KP10xx verifier refuted the lowering: the
                    # kernel toggle is pruned statically instead of
                    # relying on the runtime canary to demote it
                    return _INF
                nbytes = max(0, nbytes - 2 * kc["boundary_bytes"])
            count = self._count(vid)
            trips = max(1, math.ceil(count / chunk))
            if self.budget and count:
                # in-flight chunk residency (the scan/dispatch window's
                # live rows) must fit the per-device budget: the KP600
                # constraint that couples the chunk axis to placement
                shards = family_shards(families.get(vid), self.mesh)
                per_row = nbytes / count
                if per_row * chunk / max(1, shards) > self.budget:
                    return _INF
            sec = stage_cost(st.flops, nbytes, self.machine)
            sec += trips * DISPATCH_OVERHEAD_S
            total += sec * max(1, runs.get(vid, 1))

        # boundary-policy cast seconds (graph-level dtype flips)
        if self.prmodel is not None:
            for vid in self.order:
                if not isinstance(vid, NodeId):
                    continue
                sv = _STORAGE[policies.get(vid, POLICY_F32)]
                for d in self.graph.get_dependencies(vid):
                    if not isinstance(self.specs.get(d), DataSpec):
                        continue
                    if _STORAGE[policies.get(d, POLICY_F32)] != sv:
                        total += CAST_PENALTY_BYTES / bw

        # placement collective seconds — the planner's own formulas,
        # with the boundary bytes the chosen DTYPES actually move (the
        # interaction the sequential passes cannot see)
        pm = self.pmodel
        if pm is not None:
            for vid in pm.order:
                fam_v = families.get(vid)
                if fam_v is not None and vid in pm.menus:
                    if pm.node_cost(vid, fam_v) == _INF:
                        return _INF  # KP600: per-device residency
                    spec = self.specs.get(vid)
                    if fam_v == FAMILY_REPLICATED and spec.nbytes \
                            and spec.nbytes >= pm.threshold:
                        cost = meshlib.collective_cost(
                            "broadcast", spec.nbytes,
                            shards=int(self.mesh.devices.size),
                            mesh=self.mesh)
                        total += float(cost.seconds)
                deps = pm.data_deps(vid)
                demands = pm.demands(vid, {})
                all_deps = (list(self.graph.get_dependencies(vid))
                            if isinstance(vid, NodeId) else [])
                for d in deps:
                    fam_u = families.get(d)
                    u_spec = self.specs.get(d)
                    nbytes = self.vbytes(d, policies.get(d, POLICY_F32))
                    if nbytes is None:
                        nbytes = pm.vbytes(u_spec)
                    cost = None
                    if pm.is_host(vid):
                        cost = gather_cost(fam_u, nbytes, self.mesh)
                    else:
                        demand = None
                        if demands:
                            try:
                                i = all_deps.index(d)
                            except ValueError:
                                i = -1
                            if 0 <= i < len(demands):
                                demand = demands[i]
                        if demand is not None:
                            cost = demand_cost(demand, fam_u, nbytes,
                                               self.mesh)
                        elif fam_v is not None:
                            cost = transition_cost(fam_u, fam_v, nbytes,
                                                   self.mesh, u_spec=u_spec)
                    if cost is not None:
                        # every reshard is also one more launched
                        # program: the dispatch floor doubles as the
                        # byte planner's per-move penalty, in seconds
                        total += float(cost.seconds) + DISPATCH_OVERHEAD_S
        return total

    # ----------------------------------------------------- the sequential

    def sequential(self) -> Assignment:
        """The PR-13 composition as a point in the joint space: the
        sharding planner's enforced families, the per-program precision
        trails the sequential rule would bake (its enforcement floor
        included), `plan_precision`'s own clamped graph-level policies
        (the --explain-precision surface), the config chunk, and no
        cache points (autocache is a separate opt-in optimizer in the
        sequential world)."""
        families = dict(self.splan.families) if self.splan else {}
        policies = dict(self.pplan.policies) if self.pplan else {}
        trails = {
            vid: bool(saved >= self.precision_floor_bytes)
            for vid, (_, saved, _) in self.program_trails.items()
        }
        return _assign(families, policies, trails, self.chunk_default,
                       frozenset())

    # ------------------------------------------------------------ solver

    def chain_dp(self, seed: Assignment) -> Assignment:
        """The chain-DP + frontier merge generalized to the product
        menu: along each maximal fan-out-free chain of choosable
        vertices the state is a (family, policy) PAIR, transitions
        price the placement collective (at the producer's policy-scaled
        bytes) plus the cast flip, and fan-in freezes greedily at the
        best table entry — the planner's solver shape, one product
        state space."""
        families = seed.fam()
        policies = seed.pol()
        fam_menu = dict(self.pmodel.menus) if self.pmodel else {}
        pol_menu = dict(self.prmodel.menus) if self.prmodel else {}
        choosable = set(fam_menu) | set(pol_menu)
        if not choosable:
            return seed
        users = {vid: [u for u in self.graph.users_of(vid)
                       if not isinstance(u, SinkId)]
                 for vid in self.order}

        def states(vid) -> List[Tuple[Optional[str], str]]:
            fams = list(fam_menu.get(vid, (families.get(vid),)))
            pols = list(pol_menu.get(vid, (policies.get(vid, POLICY_F32),)))
            return [(f, p) for f in fams for p in pols]

        def edge_cost(u, us, v, vs) -> float:
            fam_u, pol_u = us
            fam_v, pol_v = vs
            sec = 0.0
            u_spec = self.specs.get(u)
            nbytes = self.vbytes(u, pol_u)
            cost = transition_cost(fam_u, fam_v, nbytes, self.mesh,
                                   u_spec=u_spec)
            if cost is not None:
                sec += float(cost.seconds) + DISPATCH_OVERHEAD_S
            if _STORAGE[pol_u] != _STORAGE[pol_v]:
                sec += CAST_PENALTY_BYTES / self.machine.peak_bw
            return sec

        def node_cost(v, vs) -> float:
            fam_v, pol_v = vs
            if self.pmodel and v in fam_menu and fam_v is not None:
                if self.pmodel.node_cost(v, fam_v) == _INF:
                    return _INF
            st = self.roof.stages.get(v)
            if st is None:
                return 0.0
            out_b = self.vbytes(v, pol_v)
            nbytes = 2 * out_b if out_b is not None else st.hbm_bytes
            return stage_cost(st.flops, nbytes, self.machine)

        visited: set = set()
        for vid in self.order:
            if vid not in choosable or vid in visited:
                continue
            head = vid
            while isinstance(head, NodeId):
                deps = [d for d in self.graph.get_dependencies(head)
                        if d in choosable]
                if len(deps) == 1 and len(users.get(deps[0], ())) == 1 \
                        and deps[0] not in visited:
                    head = deps[0]
                else:
                    break
            chain = [head]
            cur = head
            while True:
                kids = [u for u in users.get(cur, ())
                        if isinstance(u, NodeId) and u in choosable]
                if len(users.get(cur, ())) == 1 and len(kids) == 1 \
                        and kids[0] not in visited:
                    chain.append(kids[0])
                    cur = kids[0]
                else:
                    break
            visited.update(chain)
            # exact DP along the chain over product states
            table: Dict[Tuple, float] = {s: node_cost(chain[0], s)
                                         for s in states(chain[0])}
            back: List[Dict[Tuple, Tuple]] = []
            for prev, v in zip(chain, chain[1:]):
                nxt: Dict[Tuple, float] = {}
                bp: Dict[Tuple, Tuple] = {}
                for s in states(v):
                    best, best_c = None, _INF
                    for ps, pc in table.items():
                        c = pc + edge_cost(prev, ps, v, s)
                        if c < best_c:
                            best, best_c = ps, c
                    nxt[s] = best_c + node_cost(v, s)
                    bp[s] = best
                back.append(bp)
                table = nxt
            # greedy freeze at the tail, walk backpointers up the chain
            tail_state = min(table, key=lambda s: (table[s],
                                                   str(s)))
            if table[tail_state] == _INF:
                continue  # every product entry infeasible: keep seed
            assign = [tail_state]
            for bp in reversed(back):
                assign.append(bp[assign[-1]])
            assign.reverse()
            for v, (f, p) in zip(chain, assign):
                if v in fam_menu and f is not None:
                    families[v] = f
                if v in pol_menu:
                    policies[v] = p
        return replace(seed,
                       families=_assign(families, {}, {}, 0, ()).families,
                       policies=_assign({}, policies, {}, 0, ()).policies)

    def descend(self, seed: Assignment, obj: float,
                ladder: Tuple[int, ...],
                sweeps: int = 2) -> Tuple[Assignment, float,
                                          List[Dict[str, Any]]]:
        """Bounded local descent ACROSS decision kinds: per-vertex
        family/policy sweeps, per-program trail toggles, the chunk
        ladder, and greedy cache additions — each trial re-scored by
        the one shared scorer, strict improvements kept. Returns the
        best assignment, its objective, and the priced entries it
        actually scored (the ledger's product menu)."""
        scored: List[Dict[str, Any]] = []
        seen_entries: set = set()
        best, best_obj = seed, obj

        def try_(label: str, cand: Assignment) -> None:
            nonlocal best, best_obj
            c = self.score(cand)
            if label not in seen_entries:
                # one priced entry per menu label: later rounds re-score
                # the same toggle against a different intermediate
                # assignment, and duplicate labels with conflicting
                # prices would make the ledger's alternatives ambiguous
                seen_entries.add(label)
                scored.append({"entry": label, "predicted_seconds":
                               (None if c == _INF else float(c)),
                               "feasible": c != _INF})
            if c < best_obj:
                best, best_obj = cand, c

        # chunk ladder (the plan-level axis: cheap, solve it first)
        for chunk in ladder:
            if chunk != best.chunk:
                try_(f"chunk_{chunk}", replace(best, chunk=chunk))
        # program-trail toggles
        for vid in self.program_trails:
            trails = best.trl()
            trails[vid] = not trails.get(vid, False)
            try_(f"trail_{getattr(vid, 'id', vid)}_"
                 f"{'on' if trails[vid] else 'off'}",
                 replace(best, trails=_assign({}, {}, trails, 0,
                                              ()).trails))
        # chain-megakernel toggles (the kernel-vs-XLA axis): an
        # infeasible kernel scores INF here — the scored entry IS the
        # demotion record
        for vid in self.kernel_candidates:
            kernels = best.krn()
            kernels[vid] = not kernels.get(vid, False)
            try_(f"kernel_{getattr(vid, 'id', vid)}_"
                 f"{'on' if kernels[vid] else 'off'}",
                 replace(best, kernels=_assign({}, {}, {}, 0, (),
                                               kernels).kernels))
        # greedy cache additions (the autocache greedy shape, priced
        # statically): add the best strict improvement until none
        while True:
            gain_best, gain_cand = 0.0, None
            for vid in self.cache_candidates:
                if vid in best.caches:
                    continue
                cand = replace(best, caches=best.caches | {vid})
                c = self.score(cand)
                label = f"cache_{getattr(vid, 'id', vid)}"
                if label not in seen_entries:
                    seen_entries.add(label)
                    scored.append({"entry": label, "predicted_seconds":
                                   (None if c == _INF else float(c)),
                                   "feasible": c != _INF})
                if best_obj - c > gain_best:
                    gain_best, gain_cand = best_obj - c, cand
            if gain_cand is None:
                break
            best, best_obj = gain_cand, best_obj - gain_best
        # spill-placement toggles (the out-of-core axis): per cache
        # candidate, flip device↔host placement. Where a device cache
        # busts the KP600 budget (scored INF in the greedy loop above),
        # the host-placed variant prices window residency + reload
        # seconds instead — a tight budget becomes satisfiable, and the
        # INF/feasible pair IS the ledger's priced alternative set.
        if self.allow_spill:
            for vid in self.cache_candidates:
                caches = set(best.caches)
                spills = set(best.spills)
                if vid in spills:
                    spills.discard(vid)  # back to device placement
                else:
                    caches.add(vid)
                    spills.add(vid)
                flipped = replace(best, caches=frozenset(caches),
                                  spills=frozenset(spills))
                # the spill and window decisions are coupled: a spilled
                # cache pins O(window) residency, so the toggle is
                # priced at its best rung — scoring it only at the
                # incumbent chunk would report INF for spills a smaller
                # window makes feasible
                cands = [flipped] + [replace(flipped, chunk=c)
                                     for c in ladder
                                     if c != flipped.chunk]
                try_(f"spill_{getattr(vid, 'id', vid)}",
                     min(cands, key=self.score))
            if best.spills:
                # a spilled cache changes the chunk economics (reload
                # trips vs window residency): re-walk the ladder once
                for chunk in ladder:
                    if chunk != best.chunk:
                        try_(f"chunk_{chunk}",
                             replace(best, chunk=chunk))
        # family/policy coordinate sweeps
        fam_menu = dict(self.pmodel.menus) if self.pmodel else {}
        pol_menu = dict(self.prmodel.menus) if self.prmodel else {}
        for _sweep in range(sweeps):
            changed = False
            for vid in self.order:
                for fam in fam_menu.get(vid, ()):
                    if fam == best.fam().get(vid):
                        continue
                    fams = best.fam()
                    fams[vid] = fam
                    cand = replace(best, families=_assign(
                        fams, {}, {}, 0, ()).families)
                    c = self.score(cand)
                    if c < best_obj:
                        best, best_obj, changed = cand, c, True
                for pol in pol_menu.get(vid, ()):
                    if pol == best.pol().get(vid, POLICY_F32):
                        continue
                    pols = best.pol()
                    pols[vid] = pol
                    cand = replace(best, policies=_assign(
                        {}, pols, {}, 0, ()).policies)
                    c = self.score(cand)
                    if c < best_obj:
                        best, best_obj, changed = cand, c, True
            if not changed:
                break
        return best, best_obj, scored


# --------------------------------------------------------------- the plan


@dataclass
class UnifiedPlan:
    """The joint decision: the chosen assignment, the sequential PR-13
    composition it was scored against (same scorer), and the priced
    menu. When ``improved`` is False the assignment IS the sequential
    composition and nothing deviates."""

    mesh: Any
    chosen: Assignment
    sequential_assignment: Assignment
    joint_seconds: float
    sequential_seconds: float
    #: the product-menu entries the solver actually scored — the
    #: decision ledger's alternatives
    scored_candidates: List[Dict[str, Any]] = field(default_factory=list)
    #: a `ShardingPlan` whose families are the JOINT choice (spec_for /
    #: changed_vertices drive enforcement exactly like PR 9)
    sharding: Optional[ShardingPlan] = None
    #: vid -> (storage, saved_bytes, menu) for every program trail the
    #: joint plan turns ON (the PR-10 enforcement payload)
    program_precision: Dict[Any, Tuple] = field(default_factory=dict)
    #: a `PrecisionPlan` whose policies are the JOINT graph-level
    #: choice — the KP7xx lint surface (`precision_pass(plan=...)`),
    #: None when the dtype axis had nothing to decide
    boundary_precision: Optional[Any] = None
    #: vid -> the KP801 candidate dict (stage_slice, lowerable verdict,
    #: kernel_seconds vs chain_seconds, boundary_bytes) for every
    #: fused program the joint plan lowers to a chain megakernel — the
    #: `UnifiedPlannerRule` kernel-enforcement payload
    kernel_choices: Dict[Any, Dict[str, Any]] = field(default_factory=dict)
    #: vid -> {bytes, window_trips, reload_seconds} for every spilled
    #: cache point — the ledger's predicted side of the spill decision
    #: (`reconcile_decisions` joins it against the observed
    #: spill.reload_stall_s histogram and spill_window spans)
    spill_predictions: Dict[Any, Dict[str, Any]] = field(
        default_factory=dict)
    unpriced_stages: int = 0

    @property
    def improved(self) -> bool:
        return self.joint_seconds < self.sequential_seconds

    @property
    def savings_seconds(self) -> float:
        return max(0.0, self.sequential_seconds - self.joint_seconds)

    @property
    def chunk_size(self) -> int:
        return self.chosen.chunk

    @property
    def default_chunk_size(self) -> int:
        return self.sequential_assignment.chunk

    @property
    def cache_vertices(self) -> List:
        return sorted(self.chosen.caches,
                      key=lambda v: getattr(v, "id", -1))

    @property
    def spill_vertices(self) -> List:
        """Cache points the joint plan places on the HOST (⊆
        cache_vertices) — the `UnifiedPlannerRule` spill-enforcement
        payload (`CacheMarker(placement="host")`)."""
        return sorted(self.chosen.spills,
                      key=lambda v: getattr(v, "id", -1))

    def changed_kinds(self) -> List[str]:
        """Which decision kinds deviate from the sequential
        composition — what `UnifiedPlannerRule` must enforce (and
        record) itself."""
        out = []
        if self.chosen.families != self.sequential_assignment.families:
            out.append("placement")
        if (self.chosen.trails != self.sequential_assignment.trails
                or self.chosen.policies
                != self.sequential_assignment.policies):
            out.append("precision")
        if self.chosen.chunk != self.sequential_assignment.chunk:
            out.append("chunk")
        if self.chosen.caches != self.sequential_assignment.caches:
            out.append("cache")
        if self.chosen.kernels != self.sequential_assignment.kernels:
            out.append("kernel")
        if self.chosen.spills != self.sequential_assignment.spills:
            out.append("spill")
        return out

    def rows(self, graph: Graph) -> List[Dict[str, Any]]:
        """Per-stage chosen-vs-sequential table (topo order),
        JSON-ready — the ``--explain-unified`` payload."""
        order, _ = toposort(graph)
        fams, seq_fams = self.chosen.fam(), self.sequential_assignment.fam()
        pols, seq_pols = self.chosen.pol(), self.sequential_assignment.pol()
        trails = self.chosen.trl()
        seq_trails = self.sequential_assignment.trl()
        caches = set(self.chosen.caches)
        spills = set(self.chosen.spills)
        kernels = self.chosen.krn()
        rows = []
        for vid in order:
            if not isinstance(vid, NodeId):
                continue
            if vid not in fams and vid not in pols \
                    and vid not in trails and vid not in caches \
                    and vid not in kernels:
                continue
            rows.append({
                "vertex": vid.id,
                "label": _label(graph, vid),
                "family": fams.get(vid),
                "sequential_family": seq_fams.get(vid),
                "policy": pols.get(vid, POLICY_F32),
                "sequential_policy": seq_pols.get(vid, POLICY_F32),
                "trail": trails.get(vid),
                "sequential_trail": seq_trails.get(vid),
                "cached": vid in caches,
                "spilled": vid in spills,
                "kernel": bool(kernels.get(vid)),
                "changed": (fams.get(vid) != seq_fams.get(vid)
                            or pols.get(vid) != seq_pols.get(vid)
                            or trails.get(vid) != seq_trails.get(vid)
                            or vid in caches
                            or bool(kernels.get(vid))),
            })
        return rows


def format_plan(plan: UnifiedPlan, graph: Graph) -> str:
    lines = [
        f"joint ≈{plan.joint_seconds:.3e}s vs sequential "
        f"≈{plan.sequential_seconds:.3e}s "
        f"({'strict win' if plan.improved else 'no win: sequential plan'}"
        f", chunk {plan.default_chunk_size} → {plan.chunk_size}, "
        f"{len(plan.cache_vertices)} cache point(s), "
        f"{len(plan.spill_vertices)} spilled to host)"
    ]
    header = (f"{'stage':<36} {'family':<22} {'policy':<14} "
              f"{'cache':>5} {'kern':>5}")
    body = [header]
    for r in plan.rows(graph):
        mark = "*" if r["changed"] else " "
        fam = (f"{r['sequential_family'] or '—'}"
               + (f"→{r['family']}" if r["family"]
                  != r["sequential_family"] else ""))
        pol = (f"{r['sequential_policy']}"
               + (f"→{r['policy']}" if r["policy"]
                  != r["sequential_policy"] else ""))
        body.append(
            f"{mark}{(r['label'] + '@' + str(r['vertex']))[:35]:<35} "
            f"{fam[:22]:<22} {pol[:14]:<14} "
            f"{('host' if r.get('spilled') else 'yes') if r['cached'] else '':>5} "
            f"{'yes' if r.get('kernel') else '':>5}")
    if len(body) > 1:
        lines.extend(body)
    return "\n".join(lines)


# ------------------------------------------------------------ entry point


def plan_unified(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    mesh=None,
    hbm_budget_bytes: Optional[int] = None,
    chunk_default: Optional[int] = None,
    machine: Optional[Machine] = None,
    weights=None,
    include_boundary_policies: bool = True,
    precision_floor_bytes: int = 0,
    ladder: Tuple[int, ...] = CHUNK_LADDER,
    allow_spill: Optional[bool] = None,
) -> Optional[UnifiedPlan]:
    """Solve the joint decision IR for one graph.

    ``weights`` (a `calibrate.CostWeights`, e.g. from
    `reconcile.drift_cost_weights(trace)`) recalibrates the time
    model's peaks from a live trace; ``machine`` pins them directly;
    neither falls back to `calibrate.machine_rates()`. Returns None
    when there is nothing to decide (no priceable stage and no axis
    with more than one entry). ``improved`` is a STRICT win over the
    sequential composition scored by the same function — otherwise the
    plan is the sequential assignment and nothing deviates."""
    mesh = mesh or meshlib.current_mesh()
    if weights is not None and machine is None:
        machine = machine_from_weights(weights)
    machine = machine or default_machine()
    from ..workflow.env import execution_config

    cfg = execution_config()
    chunk_default = int(chunk_default or cfg.chunk_size)
    if allow_spill is None:
        # KEYSTONE_OOC_SPILL=0 is the bit-for-bit kill switch: no spill
        # toggle is scored and the chosen plan matches PR 19 exactly
        allow_spill = bool(getattr(cfg, "ooc_spill", False))
    model = _UnifiedModel(
        graph, specs, mesh, hbm_budget_bytes, chunk_default, machine,
        include_boundary_policies=include_boundary_policies,
        precision_floor_bytes=precision_floor_bytes,
        allow_spill=allow_spill)
    if not model.roof.stages:
        return None
    has_axis = bool(model.cache_candidates or model.program_trails
                    or model.kernel_candidates
                    or (model.pmodel and model.pmodel.menus)
                    or (model.prmodel and model.prmodel.menus)
                    or any(model._count(v) > min(ladder)
                           for v in model.roof.stages))
    if not has_axis:
        return None

    # the chunk ladder never exceeds the largest known count's padded
    # shape (bigger chunks change nothing but the pad waste)
    max_count = max((model._count(v) for v in model.roof.stages),
                    default=chunk_default)
    ladder = tuple(sorted({c for c in ladder
                           if c <= max(max_count, chunk_default)}
                          | {chunk_default}))

    seq = model.sequential()
    seq_obj = model.score(seq)
    scored: List[Dict[str, Any]] = [
        {"entry": "sequential", "predicted_seconds": float(seq_obj),
         "feasible": seq_obj != _INF},
    ]

    # the product chain-DP seed, then descent across decision kinds
    dp_seed = model.chain_dp(seq)
    dp_obj = model.score(dp_seed)
    scored.append({"entry": "chain_dp_product",
                   "predicted_seconds":
                   (None if dp_obj == _INF else float(dp_obj)),
                   "feasible": dp_obj != _INF})
    best, best_obj = (dp_seed, dp_obj) if dp_obj < seq_obj \
        else (seq, seq_obj)
    best, best_obj, descent_scored = model.descend(best, best_obj, ladder)
    scored.extend(descent_scored)
    scored.append({"entry": "joint_optimum",
                   "predicted_seconds":
                   (None if best_obj == _INF else float(best_obj)),
                   "feasible": best_obj != _INF})

    if not best_obj < seq_obj:
        best, best_obj = seq, seq_obj  # the plan IS the sequential one

    # the enforcement payloads: a ShardingPlan over the JOINT families
    # (PR-9 machinery) and the ON program trails (PR-10 machinery)
    sharding = None
    if model.splan is not None and model.pmodel is not None:
        fams = best.fam()
        choices = {vid: model.pmodel.menus[vid][fam]
                   for vid, fam in fams.items()
                   if vid in model.pmodel.menus
                   and fam in model.pmodel.menus[vid]}
        _, planned_bytes, planned_boundary = model.pmodel.score(fams)
        sharding = ShardingPlan(
            mesh=mesh,
            families=fams,
            default_families=model.splan.default_families,
            choices=choices,
            default_shardings=model.splan.default_shardings,
            planned_cost_bytes=planned_bytes,
            default_cost_bytes=model.splan.default_cost_bytes,
            planned_boundary=planned_boundary,
            default_boundary=model.splan.default_boundary,
            scored_candidates=model.splan.scored_candidates,
        )
    program_precision = {
        vid: model.program_trails[vid]
        for vid, on in best.trl().items()
        if on and vid in model.program_trails
    }
    kernel_choices = {
        vid: model.kernel_candidates[vid]
        for vid, on in best.krn().items()
        if on and vid in model.kernel_candidates
    }
    spill_predictions: Dict[Any, Dict[str, Any]] = {}
    if best.spills:
        host_bw = model.host_bandwidth()
        pols = best.pol()
        for vid in best.spills:
            nb = model.vbytes(vid, pols.get(vid, POLICY_F32)) or 0
            count = max(1, model._count(vid))
            trips = max(1, math.ceil(count / max(1, best.chunk)))
            spill_predictions[vid] = {
                "bytes": int(nb),
                "window_trips": int(trips),
                "reload_seconds": float(
                    2 * nb / host_bw + trips * DISPATCH_OVERHEAD_S),
            }
    boundary_precision = None
    if model.pplan is not None and model.prmodel is not None:
        from .precision import PrecisionPlan

        policies = dict(model.pplan.default_policies)
        policies.update(best.pol())
        cost, boundary = model.prmodel.score(policies)
        boundary_precision = PrecisionPlan(
            policies=policies,
            default_policies=model.pplan.default_policies,
            planned_cost_bytes=cost,
            default_cost_bytes=model.pplan.default_cost_bytes,
            planned_boundary=boundary,
            default_boundary=model.pplan.default_boundary,
            tolerances=model.pplan.tolerances,
        )
    return UnifiedPlan(
        mesh=mesh,
        chosen=best,
        sequential_assignment=seq,
        joint_seconds=float(best_obj),
        sequential_seconds=float(seq_obj),
        scored_candidates=scored,
        sharding=sharding,
        program_precision=program_precision,
        boundary_precision=boundary_precision,
        kernel_choices=kernel_choices,
        spill_predictions=spill_predictions,
        unpriced_stages=model.unpriced_stages,
    )
