"""CLI: statically validate the example pipelines / audit the operator
registry.

    python -m keystone_tpu.analysis                 # all examples, level=full
    python -m keystone_tpu.analysis MnistRandomFFT  # one example
    python -m keystone_tpu.analysis --level specs --hbm-budget-gb 16
    python -m keystone_tpu.analysis --audit-operators   # registry-wide KP5xx
    python -m keystone_tpu.analysis --audit-operators --json
    python -m keystone_tpu.analysis --list-rules

Exit code 1 if any example produces ERROR-severity findings (or any
finding at all with ``--strict``), or — under ``--audit-operators`` — if
ANY unsuppressed KP5xx contract finding remains anywhere in the
registered operator registry. Runs entirely abstractly — no data loads,
no device programs execute.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import LEVELS, RULES, Severity, validate_graph
from .examples import EXAMPLES, build_example


def _audit_main(args) -> int:
    """Registry-wide operator contract audit (KP5xx): sweep every
    registered Operator/Estimator subclass, not just built pipelines."""
    from .contracts import audit_registry

    findings, stats = audit_registry()
    if args.ignore:
        findings = [(c, d) for c, d in findings if d.rule not in args.ignore]
    if args.json:
        print(json.dumps({
            "audited_classes": stats["classes"],
            "probed_classes": stats["probed"],
            "findings": [
                {
                    "class": cls.__qualname__,
                    "module": cls.__module__,
                    "rule": d.rule,
                    "severity": d.severity.name,
                    "message": d.message,
                }
                for cls, d in findings
            ],
        }, indent=2))
        return 1 if findings else 0
    for cls, d in findings:
        print(f"✗ {cls.__module__}.{cls.__qualname__}: "
              f"[{d.severity.name}] {d.rule} {d.message}")
    mark = "✗" if findings else "✓"
    print(f"{mark} operator contract audit: {stats['classes']} class(es) "
          f"swept ({stats['probed']} probed), {len(findings)} finding(s)")
    return 1 if findings else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("examples", nargs="*", metavar="EXAMPLE",
                   help="example names (default: all registered)")
    p.add_argument("--level", choices=LEVELS, default="full")
    p.add_argument("--hbm-budget-gb", type=float, default=None,
                   help="HBM budget for KP201/KP202 (GiB)")
    p.add_argument("--ignore", action="append", default=[], metavar="RULE",
                   help="suppress a rule id (repeatable)")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too")
    p.add_argument("--audit-operators", action="store_true",
                   help="sweep EVERY registered Operator/Estimator subclass "
                        "for KP5xx contract violations (zero tolerated)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (CI annotation)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    if args.audit_operators:
        return _audit_main(args)

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2

    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else None)
    failed = False
    records = []
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            report = pipeline.validate(
                source_spec, level=args.level, ignore=args.ignore,
                hbm_budget_bytes=budget, raise_on_error=False)
        except Exception as e:  # a factory bug is a failure, not a crash
            if args.json:
                records.append({"example": name, "build_error":
                                f"{type(e).__name__}: {e}"})
            else:
                print(f"✗ {name}: failed to build/validate: "
                      f"{type(e).__name__}: {e}")
            failed = True
            continue
        bad = bool(report.errors) or (args.strict and report.warnings)
        if args.json:
            records.append({
                "example": name,
                "errors": len(report.errors),
                "warnings": len(report.warnings),
                "diagnostics": [
                    {"rule": d.rule, "severity": d.severity.name,
                     "anchor": d.anchor, "message": d.message}
                    for d in report.diagnostics
                ],
            })
        else:
            mark = "✗" if bad else "✓"
            print(f"{mark} {name}: {len(report.errors)} error(s), "
                  f"{len(report.warnings)} warning(s)"
                  + (f", peak ≈ {report.memory.peak_bytes >> 20} MiB"
                     if report.memory and report.memory.peak_bytes else ""))
            for d in report.diagnostics:
                if d.severity >= Severity.WARNING or args.strict:
                    print(f"    {d}")
        failed |= bad
    if args.json:
        print(json.dumps({"examples": records}, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
