"""CLI: statically validate the example pipelines.

    python -m keystone_tpu.analysis                 # all examples, level=full
    python -m keystone_tpu.analysis MnistRandomFFT  # one example
    python -m keystone_tpu.analysis --level specs --hbm-budget-gb 16
    python -m keystone_tpu.analysis --list-rules

Exit code 1 if any example produces ERROR-severity findings (or any
finding at all with ``--strict``). Runs entirely abstractly — no data
loads, no device programs execute.
"""

from __future__ import annotations

import argparse
import sys

from . import LEVELS, RULES, Severity, validate_graph
from .examples import EXAMPLES, build_example


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("examples", nargs="*", metavar="EXAMPLE",
                   help="example names (default: all registered)")
    p.add_argument("--level", choices=LEVELS, default="full")
    p.add_argument("--hbm-budget-gb", type=float, default=None,
                   help="HBM budget for KP201/KP202 (GiB)")
    p.add_argument("--ignore", action="append", default=[], metavar="RULE",
                   help="suppress a rule id (repeatable)")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2

    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else None)
    failed = False
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            report = pipeline.validate(
                source_spec, level=args.level, ignore=args.ignore,
                hbm_budget_bytes=budget, raise_on_error=False)
        except Exception as e:  # a factory bug is a failure, not a crash
            print(f"✗ {name}: failed to build/validate: "
                  f"{type(e).__name__}: {e}")
            failed = True
            continue
        bad = bool(report.errors) or (args.strict and report.warnings)
        mark = "✗" if bad else "✓"
        print(f"{mark} {name}: {len(report.errors)} error(s), "
              f"{len(report.warnings)} warning(s)"
              + (f", peak ≈ {report.memory.peak_bytes >> 20} MiB"
                 if report.memory and report.memory.peak_bytes else ""))
        for d in report.diagnostics:
            if d.severity >= Severity.WARNING or args.strict:
                print(f"    {d}")
        failed |= bad
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
