"""CLI: statically validate the example pipelines / audit the operator
registry.

    python -m keystone_tpu.analysis                 # all examples, level=full
    python -m keystone_tpu.analysis MnistRandomFFT  # one example
    python -m keystone_tpu.analysis --level specs --hbm-budget-gb 16
    python -m keystone_tpu.analysis --audit-operators   # registry-wide KP5xx
    python -m keystone_tpu.analysis --audit-operators --json
    python -m keystone_tpu.analysis --audit-kernels     # KP10xx chain-kernel
    python -m keystone_tpu.analysis --audit-kernels --json
    python -m keystone_tpu.analysis --explain-sharding  # per-stage placement
    python -m keystone_tpu.analysis --explain-sharding --json
    python -m keystone_tpu.analysis --explain-sharding --plan --mesh-shape 2x4
    python -m keystone_tpu.analysis --explain-precision # per-stage dtype plan
    python -m keystone_tpu.analysis --explain-precision --json
    python -m keystone_tpu.analysis --explain-roofline  # per-stage flops/bytes
    python -m keystone_tpu.analysis --explain-roofline --json
    python -m keystone_tpu.analysis --explain-unified   # joint decision IR
    python -m keystone_tpu.analysis --explain-unified --json --mesh-shape 2x4
    python -m keystone_tpu.analysis --certify-serving   # KP9xx serving gate
    python -m keystone_tpu.analysis --certify-serving --slo-ms 1500 --json
    python -m keystone_tpu.analysis --list-rules

Exit code 1 if any example produces ERROR-severity findings (or any
finding at all with ``--strict``), or — under ``--audit-operators`` — if
ANY unsuppressed KP5xx contract finding remains anywhere in the
registered operator registry, or — under ``--explain-sharding`` — if any
unsuppressed KP6xx sharding finding remains in any example. Runs
entirely abstractly — no data loads, no device programs execute.

``--explain-sharding`` renders, per example, the propagated per-stage
partition table: spec (analysis/sharding.py's propagation over the
current mesh), per-device bytes (the KP2xx residency divided by each
leaf's shard count), and the priced boundary collective cost (KP601
all-to-all / KP603 all-gather bytes). Run it on a multi-device mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) to see real
shard counts; a 1-device mesh degenerates to whole-value placement.

``--explain-precision`` runs the mixed-precision policy planner
(analysis/precision.py) per example: the rendered table shows each
stage's chosen storage dtype, tolerance (and whether it was declared or
eval_shape-probed), and the boundary bytes the policy saves; KP7xx
findings are linted UNDER the chosen policy and the KP2xx memory model
is re-priced with the decided dtypes (KP703 rows). Exit code 1 on any
unsuppressed WARNING/ERROR KP7xx finding, or when a chosen policy
prices WORSE than the all-f32 default.

``--explain-roofline`` runs the static roofline analyzer
(analysis/roofline.py) per example: every priceable stage's jaxpr-level
FLOP count, stage-at-a-time HBM bytes, arithmetic intensity,
compute-vs-bandwidth classification against the calibrated machine
balance, and predicted seconds (``max(flops/peak_flops,
bytes/peak_bw)``); KP801 Pallas-candidate chains are listed with their
priced fusion speedup. Exit code 1 only on ERROR-severity findings (the
KP8xx tier is advisory — candidates and re-pricings are INFO/WARNING)
or a failed example build.

``--explain-unified`` runs the unified plan optimizer
(analysis/plan_ir.py) per example: one decision IR spanning {placement
family × storage dtype × chunk size × cache point} per stage boundary,
solved jointly in predicted seconds (roofline stage costs +
collective-cost seconds at family flips + per-trip dispatch floors,
recomputation-weighted under chosen cache points) against the
sequential PR-13 composition scored by the same function. Findings are
linted UNDER the chosen plan (KP6xx against the joint placement, KP7xx
against the joint dtypes, KP8xx errors at the chosen chunk). Exit code
1 when a joint plan prices worse than the sequential composition (an
invariant re-assertion — `plan_unified` clamps non-strict wins) or any
WARNING/ERROR finding survives. ``--trace-artifact`` recalibrates the
time model from a live trace's observed span timings.

``--plan`` (with ``--explain-sharding``) additionally runs the sharding
planner (analysis/planner.py) per example: the rendered table compares
chosen vs default placement per stage with the priced boundary-byte
delta, and KP6xx findings are linted UNDER the chosen plan.
``--mesh-shape 2x4`` forces a ('data','model') mesh of that shape over
the local devices — the lint.sh planner audit runs this on 8 forced CPU
devices and asserts planner cost ≤ default on every example (strict <
on at least 2).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import LEVELS, RULES, Severity, validate_graph
from .examples import EXAMPLES, build_example


def _audit_main(args) -> int:
    """Registry-wide operator contract audit (KP5xx): sweep every
    registered Operator/Estimator subclass, not just built pipelines."""
    from .contracts import audit_registry

    findings, stats = audit_registry()
    if args.ignore:
        findings = [(c, d) for c, d in findings if d.rule not in args.ignore]
    if args.json:
        print(json.dumps({
            "audited_classes": stats["classes"],
            "probed_classes": stats["probed"],
            "findings": [
                {
                    "class": cls.__qualname__,
                    "module": cls.__module__,
                    "rule": d.rule,
                    "severity": d.severity.name,
                    "message": d.message,
                }
                for cls, d in findings
            ],
        }, indent=2))
        return 1 if findings else 0
    for cls, d in findings:
        print(f"✗ {cls.__module__}.{cls.__qualname__}: "
              f"[{d.severity.name}] {d.rule} {d.message}")
    mark = "✗" if findings else "✓"
    print(f"{mark} operator contract audit: {stats['classes']} class(es) "
          f"swept ({stats['probed']} probed), {len(findings)} finding(s)")
    return 1 if findings else 0


def _audit_kernels_main(args) -> int:
    """Registry-wide chain-kernel verification audit (KP10xx): sweep
    every example pipeline's lowerable KP801 candidates through the
    static verifier (analysis/kernels.py — coverage, ragged bounds,
    VMEM proof, mask discipline, oracle equivalence). Same
    CI-annotation schema and exit discipline as --audit-operators:
    exit 1 on any unsuppressed KP10xx finding or a broken example."""
    from .kernels import audit_kernels

    names = args.examples or None
    findings, stats = audit_kernels(names)
    if args.ignore:
        findings = [(n, p, d) for n, p, d in findings
                    if d.rule not in args.ignore]
    failed = bool(findings) or bool(stats["build_errors"])
    if args.json:
        print(json.dumps({
            "audited_examples": stats["examples"],
            "verified_lowerings": stats["verified"],
            "total_lowerings": stats["lowerings"],
            "build_errors": stats["build_errors"],
            "suppressed": stats["suppressed"],
            "proofs": [
                {k: v for k, v in p.items() if k != "vertices"}
                for p in stats["proofs"]
            ],
            "findings": [
                {
                    "example": name,
                    "lowering": proof.get("label", ""),
                    "family": proof.get("family"),
                    "rule": d.rule,
                    "severity": d.severity.name,
                    "message": d.message,
                }
                for name, proof, d in findings
            ],
        }, indent=2, default=str))
        return 1 if failed else 0
    for name, ex_err in sorted(stats["build_errors"].items()):
        print(f"✗ {name}: failed to build/verify: {ex_err}")
    for name, proof, d in findings:
        print(f"✗ {name} [{proof.get('family')}] "
              f"{proof.get('label', '')}: [{d.severity.name}] {d.rule} "
              f"{d.message}")
    for s in stats["suppressed"]:
        print(f"  suppressed {s['rule']} on {s['example']}: "
              f"{s['reason']}")
    mark = "✗" if failed else "✓"
    print(f"{mark} chain-kernel verification audit: "
          f"{stats['examples']} example(s) swept, "
          f"{stats['verified']}/{stats['lowerings']} lowering(s) "
          f"statically verified, {len(findings)} finding(s)")
    return 1 if failed else 0


def _parse_mesh_shape(raw):
    """``--mesh-shape 2x4`` → a ('data', 'model') mesh context over the
    first data×model local devices; None means the ambient mesh."""
    if not raw:
        return None
    import jax

    from ..parallel import mesh as meshlib

    try:
        parts = [int(p) for p in raw.lower().split("x")]
    except ValueError:
        parts = []
    if len(parts) != 2 or any(p < 1 for p in parts):
        raise ValueError(f"--mesh-shape must be DATAxMODEL (e.g. 2x4), "
                         f"got {raw!r}")
    n = parts[0] * parts[1]
    devs = jax.devices()
    if len(devs) < n:
        raise ValueError(
            f"--mesh-shape {raw} needs {n} devices, found {len(devs)}")
    return meshlib.make_mesh(
        devs[:n], shape=tuple(parts),
        axis_names=(meshlib.DATA_AXIS, meshlib.MODEL_AXIS))


def _explain_sharding_main(args) -> int:
    """Per-example sharding explanation (KP6xx gate): propagate partition
    specs, scale memory per device, price boundary collectives, and fail
    on any unsuppressed KP6xx finding. With ``--plan`` the sharding
    planner additionally chooses a placement per example; the rendered
    table (and JSON ``planner`` record) compares chosen vs default
    placement and their priced boundary bytes, and findings are computed
    UNDER the chosen plan — so the gate proves the decided placement
    clean, not just the static default."""
    from contextlib import nullcontext

    from ..parallel import mesh as meshlib
    from ..workflow.env import execution_config
    from .memory import memory_pass
    from .planner import format_plan, plan_sharding
    from .propagate import spec_pass
    from .sharding import (
        explain_rows,
        format_explain,
        per_device_pass,
        sharding_pass,
    )
    from . import as_source_spec

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2
    try:
        forced_mesh = _parse_mesh_shape(args.mesh_shape)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2  # usage error, not a findings failure
    mesh_ctx = (meshlib.use_mesh(forced_mesh) if forced_mesh is not None
                else nullcontext())
    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else execution_config().hbm_budget_bytes)

    failed = False
    records = []
    with mesh_ctx:
        mesh = meshlib.current_mesh()
        for name in names:
            try:
                pipeline, source_spec = build_example(name)
                graph = pipeline.graph
                specs, _ = spec_pass(
                    graph, {pipeline.source: as_source_spec(source_spec)})
                splan = None
                plan_choices = None
                if args.plan:
                    splan = plan_sharding(
                        graph, specs, mesh=mesh, hbm_budget_bytes=budget)
                    plan_choices = splan.choices if splan else None
                shardings, diags, boundary = sharding_pass(
                    graph, specs, mesh=mesh, plan=plan_choices)
                est, _ = memory_pass(graph, specs)
                per_dev, pd_diags = per_device_pass(
                    graph, specs, shardings, est, mesh=mesh,
                    hbm_budget_bytes=budget)
                diags = [d for d in diags + pd_diags
                         if d.rule not in set(args.ignore)]
                rows = explain_rows(graph, specs, shardings, boundary,
                                    per_dev)
            except Exception as e:  # a factory bug is a failure, not a crash
                if args.json:
                    records.append({"example": name, "build_error":
                                    f"{type(e).__name__}: {e}"})
                else:
                    print(f"✗ {name}: failed to build/explain: "
                          f"{type(e).__name__}: {e}")
                failed = True
                continue
            failed |= bool(diags)
            if args.json:
                rec = {
                    "example": name,
                    "devices": int(mesh.devices.size),
                    "per_device_peak_bytes": est.per_device_peak_bytes,
                    "stages": rows,
                    "findings": [
                        {"rule": d.rule, "severity": d.severity.name,
                         "anchor": d.anchor, "message": d.message}
                        for d in diags
                    ],
                }
                if splan is not None:
                    rec["planner"] = {
                        "planned_cost_bytes": int(splan.planned_cost_bytes),
                        "default_cost_bytes": int(splan.default_cost_bytes),
                        "savings_bytes": splan.savings_bytes,
                        "improved": splan.improved,
                        "changed_stages": len(splan.changed_vertices()),
                        "stages": splan.rows(graph),
                    }
                elif args.plan:
                    rec["planner"] = None  # nothing to decide (1 device)
                records.append(rec)
            else:
                mark = "✗" if diags else "✓"
                print(f"{mark} {name} (mesh: {int(mesh.devices.size)} "
                      f"device(s), per-device peak ≈ "
                      f"{est.per_device_peak_bytes >> 10} KiB)")
                if splan is not None:
                    print(f"  planner: boundary bytes "
                          f"{int(splan.default_cost_bytes):,} (default) → "
                          f"{int(splan.planned_cost_bytes):,} (chosen), "
                          f"{splan.savings_bytes:,} saved, "
                          f"{len(splan.changed_vertices())} stage(s) "
                          "changed")
                    print("  " + format_plan(splan.rows(graph))
                          .replace("\n", "\n  "))
                else:
                    print("  " + format_explain(rows).replace("\n", "\n  "))
                for d in diags:
                    print(f"    {d}")
    if args.json:
        print(json.dumps({
            "devices": int(mesh.devices.size),
            "examples": records,
        }, indent=2))
    return 1 if failed else 0


def _explain_precision_main(args) -> int:
    """Per-example precision explanation (KP7xx gate): run the
    mixed-precision planner over each example's raw stage graph, render
    the per-stage chosen dtype / bytes-saved / tolerance-source table,
    lint the chosen policy (KP701/KP702), and re-price the KP2xx memory
    model under the decided dtypes (KP703 rows). Fails on any
    WARNING/ERROR KP7xx finding — the decided dtypes are proven clean,
    not just the reference. (``planned ≤ default`` is an invariant of
    ``plan_precision`` — it clamps to the all-f32 default on any
    non-strict win — but the gate re-asserts it here so a planner
    regression fails the audit instead of shipping silently.)"""
    from . import as_source_spec
    from .precision import (
        format_plan,
        plan_precision,
        precision_pass,
        reprice_memory,
    )
    from .propagate import spec_pass

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2

    failed = False
    records = []
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            graph = pipeline.graph
            specs, _ = spec_pass(
                graph, {pipeline.source: as_source_spec(source_spec)})
            pplan = plan_precision(graph, specs)
            diags = []
            repriced = None
            if pplan is not None:
                diags = precision_pass(graph, specs, pplan)
                est0, est1, kp703 = reprice_memory(graph, specs, pplan)
                diags.extend(kp703)
                repriced = {
                    "peak_bytes_default": int(est0.peak_bytes),
                    "peak_bytes_planned": int(est1.peak_bytes),
                }
            diags = [d for d in diags if d.rule not in set(args.ignore)]
            gate = [d for d in diags if d.severity >= Severity.WARNING]
        except Exception as e:  # a factory bug is a failure, not a crash
            if args.json:
                records.append({"example": name, "build_error":
                                f"{type(e).__name__}: {e}"})
            else:
                print(f"✗ {name}: failed to build/explain: "
                      f"{type(e).__name__}: {e}")
            failed = True
            continue
        # invariant re-assertion, not a reachable decision branch:
        # plan_precision clamps any non-strict win to the all-f32
        # default, so `over` only fires if that clamp regresses
        over = (pplan is not None
                and pplan.planned_cost_bytes > pplan.default_cost_bytes)
        failed |= bool(gate) or over
        if args.json:
            rec = {"example": name, "findings": [
                {"rule": d.rule, "severity": d.severity.name,
                 "anchor": d.anchor, "message": d.message}
                for d in diags
            ]}
            if pplan is not None:
                rec["planner"] = {
                    "planned_cost_bytes": int(pplan.planned_cost_bytes),
                    "default_cost_bytes": int(pplan.default_cost_bytes),
                    "savings_bytes": pplan.savings_bytes,
                    "improved": pplan.improved,
                    "changed_stages": len(pplan.changed_vertices()),
                    "stages": pplan.rows(graph, specs),
                }
                if repriced:
                    rec["planner"]["memory"] = repriced
            else:
                rec["planner"] = None  # nothing to decide
            records.append(rec)
        else:
            mark = "✗" if (gate or over) else "✓"
            if pplan is None:
                print(f"{mark} {name}: no tolerant float boundary — "
                      "policy stays all-f32")
                continue
            print(f"{mark} {name}: boundary bytes "
                  f"{int(pplan.default_cost_bytes):,} (f32) → "
                  f"{int(pplan.planned_cost_bytes):,} (chosen), "
                  f"{pplan.savings_bytes:,} saved, "
                  f"{len(pplan.changed_vertices())} stage(s) reduced")
            print("  " + format_plan(pplan.rows(graph, specs))
                  .replace("\n", "\n  "))
            for d in diags:
                if d.severity >= Severity.WARNING or args.strict:
                    print(f"    {d}")
    if args.json:
        print(json.dumps({"examples": records}, indent=2))
    return 1 if failed else 0


def _explain_roofline_main(args) -> int:
    """Per-example roofline explanation (KP8xx): price every stage's
    FLOPs/bytes/intensity/predicted-seconds against the calibrated
    machine balance and list the KP801 Pallas-candidate chains. The
    tier is advisory — the gate fails only on ERROR findings (none are
    currently emitted) or a broken example build, but the lint.sh
    audit additionally asserts the candidate list is non-empty (the
    Pallas megakernel backend needs a statically identified target)."""
    from .propagate import spec_pass
    from .roofline import format_roofline, roofline_pass
    from . import as_source_spec

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2

    failed = False
    records = []
    machine = None
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            graph = pipeline.graph
            specs, _ = spec_pass(
                graph, {pipeline.source: as_source_spec(source_spec)})
            est, diags = roofline_pass(graph, specs)
            machine = est.machine
            diags = [d for d in diags if d.rule not in set(args.ignore)]
            gate = [d for d in diags if d.severity >= Severity.ERROR]
            rows = est.rows(graph)
        except Exception as e:  # a factory bug is a failure, not a crash
            if args.json:
                records.append({"example": name, "build_error":
                                f"{type(e).__name__}: {e}"})
            else:
                print(f"✗ {name}: failed to build/explain: "
                      f"{type(e).__name__}: {e}")
            failed = True
            continue
        failed |= bool(gate)
        if args.json:
            records.append({
                "example": name,
                "plan_predicted_seconds": est.plan_seconds,
                "unpriced_stages": est.unknown_stages,
                "stages": rows,
                "candidates": [
                    {**c, "vertices": [v.id for v in c["vertices"]]}
                    for c in est.candidates
                ],
                "findings": [
                    {"rule": d.rule, "severity": d.severity.name,
                     "anchor": d.anchor, "message": d.message}
                    for d in diags
                ],
            })
        else:
            mark = "✗" if gate else "✓"
            print(f"{mark} {name}: {len(rows)} priced stage(s), "
                  f"≈{est.plan_seconds:.3e}s predicted, "
                  f"{len(est.candidates)} pallas candidate(s)"
                  + (f", {est.unknown_stages} unpriced"
                     if est.unknown_stages else ""))
            if rows:
                print("  " + format_roofline(rows).replace("\n", "\n  "))
            for d in diags:
                if d.severity >= Severity.WARNING or args.strict:
                    print(f"    {d}")
    if args.json:
        print(json.dumps({
            "machine": {
                "peak_flops": machine.peak_flops,
                "peak_bw": machine.peak_bw,
                "balance": machine.balance,
            } if machine is not None else None,
            "examples": records,
        }, indent=2))
    return 1 if failed else 0


def _explain_unified_main(args) -> int:
    """Per-example unified-plan explanation (the joint-decision gate):
    run the unified plan optimizer (`analysis.plan_ir`) over each
    example's stage graph — placement × dtype × chunk × cache solved
    jointly in predicted seconds — and render joint-vs-sequential
    scores, the chosen axes, and the findings UNDER the chosen plan:
    KP6xx linted against the joint placement, KP7xx against the joint
    dtype policies, KP8xx roofline errors at the chosen chunk. Exit 1
    when any example's joint plan prices WORSE than the sequential
    composition (the ≤ invariant is re-asserted so a solver regression
    fails the audit) or any unsuppressed WARNING/ERROR finding remains
    under a chosen plan. ``--trace-artifact <path>`` recalibrates the
    time model's peaks from a live trace
    (`reconcile.drift_cost_weights`)."""
    from contextlib import nullcontext

    from ..parallel import mesh as meshlib
    from ..workflow.env import execution_config
    from . import as_source_spec
    from .memory import memory_pass
    from .plan_ir import format_plan, plan_unified
    from .precision import precision_pass
    from .propagate import spec_pass
    from .roofline import roofline_pass
    from .sharding import per_device_pass, sharding_pass

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2
    try:
        forced_mesh = _parse_mesh_shape(args.mesh_shape)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    weights = None
    if getattr(args, "trace_artifact", None):
        import json as _json

        from .reconcile import drift_cost_weights

        with open(args.trace_artifact) as f:
            weights = drift_cost_weights(_json.load(f))
    mesh_ctx = (meshlib.use_mesh(forced_mesh) if forced_mesh is not None
                else nullcontext())
    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else execution_config().hbm_budget_bytes)

    failed = False
    records = []
    with mesh_ctx:
        mesh = meshlib.current_mesh()
        for name in names:
            try:
                pipeline, source_spec = build_example(name)
                graph = pipeline.graph
                specs, _ = spec_pass(
                    graph, {pipeline.source: as_source_spec(source_spec)})
                uplan = plan_unified(
                    graph, specs, mesh=mesh, hbm_budget_bytes=budget,
                    weights=weights)
                diags = []
                if uplan is not None:
                    plan_choices = (uplan.sharding.choices
                                    if uplan.sharding else None)
                    shardings, s_diags, _ = sharding_pass(
                        graph, specs, mesh=mesh, plan=plan_choices)
                    # the memory gate prices the CHOSEN chunk, not the
                    # config default — the enforced chunking is what
                    # the per-device budget must hold under
                    est, _ = memory_pass(graph, specs,
                                         chunk_rows=uplan.chunk_size)
                    _, pd_diags = per_device_pass(
                        graph, specs, shardings, est, mesh=mesh,
                        hbm_budget_bytes=budget)
                    diags.extend(s_diags)
                    diags.extend(pd_diags)
                    if uplan.boundary_precision is not None:
                        diags.extend(precision_pass(
                            graph, specs, uplan.boundary_precision))
                    _, r_diags = roofline_pass(
                        graph, specs, chunk_rows=uplan.chunk_size)
                    diags.extend(d for d in r_diags
                                 if d.severity >= Severity.ERROR)
                diags = [d for d in diags
                         if d.rule not in set(args.ignore)]
                gate = [d for d in diags
                        if d.severity >= Severity.WARNING]
            except Exception as e:  # a factory bug is a failure
                if args.json:
                    records.append({"example": name, "build_error":
                                    f"{type(e).__name__}: {e}"})
                else:
                    print(f"✗ {name}: failed to build/explain: "
                          f"{type(e).__name__}: {e}")
                failed = True
                continue
            # the ≤ invariant, re-asserted: plan_unified clamps any
            # non-strict win to the sequential composition, so `over`
            # only fires when that clamp regresses
            over = (uplan is not None
                    and uplan.joint_seconds > uplan.sequential_seconds)
            failed |= bool(gate) or over
            if args.json:
                rec = {"example": name, "findings": [
                    {"rule": d.rule, "severity": d.severity.name,
                     "anchor": d.anchor, "message": d.message}
                    for d in diags
                ]}
                if uplan is not None:
                    rec["planner"] = {
                        "joint_seconds": uplan.joint_seconds,
                        "sequential_seconds": uplan.sequential_seconds,
                        "savings_seconds": uplan.savings_seconds,
                        "improved": uplan.improved,
                        "chunk_size": uplan.chunk_size,
                        "sequential_chunk_size": uplan.default_chunk_size,
                        "cache_points": [v.id for v in
                                         uplan.cache_vertices],
                        "changed_kinds": uplan.changed_kinds(),
                        "unpriced_stages": uplan.unpriced_stages,
                        "stages": uplan.rows(graph),
                        "scored_candidates": uplan.scored_candidates,
                    }
                else:
                    rec["planner"] = None  # nothing to decide
                records.append(rec)
            else:
                mark = "✗" if (gate or over) else "✓"
                if uplan is None:
                    print(f"{mark} {name}: nothing to decide (no priced "
                          "stage / no axis with more than one entry)")
                    continue
                print(f"{mark} {name}:")
                print("  " + format_plan(uplan, graph)
                      .replace("\n", "\n  "))
                if uplan.unpriced_stages:
                    print(f"  ({uplan.unpriced_stages} stage(s) "
                          "unpriced — excluded from both sides)")
                for d in diags:
                    if d.severity >= Severity.WARNING or args.strict:
                        print(f"    {d}")
    if args.json:
        print(json.dumps({
            "devices": int(mesh.devices.size),
            "examples": records,
        }, indent=2))
    return 1 if failed else 0


def _certify_serving_main(args) -> int:
    """Per-example serving-readiness certification (KP9xx gate): price
    every example's apply path against a declared envelope (batch
    range + SLO + tenancy) and fail on any unsuppressed KP9xx ERROR.
    Examples that genuinely cannot certify yet carry NAMED suppressions
    (`serving.SERVING_SUPPRESSIONS` — each names the stage and the
    fix), so the audit output states exactly what is uncertified and
    why instead of silently passing. Ingress-declared examples
    (`serving.SERVING_INGRESS`) are certified from their declared
    request boundary, which the rendered certificate names."""
    from .serving import (
        SERVING_SUPPRESSIONS,
        ServingEnvelope,
        certify_example,
        envelope_from_env,
        format_certificate,
    )
    from ..workflow.env import execution_config

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2
    # require_slo=False: this surface certifies unconditionally, so the
    # batch/tenant env refinements are honored without KEYSTONE_SLO_MS
    # (the flags' documented defaults)
    base = envelope_from_env(require_slo=False)
    envelope = ServingEnvelope(
        max_batch=args.max_batch or base.max_batch,
        slo_seconds=(args.slo_ms / 1e3) if args.slo_ms else base.slo_seconds,
        tenants=args.tenants or base.tenants)
    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else execution_config().hbm_budget_bytes)

    failed = False
    records = []
    for name in names:
        try:
            cert, diags = certify_example(
                name, envelope, hbm_budget_bytes=budget, record=True)
        except Exception as e:  # a factory bug is a failure, not a crash
            if args.json:
                records.append({"example": name, "build_error":
                                f"{type(e).__name__}: {e}"})
            else:
                print(f"✗ {name}: failed to build/certify: "
                      f"{type(e).__name__}: {e}")
            failed = True
            continue
        suppressions = dict(SERVING_SUPPRESSIONS.get(name, {}))
        ignored = set(args.ignore)
        gate = [d for d in diags if d.severity >= Severity.ERROR
                and d.rule not in suppressions and d.rule not in ignored]
        suppressed = sorted({d.rule for d in diags
                             if d.severity >= Severity.ERROR
                             and d.rule in suppressions})
        failed |= bool(gate)
        if args.json:
            records.append({
                "example": name,
                "certified": cert.certified,
                "unsuppressed_errors": len(gate),
                "suppressions": {r: suppressions[r] for r in suppressed},
                "certificate": cert.as_record(),
                "findings": [
                    {"rule": d.rule, "severity": d.severity.name,
                     "anchor": d.anchor, "message": d.message}
                    for d in diags
                ],
            })
        else:
            mark = "✗" if gate else "✓"
            verdict = ("certified" if cert.certified else
                       ("uncertified (suppressed: " + ", ".join(suppressed)
                        + ")" if suppressed and not gate else "UNCERTIFIED"))
            print(f"{mark} {name}: {verdict}")
            print("  " + format_certificate(cert).replace("\n", "\n  "))
            for rule in suppressed:
                print(f"    suppressed {rule}: {suppressions[rule]}")
            for d in diags:
                if d.severity >= Severity.WARNING or args.strict:
                    print(f"    {d}")
    if args.json:
        print(json.dumps({
            "envelope": {
                "min_batch": envelope.min_batch,
                "max_batch": envelope.max_batch,
                "slo_seconds": envelope.slo_seconds,
                "tenants": envelope.tenants,
            },
            "examples": records,
        }, indent=2, default=str))
    return 1 if failed else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("examples", nargs="*", metavar="EXAMPLE",
                   help="example names (default: all registered)")
    p.add_argument("--level", choices=LEVELS, default="full")
    p.add_argument("--hbm-budget-gb", type=float, default=None,
                   help="HBM budget for KP201/KP202 (GiB)")
    p.add_argument("--ignore", action="append", default=[], metavar="RULE",
                   help="suppress a rule id (repeatable)")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings too")
    p.add_argument("--audit-operators", action="store_true",
                   help="sweep EVERY registered Operator/Estimator subclass "
                        "for KP5xx contract violations (zero tolerated)")
    p.add_argument("--audit-kernels", action="store_true",
                   help="statically verify EVERY lowerable KP801 "
                        "chain-kernel candidate across the example "
                        "registry (KP10xx: grid coverage, ragged-tail "
                        "bounds, VMEM working-set proof, mask "
                        "discipline, oracle equivalence); fail on any "
                        "unsuppressed finding")
    p.add_argument("--explain-sharding", action="store_true",
                   help="render each example's per-stage partition table "
                        "(spec, per-device bytes, boundary collective "
                        "cost) and fail on any unsuppressed KP6xx finding")
    p.add_argument("--explain-precision", action="store_true",
                   help="run the mixed-precision policy planner per "
                        "example and render the per-stage chosen dtype / "
                        "bytes-saved / tolerance-source table; fail on "
                        "any unsuppressed WARNING/ERROR KP7xx finding "
                        "(planner ≤ all-f32 bytes is re-asserted as an "
                        "invariant)")
    p.add_argument("--explain-roofline", action="store_true",
                   help="run the static roofline analyzer per example "
                        "and render the per-stage flops / HBM bytes / "
                        "intensity / bound / predicted-seconds table "
                        "plus the KP801 Pallas-candidate chains; fail "
                        "only on ERROR-severity KP8xx findings")
    p.add_argument("--explain-unified", action="store_true",
                   help="run the unified plan optimizer per example "
                        "(placement x dtype x chunk x cache solved "
                        "jointly in predicted seconds) and render "
                        "joint-vs-sequential scores with findings "
                        "linted UNDER the chosen plan; fail when the "
                        "joint plan prices worse than the sequential "
                        "composition or any WARNING/ERROR "
                        "KP6xx/KP7xx/KP8xx finding remains")
    p.add_argument("--trace-artifact", default=None, metavar="TRACE",
                   help="with --explain-unified: recalibrate the time "
                        "model's peaks from this trace's observed span "
                        "timings (reconcile.drift_cost_weights)")
    p.add_argument("--certify-serving", action="store_true",
                   help="run the KP9xx serving-readiness certifier per "
                        "example (per-shape latency bounds vs the SLO, "
                        "warmup-manifest coverage, host/donation/tenancy "
                        "checks); fail on any unsuppressed KP9xx ERROR")
    p.add_argument("--slo-ms", type=float, default=None,
                   help="serving SLO in milliseconds for "
                        "--certify-serving (default: KEYSTONE_SLO_MS or "
                        "1000)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="largest coalesced request batch the envelope "
                        "certifies (default: KEYSTONE_SERVING_MAX_BATCH "
                        "or 64)")
    p.add_argument("--tenants", type=int, default=None,
                   help="concurrent warmed pipelines sharing the device "
                        "(KP905; default 1)")
    p.add_argument("--plan", action="store_true",
                   help="with --explain-sharding: run the sharding "
                        "planner per example and render chosen-vs-default "
                        "placement with priced savings; findings are "
                        "linted under the CHOSEN plan")
    p.add_argument("--mesh-shape", default=None, metavar="DATAxMODEL",
                   help="force a ('data','model') mesh of this shape "
                        "(e.g. 2x4) over the local devices for "
                        "--explain-sharding")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (CI annotation)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    if args.audit_operators:
        return _audit_main(args)

    if args.audit_kernels:
        return _audit_kernels_main(args)

    if args.explain_sharding:
        return _explain_sharding_main(args)

    if args.explain_precision:
        return _explain_precision_main(args)

    if args.explain_roofline:
        return _explain_roofline_main(args)

    if args.explain_unified:
        return _explain_unified_main(args)

    if args.certify_serving:
        return _certify_serving_main(args)

    names = args.examples or sorted(EXAMPLES)
    unknown = [n for n in names if n not in EXAMPLES]
    if unknown:
        print(f"unknown example(s): {', '.join(unknown)}; "
              f"known: {', '.join(sorted(EXAMPLES))}", file=sys.stderr)
        return 2

    budget = (int(args.hbm_budget_gb * (1 << 30))
              if args.hbm_budget_gb else None)
    failed = False
    records = []
    for name in names:
        try:
            pipeline, source_spec = build_example(name)
            report = pipeline.validate(
                source_spec, level=args.level, ignore=args.ignore,
                hbm_budget_bytes=budget, raise_on_error=False)
        except Exception as e:  # a factory bug is a failure, not a crash
            if args.json:
                records.append({"example": name, "build_error":
                                f"{type(e).__name__}: {e}"})
            else:
                print(f"✗ {name}: failed to build/validate: "
                      f"{type(e).__name__}: {e}")
            failed = True
            continue
        bad = bool(report.errors) or (args.strict and report.warnings)
        if args.json:
            records.append({
                "example": name,
                "errors": len(report.errors),
                "warnings": len(report.warnings),
                "diagnostics": [
                    {"rule": d.rule, "severity": d.severity.name,
                     "anchor": d.anchor, "message": d.message}
                    for d in report.diagnostics
                ],
            })
        else:
            mark = "✗" if bad else "✓"
            print(f"{mark} {name}: {len(report.errors)} error(s), "
                  f"{len(report.warnings)} warning(s)"
                  + (f", peak ≈ {report.memory.peak_bytes >> 20} MiB"
                     if report.memory and report.memory.peak_bytes else ""))
            for d in report.diagnostics:
                if d.severity >= Severity.WARNING or args.strict:
                    print(f"    {d}")
        failed |= bad
    if args.json:
        print(json.dumps({"examples": records}, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
