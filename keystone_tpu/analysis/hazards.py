"""Donation and streaming hazard detection.

Three hazards introduced (or made dangerous) by the PR-1 overlap engine:

  - **Donation reuse (KP301, error).** An operator that declares
    ``donates_deps = (i, ...)`` hands dependency ``i``'s forced buffer
    to XLA for in-place reuse (`donate_argnums`). If the producing
    vertex is still reachable by any *other* consumer or sink, that
    consumer would read a deleted buffer — a crash (or garbage) deep
    into the run. Statically: every donated dependency's producer must
    have exactly one user.
  - **Silent stream materialization (KP302, warning).** A
    stream-producing stage feeding a non-chunkable operator forces the
    whole stage to assemble in memory — correct, but it silently
    forfeits the overlap win and the O(chunk) memory bound the producer
    was written for.
  - **Cache on a streaming stage (KP303, warning).** Cache/autocache
    nodes (``saveable`` transformers) pin their input's full value; on
    a streaming stage this materializes the stream at the cache point.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..workflow.graph import Graph, GraphId, NodeId, SinkId
from .diagnostics import Diagnostic, Severity
from .memory import _may_stream
from .propagate import _label
from .specs import DataSpec


def _is_cache_node(op) -> bool:
    from ..workflow.operators import TransformerOperator

    return isinstance(op, TransformerOperator) and getattr(op, "saveable", False)


def hazard_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    overlap: bool = True,
) -> List[Diagnostic]:
    diags: List[Diagnostic] = []

    for node in sorted(graph.operators, key=lambda n: n.id):
        op = graph.get_operator(node)
        deps = graph.get_dependencies(node)
        label = _label(graph, node)

        # --- KP301: donated dependency still reachable elsewhere
        for i in getattr(op, "donates_deps", ()) or ():
            if i >= len(deps):
                diags.append(Diagnostic(
                    "KP002", Severity.ERROR,
                    f"donates_deps index {i} out of range for "
                    f"{len(deps)} dependency(ies)",
                    vertex=node, label=label))
                continue
            producer = deps[i]
            others = [u for u in graph.users_of(producer) if u != node]
            # the donating node itself re-reading the producer at another
            # dependency index is the same read-after-donation hazard
            # (duplicated deps are real: CSE-merged gather branches)
            self_dups = [j for j, d in enumerate(deps)
                         if d == producer and j != i]
            if others or self_dups:
                names = ", ".join(
                    [f"{_label(graph, u)}@{u}" for u in others]
                    + [f"this node's dependency index {j}"
                       for j in self_dups])
                diags.append(Diagnostic(
                    "KP301", Severity.ERROR,
                    f"dependency {i} ({_label(graph, producer)}@{producer}) "
                    f"is donated by this node but still consumed by {names}; "
                    "the donated buffer would be read after XLA reuses it",
                    vertex=node, label=label))

        if not overlap:
            continue

        # Streaming hazards key on whether the *input* stage streams.
        for d in deps:
            if not isinstance(d, NodeId):
                continue
            dep_spec = specs.get(d)
            dep_streams = (
                isinstance(dep_spec, DataSpec) and dep_spec.streaming
            ) or _is_stream_origin(graph.get_operator(d))
            if not dep_streams:
                continue
            if _is_cache_node(op):
                diags.append(Diagnostic(
                    "KP303", Severity.WARNING,
                    f"cache node pins the full value of streaming stage "
                    f"{_label(graph, d)}@{d}; the stream materializes here "
                    "and downstream overlap is lost",
                    vertex=node, label=label))
            elif _is_materializing_transformer(op):
                diags.append(Diagnostic(
                    "KP302", Severity.WARNING,
                    f"non-chunkable operator consumes streaming stage "
                    f"{_label(graph, d)}@{d}: the stream silently "
                    "materializes (set `chunkable = True` if the batch "
                    "path distributes over chunks)",
                    vertex=node, label=label))
    return diags


def _is_materializing_transformer(op) -> bool:
    """A transformer stage that would materialize an incoming stream —
    neither chunk-passthrough nor a stream producer itself. Estimators
    and delegates are excluded: an estimator *must* see the whole
    dataset (materialization is semantic, not silent), and a delegate's
    chunk capability depends on the fitted transformer, which does not
    exist statically."""
    from ..workflow.operators import TransformerOperator

    return (
        isinstance(op, TransformerOperator)
        and not getattr(op, "chunkable", False)
        and not _may_stream(op)
    )


def _is_stream_origin(op) -> bool:
    """Operators that *produce* a chunk stream themselves (overridden
    streaming batch path), as opposed to passing chunks through."""
    from ..workflow.pipeline import Transformer

    fn = getattr(type(op), "apply_batch_stream", None)
    return fn is not None and fn is not Transformer.apply_batch_stream


def megafusion_pass(graph: Graph) -> List[Diagnostic]:
    """KP401 (info): why this plan cannot collapse to ONE XLA program.

    Simulates the optimizer's node-fusion pass (a pure, data-free graph
    rewrite) and asks `workflow.fusion_rule.megafusion_blockers` which
    remaining stages interrupt an otherwise-fusable chain — fan-out,
    host-code stages, stream origins, unfusable estimator fits. Those
    plans fall back cleanly to the per-program dispatch path at run
    time; this pass is how ``validate()`` says why."""
    try:
        from ..workflow.fusion_rule import megafusion_blockers

        blockers = megafusion_blockers(graph)
    except Exception:
        return []  # diagnosis must never break validation
    return [
        Diagnostic(
            "KP401", Severity.INFO,
            f"megafusion fallback: {reason}",
            vertex=vid, label=label)
        for vid, label, reason in blockers
    ]
