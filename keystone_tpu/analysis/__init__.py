"""Static pipeline analyzer — verify pipelines abstractly, before any
data loads.

KeystoneML's optimizer reasons about the whole DAG before execution; this
package extends that discipline from topology to *semantics*: abstract
shape/dtype propagation (`jax.eval_shape` traces, zero data movement),
static memory estimation against an HBM budget, and donation/streaming
hazard lints. A shape mismatch, HBM blowup, or donated-buffer aliasing
bug fails in milliseconds here instead of minutes into a TPU job.

Entry points:

  - ``Pipeline.validate(source_spec, level=...)`` — the user-facing API.
  - ``validate_graph(graph, source_specs, ...)`` — the graph-level core.
  - ``python -m keystone_tpu.analysis`` — CLI validating every example
    pipeline in `keystone_tpu/pipelines/` with synthetic specs.
  - `GraphExecutor` runs the structural tier automatically before the
    first force.

Levels are cumulative: ``"structure"`` (topology lints only) ⊂
``"specs"`` (+ shape/dtype propagation) ⊂ ``"memory"`` (+ live-memory
estimates) ⊂ ``"full"`` (+ donation/streaming hazards). Rule ids and the
suppression story are documented in ANALYSIS.md.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional

from .diagnostics import (
    RULES,
    Diagnostic,
    PipelineValidationError,
    Severity,
    ValidationReport,
)
from .contracts import audit_operator, audit_registry, contract_pass
from .effects import class_effects, interference_pass, operator_effects
from .hazards import hazard_pass
from .kernels import (
    audit_kernels,
    batcher_pad_targets,
    kernel_pass,
    statically_verified,
    verify_lowering,
)
from .memory import (
    DEFAULT_CHUNK_ROWS,
    MemoryEstimate,
    memory_pass,
    resolve_chunk_rows,
)
from .propagate import spec_pass, structural_pass, toposort
from .sharding import (
    PartitionRule,
    ShardedValue,
    ShardingResult,
    fit_sharding_demands,
    per_device_pass,
    sharding_pass,
)
from .plan_ir import UnifiedPlan, plan_unified
from .planner import ShardingPlan, plan_sharding
from .roofline import (
    Machine,
    RooflineEstimate,
    StageRoofline,
    default_machine,
    jaxpr_counts,
    roofline_pass,
    stage_cost,
    xla_cost_analysis,
)
from .precision import (
    PrecisionPlan,
    plan_precision,
    precision_pass,
    reprice_memory,
    shrink_to_band,
)
from .serving import (
    ServingCertificate,
    ServingEnvelope,
    certify_example,
    envelope_from_env,
    ladder_shapes,
    serving_pass,
    warmup_manifest,
)
from .specs import (
    UNKNOWN,
    DataSpec,
    SpecDataset,
    SpecMismatchError,
    TransformerSpec,
    as_source_spec,
    element_nbytes,
    shape_struct,
    spec_of,
)

LEVELS = ("structure", "specs", "memory", "full")


def validate_graph(
    graph,
    source_specs: Optional[Dict] = None,
    *,
    level: str = "full",
    ignore: Iterable[str] = (),
    hbm_budget_bytes: Optional[int] = None,
    chunk_rows: Optional[int] = None,
    partition_rules: Iterable = (),
    serving=None,
) -> ValidationReport:
    """Run the analyzer tiers up to ``level`` over a lowered graph.

    ``source_specs`` maps each unbound `SourceId` to its abstract input
    spec (anything `as_source_spec` accepts); unlisted sources propagate
    UNKNOWN. ``partition_rules`` (level="full") are declarative
    `sharding.PartitionRule`s / ``(regex, PartitionSpec)`` pairs pinning
    per-stage placement. ``serving`` (level="full") is a
    `serving.ServingEnvelope` arming the KP9xx serving-readiness
    certifier — None falls back to the env-declared envelope
    (``KEYSTONE_SLO_MS``), and with neither the serving tier is
    skipped; the certificate lands on ``report.serving``. Never touches
    data or devices."""
    if level not in LEVELS:
        raise ValueError(f"level must be one of {LEVELS}, got {level!r}")
    tier = LEVELS.index(level)

    diags = list(structural_pass(graph))
    specs: Dict = {}
    memory: Optional[MemoryEstimate] = None
    shardings: Dict = {}
    roofline = None

    if tier >= 1:
        normalized = {
            src: as_source_spec(s) for src, s in (source_specs or {}).items()
        }
        specs, spec_diags = spec_pass(graph, normalized)
        # toposort cycle errors already reported by the structural pass
        diags.extend(d for d in spec_diags if d.rule != "KP001")
    if tier >= 2:
        memory, mem_diags = memory_pass(
            graph, specs, hbm_budget_bytes=hbm_budget_bytes,
            chunk_rows=chunk_rows)
        diags.extend(mem_diags)
    if tier >= 3:
        from ..workflow.env import execution_config

        cfg = execution_config()
        diags.extend(hazard_pass(graph, specs, overlap=cfg.overlap))
        if cfg.megafusion:
            from .hazards import megafusion_pass

            diags.extend(megafusion_pass(graph))
        # contract tier: per-operator KP5xx audit over this graph's
        # instances (the registry-wide sweep is `contracts.audit_registry`
        # / the --audit-operators CLI)
        from .contracts import contract_pass

        diags.extend(contract_pass(graph, specs))
        if cfg.concurrent_dispatch:
            # KP511 only matters while the concurrent scheduler can
            # actually force unordered vertices simultaneously
            from .effects import interference_pass

            diags.extend(interference_pass(graph))
        # sharding tier: partition-spec propagation + collective lints
        # (KP601-604) + the per-device memory model. KP600 REPLACES the
        # whole-fleet KP202 budget check here: once placement is known,
        # "peak live set vs budget" is a per-chip question — the fleet
        # sum is not what any device's allocator sees.
        from .sharding import per_device_pass, sharding_pass

        shardings, shard_diags, _ = sharding_pass(
            graph, specs, rules=partition_rules)
        diags.extend(shard_diags)
        if memory is not None:
            budget = hbm_budget_bytes
            if budget is None:
                budget = cfg.hbm_budget_bytes
            _, pd_diags = per_device_pass(
                graph, specs, shardings, memory,
                hbm_budget_bytes=budget)
            # the per-device check supersedes the whole-fleet one: a
            # fleet sum over budget while every chip is under is not a
            # violation, and a chip over budget is KP600's finding
            diags = [d for d in diags if d.rule != "KP202"] + pd_diags
        # roofline tier (KP8xx): jaxpr-level FLOP/byte pricing and the
        # time-domain cost model — the compute half of the cost model
        # the KP2xx/KP6xx/KP7xx byte tiers were missing
        roofline, roof_diags = roofline_pass(graph, specs,
                                             chunk_rows=chunk_rows)
        diags.extend(roof_diags)
        # kernel verification tier (KP10xx): prove every lowerable
        # KP801 candidate's chain-kernel geometry safe from the
        # propagated element specs — coverage, ragged bounds, VMEM,
        # mask discipline, oracle equivalence — before any TPU time
        from .kernels import kernel_pass

        _, kern_diags = kernel_pass(graph, specs, roofline)
        diags.extend(kern_diags)

    serving_cert = None
    if tier >= 3:
        # serving tier (KP9xx): only when an envelope is declared — the
        # serving-readiness certificate is a contract check against a
        # stated envelope, not an unconditional lint
        envelope = serving if serving is not None else envelope_from_env()
        if envelope is not None:
            serving_cert, serve_diags = serving_pass(
                graph, specs, envelope, memory=memory, roofline=roofline,
                hbm_budget_bytes=hbm_budget_bytes, chunk_rows=chunk_rows)
            diags.extend(serve_diags)

    report = ValidationReport(diags, specs=specs, memory=memory,
                              level=level, shardings=shardings,
                              roofline=roofline, serving=serving_cert)
    return report.filter(ignore) if ignore else report


def structural_report(graph) -> ValidationReport:
    """Structure tier only — the cheap O(V+E) gate `GraphExecutor` runs
    before the first force."""
    return ValidationReport(structural_pass(graph), level="structure")


__all__ = [
    "DEFAULT_CHUNK_ROWS",
    "DataSpec",
    "Diagnostic",
    "LEVELS",
    "MemoryEstimate",
    "PartitionRule",
    "PipelineValidationError",
    "RULES",
    "Severity",
    "ShardedValue",
    "ShardingPlan",
    "UnifiedPlan",
    "ShardingResult",
    "SpecDataset",
    "SpecMismatchError",
    "TransformerSpec",
    "UNKNOWN",
    "ValidationReport",
    "as_source_spec",
    "audit_kernels",
    "audit_operator",
    "audit_registry",
    "batcher_pad_targets",
    "kernel_pass",
    "statically_verified",
    "verify_lowering",
    "class_effects",
    "contract_pass",
    "element_nbytes",
    "fit_sharding_demands",
    "hazard_pass",
    "interference_pass",
    "operator_effects",
    "jaxpr_counts",
    "Machine",
    "memory_pass",
    "per_device_pass",
    "plan_precision",
    "plan_sharding",
    "plan_unified",
    "precision_pass",
    "PrecisionPlan",
    "reprice_memory",
    "shrink_to_band",
    "resolve_chunk_rows",
    "roofline_pass",
    "RooflineEstimate",
    "ServingCertificate",
    "ServingEnvelope",
    "StageRoofline",
    "certify_example",
    "envelope_from_env",
    "ladder_shapes",
    "serving_pass",
    "warmup_manifest",
    "default_machine",
    "stage_cost",
    "xla_cost_analysis",
    "sharding_pass",
    "shape_struct",
    "spec_of",
    "spec_pass",
    "structural_pass",
    "structural_report",
    "toposort",
    "validate_graph",
]
