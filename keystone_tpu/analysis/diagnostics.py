"""Diagnostics emitted by the static pipeline analyzer.

Every finding is a `Diagnostic` with a stable rule id (documented in
ANALYSIS.md), a severity, and the graph vertex it anchors to. Diagnostics
key on ``{operator.label}@{vertex}`` — operator labels are audited to be
stable and unique per node (tests/test_analysis.py), so a rule id +
anchor is a reproducible address for suppression and triage.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Iterable, List, Optional, Sequence


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2


#: rule id -> one-line description (ANALYSIS.md holds the full docs).
RULES = {
    # structural tier
    "KP001": "cycle: the graph contains a dependency cycle",
    "KP002": "arity: an operator has the wrong number of dependencies",
    "KP003": "fit-before-use: an estimator's output is consumed as data",
    "KP004": "delegate-without-estimator: a DelegatingOperator's first "
             "dependency does not produce a transformer",
    "KP005": "dangling-source: a source has no consumers",
    # spec tier
    "KP101": "shape-mismatch: abstract tracing proved a stage cannot run "
             "on its input shapes/dtypes",
    "KP102": "count-mismatch: sibling datasets disagree on example count",
    # memory tier
    "KP201": "node-hbm: one node's materialized output exceeds the HBM budget",
    "KP202": "peak-hbm: peak live memory across the schedule exceeds the "
             "HBM budget",
    "KP203": "overlap-amplification: prefetch depth multiplies a streaming "
             "stage's resident footprint",
    "KP204": "megafused-scan-live-set: the in-program chunk loop's per-trip "
             "carry rides on top of stacked-input + output residency",
    # hazard tier
    "KP301": "donation-reuse: a buffer donated by one consumer is still "
             "reachable by another sink",
    "KP302": "stream-materialization: a streaming stage feeds a "
             "non-chunkable operator, silently materializing the stream",
    "KP303": "cache-on-stream: a cache node on a streaming stage "
             "materializes the stream and defeats overlap",
    "KP401": "megafusion-fallback: a stage keeps this plan from collapsing "
             "to one XLA program (fan-out, host code, or a streaming "
             "origin); the per-program dispatch path remains",
    # sharding tier (partition-spec propagation; see analysis/sharding)
    "KP600": "per-device-hbm: peak live memory per device — live-set "
             "residency divided over each leaf's actual shard count — "
             "exceeds the per-device HBM budget",
    "KP601": "implicit-reshard: producer and consumer disagree on a stage "
             "boundary's partition spec; XLA inserts an all-to-all of the "
             "boundary bytes there",
    "KP602": "large-operand-replicated: an array above the replication "
             "threshold is held replicated although a mesh axis could "
             "shard one of its dimensions evenly",
    "KP603": "gather-of-sharded-into-host: a host-code stage consumes "
             "device-sharded data, forcing an all-gather of every shard "
             "onto the host",
    "KP604": "mesh-indivisible-rows: the data-shard count does not divide "
             "the propagated example count, so padded/ragged shards "
             "change per-device shapes (and recompile) across stages",
    "KP605": "invalid-partition-rule: a PartitionRule pins a spec that "
             "cannot apply to the matched stage — more entries than the "
             "value has dimensions, or a mesh axis the current mesh does "
             "not have",
    # precision tier (mixed-precision policy pass; see analysis/precision)
    "KP701": "precision-policy-on-intolerant-stage: a reduced-precision "
             "policy is pinned on a boundary whose producer or consumer "
             "declares (or probes) exact f32/HIGHEST precision",
    "KP702": "cast-thrash: a boundary stores bf16 but every consumer's "
             "boundary is f32 and the halving saves less than the two "
             "convert_element_type casts the flip pair costs",
    "KP703": "dtype-dependent memory re-pricing: a chosen precision "
             "policy changes a stage's static KP2xx residency (bf16 "
             "halves the chosen float boundaries) — informational",
    # roofline tier (jaxpr-level FLOP/byte pricing; see analysis/roofline)
    "KP801": "pallas-candidate: a bandwidth-bound fan-out-free fused "
             "chain of >=2 stages whose internal boundaries round-trip "
             "HBM under stage-at-a-time lowering — a Pallas megakernel "
             "candidate, priced with the boundary bytes the kernel "
             "would keep in VMEM",
    "KP802": "data-movement-dominated stage: pure "
             "transpose/reshape/gather traffic at least the larger of "
             "the stage's compute and its unavoidable boundary bytes — "
             "the stage pays for layout, not math",
    "KP803": "plan-roofline: the whole plan re-priced in predicted "
             "seconds (max(flops/peak_flops, bytes/peak_bw) per stage) "
             "against the calibrated machine balance — informational",
    "KP804": "megafused-scan-underfilled: the in-program chunk loop's "
             "per-trip compute is below the dispatch/loop overhead "
             "floor; the scan cannot amortize its trips — raise "
             "chunk_size",
    "KP805": "chain-kernel-wins: a KP801 candidate lowers to one "
             "double-buffered Pallas megakernel (ops/chain_kernels) "
             "whose predicted seconds beat the XLA chain — the unified "
             "planner's kernel axis should pick it up — informational",
    # kernel verification tier (static chain-kernel proofs; see
    # analysis/kernels)
    "KP1001": "kernel-grid-coverage: a chain-kernel lowering's grid × "
              "block shape does not tile the padded output exactly — a "
              "double-write, gap, or out-of-bounds write in the "
              "index-map coverage proof",
    "KP1002": "kernel-ragged-bounds: a chain-kernel block read escapes "
              "the padded operand shapes for a batch count the host "
              "batcher's pad ladder can emit (checked against "
              "utils/batching's actual pad targets)",
    "KP1003": "kernel-vmem-proof: the chain kernel's working set (2x "
              "double-buffered streamed blocks + intermediates + "
              "closure params, the SAME chain_vmem_bytes arithmetic "
              "the runtime chooser uses) exceeds the VMEM budget, or "
              "the static choice diverges from chain_feasible",
    "KP1004": "kernel-mask-discipline: a fuse_masks_output stage inside "
              "a kernel body does not consume the streamed mask operand "
              "at its original chain position — the padded-row "
              "corruption class, detected structurally from "
              "stage_statics",
    "KP1005": "kernel-oracle-equivalence: the per-block kernel body "
              "disagrees with the pure-jnp reference oracle on shape "
              "or dtype at a stage boundary (or does not preserve the "
              "block's batch axis)",
    # serving tier (static serving-readiness certifier; see analysis/serving)
    "KP901": "serving-host-stage: an apply-path stage whose body cannot "
             "be abstractly traced (host code, or no propagated element "
             "spec) — it can neither be AOT-warmed nor enter the "
             "megafused scan, so the one-warm-program serving claim "
             "fails at this stage",
    "KP902": "serving-recompile-exposure: an apply-path device stage "
             "outside every warmable fused program compiles cold at "
             "each pad-ladder shape the envelope can produce (INFO "
             "when the warmup manifest covers every shape)",
    "KP903": "serving-latency-bound: the certified per-shape latency "
             "upper bound (headroom x roofline seconds + per-program "
             "dispatch floors) vs the declared SLO; ERROR when the "
             "worst in-envelope shape busts it, with the dominating "
             "stage named",
    "KP904": "serving-donated-request: an apply-path operator donates "
             "the pipeline's own input buffer — a serving caller "
             "retains the request it passed, so every repeated apply "
             "would read (or force a copy of) a deleted buffer",
    "KP905": "serving-multi-tenant-residency: per-device peak bytes x "
             "declared concurrent warmed pipelines exceeds the HBM "
             "budget — the tenant count the envelope declares cannot "
             "co-reside",
    "KP906": "serving-telemetry-cardinality: an apply-path operator "
             "formats a telemetry metric name dynamically in a hot "
             "method — per-request names grow the process-wide registry "
             "without bound (the graph-level twin of jaxlint KJ012)",
    # contract tier (registry-wide operator audit; see analysis/contracts)
    "KP501": "fusable-without-structural-fuse: a fusable stage's fused "
             "program key is id-keyed (opaque), so fused programs "
             "containing it re-trace on every rebuilt pipeline",
    "KP502": "chunkable-non-distributive: a chunkable-declared batch path "
             "provably does not distribute over host chunks "
             "(f(concat(chunks)) != concat(f(chunks)) under eval_shape)",
    "KP503": "donation-not-implemented: donates_deps is declared but no "
             "reachable jitted step donates its arguments (or the "
             "donate_argnums are mis-indexed against the step signature)",
    "KP504": "unmasked-fused-stage: the unfused batch path masks padded "
             "rows but fuse_masks_output is undeclared — fused programs "
             "would corrupt padded rows",
    # concurrency effect tier (see analysis/effects)
    "KP511": "concurrent-effect-interference: two effectful vertices with "
             "no dependency ordering share mutable state; the concurrent "
             "scheduler may force them simultaneously",
}


@dataclass(frozen=True)
class Diagnostic:
    rule: str
    severity: Severity
    message: str
    vertex: Optional[Any] = None  # GraphId
    label: str = ""

    @property
    def anchor(self) -> str:
        """Stable diagnostic key: ``label@vertex``."""
        if self.vertex is None:
            return self.label or "<graph>"
        return f"{self.label}@{self.vertex}" if self.label else str(self.vertex)

    def __str__(self) -> str:
        return f"[{self.severity.name}] {self.rule} {self.anchor}: {self.message}"


class ValidationReport:
    """The analyzer's result: diagnostics plus (when the spec/memory
    tiers ran) the per-vertex specs and the memory estimate."""

    def __init__(
        self,
        diagnostics: Sequence[Diagnostic],
        specs: Optional[dict] = None,
        memory: Optional[Any] = None,
        level: str = "structure",
        shardings: Optional[dict] = None,
        roofline: Optional[Any] = None,
        serving: Optional[Any] = None,
    ):
        self.diagnostics: List[Diagnostic] = list(diagnostics)
        self.specs = specs or {}
        self.memory = memory
        self.level = level
        #: per-vertex propagated partition specs (analysis/sharding.py);
        #: populated at level="full", empty otherwise
        self.shardings = shardings or {}
        #: the roofline estimate (analysis/roofline.RooflineEstimate —
        #: per-stage flops/bytes/intensity/predicted-seconds);
        #: populated at level="full", None otherwise
        self.roofline = roofline
        #: the serving certificate (analysis/serving.ServingCertificate —
        #: per-shape latency bounds, warmup manifest, verdict); populated
        #: at level="full" when a `ServingEnvelope` is declared (the
        #: ``serving=`` kwarg or ``KEYSTONE_SLO_MS``), None otherwise
        self.serving = serving

    # ------------------------------------------------------------- views

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def filter(self, ignore: Iterable[str]) -> "ValidationReport":
        """Drop diagnostics whose rule id is in ``ignore`` (the
        `validate(ignore=[...])` suppression channel)."""
        ignore = set(ignore)
        return ValidationReport(
            [d for d in self.diagnostics if d.rule not in ignore],
            specs=self.specs, memory=self.memory, level=self.level,
            shardings=self.shardings, roofline=self.roofline,
            serving=self.serving,
        )

    def raise_for_errors(self) -> "ValidationReport":
        if self.errors:
            raise PipelineValidationError(self)
        return self

    def __str__(self) -> str:
        head = (
            f"pipeline validation [{self.level}]: "
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s)"
        )
        if not self.diagnostics:
            return head
        return head + "\n" + "\n".join(f"  {d}" for d in self.diagnostics)

    def __repr__(self) -> str:
        return (
            f"ValidationReport(level={self.level!r}, "
            f"errors={len(self.errors)}, warnings={len(self.warnings)})"
        )


class PipelineValidationError(ValueError):
    """Static validation rejected the pipeline before any data loaded.

    Subclasses ValueError so call sites treating malformed graphs as
    value errors (the pre-analyzer contract) keep working."""

    def __init__(self, report: ValidationReport):
        super().__init__(str(report))
        self.report = report
