"""Static serving-readiness certifier — the KP9xx tier.

The ROADMAP's low-latency serving runtime ("millions of users") needs a
gate before it needs a server: KeystoneML only ever *measured* per-item
latency after the fact (arXiv 1610.09451 §6); this tier *certifies*
serving properties statically, the same budget-as-constraint discipline
arXiv 2206.14148 applies to memory — applied to latency, warmth, and
host synchronization. Given a fitted (or ``analyzable()``) pipeline and
a declared serving envelope (batch range + SLO), the pass proves — or
names the stage that breaks — each leg of the serving claim *before any
traffic arrives*:

  - **KP901 (error)** — an apply-path stage whose body cannot be
    abstractly traced (host code, or no propagated element spec). Such
    a stage can neither be AOT-warmed nor enter the megafused scan, so
    the one-warm-program claim fails there. The fix is named per stage:
    a device-traceable body, or a declared serving-ingress spec
    (`SERVING_INGRESS` — requests enter pre-decoded at a stated
    boundary, seeded through ``spec_pass(seeds=...)``).
  - **KP902** — recompile exposure: every pad-ladder shape the envelope
    can produce (`ladder_shapes`, the exact image of PR 5's
    `utils.batching._pad_target`) is enumerated and checked against the
    warmable program set. Apply-path device stages *outside* every
    warmable fused program compile cold once per shape (WARNING, stages
    named); when the `warmup_manifest` covers everything the finding is
    INFO and states the coverage. The manifest is not advisory: with an
    envelope armed (``KEYSTONE_SLO_MS``), `GraphExecutor._warm_plan`
    consumes the same enumeration and AOT-compiles every ladder shape,
    so warm serving at ANY in-envelope shape performs 0 cold compiles
    (test-pinned in tests/test_serving.py).
  - **KP903** — the static latency bound per ladder shape: the certified
    upper bound is ``BOUND_HEADROOM × Σ roofline.stage_cost`` plus a
    per-program dispatch floor and a per-apply host floor (constants
    below). ERROR when the worst in-envelope shape busts the declared
    SLO, with the dominating stage named; INFO otherwise, carrying the
    whole per-shape table. Each row also reports the *machine bound*
    (raw roofline seconds + the ~50 µs `DISPATCH_OVERHEAD_S` floor per
    program) — the hardware lower envelope the headroom calibrates
    against; `reconcile.reconcile_serving` joins the certified bounds
    against observed `scripts/serving_latency.py` percentiles, and the
    residual is the headroom's recalibration feed.
  - **KP904 (error)** — donation-unsafe repeated apply: an apply-path
    operator that donates the pipeline's own input buffer. A serving
    caller retains the request it passed; donating it makes every
    repeated apply read (or defensively copy) a deleted buffer.
  - **KP905** — multi-tenant residency: per-device peak bytes × the
    envelope's declared concurrent warmed pipelines vs the HBM budget
    (the KP600 per-device model multiplied by tenancy).
  - **KP906 (warning)** — unbounded telemetry cardinality on the apply
    path: an apply-path operator hot method that formats a metric name
    dynamically (the graph-level twin of jaxlint KJ012 — here the check
    runs over the *instantiated* operator classes of this plan, so
    third-party operators are audited too, not just this repo's files).

Surfaces: ``Pipeline.validate(serving=ServingEnvelope(...))`` (or the
``KEYSTONE_SLO_MS`` env arming a default envelope) attaches the
`ServingCertificate` to ``report.serving``; ``python -m
keystone_tpu.analysis --certify-serving [--json]`` certifies every
example; ``scripts/perf_table.py --serving`` renders the markdown
table; the executor embeds ``keystone.serving`` trace metadata and the
ledger records one ``serving_cert`` decision per certification.
Everything here is pure spec arithmetic — no data loads, no device
programs execute.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .diagnostics import Diagnostic, Severity
from .memory import _fmt_bytes, resolve_chunk_rows
from .propagate import _label, toposort
from .roofline import DISPATCH_OVERHEAD_S, roofline_pass
from .specs import DataSpec, is_known, shape_struct

# ------------------------------------------------------------- constants

#: default SLO when an envelope is armed without one (seconds).
DEFAULT_SLO_S = 1.0

#: default micro-batch coalescing window: the largest request batch the
#: serving runtime's pad ladder is certified for when the envelope does
#: not declare one.
DEFAULT_MAX_BATCH = 64

#: roofline-to-certified-bound guardband. The roofline's
#: ``max(flops/peak, bytes/bw)`` is the hardware's *lower* envelope;
#: XLA attains a single-digit percent of the analytic peaks at serving
#: batch sizes, so the certified UPPER bound divides the ideal rates by
#: this attained fraction. `reconcile.reconcile_serving`'s residuals
#: (certified bound − observed p50) are the recalibration feed: a
#: persistently large positive residual means the headroom can shrink.
BOUND_HEADROOM = 48.0

#: per-program floor of the certified bound: device dispatch
#: (`DISPATCH_OVERHEAD_S`) plus the executor's per-program force path
#: (expression wiring, memo lookups, result placement) — the measured
#: CPU-tier order of magnitude, conservative for a warm persistent
#: serving process.
PROGRAM_FLOOR_S = 1e-3

#: per-apply floor: one request's graph-bind + force overhead that no
#: batch size amortizes (`FittedPipeline.apply` builds an executor per
#: request today; the serving runtime's request loop pays an analogous
#: fixed cost). Calibrated against the CPU-tier observed p50 of the
#: gather-shaped dispatch-bench instances (≈8 ms/request for
#: MnistRandomFFT) — `reconcile_serving` residuals are the feed for
#: shrinking it once the serving runtime amortizes the bind.
APPLY_FLOOR_S = 1e-2


# -------------------------------------------------------------- envelope


@dataclass(frozen=True)
class ServingEnvelope:
    """The declared serving contract a certificate is issued against:
    request batches in ``[min_batch, max_batch]`` (coalesced onto the
    PR-5 pad ladder), a latency SLO in seconds, and the number of
    concurrently warmed pipelines sharing the device (KP905)."""

    min_batch: int = 1
    max_batch: int = DEFAULT_MAX_BATCH
    slo_seconds: float = DEFAULT_SLO_S
    tenants: int = 1

    def __post_init__(self):
        if self.min_batch < 1 or self.max_batch < self.min_batch:
            raise ValueError(
                f"batch range [{self.min_batch}, {self.max_batch}] is empty")
        if self.slo_seconds <= 0:
            raise ValueError("slo_seconds must be positive")
        if self.tenants < 1:
            raise ValueError("tenants must be >= 1")


def envelope_from_env(require_slo: bool = True) -> Optional[ServingEnvelope]:
    """The env-declared envelope, or None when serving certification is
    not armed. ``KEYSTONE_SLO_MS`` arms it (the SLO in milliseconds);
    ``KEYSTONE_SERVING_MAX_BATCH`` / ``KEYSTONE_SERVING_TENANTS``
    refine the batch range and tenancy. A malformed value disarms
    rather than breaking validation. ``require_slo=False`` is for
    surfaces that certify unconditionally (``--certify-serving``,
    ``perf_table --serving``): ALWAYS returns an envelope — the
    refinement vars are honored without ``KEYSTONE_SLO_MS``, and
    malformed fields degrade to their defaults."""
    raw = os.environ.get("KEYSTONE_SLO_MS")
    if raw:
        try:
            return ServingEnvelope(
                max_batch=int(os.environ.get(
                    "KEYSTONE_SERVING_MAX_BATCH", str(DEFAULT_MAX_BATCH))),
                slo_seconds=float(raw) / 1e3,
                tenants=int(os.environ.get("KEYSTONE_SERVING_TENANTS", "1")),
            )
        except (TypeError, ValueError):
            if require_slo:
                return None
    if require_slo:
        return None

    def _int(var: str, default: int) -> int:
        try:
            return int(os.environ.get(var, ""))
        except (TypeError, ValueError):
            return default

    try:
        return ServingEnvelope(
            max_batch=_int("KEYSTONE_SERVING_MAX_BATCH", DEFAULT_MAX_BATCH),
            tenants=_int("KEYSTONE_SERVING_TENANTS", 1))
    except ValueError:
        return ServingEnvelope()


def ladder_shapes(envelope: ServingEnvelope,
                  chunk_rows: Optional[int] = None) -> List[int]:
    """Every padded leading dim the envelope can produce — the exact
    image of `utils.batching._pad_target` over the batch range: the
    power-of-two ladder up to the chunk size, then the chunk size
    itself. These are the program shapes warm serving must cover."""
    from ..utils.batching import _pad_target

    chunk = resolve_chunk_rows(chunk_rows)
    lo = max(1, int(envelope.min_batch))
    hi = max(lo, int(envelope.max_batch))
    shapes = {_pad_target(lo, chunk, lo)}
    p = 1 << max(0, lo - 1).bit_length()  # pow-2 ceiling of lo
    while p < min(hi, chunk):
        p <<= 1
        shapes.add(min(chunk, p))
    if hi >= chunk:
        shapes.add(chunk)
    return sorted(shapes)


# ---------------------------------------------------- example registries

#: declared serving-ingress boundaries: examples whose TRAINING source
#: is opaque host objects (labeled images) but whose serving requests
#: are fixed-shape arrays. The named stage's output is seeded with the
#: declared element (``spec_pass(seeds=...)`` — a seed only fills what
#: propagation could not know), so the device apply path downstream of
#: the ingress is priced and certified; the certificate names the
#: boundary it was issued at.
SERVING_INGRESS: Dict[str, Dict[str, Any]] = {
    "VOCSIFTFisher": {
        "stage": "MultiLabeledImageExtractor",
        "shape": (96, 96, 3),
        "dtype": "float32",
        "note": "requests enter as decoded fixed-size images; the "
                "label-extract wrapper is train-time plumbing",
    },
    "ImageNetSiftLcsFV": {
        "stage": "_Image",
        "shape": (64, 64, 3),
        "dtype": "float32",
        "note": "requests enter as decoded fixed-size images; the "
                "label-extract wrapper is train-time plumbing",
    },
}

#: named per-example suppressions for pipelines that genuinely cannot
#: certify yet: rule id -> the stage-level rationale AND the fix. The
#: --certify-serving CLI (and the lint.sh serving audit) treats these
#: findings as acknowledged — every suppression names its reason, so
#: the audit output still says exactly what is uncertified and why.
SERVING_SUPPRESSIONS: Dict[str, Dict[str, str]] = {
    "VOCSIFTFisher": {
        "KP903": "the worst in-envelope shape (batch 64) prices "
                 "≈1.07s against the 1s default SLO — dominated by "
                 "SIFTExtractor (the dense multi-scale descriptor "
                 "grid). Fix: the serving runtime caps this "
                 "pipeline's coalescing window at max_batch 32 "
                 "(every shape ≤32 certifies with ≈2× margin) until "
                 "the Pallas SIFT kernel (ROADMAP) lands; "
                 "--certify-serving --max-batch 32 certifies clean "
                 "today",
    },
    "NewsgroupsPipeline": {
        "KP901": "the NLP front-end (Trim >> LowerCase >> Tokenizer >> "
                 "NGramsFeaturizer >> TermFrequency) is host string code "
                 "by design — it can never enter one XLA program. Fix: "
                 "the serving runtime pre-tokenizes requests at ingress "
                 "and serves the device tail (sparse featurize -> "
                 "classifier); certification of that tail lands with "
                 "the serving-runtime PR's request schema",
    },
}


# ------------------------------------------------------------ apply path


def apply_path(graph: Graph, source: Optional[SourceId] = None,
               sink: Optional[SinkId] = None) -> List[NodeId]:
    """The serving apply path: vertices a request flows through —
    descendants of the pipeline input that reach the sink, in topo
    order. With no unbound source (a bound/fitted graph) every sink
    ancestor is on the path (training branches were pruned at fit)."""
    from ..workflow.analysis import ancestors, descendants

    order, _ = toposort(graph)
    sinks = [sink] if sink is not None else sorted(graph.sink_ids)
    anc: set = set()
    for s in sinks:
        anc |= ancestors(graph, s)
        anc.add(graph.get_sink_dependency(s))
    sources = [source] if source is not None else sorted(graph.sources)
    if sources:
        desc: set = set()
        for s in sources:
            desc |= descendants(graph, s)
        anc &= desc
    return [v for v in order if v in anc and isinstance(v, NodeId)]


def ingress_seeds(graph: Graph, name: Optional[str],
                  count: int = 64) -> Tuple[Dict[NodeId, DataSpec],
                                            Optional[Dict[str, Any]]]:
    """The `SERVING_INGRESS` seed map for one registered example: every
    vertex whose operator label matches the declared ingress stage
    (training-branch copies included — the estimator fits must see the
    same declared element or their `abstract_fit` demands stay
    unknown). Returns ``(seeds, ingress_decl)``; empty for examples
    with no declared ingress."""
    decl = SERVING_INGRESS.get(name or "")
    if not decl:
        return {}, None
    elem = shape_struct(decl["shape"], np.dtype(decl["dtype"]))
    seeds = {
        vid: DataSpec(element=elem, count=count)
        for vid in graph.operators
        if graph.get_operator(vid).label == decl["stage"]
    }
    return seeds, decl


# ------------------------------------------------------- warmup manifest


def _fused_plan(graph: Graph):
    """The fused projection of ``graph`` — the plan whose fused
    operators are the executor's AOT-warmable program sites, simulated
    with the SAME rules the default optimizer runs (node fusion, then
    whole-plan megafusion — which is what absorbs Cacher passthroughs
    and lone fusable stages into one warmable program). Fitted graphs
    already carry `FusedBatchTransformer`s; raw (analyzable) graphs are
    rewritten on a throwaway copy exactly as
    `fusion_rule.megafusion_blockers` does. Never pollutes the ledger:
    no executor will enforce this rewrite."""
    from ..telemetry import ledger
    from ..workflow.env import execution_config
    from ..workflow.fusion_rule import MegafusionRule, NodeFusionRule

    with ledger.suppressed():
        plan = NodeFusionRule().apply((graph, {}))
        if execution_config().megafusion:
            plan = MegafusionRule().apply(plan)
        return plan[0]


def _is_warm_target(op) -> bool:
    from ..nodes.util.fusion import FusedBatchTransformer
    from ..workflow.fusion_rule import FusedChainOperator

    return isinstance(op, (FusedBatchTransformer, FusedChainOperator))


def _manifest_entries(fused: Graph, specs: Dict[GraphId, Any],
                      counts: List[int],
                      path: Optional[set] = None
                      ) -> Tuple[List[Dict[str, Any]], set]:
    """One manifest entry per warmable fused program site whose input
    spec is a known on-device dataset — the SINGLE enumeration behind
    `warmup_manifest()` (the executor-enforced warm contract) and
    KP902's coverage accounting, so the certificate and the enforcement
    can never drift onto different site sets. ``path`` optionally
    restricts to apply-path vertices. Returns ``(entries,
    covered_vertex_ids)``."""
    entries: List[Dict[str, Any]] = []
    covered: set = set()
    for vid in sorted(fused.operators, key=lambda n: n.id):
        op = fused.get_operator(vid)
        if not _is_warm_target(op):
            continue
        if path is not None and vid not in path:
            continue
        deps = fused.get_dependencies(vid)
        if not deps:
            continue
        data_spec = specs.get(deps[-1])
        if not (isinstance(data_spec, DataSpec)
                and data_spec.kind == "dataset"
                and is_known(data_spec.element)):
            continue
        entries.append({
            "vertex": vid.id,
            "label": op.label,
            "element": data_spec.element,
            "counts": list(counts),
        })
        covered.add(vid)
    return entries, covered


def warmup_manifest(
    graph: Graph,
    source_specs: Optional[Dict] = None,
    *,
    envelope: Optional[ServingEnvelope] = None,
    chunk_rows: Optional[int] = None,
    seeds: Optional[Dict[NodeId, DataSpec]] = None,
) -> List[Dict[str, Any]]:
    """The AOT warmup enumeration for an envelope: one entry per
    warmable fused program site with the element spec its programs
    trace from and EVERY pad-ladder count the envelope can produce.
    `GraphExecutor._warm_plan` consumes the same (element × ladder)
    expansion when ``KEYSTONE_SLO_MS`` is armed, so warm serving at any
    in-envelope shape performs zero cold compiles."""
    from .propagate import spec_pass

    envelope = envelope or envelope_from_env() or ServingEnvelope()
    counts = ladder_shapes(envelope, chunk_rows)
    fused = _fused_plan(graph)
    specs, _ = spec_pass(fused, source_specs, seeds=seeds)
    entries, _ = _manifest_entries(fused, specs, counts)
    return entries


# --------------------------------------------------- KP906 (cardinality)

_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})
#: attribute-call receivers that resolve to THIS repo's metrics
#: registry; `np.histogram`/`jnp.histogram` must never match (the same
#: receiver filter jaxlint KJ012 applies).
_METRIC_RECEIVERS = frozenset({"telemetry", "metrics", "registry"})


def _is_metric_factory(func: ast.AST) -> bool:
    """Is this call expression a telemetry metric factory? Bare names
    (``counter(...)`` imported from telemetry, underscore aliases) and
    attribute calls whose receiver is the telemetry module / a
    ``registry()`` call; `np.histogram`-style attribute calls on other
    receivers are not metrics."""
    if isinstance(func, ast.Name):
        return func.id.lstrip("_") in _METRIC_FACTORIES
    if isinstance(func, ast.Attribute):
        if func.attr.lstrip("_") not in _METRIC_FACTORIES:
            return False
        recv = func.value
        if isinstance(recv, ast.Name):
            return recv.id.lstrip("_") in _METRIC_RECEIVERS
        if isinstance(recv, ast.Call) and isinstance(recv.func, ast.Name):
            return recv.func.id.lstrip("_") == "registry"
        return False
    return False


def _dynamic_metric_sites(cls: type) -> List[Tuple[str, int]]:
    """``(method, lineno)`` sites in this operator class's hot methods
    where a telemetry metric factory is called with a non-literal name
    — per-request names mint unbounded registry cardinality (jaxlint
    KJ012 polices this repo's files; this walk covers the operator
    classes a plan actually instantiates, wherever they come from)."""
    from .effects import HOT_METHODS, _class_defn, _suppressed

    defn = _class_defn(cls)
    if defn is None:
        return []
    cls_node, lines = defn
    out: List[Tuple[str, int]] = []
    for fn in cls_node.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name not in HOT_METHODS:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if not _is_metric_factory(func):
                continue
            arg = sub.args[0] if sub.args else None
            if arg is None:
                for kw in sub.keywords:
                    if kw.arg == "name":
                        arg = kw.value
                        break
            if arg is None or (isinstance(arg, ast.Constant)
                               and isinstance(arg.value, str)):
                continue
            if _suppressed(lines, sub.lineno, "KP906"):
                continue
            out.append((fn.name, sub.lineno))
    return out


# ------------------------------------------------------- the certificate


@dataclass
class ServingCertificate:
    """One pipeline's serving verdict: the envelope it was issued
    against, the per-shape certified latency bounds, the warmup
    manifest, and the apply-path accounting the KP9xx findings were
    derived from. ``certified`` means zero ERROR-severity KP9xx
    findings — the pipeline is provably one warm, host-free,
    latency-bounded program over the whole envelope."""

    envelope: ServingEnvelope
    shapes: List[Dict[str, Any]] = field(default_factory=list)
    per_item_seconds: float = 0.0
    programs: int = 0
    priced_stages: int = 0
    unpriced_stages: int = 0
    dominating_stage: Optional[str] = None
    manifest: List[Dict[str, Any]] = field(default_factory=list)
    exposed_stages: List[str] = field(default_factory=list)
    per_device_peak_bytes: Optional[int] = None
    ingress: Optional[Dict[str, Any]] = None
    certified: bool = False

    @property
    def worst_shape(self) -> Optional[Dict[str, Any]]:
        return max(self.shapes, default=None,
                   key=lambda s: s["predicted_seconds"])

    def as_record(self) -> Dict[str, Any]:
        """The JSON / trace-metadata (``keystone.serving``) form — what
        `reconcile.reconcile_serving` joins observed percentiles
        against."""
        return {
            "certified": self.certified,
            "slo_seconds": self.envelope.slo_seconds,
            "min_batch": self.envelope.min_batch,
            "max_batch": self.envelope.max_batch,
            "tenants": self.envelope.tenants,
            "per_item_seconds": self.per_item_seconds,
            "programs": self.programs,
            "priced_stages": self.priced_stages,
            "unpriced_stages": self.unpriced_stages,
            "dominating_stage": self.dominating_stage,
            "exposed_stages": list(self.exposed_stages),
            "per_device_peak_bytes": self.per_device_peak_bytes,
            "ingress": dict(self.ingress) if self.ingress else None,
            "shapes": [dict(s) for s in self.shapes],
            "warmup_manifest": [
                {"vertex": e["vertex"], "label": e["label"],
                 "counts": list(e["counts"])}
                for e in self.manifest
            ],
        }

    def __repr__(self) -> str:
        verdict = "certified" if self.certified else "UNCERTIFIED"
        worst = self.worst_shape
        bound = (f", worst shape {worst['batch']} ≈ "
                 f"{worst['predicted_seconds'] * 1e3:.1f}ms"
                 if worst else "")
        return (f"ServingCertificate({verdict}, "
                f"{len(self.shapes)} ladder shape(s){bound}, "
                f"SLO {self.envelope.slo_seconds * 1e3:.0f}ms)")


def shape_bound(per_item_seconds: float, batch: int,
                programs: int) -> Tuple[float, float]:
    """``(certified_seconds, machine_seconds)`` for one ladder shape.
    The machine bound is the raw roofline sum plus the ~50 µs dispatch
    floor per program — the hardware's lower envelope, exactly the
    issue-level model; the certified bound multiplies the compute term
    by `BOUND_HEADROOM` and pays the measured per-program and per-apply
    host floors, making it an honest UPPER bound on a warm serving
    platform (reconcile_serving checks bound ≥ observed p50)."""
    roofline = per_item_seconds * batch
    machine = roofline + programs * DISPATCH_OVERHEAD_S
    certified = (BOUND_HEADROOM * roofline
                 + programs * PROGRAM_FLOOR_S + APPLY_FLOOR_S)
    return certified, machine


# --------------------------------------------------------------- the pass


def serving_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    envelope: Optional[ServingEnvelope] = None,
    *,
    source: Optional[SourceId] = None,
    sink: Optional[SinkId] = None,
    memory=None,
    roofline=None,
    hbm_budget_bytes: Optional[int] = None,
    chunk_rows: Optional[int] = None,
    label: Optional[str] = None,
    ingress: Optional[Dict[str, Any]] = None,
    seeds: Optional[Dict[NodeId, DataSpec]] = None,
    record: bool = True,
) -> Tuple[ServingCertificate, List[Diagnostic]]:
    """Certify one pipeline's apply path against a serving envelope.

    ``specs`` are the propagated specs (ingress seeds already applied
    by the caller when a boundary is declared; pass the same ``seeds``
    map here so the seeded vertices — and anything upstream of them —
    are treated as the request ingress rather than KP901 failures).
    ``memory`` / ``roofline`` optionally supply the KP2xx / KP8xx
    estimates already computed by the caller (validate's tier order,
    the executor's trace embed) so KP905/KP903 price without re-walking
    the graph — re-tracing every stage body is the expensive half of a
    full validate; ``record`` appends one ``serving_cert`` ledger
    record. Pure spec arithmetic — never touches data or devices."""
    envelope = envelope or envelope_from_env() or ServingEnvelope()
    cert = ServingCertificate(envelope=envelope, ingress=ingress)
    diags: List[Diagnostic] = []
    path = apply_path(graph, source, sink)
    shapes = ladder_shapes(envelope, chunk_rows)

    # vertices at or upstream of a declared ingress boundary run at
    # request ingress (decode/extract), outside the certified program
    at_ingress: set = set(seeds or ())
    if at_ingress:
        from ..workflow.analysis import ancestors

        for vid in list(at_ingress):
            at_ingress |= ancestors(graph, vid)
        path = [v for v in path if v not in at_ingress]

    # ---- roofline pricing of the apply path (KP901 + KP903 inputs)
    if roofline is not None:
        est = roofline
    else:
        est, _ = roofline_pass(graph, specs, chunk_rows=chunk_rows)
    per_item = 0.0
    dominating: Tuple[float, Optional[str]] = (0.0, None)
    unpriced: List[Tuple[NodeId, str]] = []
    from .sharding import _is_host_stage

    for vid in path:
        st = est.stages.get(vid)
        if st is not None:
            if st.count:
                item_s = st.predicted_seconds / st.count
                per_item += item_s
                if item_s > dominating[0]:
                    dominating = (item_s, st.label)
            cert.priced_stages += 1
            continue
        op = graph.get_operator(vid)
        out_spec = specs.get(vid)
        if not isinstance(out_spec, DataSpec) or not graph.get_dependencies(vid):
            continue  # estimator outputs / bound data roots: not a stage
        unpriced.append((vid, _label(graph, vid)))
        provable_host = _is_host_stage(graph, vid, specs)
        why = ("host code: the body cannot be traced into an XLA program"
               if provable_host else
               "no propagated element spec reaches this stage")
        fix = ("move the computation into a device-traceable body (or "
               "pre-featurize at ingress and certify the device tail)"
               if provable_host else
               "declare a serving-ingress spec for the request boundary "
               "(analysis.serving.SERVING_INGRESS / spec_pass seeds)")
        diags.append(Diagnostic(
            "KP901", Severity.ERROR,
            f"apply-path stage cannot be warmed or scanned — {why}; "
            f"the one-warm-program serving claim fails here. Fix: {fix}",
            vertex=vid, label=_label(graph, vid)))
    cert.unpriced_stages = len(unpriced)
    cert.per_item_seconds = per_item
    cert.dominating_stage = dominating[1]

    # programs per apply: conservative upper bound — one program per
    # priced apply-path stage (fusion/megafusion only ever lowers it,
    # and an upper bound is the honest direction for a latency bound)
    cert.programs = max(1, cert.priced_stages)

    # ---- KP902: recompile exposure over the fused plan
    manifest_entries: List[Dict[str, Any]] = []
    exposed: List[str] = []
    try:
        fused = _fused_plan(graph)
        fused_specs, _ = spec_pass_like(graph, fused, specs)
        fpath = set(apply_path(fused, source, sink))
        manifest_entries, covered_inputs = _manifest_entries(
            fused, fused_specs, shapes, path=fpath)
        unpriced_ids = {v for v, _ in unpriced}
        from .hazards import _is_cache_node

        for vid in sorted(fpath, key=lambda n: n.id):
            if vid in covered_inputs or vid in unpriced_ids \
                    or vid in at_ingress:
                continue
            op = fused.get_operator(vid)
            if _is_warm_target(op):
                continue  # a warm target whose input spec is unknown:
                # already carried by the KP901/unpriced accounting
            if _is_cache_node(op) \
                    or getattr(op, "precision_passthrough", False):
                continue  # value-preserving plumbing compiles nothing
            out_spec = fused_specs.get(vid)
            if not isinstance(out_spec, DataSpec) \
                    or not fused.get_dependencies(vid):
                continue
            if not is_known(out_spec.element):
                continue  # unpriceable: KP901's finding, not exposure
            exposed.append(op.label)
    except Exception:
        pass  # exposure analysis must never break certification
    cert.manifest = manifest_entries
    cert.exposed_stages = exposed
    if exposed:
        diags.append(Diagnostic(
            "KP902", Severity.WARNING,
            f"recompile exposure: {len(exposed)} apply-path device "
            f"stage(s) outside every warmable fused program "
            f"[{', '.join(sorted(set(exposed))[:4])}] compile cold once "
            f"per ladder shape — up to {len(exposed) * len(shapes)} cold "
            f"compiles across the envelope's {len(shapes)} shape(s); "
            "declare fusable/fuse() so the AOT warmup manifest covers "
            "them",
            vertex=None, label=label or "<plan>"))
    elif manifest_entries:
        diags.append(Diagnostic(
            "KP902", Severity.INFO,
            f"warm coverage: {len(manifest_entries)} fused program "
            f"site(s) × {len(shapes)} ladder shape(s) "
            f"{shapes} enumerated by warmup_manifest — with "
            "KEYSTONE_SLO_MS armed the executor AOT-compiles every "
            "entry, so warm serving performs 0 cold compiles at any "
            "in-envelope shape",
            vertex=None, label=label or "<plan>"))

    # ---- KP903: per-shape certified latency bound vs the SLO
    for n in shapes:
        certified_s, machine_s = shape_bound(per_item, n, cert.programs)
        cert.shapes.append({
            "batch": n,
            "predicted_seconds": certified_s,
            "machine_seconds": machine_s,
        })
    if not unpriced and cert.priced_stages:
        worst = cert.worst_shape
        if worst["predicted_seconds"] > envelope.slo_seconds:
            diags.append(Diagnostic(
                "KP903", Severity.ERROR,
                f"worst in-envelope shape (batch {worst['batch']}) "
                f"predicts ≈{worst['predicted_seconds'] * 1e3:.1f}ms — "
                f"over the {envelope.slo_seconds * 1e3:.0f}ms SLO; "
                f"dominating stage: {cert.dominating_stage} "
                f"(≈{dominating[0] * 1e6:.0f}µs/item). Shrink the "
                "envelope's max_batch, raise the SLO, or optimize the "
                "dominating stage",
                vertex=None, label=label or "<plan>"))
        else:
            diags.append(Diagnostic(
                "KP903", Severity.INFO,
                f"latency bound holds: worst shape (batch "
                f"{worst['batch']}) ≈{worst['predicted_seconds'] * 1e3:.1f}"
                f"ms ≤ {envelope.slo_seconds * 1e3:.0f}ms SLO over "
                f"{len(shapes)} ladder shape(s); dominating stage "
                f"{cert.dominating_stage}; machine bound "
                f"≈{worst['machine_seconds'] * 1e3:.2f}ms",
                vertex=None, label=label or "<plan>"))

    # ---- KP904: donated plan input the caller retains
    for vid in path:
        op = graph.get_operator(vid)
        deps = graph.get_dependencies(vid)
        for i in getattr(op, "donates_deps", ()) or ():
            if i >= len(deps):
                continue  # arity error: KP002's finding
            donated = deps[i]
            if _is_caller_buffer(graph, donated):
                diags.append(Diagnostic(
                    "KP904", Severity.ERROR,
                    f"dependency {i} is the pipeline's own input — a "
                    "serving caller retains the request buffer it "
                    "passed, so every repeated apply would read a "
                    "deleted buffer (or force a defensive copy per "
                    "request); drop the donation or copy at ingress",
                    vertex=vid, label=_label(graph, vid)))

    # ---- KP905: multi-tenant residency
    if memory is None:
        try:
            from .memory import memory_pass

            memory, _ = memory_pass(graph, specs, chunk_rows=chunk_rows)
        except Exception:
            memory = None
    per_dev = None
    if memory is not None:
        per_dev = int(getattr(memory, "per_device_peak_bytes", 0) or 0)
        if not per_dev:
            # the sharding tier didn't run: approximate per-device
            # residency by dividing the whole-plan peak across the data
            # shards (the row-sharded default placement) — comparing
            # the WHOLE-plan peak against a per-device HBM budget would
            # overstate tenancy by the device count
            total = int(getattr(memory, "peak_bytes", 0) or 0)
            try:
                from ..parallel import mesh as meshlib

                shards = meshlib.current_mesh().shape.get(
                    meshlib.DATA_AXIS, 1)
            except Exception:
                shards = 1
            per_dev = -(-total // max(1, shards)) if total else None
    cert.per_device_peak_bytes = per_dev
    if per_dev:
        budget = hbm_budget_bytes
        if budget is None:
            from ..workflow.env import execution_config

            budget = execution_config().hbm_budget_bytes
        resident = per_dev * envelope.tenants
        if budget and resident > budget:
            diags.append(Diagnostic(
                "KP905", Severity.ERROR,
                f"multi-tenant residency: {envelope.tenants} warmed "
                f"pipeline(s) × {_fmt_bytes(per_dev)} per-device peak = "
                f"{_fmt_bytes(resident)} exceeds the "
                f"{_fmt_bytes(budget)} HBM budget; lower the tenant "
                "count or the per-pipeline residency",
                vertex=None, label=label or "<plan>"))
        elif envelope.tenants > 1:
            diags.append(Diagnostic(
                "KP905", Severity.INFO,
                f"multi-tenant residency: {envelope.tenants} × "
                f"{_fmt_bytes(per_dev)} = {_fmt_bytes(resident)}"
                + (f" within the {_fmt_bytes(budget)} budget"
                   if budget else " (no HBM budget declared)"),
                vertex=None, label=label or "<plan>"))

    # ---- KP906: unbounded telemetry cardinality on the apply path
    seen_classes: set = set()
    for vid in path:
        cls = type(graph.get_operator(vid))
        if cls in seen_classes:
            continue
        seen_classes.add(cls)
        for method, lineno in _dynamic_metric_sites(cls):
            diags.append(Diagnostic(
                "KP906", Severity.WARNING,
                f"{cls.__qualname__}.{method} (line {lineno}) formats a "
                "telemetry metric name dynamically on the apply path — "
                "per-request names grow the process-wide registry "
                "without bound; use one literal name and carry the "
                "dimension in a span arg (jaxlint KJ012 is the "
                "file-level twin)",
                vertex=vid, label=_label(graph, vid)))

    cert.certified = not any(d.severity >= Severity.ERROR for d in diags)

    if record:
        _record_certificate(cert, label)
    return cert, diags


def spec_pass_like(raw_graph: Graph, fused: Graph,
                   raw_specs: Dict[GraphId, Any]):
    """Specs for the fused projection of an already-propagated graph:
    re-propagate over the fused graph, seeding every surviving vertex
    with the raw graph's propagated spec (fusion preserves vertex ids
    for chain heads, and a seed never overrides a derivable spec), so
    an ingress declaration made on the raw graph carries through."""
    from .propagate import spec_pass

    sources = {
        s: raw_specs[s]
        for s in fused.sources
        if isinstance(raw_specs.get(s), DataSpec)
    }
    seeds = {
        vid: raw_specs[vid]
        for vid in fused.operators
        if isinstance(raw_specs.get(vid), DataSpec)
        and is_known(raw_specs[vid].element)
    }
    return spec_pass(fused, sources, seeds=seeds)


def _is_caller_buffer(graph: Graph, dep: GraphId) -> bool:
    """Is this dependency the pipeline's own input — an unbound source,
    or the data vertex `apply` bound the caller's value into?"""
    from ..workflow.operators import DatasetOperator, DatumOperator

    if isinstance(dep, SourceId):
        return True
    if isinstance(dep, NodeId):
        op = graph.get_operator(dep)
        return isinstance(op, (DatasetOperator, DatumOperator)) \
            and not graph.get_dependencies(dep)
    return False


def _record_certificate(cert: ServingCertificate,
                        label: Optional[str]) -> None:
    """One ``serving_cert`` ledger record per certification: the
    verdict, the per-shape priced menu (the alternatives a serving
    scheduler would choose batch sizes from), and the predicted worst
    bound — auditable and diffable like every other priced decision."""
    try:
        from ..telemetry.ledger import record_decision

        worst = cert.worst_shape
        record_decision(
            kind="serving_cert",
            rule="ServingCertifier",
            vertices=[],
            labels=[label or "<pipeline>"],
            chosen={"entry": "certified" if cert.certified
                    else "uncertified"},
            alternatives=[
                {"entry": f"batch={s['batch']}",
                 "cost_seconds": s["predicted_seconds"]}
                for s in cert.shapes
            ],
            predicted={
                "worst_shape_seconds": (worst or {}).get(
                    "predicted_seconds", 0.0),
                "slo_seconds": cert.envelope.slo_seconds,
                "ladder_shapes": len(cert.shapes),
                "programs": cert.programs,
            },
            enforced=cert.certified,
        )
    except Exception:
        pass  # a ledger bug must never break certification


def record_runtime_handoff(cert: ServingCertificate,
                           label: Optional[str], *,
                           warmed_sites: int = 0,
                           queue_depth: int = 0,
                           window_ms: float = 0.0,
                           coalesce: bool = True) -> None:
    """One ``serving_handoff`` ledger record per runtime start/swap: the
    auditable moment a static certificate became a live server. Carries
    the runtime's actual coalescing knobs and how many fused program
    sites its warm step submitted, next to the certificate's predicted
    worst bound — `--explain` can answer "what certificate is this
    process serving under, and was it warmed?" after the fact."""
    try:
        from ..telemetry.ledger import record_decision

        worst = cert.worst_shape
        record_decision(
            kind="serving_handoff",
            rule="ServingRuntime",
            vertices=[],
            labels=[label or "<pipeline>"],
            chosen={
                "entry": ("coalesced micro-batching" if coalesce
                          else "per-request dispatch"),
                "warmed_sites": int(warmed_sites),
                "queue_depth": int(queue_depth),
                "window_ms": float(window_ms),
                "ladder_shapes": [s["batch"] for s in cert.shapes],
            },
            alternatives=[
                {"entry": "per-request dispatch"
                 if coalesce else "coalesced micro-batching",
                 "cost_seconds": 0.0},
            ],
            predicted={
                "worst_shape_seconds": (worst or {}).get(
                    "predicted_seconds", 0.0),
                "slo_seconds": cert.envelope.slo_seconds,
                "per_device_peak_bytes": float(
                    cert.per_device_peak_bytes or 0),
            },
            enforced=cert.certified,
        )
    except Exception:
        pass  # the ledger must never take down a serving start


# ----------------------------------------------------- example certification


def certify_example(name: str, envelope: Optional[ServingEnvelope] = None,
                    *, hbm_budget_bytes: Optional[int] = None,
                    record: bool = False):
    """Certify one registered example end-to-end: build its
    `analyzable()` graph, seed the declared `SERVING_INGRESS` boundary,
    propagate specs, price memory, and run the KP9xx pass. The ONE
    recipe behind every certification surface (`--certify-serving`,
    ``perf_table --serving``, the lint.sh audit), so they cannot drift
    onto different verdicts. Returns ``(cert, diags)``."""
    from . import as_source_spec
    from .examples import build_example
    from .memory import memory_pass
    from .propagate import spec_pass

    pipeline, source_spec = build_example(name)
    graph = pipeline.graph
    seeds, decl = ingress_seeds(graph, name)
    specs, _ = spec_pass(
        graph, {pipeline.source: as_source_spec(source_spec)}, seeds=seeds)
    mem, _ = memory_pass(graph, specs)
    return serving_pass(
        graph, specs, envelope, source=pipeline.source, sink=pipeline.sink,
        memory=mem, hbm_budget_bytes=hbm_budget_bytes, label=name,
        ingress=decl, seeds=seeds, record=record)


# ------------------------------------------------------------- rendering


def format_certificate(cert: ServingCertificate) -> str:
    """Text table of one certificate (the --certify-serving
    rendering)."""
    lines = [
        f"{'batch':>6} {'certified bound':>16} {'machine bound':>14} "
        f"{'SLO':>10} {'verdict':<8}"
    ]
    slo = cert.envelope.slo_seconds
    for s in cert.shapes:
        ok = "ok" if s["predicted_seconds"] <= slo else "OVER"
        lines.append(
            f"{s['batch']:>6} {s['predicted_seconds'] * 1e3:>13.2f} ms "
            f"{s['machine_seconds'] * 1e3:>11.3f} ms "
            f"{slo * 1e3:>7.0f} ms {ok:<8}")
    if cert.dominating_stage:
        lines.append(f"dominating stage: {cert.dominating_stage} "
                     f"({cert.priced_stages} priced stage(s), "
                     f"≤{cert.programs} program(s)/apply)")
    if cert.ingress:
        lines.append(
            f"ingress: requests enter at {cert.ingress['stage']} as "
            f"{cert.ingress['dtype']}{tuple(cert.ingress['shape'])} — "
            f"{cert.ingress.get('note', '')}")
    if cert.manifest:
        lines.append(
            f"warmup manifest: {len(cert.manifest)} program site(s) × "
            f"{len(cert.shapes)} shapes")
    return "\n".join(lines)
