"""Abstract value specs flowing through the static pipeline analyzer.

The analyzer (see `propagate.py`) walks a lowered `Graph` in topological
order and assigns each vertex a *spec* — an abstract description of the
Expression the vertex would produce at force time, without touching any
data. Specs follow the static-compilation discipline of arxiv 1810.09868
(abstract interpretation of the whole program before any device work) and
are deliberately tiny:

  - ``DataSpec``     — a dataset or datum: a pytree of
    `jax.ShapeDtypeStruct` element specs plus an example count. This is
    exactly what `jax.eval_shape` consumes and produces, so spec
    propagation through dense transformers is a zero-FLOP trace.
  - ``TransformerSpec`` — the output of an estimator node: an abstract
    fitted transformer, optionally carrying an element→element shape
    function so the downstream apply's output spec is known before the
    fit ever runs.
  - ``UNKNOWN``      — the honest bottom: host objects (strings, token
    lists, variable-size images) and untraceable stages propagate
    UNKNOWN instead of guessing. Unknown in, unknown out — never an
    error by itself.

This module intentionally imports nothing from `workflow` so operator
classes can import it lazily without cycles.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np


class _Unknown:
    """Singleton bottom spec: 'statically unknowable, not an error'."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "UNKNOWN"

    def __reduce__(self):
        return (_Unknown, ())


UNKNOWN = _Unknown()


class SpecMismatchError(Exception):
    """An abstract-eval hook proved the pipeline cannot run: shapes,
    dtypes, counts, or arity are inconsistent. Carries the analyzer rule
    id so `propagate` files the diagnostic under the right lint."""

    def __init__(self, message: str, rule: str = "KP101"):
        super().__init__(message)
        self.rule = rule


def is_known(spec: Any) -> bool:
    return spec is not UNKNOWN and spec is not None


def element_nbytes(element: Any) -> Optional[int]:
    """Bytes of one element (pytree of ShapeDtypeStruct), or None when
    the element spec is UNKNOWN / contains unknown leaves."""
    if not is_known(element):
        return None
    total = 0
    for leaf in jax.tree_util.tree_leaves(element):
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            return None
        total += int(np.prod(leaf.shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize
    return total


@dataclass(frozen=True)
class DataSpec:
    """Abstract dataset/datum: element pytree + example count.

    ``streaming`` marks values that arrive chunk-by-chunk under the
    overlap engine (a stream-producing stage, or a chunkable stage fed
    by one) — the hazard pass keys on it.
    """

    element: Any = UNKNOWN  # pytree of jax.ShapeDtypeStruct, or UNKNOWN
    count: Optional[int] = None
    kind: str = "dataset"  # "dataset" | "datum"
    on_device: bool = True
    streaming: bool = False

    @property
    def nbytes(self) -> Optional[int]:
        """Full materialized size (count × element bytes); None when
        unknowable."""
        per = element_nbytes(self.element)
        if per is None:
            return None
        if self.kind == "datum":
            return per
        if self.count is None:
            return None
        return per * int(self.count)

    def with_element(self, element: Any) -> "DataSpec":
        return replace(self, element=element)

    def __repr__(self) -> str:
        def fmt(e):
            if not is_known(e):
                return "?"
            leaves = jax.tree_util.tree_leaves(e)
            if len(leaves) == 1 and leaves[0] is e:
                return f"{tuple(e.shape)}:{np.dtype(e.dtype).name}"
            return jax.tree_util.tree_map(
                lambda l: f"{tuple(l.shape)}:{np.dtype(l.dtype).name}", e
            ).__repr__()

        n = "?" if self.count is None else self.count
        tag = "~stream" if self.streaming else ""
        return f"DataSpec[{self.kind} n={n} elem={fmt(self.element)}{tag}]"


@dataclass(frozen=True)
class TransformerSpec:
    """Abstract fitted transformer (the spec of a TransformerExpression).

    ``elem_fn`` maps an input element spec to the fitted transformer's
    output element spec; it may raise `SpecMismatchError` when the input
    provably cannot feed the model (e.g. feature-dim mismatch against
    the training data the estimator saw). None means the estimator
    declared nothing — downstream applies propagate UNKNOWN."""

    elem_fn: Optional[Callable[[Any], Any]] = field(default=None, compare=False)
    label: str = ""
    chunkable: bool = False

    def apply_element(self, element: Any) -> Any:
        if self.elem_fn is None or not is_known(element):
            return UNKNOWN
        return self.elem_fn(element)

    def __repr__(self) -> str:
        known = "known" if self.elem_fn is not None else "opaque"
        return f"TransformerSpec[{self.label or 'fitted'}:{known}]"


def shape_struct(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), np.dtype(dtype))


class SpecDataset:
    """A dataset *placeholder* carrying only an abstract spec.

    Used to build example pipelines for validation without loading any
    data: `Pipeline.apply` / `Estimator.with_data` accept it (it is
    flagged ``is_dataset``), the graph wires up exactly as with real
    data, and `DatasetOperator.abstract_eval` reads the declared spec —
    but any attempt to actually force the pipeline fails loudly.

    ``element=None`` declares a host dataset of opaque objects (strings,
    images of varying size): the spec propagates UNKNOWN elements, which
    exercises the structural tier without pretending to know shapes.
    """

    is_dataset = True

    def __init__(self, shape=None, dtype=np.float32, count: Optional[int] = None,
                 on_device: bool = True, name: str = "spec", element=None):
        if element is None and shape is not None:
            element = shape_struct(shape, dtype)
        self.spec = DataSpec(
            element=element if element is not None else UNKNOWN,
            count=count,
            kind="dataset",
            on_device=on_device if element is not None else False,
        )
        self.name = name

    @property
    def count(self) -> Optional[int]:
        return self.spec.count

    def __len__(self) -> int:
        if self.spec.count is None:
            raise TypeError(f"SpecDataset {self.name!r} has no declared count")
        return self.spec.count

    def __repr__(self) -> str:
        return f"SpecDataset[{self.name}]({self.spec})"

    def _refuse(self, what: str):
        raise RuntimeError(
            f"SpecDataset {self.name!r} is an abstract placeholder for static "
            f"validation; {what} would require real data. Build the pipeline "
            "with a real Dataset/HostDataset to execute it."
        )

    # Any materialization path fails loudly instead of fabricating data.
    @property
    def array(self):
        self._refuse("reading .array")

    @property
    def items(self):
        self._refuse("reading .items")

    def numpy(self):
        self._refuse("collecting to numpy")

    def cache(self):
        return self


def spec_of(value: Any) -> Any:
    """Best-effort spec of a concrete value (used by DatasetOperator /
    DatumOperator and for forced ExpressionOperators)."""
    from ..data.dataset import Dataset, HostDataset

    if isinstance(value, SpecDataset):
        return value.spec
    if getattr(value, "is_out_of_core", False) or getattr(value, "is_spilled", False):
        # Host-resident out-of-core forms: element shape from one probed
        # row (a single-shard touch for OutOfCoreDataset, free for
        # SpilledDataset), marked off-device so placement/memory passes
        # never charge the full payload against HBM.
        element = UNKNOWN
        try:
            row = value.row_loader(0, 1)
            element = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(tuple(a.shape[1:]), a.dtype),
                row)
        except Exception:
            pass
        return DataSpec(element=element, count=value.count, kind="dataset",
                        on_device=False)
    if isinstance(value, Dataset):
        element = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(tuple(a.shape[1:]), a.dtype), value.data
        )
        return DataSpec(element=element, count=value.count, kind="dataset",
                        on_device=True)
    if isinstance(value, HostDataset):
        element = UNKNOWN
        if value.items:
            first = value.items[0]
            if hasattr(first, "shape") and hasattr(first, "dtype"):
                element = jax.ShapeDtypeStruct(tuple(first.shape), first.dtype)
        return DataSpec(element=element, count=len(value.items), kind="dataset",
                        on_device=False)
    if hasattr(value, "shape") and hasattr(value, "dtype"):
        return DataSpec(
            element=jax.ShapeDtypeStruct(tuple(value.shape), value.dtype),
            count=None, kind="datum",
            on_device=not isinstance(value, np.ndarray),
        )
    return UNKNOWN


def as_source_spec(spec: Any) -> Any:
    """Normalize the user-facing ``source_spec`` argument of
    `Pipeline.validate`: accepts a DataSpec, a SpecDataset, a
    ShapeDtypeStruct (one element), a ``(shape, dtype)`` pair, a bare
    shape tuple (defaults float32), or None (UNKNOWN source)."""
    if spec is None or spec is UNKNOWN:
        return UNKNOWN
    if isinstance(spec, DataSpec):
        return spec
    if isinstance(spec, SpecDataset):
        return spec.spec
    if isinstance(spec, jax.ShapeDtypeStruct):
        return DataSpec(element=spec, kind="dataset")
    if isinstance(spec, tuple) and len(spec) == 2 and not isinstance(spec[0], int):
        return DataSpec(element=shape_struct(*spec), kind="dataset")
    if isinstance(spec, tuple) and all(isinstance(s, int) for s in spec):
        return DataSpec(element=shape_struct(spec, np.float32), kind="dataset")
    raise TypeError(f"cannot interpret {spec!r} as a source spec")


def leaf_vector_dim(spec: Any) -> Optional[int]:
    """Length of a dataset spec's 1-D single-leaf element, else None."""
    if not isinstance(spec, DataSpec) or not is_known(spec.element):
        return None
    leaves = jax.tree_util.tree_leaves(spec.element)
    if len(leaves) == 1 and getattr(leaves[0], "ndim", None) == 1:
        return int(leaves[0].shape[0])
    return None


def supervised_fit_spec(in_specs, label: str, out_dtype=np.float32,
                        max_in_dim: Optional[int] = None) -> TransformerSpec:
    """TransformerSpec for the y = f(xW)-family of supervised estimators
    (data (d,) + labels (k,) → fitted model mapping (d,) → (k,)).

    The returned ``elem_fn`` verifies the apply-time feature dim against
    the training dim (``max_in_dim`` relaxes to ≤, for feature-padding
    solvers like BlockLeastSquares) and yields the label-width output
    element. Degrades to an opaque TransformerSpec when the training
    specs are unknown."""
    data = in_specs[0] if in_specs else UNKNOWN
    labels = in_specs[1] if len(in_specs) > 1 else UNKNOWN
    d = leaf_vector_dim(data)
    k = leaf_vector_dim(labels)
    if k is None:
        return TransformerSpec(None, label=label)

    def elem_fn(elem):
        got = None
        leaves = jax.tree_util.tree_leaves(elem)
        if len(leaves) == 1 and getattr(leaves[0], "ndim", None) == 1:
            got = int(leaves[0].shape[0])
        if d is not None and got is not None:
            limit = max_in_dim if max_in_dim is not None else d
            bad = got > limit if max_in_dim is not None else got != d
            if bad:
                raise SpecMismatchError(
                    f"{label} was fit on {d}-dim features but is applied "
                    f"to a {got}-dim element")
        dtype = out_dtype if out_dtype is not None else leaves[0].dtype
        return shape_struct((k,), dtype)

    return TransformerSpec(elem_fn, label=label)


# ---------------------------------------------------------------- tracing

#: Exceptions that mean "this stage runs host code the tracer cannot
#: enter" — the default abstract-eval answers UNKNOWN for them instead of
#: reporting an error (NLP nodes, PIL images, python string ops...).
_HOST_CODE_ERRORS = (
    jax.errors.TracerArrayConversionError,
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerIntegerConversionError,
    AttributeError,
    KeyError,
    IndexError,
    NotImplementedError,
)

#: TypeError/ValueError substrings that identify a genuine jax/XLA
#: shape-system complaint (vs. host code stumbling over a tracer).
_SHAPE_ERROR_MARKERS = (
    "shape", "dtype", "dimension", "broadcast", "dot_general", "rank",
    "incompatible", "matmul", "concatenate", "scatter", "conv",
)


def trace_element(fn: Callable, elems) -> Any:
    """`jax.eval_shape` one per-item call over element specs — ZERO data
    movement, zero device allocation.

    Returns the output element pytree, UNKNOWN when ``fn`` is host code
    the tracer cannot enter, and raises `SpecMismatchError` when the
    trace dies on a shape/dtype complaint (the stage provably cannot run
    on these inputs)."""
    try:
        return jax.eval_shape(fn, *elems)
    except SpecMismatchError:
        raise
    except _HOST_CODE_ERRORS:
        return UNKNOWN
    except (TypeError, ValueError) as e:
        msg = str(e)
        low = msg.lower()
        if any(marker in low for marker in _SHAPE_ERROR_MARKERS):
            raise SpecMismatchError(msg, rule="KP101") from e
        return UNKNOWN
    except Exception:
        return UNKNOWN
