"""Static sharding analyzer: partition-spec propagation, per-device
memory, collective-cost lints (KP6xx).

KeystoneML's optimizer picks physical operators from cost models *before*
execution; the KP1xx–KP5xx tiers already do that for shapes, memory, and
operator contracts. This pass makes *placement* a checked, priced
property too: every stage boundary of a lowered Graph is assigned a
`jax.sharding.PartitionSpec` (per element leaf, leading example axis
included), flowed the same way the runtime actually places data —

  - **seeded** from `data.dataset.leaf_sharding`'s placement decision
    (leading axis over ``"data"``; 1-D elements additionally shard their
    feature axis over ``"model"`` when the mesh has one and the width
    divides — the VectorSplitter analog),
  - **propagated** through operator ``abstract_sharding`` hooks when
    declared (solver fits state their row-sharded input demands this
    way), with a default rule: leading-axis data sharding survives
    elementwise/chunkable device stages, collapses to replicated when
    the input was replicated, and dies at host-code stages,
  - **overridden** by declarative regex partition rules
    (`PartitionRule`), so a pipeline can pin per-stage placement without
    touching node code (the `match_partition_rules` idiom).

On top of the propagated specs:

  - the KP2xx memory model goes **per-device** (`per_device_pass`):
    live-set residency divided by each leaf's actual shard count,
    replicated operands charged in full per device, with a KP600 budget
    violation replacing the whole-fleet KP202 estimate at the full tier
    — the memory-safe-XLA discipline of arXiv 2206.14148 applied per
    chip;
  - a collective/reshard detector prices boundary movement: KP601
    implicit reshard (producer and consumer specs disagree → an
    all-to-all of the boundary bytes), KP602 large-operand-replicated,
    KP603 gather-of-sharded-into-host (an all-gather of every shard),
    KP604 mesh-indivisible example counts (ragged/padded shards change
    per-device shapes and recompile).

Everything here is pure spec arithmetic: no data moves, no device
allocates, no program compiles. Surfaced through
``validate(level="full")`` and ``python -m keystone_tpu.analysis
--explain-sharding``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as meshlib
from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .diagnostics import Diagnostic, Severity
from .memory import MemoryEstimate, _fmt_bytes, live_set_walk
from .propagate import _label, toposort
from .specs import UNKNOWN, DataSpec, is_known

#: Replicated operands smaller than this never trip KP602 — broadcasting
#: a scaler's mean vector is free; broadcasting a feature matrix is not.
DEFAULT_REPLICATED_THRESHOLD = 64 << 20

#: `abstract_sharding` demand values: what a dependency's layout must be
#: for the operator's device program to run collective-free.
DEMAND_DATA_SHARDED = "data-sharded"
DEMAND_REPLICATED = "replicated"


# ------------------------------------------------------------------ values


@dataclass(frozen=True)
class ShardedValue:
    """Propagated sharding of one vertex: a pytree of `PartitionSpec`s
    aligned with the vertex's `DataSpec` element leaves. Dataset specs
    are *batch-level* (a leading example axis precedes the element
    dims); datum specs match the element rank exactly."""

    specs: Any
    kind: str = "dataset"  # "dataset" | "datum"

    def leaf_specs(self) -> List[P]:
        return [
            s for s in jax.tree_util.tree_leaves(
                self.specs, is_leaf=lambda x: isinstance(x, P))
        ]

    def max_shards(self, mesh=None) -> int:
        """Largest shard count any leaf is split into (1 = replicated)."""
        mesh = mesh or meshlib.current_mesh()
        return max(
            (meshlib.spec_shards(s, mesh) for s in self.leaf_specs()),
            default=1)

    def __repr__(self) -> str:
        return f"ShardedValue[{spec_str(self)}]"


def spec_str(sv: Optional["ShardedValue"]) -> str:
    """Human-readable spec — the per-stage table's second column."""
    if sv is None:
        return "—"

    def one(s: P) -> str:
        entries = ", ".join(repr(e) if e is not None else "None" for e in s)
        return f"P({entries})" if entries else "P()"

    leaves = sv.leaf_specs()
    if len(leaves) == 1:
        return one(leaves[0])
    return "(" + ", ".join(one(s) for s in leaves) + ")"


@dataclass(frozen=True)
class ShardingResult:
    """Return value of an operator's optional ``abstract_sharding(
    in_shardings, in_specs)`` hook.

    ``out``: the output `ShardedValue` (None → the default rule decides).
    ``demands``: per-dependency input layout demands
    (`DEMAND_DATA_SHARDED` / `DEMAND_REPLICATED` / None) — a producer
    whose propagated spec disagrees with a demand is an implicit reshard
    boundary (KP601), priced at the producer's full bytes."""

    out: Optional[ShardedValue] = None
    demands: Tuple[Optional[str], ...] = ()


def fit_sharding_demands(n_deps: int) -> ShardingResult:
    """The distributed-solver hook: every training dependency must
    arrive row-sharded over the ``data`` axis (the TSQR per-shard QR /
    BCD per-shard Gram layout); the fitted model itself is replicated
    state, not a dataset, so no output sharding is declared."""
    return ShardingResult(demands=(DEMAND_DATA_SHARDED,) * n_deps)


@dataclass(frozen=True)
class PartitionRule:
    """Declarative placement override: ``pattern`` is a regex matched
    (re.search) against the stage label and its ``label@vertex`` anchor;
    ``spec`` is the PartitionSpec pinned on every output leaf of the
    first matching stage. First matching rule wins."""

    pattern: str
    spec: P

    def matches(self, label: str, anchor: str) -> bool:
        return re.search(self.pattern, label) is not None or \
            re.search(self.pattern, anchor) is not None


def _as_rules(rules) -> List[PartitionRule]:
    out = []
    for r in rules or ():
        if isinstance(r, PartitionRule):
            out.append(r)
        else:
            pattern, spec = r
            out.append(PartitionRule(pattern, spec))
    return out


# ----------------------------------------------------------------- seeding


def element_leaf_spec(mesh, elem_leaf) -> P:
    """Batch-level PartitionSpec `Dataset` placement would give a leaf
    with this per-item shape — the static mirror of
    `data.dataset.leaf_sharding` (which operates on the padded batch
    shape): leading example axis over ``data``; 1-D elements shard the
    feature axis over ``model`` when the mesh has one and the width
    divides evenly."""
    shape = tuple(getattr(elem_leaf, "shape", ()))
    if len(shape) == 1:
        model = int(mesh.shape.get(meshlib.MODEL_AXIS, 1))
        if model > 1 and shape[0] % model == 0:
            return P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)
    return P(meshlib.DATA_AXIS, *([None] * len(shape)))


def seed_sharding(spec: Any, mesh) -> Optional[ShardedValue]:
    """Placement of a freshly materialized value: what `Dataset.__init__`
    / `HostDataset.stack` would assign. None for host values and unknown
    elements (there is nothing on device to shard)."""
    if not isinstance(spec, DataSpec) or not is_known(spec.element) \
            or not spec.on_device:
        return None
    if spec.kind == "datum":
        specs = jax.tree_util.tree_map(
            lambda l: P(*([None] * len(getattr(l, "shape", ())))),
            spec.element)
        return ShardedValue(specs, kind="datum")
    specs = jax.tree_util.tree_map(
        lambda l: element_leaf_spec(mesh, l), spec.element)
    return ShardedValue(specs, kind="dataset")


def _replicated_like(spec: DataSpec) -> Optional[ShardedValue]:
    if not is_known(spec.element):
        return None
    extra = 1 if spec.kind == "dataset" else 0
    specs = jax.tree_util.tree_map(
        lambda l: P(*([None] * (len(getattr(l, "shape", ())) + extra))),
        spec.element)
    return ShardedValue(specs, kind=spec.kind)


def _leading_axis(sv: Optional[ShardedValue]):
    """Mesh axis (or None) the leading example dim is sharded over, read
    off the first leaf. Datum values have no example axis → None."""
    if sv is None or sv.kind != "dataset":
        return None
    leaves = sv.leaf_specs()
    if not leaves or not len(leaves[0]):
        return None
    first = leaves[0][0]
    if isinstance(first, (tuple, list)):
        return first[0] if first else None
    return first


# ------------------------------------------------------------- propagation


def _is_host_stage(graph: Graph, vid: NodeId, specs: Dict) -> bool:
    """Statically provable host-code stage: a plain transformer whose
    abstract trace died on host code (known input elements, UNKNOWN
    output element) or whose output spec says host. Delegates and
    estimators are excluded — a delegate's opaque fitted transformer is
    *unknowable*, not provably host, and an estimator must see the whole
    dataset by construction (the KP302 reasoning)."""
    from ..workflow.operators import (
        DelegatingOperator,
        EstimatorOperator,
        TransformerOperator,
    )

    op = graph.get_operator(vid)
    if isinstance(op, (DelegatingOperator, EstimatorOperator)):
        return False
    if not isinstance(op, TransformerOperator):
        return False
    out = specs.get(vid)
    if isinstance(out, DataSpec) and not out.on_device:
        return True
    in_specs = [specs.get(d) for d in graph.get_dependencies(vid)]
    data_in = [s for s in in_specs if isinstance(s, DataSpec)]
    if not data_in or not all(is_known(s.element) for s in data_in):
        return False
    return isinstance(out, DataSpec) and not is_known(out.element)


def sharding_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    mesh=None,
    rules: Sequence = (),
    plan: Optional[Dict[GraphId, ShardedValue]] = None,
    replicated_threshold_bytes: int = DEFAULT_REPLICATED_THRESHOLD,
) -> Tuple[Dict[GraphId, Optional[ShardedValue]], List[Diagnostic],
           Dict[NodeId, int]]:
    """Propagate partition specs over the graph and lint the boundaries.

    Returns ``(shardings, diagnostics, boundary_costs)`` where
    ``boundary_costs[vid]`` is the priced bytes of collective traffic
    the placement implies at that stage's boundary (KP601 all-to-all,
    KP603 all-gather), priced through the shared
    `parallel.mesh.collective_cost` formula. Pure spec arithmetic —
    zero device work.

    ``plan`` is the sharding planner's chosen assignment
    (`analysis.planner.plan_sharding`): a vid → `ShardedValue` map that
    REPLACES default propagation and declarative rules on the vids it
    covers. Planned placements are the placement *decision*, not an
    adversarial pin, so deviating from what propagation would have
    chosen is not an implicit reshard (the planner already priced and
    enforces those moves explicitly); demand checks (KP601), host
    gathers (KP603), replication (KP602), and divisibility (KP604)
    still lint the planned placement — a plan that violates an operator
    demand fails loudly here."""
    mesh = mesh or meshlib.current_mesh()
    rules = _as_rules(rules)
    plan = plan or {}
    order, _ = toposort(graph)
    shardings: Dict[GraphId, Optional[ShardedValue]] = {}
    diags: List[Diagnostic] = []
    boundary: Dict[NodeId, int] = {}
    data_shards = int(mesh.shape.get(meshlib.DATA_AXIS, 1))
    flagged_counts: set = set()

    def add_cost(vid: NodeId, nbytes: Optional[int]) -> None:
        if nbytes:
            boundary[vid] = boundary.get(vid, 0) + int(nbytes)

    for vid in order:
        if isinstance(vid, SourceId):
            shardings[vid] = plan.get(vid) or seed_sharding(
                specs.get(vid), mesh)
            continue
        if isinstance(vid, SinkId):
            shardings[vid] = shardings.get(graph.get_sink_dependency(vid))
            continue

        op = graph.get_operator(vid)
        deps = graph.get_dependencies(vid)
        label = _label(graph, vid)
        anchor = f"{label}@{vid}"
        in_shardings = [shardings.get(d) for d in deps]
        in_specs = [specs.get(d, UNKNOWN) for d in deps]
        out_spec = specs.get(vid)

        # ---- operator hook: demands + (optionally) the output placement
        assigned: Optional[ShardedValue] = None
        hook = getattr(op, "abstract_sharding", None)
        if hook is not None:
            try:
                res = hook(in_shardings, in_specs)
            except Exception as e:
                # a buggy hook must not kill validation, but it must be
                # loud: silently falling to the default rule would also
                # silently drop the hook's KP601 demand checks, and the
                # sharding gate would stay green on a broken hook
                res = None
                diags.append(Diagnostic(
                    "KP605", Severity.WARNING,
                    f"abstract_sharding hook raised "
                    f"{type(e).__name__}: {e} — this stage's placement "
                    "demands were skipped (default propagation applied)",
                    vertex=vid, label=label))
            if isinstance(res, ShardedValue):
                res = ShardingResult(out=res)
            if isinstance(res, ShardingResult):
                assigned = res.out
                if assigned is not None:
                    problem = _sharded_value_problem(
                        assigned, out_spec, mesh)
                    if problem is not None:
                        # same contract as rule specs (KP605): an
                        # unrealizable placement must fail loudly, not
                        # silently model shard-count 1
                        diags.append(Diagnostic(
                            "KP605", Severity.ERROR,
                            f"abstract_sharding hook on this stage "
                            f"returned {spec_str(assigned)} but "
                            f"{problem}; the hook's placement is "
                            "ignored here",
                            vertex=vid, label=label))
                        assigned = None
                for i, demand in enumerate(res.demands):
                    if demand is None or i >= len(deps):
                        continue
                    dep_sv = in_shardings[i]
                    dep_spec = in_specs[i]
                    if dep_sv is None or not isinstance(dep_spec, DataSpec):
                        continue
                    lead = _leading_axis(dep_sv)
                    bad = (
                        demand == DEMAND_DATA_SHARDED
                        and lead != meshlib.DATA_AXIS
                        and data_shards > 1
                    ) or (
                        demand == DEMAND_REPLICATED
                        and dep_sv.max_shards(mesh) > 1
                    )
                    if bad:
                        # meeting a replication demand is an all-gather
                        # of the whole value; a sharding demand is an
                        # all-to-all between layouts
                        if demand == DEMAND_REPLICATED:
                            cost = meshlib.collective_cost(
                                "all_gather", dep_spec.nbytes,
                                shards=dep_sv.max_shards(mesh), mesh=mesh)
                        else:
                            cost = meshlib.collective_cost(
                                "all_to_all", dep_spec.nbytes,
                                shards=max(dep_sv.max_shards(mesh),
                                           data_shards),
                                mesh=mesh)
                        add_cost(vid, cost.bytes_moved)
                        diags.append(Diagnostic(
                            "KP601", Severity.WARNING,
                            f"implicit reshard: dependency {i} "
                            f"({_label(graph, deps[i])}@{deps[i]}) arrives "
                            f"as {spec_str(dep_sv)} but this stage demands "
                            f"a {demand} layout — XLA inserts "
                            f"{'an all-gather' if cost.kind == 'all_gather' else 'an all-to-all'} "
                            f"of ≈{_fmt_bytes(cost.bytes_moved)} "
                            "at this boundary",
                            vertex=vid, label=label))

        # ---- planner assignment: the chosen placement IS the decision.
        # It replaces both the default rule and declarative pins on the
        # vids it covers (the planner already priced its deviations and
        # enforces them explicitly — with_sharding_constraint / reshard
        # — so they are not *implicit* reshards); everything below
        # (KP602/KP603/KP604, demand checks above) still lints it.
        planned = plan.get(vid)
        if planned is not None and isinstance(out_spec, DataSpec) \
                and is_known(out_spec.element) and out_spec.on_device:
            problem = _sharded_value_problem(planned, out_spec, mesh)
            if problem is not None:
                diags.append(Diagnostic(
                    "KP605", Severity.ERROR,
                    f"planner assignment {spec_str(planned)} on this "
                    f"stage but {problem}; the assignment is ignored "
                    "here",
                    vertex=vid, label=label))
                planned = None
        else:
            planned = None
        if planned is not None:
            assigned = planned

        # ---- default rule when neither hook nor rule decided the output
        if assigned is None:
            assigned = _default_out_sharding(
                op, out_spec, in_shardings, in_specs, mesh)

        # ---- declarative regex override (first matching rule wins).
        # Host-resident values take no device placement (mirroring
        # seed_sharding/_default_out_sharding): pinning a device spec on
        # one would divide per-device bytes by shards that don't exist
        # and fabricate KP603 all-gathers downstream.
        if planned is None and isinstance(out_spec, DataSpec) \
                and is_known(out_spec.element) and out_spec.on_device:
            for rule in rules:
                if not rule.matches(label, anchor):
                    continue
                problem = _rule_problem(rule, out_spec, mesh)
                if problem is not None:
                    # a rule the mesh/value cannot realize must fail
                    # loudly — silently dividing by impossible shard
                    # counts would corrupt every KP600/KP602 number
                    diags.append(Diagnostic(
                        "KP605", Severity.ERROR,
                        f"partition rule {rule.pattern!r} pins "
                        f"{rule.spec} on this stage but {problem}; the "
                        "rule is ignored here",
                        vertex=vid, label=label))
                    break
                pinned = ShardedValue(
                    jax.tree_util.tree_map(lambda l: rule.spec,
                                           out_spec.element),
                    kind=out_spec.kind)
                if assigned is not None and not _same_placement(
                        assigned, pinned, mesh):
                    cost = meshlib.collective_cost(
                        "all_to_all", out_spec.nbytes,
                        shards=max(assigned.max_shards(mesh),
                                   pinned.max_shards(mesh),
                                   data_shards),
                        mesh=mesh)
                    add_cost(vid, cost.bytes_moved)
                    diags.append(Diagnostic(
                        "KP601", Severity.WARNING,
                        f"implicit reshard: propagation gives this stage "
                        f"{spec_str(assigned)} but partition rule "
                        f"{rule.pattern!r} pins {spec_str(pinned)} — the "
                        f"boundary moves ≈{_fmt_bytes(cost.bytes_moved)} "
                        "(all-to-all) to honor the rule",
                        vertex=vid, label=label))
                assigned = pinned
                break

        shardings[vid] = assigned

        # ---- KP603: device-sharded data gathered into a host stage
        if _is_host_stage(graph, vid, specs):
            gathered = 0
            for d, dep_sv, dep_spec in zip(deps, in_shardings, in_specs):
                if dep_sv is None or not isinstance(dep_spec, DataSpec):
                    continue
                if dep_sv.max_shards(mesh) > 1 and dep_spec.nbytes:
                    cost = meshlib.collective_cost(
                        "all_gather", dep_spec.nbytes,
                        shards=dep_sv.max_shards(mesh), mesh=mesh)
                    gathered += cost.bytes_moved
                    diags.append(Diagnostic(
                        "KP603", Severity.WARNING,
                        f"host-code stage consumes device-sharded "
                        f"{_label(graph, d)}@{d} ({spec_str(dep_sv)}): "
                        f"every shard all-gathers to the host "
                        f"(≈{_fmt_bytes(cost.bytes_moved)}); keep the "
                        "stage on device or reshard explicitly",
                        vertex=vid, label=label))
            add_cost(vid, gathered)

        # ---- KP602: large operand held replicated though shardable
        if assigned is not None and isinstance(out_spec, DataSpec):
            total = out_spec.nbytes
            if total and total >= replicated_threshold_bytes \
                    and assigned.max_shards(mesh) <= 1:
                axis = _shardable_axis(out_spec, mesh)
                if axis is not None:
                    diags.append(Diagnostic(
                        "KP602", Severity.WARNING,
                        f"{_fmt_bytes(total)} held replicated on every "
                        f"device although the {axis!r} mesh axis divides "
                        "one of its dimensions — a sharded placement "
                        "exists (pin one with a PartitionRule or an "
                        "abstract_sharding hook)",
                        vertex=vid, label=label))

        # ---- KP604: data-shard count does not divide the example count
        if assigned is not None and assigned.kind == "dataset" \
                and _leading_axis(assigned) == meshlib.DATA_AXIS \
                and isinstance(out_spec, DataSpec) \
                and out_spec.count and data_shards > 1 \
                and out_spec.count % data_shards != 0 \
                and out_spec.count not in flagged_counts:
            flagged_counts.add(out_spec.count)
            diags.append(Diagnostic(
                "KP604", Severity.WARNING,
                f"{data_shards} data shards do not divide the propagated "
                f"example count {out_spec.count}: placement pads to "
                f"{-(-out_spec.count // data_shards) * data_shards} rows, "
                "so per-device shapes differ from same-pipeline stages "
                "at other counts and every distinct residue recompiles",
                vertex=vid, label=label))

    return shardings, diags, boundary


def _spec_problem(spec: P, out_spec: DataSpec, mesh) -> Optional[str]:
    """Why one PartitionSpec cannot apply to this stage's value, or None
    when it can: every named axis must exist on the mesh, and the spec
    may not have more entries than the value's (batch-level) rank."""
    unknown = [ax for ax in meshlib.spec_axes(spec)
               if ax not in mesh.shape]
    if unknown:
        names = ", ".join(repr(a) for a in sorted(set(unknown)))
        return (f"the current mesh (axes "
                f"{tuple(mesh.axis_names)}) has no axis {names}")
    n_entries = len(tuple(spec))
    extra = 1 if out_spec.kind == "dataset" else 0
    min_rank = min(
        (len(getattr(l, "shape", ())) + extra
         for l in jax.tree_util.tree_leaves(out_spec.element)),
        default=0)
    if n_entries > min_rank:
        return (f"the value's rank is {min_rank} (batch axis included) — "
                f"fewer than the spec's {n_entries} entries")
    return None


def _rule_problem(rule: PartitionRule, out_spec: DataSpec,
                  mesh) -> Optional[str]:
    return _spec_problem(rule.spec, out_spec, mesh)


def _sharded_value_problem(sv: ShardedValue, out_spec,
                           mesh) -> Optional[str]:
    """KP605 for hook-returned placements: the same realizability
    contract rule specs get, aligned per leaf when the element spec is
    known (a higher-rank leaf may legitimately carry a longer spec);
    unknown-axis names are always checkable."""
    for lspec in sv.leaf_specs():
        unknown = [ax for ax in meshlib.spec_axes(lspec)
                   if ax not in mesh.shape]
        if unknown:
            names = ", ".join(repr(a) for a in sorted(set(unknown)))
            return (f"the current mesh (axes "
                    f"{tuple(mesh.axis_names)}) has no axis {names}")
    if not isinstance(out_spec, DataSpec) or not is_known(out_spec.element):
        return None
    leaves = jax.tree_util.tree_leaves(out_spec.element)
    leaf_specs = sv.leaf_specs()
    if len(leaves) != len(leaf_specs):
        return None  # shape of the tree itself is the hook's business
    extra = 1 if sv.kind == "dataset" else 0
    for leaf, lspec in zip(leaves, leaf_specs):
        rank = len(getattr(leaf, "shape", ())) + extra
        if len(tuple(lspec)) > rank:
            return (f"a leaf's rank is {rank} (batch axis included) — "
                    f"fewer than its spec's {len(tuple(lspec))} entries")
    return None


def _same_placement(a: ShardedValue, b: ShardedValue, mesh) -> bool:
    la, lb = a.leaf_specs(), b.leaf_specs()
    if len(la) != len(lb):
        return False
    return all(meshlib.specs_equal(x, y) for x, y in zip(la, lb))


def _shardable_axis(spec: DataSpec, mesh) -> Optional[str]:
    """A mesh axis (>1 devices) that evenly divides some dimension of
    the value — proof that a sharded placement exists. Prefers the model
    axis (KP602's 'replicated over the model axis' case)."""
    leaves = jax.tree_util.tree_leaves(spec.element)
    dims: List[int] = []
    if spec.kind == "dataset" and spec.count:
        dims.append(int(spec.count))
    for leaf in leaves:
        dims.extend(int(s) for s in getattr(leaf, "shape", ()))
    for ax in (meshlib.MODEL_AXIS, meshlib.DATA_AXIS):
        n = int(mesh.shape.get(ax, 1))
        if n > 1 and any(d >= n and d % n == 0 for d in dims):
            return ax
    return None


def _default_out_sharding(
    op, out_spec, in_shardings, in_specs, mesh
) -> Optional[ShardedValue]:
    """The default propagation rule: leading-axis data sharding survives
    device stages fed by data-sharded inputs (feature axes re-derived
    from the output element, exactly as `Dataset` placement would);
    replicated inputs stay replicated; host inputs producing a device
    dataset get the fresh `Dataset.stack` placement; host/unknown
    outputs carry no sharding."""
    if not isinstance(out_spec, DataSpec) or not is_known(out_spec.element) \
            or not out_spec.on_device:
        return None
    data_pairs = [
        (sv, s) for sv, s in zip(in_shardings, in_specs)
        if isinstance(s, DataSpec)
    ]
    if not data_pairs:
        # a source-less materialization (DatasetOperator): fresh placement
        return seed_sharding(out_spec, mesh)
    first_sv = data_pairs[0][0]
    if first_sv is None:
        # host → device boundary (HostDataset.stack): fresh placement
        return seed_sharding(out_spec, mesh)
    if out_spec.kind == "datum":
        return _replicated_like(out_spec)
    if _leading_axis(first_sv) == meshlib.DATA_AXIS:
        return seed_sharding(out_spec, mesh)
    return _replicated_like(out_spec)


# -------------------------------------------------------------- per-device


def _entry_shards(entry, mesh) -> int:
    """Shard factor of ONE PartitionSpec entry (None, a name, or a tuple
    of names)."""
    if entry is None:
        return 1
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    n = 1
    for name in names:
        n *= int(mesh.shape.get(name, 1))
    return n


def per_device_bytes(spec: Any, sv: Optional[ShardedValue], mesh) -> Optional[int]:
    """Bytes of this value resident on ONE device, modeled the way the
    runtime actually shards: each dimension is padded UP to a multiple
    of its axis factor before splitting (`Dataset` pads the leading axis
    to the data-shard count), so a shard's extent is ``ceil(dim /
    factor)`` per dimension — at mesh-indivisible counts this matches
    ``addressable_shards[0].data.nbytes``, where a flat ``total/shards``
    would under-read exactly when KP604 fires. Replicated leaves are
    charged in full. Unknown shardings conservatively charge the whole
    value per device (the pre-sharding whole-fleet assumption)."""
    if not isinstance(spec, DataSpec):
        return None
    total = spec.nbytes
    if total is None:
        return None
    if sv is None:
        return total
    leaves = jax.tree_util.tree_leaves(spec.element)
    leaf_specs = sv.leaf_specs()
    if len(leaves) != len(leaf_specs):
        return total
    count = spec.count if spec.kind == "dataset" else None
    if spec.kind == "dataset" and count is None:
        return total
    out = 0
    for leaf, lspec in zip(leaves, leaf_specs):
        dims = list(getattr(leaf, "shape", ()))
        if spec.kind == "dataset":
            dims = [int(count)] + dims
        entries = list(lspec) + [None] * (len(dims) - len(lspec))
        per_dev = int(np.dtype(leaf.dtype).itemsize)
        for dim, entry in zip(dims, entries):
            factor = max(1, _entry_shards(entry, mesh))
            per_dev *= -(-int(dim) // factor)
        out += per_dev
    return out


def per_device_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    shardings: Dict[GraphId, Optional[ShardedValue]],
    memory: MemoryEstimate,
    *,
    mesh=None,
    hbm_budget_bytes: Optional[int] = None,
) -> Tuple[Dict[NodeId, Optional[int]], List[Diagnostic]]:
    """Scale the KP2xx live-set model down to ONE device's residency and
    lint it against the per-device HBM budget (KP600 — this *replaces*
    the whole-fleet KP202 estimate at the full tier: on a sharded mesh
    the fleet-wide sum is not what any chip's allocator sees).

    Per-node: the memory model's resident bytes (streaming discounts and
    scan live-sets included) scaled by this node's per-device fraction.
    The live-set walk mirrors `memory_pass` exactly — production through
    last consumer, sinks pin forever. Results are attached to ``memory``
    (``per_device``, ``per_device_peak_bytes``, ``per_device_peak_at``)
    so one `MemoryEstimate` carries both pictures."""
    mesh = mesh or meshlib.current_mesh()
    diags: List[Diagnostic] = []
    order, _ = toposort(graph)

    per_dev: Dict[NodeId, Optional[int]] = {}
    for vid in memory.per_node:
        full = memory.per_node.get(vid)
        resident = memory.resident.get(vid)
        if full is None or resident is None or full <= 0:
            per_dev[vid] = resident
            continue
        pd_full = per_device_bytes(specs.get(vid), shardings.get(vid), mesh)
        if pd_full is None:
            per_dev[vid] = resident
            continue
        # scale the (possibly streaming-discounted) residency by the
        # node's own sharded fraction
        per_dev[vid] = int(resident * (pd_full / full))

    peak, peak_at = live_set_walk(graph, order, per_dev)

    memory.per_device = per_dev
    memory.per_device_peak_bytes = peak
    memory.per_device_peak_at = peak_at

    if hbm_budget_bytes and peak > hbm_budget_bytes:
        diags.append(Diagnostic(
            "KP600", Severity.WARNING,
            f"peak PER-DEVICE live memory {_fmt_bytes(peak)} exceeds the "
            f"{_fmt_bytes(hbm_budget_bytes)} per-device HBM budget (peak "
            f"at {_label(graph, peak_at)}@{peak_at}, "
            f"{mesh.devices.size} device(s) on the mesh)",
            vertex=peak_at, label=_label(graph, peak_at)))
    return per_dev, diags


# ------------------------------------------------------------ explanation


def explain_rows(
    graph: Graph,
    specs: Dict[GraphId, Any],
    shardings: Dict[GraphId, Optional[ShardedValue]],
    boundary: Dict[NodeId, int],
    per_device: Dict[NodeId, Optional[int]],
) -> List[Dict[str, Any]]:
    """Per-stage table rows (topo order): propagated spec, per-device
    bytes, priced boundary collective bytes — the ``--explain-sharding``
    payload, JSON-ready."""
    order, _ = toposort(graph)
    rows = []
    for vid in order:
        if not isinstance(vid, NodeId):
            continue
        rows.append({
            "vertex": vid.id,
            "label": _label(graph, vid),
            "spec": spec_str(shardings.get(vid)),
            "per_device_bytes": per_device.get(vid),
            "boundary_bytes": boundary.get(vid, 0),
        })
    return rows


def format_explain(rows: List[Dict[str, Any]]) -> str:
    lines = [f"{'stage':<44} {'spec':<24} {'per-dev':>10} {'boundary':>10}"]
    for r in rows:
        pd = _fmt_bytes(r["per_device_bytes"]) \
            if r["per_device_bytes"] is not None else "?"
        bd = _fmt_bytes(r["boundary_bytes"]) if r["boundary_bytes"] else "—"
        name = f"{r['label']}@{r['vertex']}"
        lines.append(f"{name[:44]:<44} {r['spec'][:24]:<24} "
                     f"{pd:>10} {bd:>10}")
    return "\n".join(lines)
