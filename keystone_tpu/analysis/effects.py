"""Concurrency effect analysis — the race detector the concurrent DAG
scheduler (PR 4, default on) never had.

The scheduler's determinism guarantee ("values are pure functions of
already-forced dependencies") holds only while operators are actually
pure at apply time. An operator that writes ``self.*``, a module global,
or a shared mutable container inside its apply path is *effectful*: two
such vertices with no dependency ordering can be forced simultaneously
by the worker pool, and the write interleaving becomes schedule-
dependent — a data race the type system cannot see.

Two layers:

  - **Effect inference** (`class_effects` / `operator_effects`): an AST
    walk over the hot-path method bodies (``apply``, ``apply_batch``,
    ``batch_transform``, ``fuse``, ``_chunk_loop``, ...) of an operator
    class — including inherited methods and same-class helpers they
    call — collecting writes to ``self``, to declared globals, and
    in-place mutations of module-level containers. The sanctioned memo
    idioms are suppressed: ``self.__dict__[...]`` instance memoization,
    and the structure-keyed program caches (module-level ``*CACHE*`` /
    ``*PENDING*`` / ``*LOCK*`` names).
  - **Interference pass** (`interference_pass`, KP511): over a lowered
    graph, two effectful vertices that the concurrent scheduler could
    force simultaneously (`workflow.executor.concurrent_relation` — the
    scheduler's own concurrently-schedulable projection) AND that share
    mutable state (the same operator/component instance, or overlapping
    module-global targets) are flagged. Ordered vertices never flag:
    the schedule already serializes them.

Suppress a genuine exception with ``# keystone: ignore[KP511]`` on the
offending assignment line (shared with jaxlint's KJ008 file lint, which
polices the same discipline path-wide at pre-test time).
"""

from __future__ import annotations

import ast
import inspect
import re
import sys
import textwrap
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic, Severity

_IGNORE_RE = re.compile(r"#\s*keystone:\s*ignore\[([A-Z0-9,\s]+)\]")

#: operator methods that run at apply/force time (the hot path the
#: scheduler may execute concurrently). ``__init__``/``fit``/``execute``
#: run during single-threaded wiring or inside one vertex's force and
#: are excluded. Kept in lockstep with jaxlint's ``_HOT_PATH_METHODS``
#: (KJ008, the file-level police of the same discipline).
HOT_METHODS: Tuple[str, ...] = (
    "apply", "apply_batch", "apply_batch_stream", "single_transform",
    "batch_transform", "batch_transform_stream", "batch_fn", "fuse",
    "_chunk_loop",
)

#: method-call names that mutate their receiver in place.
_MUTATOR_CALLS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear",
})

#: module-level names matching the sanctioned structure-keyed cache
#: idiom (``_PROGRAM_CACHE``, ``_WARMUP_PENDING``, locks...).
_SANCTIONED_GLOBAL = re.compile(r"(CACHE|PENDING|LOCK|REGISTRY)", re.I)


@dataclass(frozen=True)
class Effect:
    """One apply-time write: ``kind`` is ``self_write`` /
    ``global_write`` / ``container_mutation``; ``target`` is
    ``attr:<name>`` for instance state or ``<module>:<name>`` for
    module-level state."""

    kind: str
    target: str
    where: str  # "Class.method:line"

    def __str__(self) -> str:
        return f"{self.kind} {self.target} at {self.where}"

    @property
    def shared_target(self) -> Optional[str]:
        """The process-wide target two DIFFERENT instances could race
        on; instance-local writes return None."""
        return None if self.kind == "self_write" else self.target


# ----------------------------------------------------------- inference


def _suppressed(lines: Sequence[str], lineno: int, rule: str) -> bool:
    if not (0 < lineno <= len(lines)):
        return False
    m = _IGNORE_RE.search(lines[lineno - 1])
    return bool(m) and rule in {r.strip() for r in m.group(1).split(",")}


def _attr_chain_root(node: ast.AST) -> Optional[ast.AST]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


def _is_self_dict(node: ast.AST) -> bool:
    """``self.__dict__`` — the sanctioned instance-memo root."""
    return (isinstance(node, ast.Attribute) and node.attr == "__dict__"
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _is_self_dict_chain(node: ast.AST) -> bool:
    """``self.__dict__`` or ``self.__dict__[...]`` — mutator calls on
    either are the sanctioned memo idiom, not shared-state mutation."""
    if _is_self_dict(node):
        return True
    return isinstance(node, ast.Subscript) and _is_self_dict(node.value)


def _first_attr(node: ast.AST) -> str:
    """Attribute name nearest ``self`` in a chain: self.a.b[c] → a."""
    names = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            names.append(node.attr)
        node = node.value
    return names[-1] if names else "?"


def _method_effects(
    cls_name: str,
    fn: ast.FunctionDef,
    lines: Sequence[str],
    module_name: str,
    module_globals: Dict[str, Any],
) -> Tuple[List[Effect], Set[str]]:
    """Effects of one method body plus the same-class helper methods it
    calls (``self.helper(...)`` names, resolved by the caller)."""
    effects: List[Effect] = []
    helpers: Set[str] = set()
    declared_globals: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Global):
            declared_globals.update(sub.names)

    def _mutable_module_name(name: Optional[str]) -> bool:
        if name is None or name not in module_globals:
            return False
        if _SANCTIONED_GLOBAL.search(name):
            return False
        return isinstance(module_globals[name], (dict, list, set, bytearray))

    def where(node) -> str:
        return f"{cls_name}.{fn.name}:{node.lineno}"

    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == "self":
                helpers.add(sub.func.attr)
            # in-place mutation of a module-level OR instance-held
            # container, one attribute/subscript hop allowed
            # (_TABLE["k"].append(...), self.seen.append(...)) — the
            # mutator spelling races exactly like the subscript-assign
            # spelling (self.seen[k] = v) already recorded below
            if sub.func.attr in _MUTATOR_CALLS \
                    and not _is_self_dict_chain(sub.func.value) \
                    and not _suppressed(lines, sub.lineno, "KP511"):
                root = _attr_chain_root(sub.func.value)
                if isinstance(root, ast.Name) \
                        and _mutable_module_name(root.id):
                    effects.append(Effect(
                        "container_mutation",
                        f"{module_name}:{root.id}", where(sub)))
                elif isinstance(root, ast.Name) and root.id == "self" \
                        and isinstance(sub.func.value,
                                       (ast.Attribute, ast.Subscript)):
                    effects.append(Effect(
                        "self_write",
                        f"attr:{_first_attr(sub.func.value)}",
                        where(sub)))

        if not isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            continue
        if _suppressed(lines, sub.lineno, "KP511"):
            continue
        targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
        for t in targets:
            if isinstance(t, ast.Tuple):
                elts: Iterable[ast.AST] = t.elts
            else:
                elts = [t]
            for e in elts:
                if isinstance(e, ast.Name):
                    if e.id in declared_globals:
                        effects.append(Effect(
                            "global_write",
                            f"{module_name}:{e.id}", where(sub)))
                    continue
                root = _attr_chain_root(e)
                if isinstance(root, ast.Name) and root.id == "self":
                    # sanctioned: self.__dict__[...] = ... memoization
                    if isinstance(e, ast.Subscript) \
                            and _is_self_dict(e.value):
                        continue
                    effects.append(Effect(
                        "self_write", f"attr:{_first_attr(e)}", where(sub)))
                elif isinstance(e, (ast.Subscript, ast.Attribute)) \
                        and isinstance(root, ast.Name) \
                        and _mutable_module_name(root.id):
                    effects.append(Effect(
                        "container_mutation",
                        f"{module_name}:{root.id}", where(sub)))
    return effects, helpers


_CLASS_SRC_CACHE: Dict[type, Optional[Tuple[ast.ClassDef, List[str]]]] = {}


def _class_defn(cls: type) -> Optional[Tuple[ast.ClassDef, List[str]]]:
    got = _CLASS_SRC_CACHE.get(cls, False)
    if got is not False:
        return got
    out = None
    try:
        src = textwrap.dedent(inspect.getsource(cls))
        tree = ast.parse(src)
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
                out = (node, src.splitlines())
                break
    except Exception:
        out = None
    _CLASS_SRC_CACHE[cls] = out
    return out


_EFFECT_CACHE: Dict[type, Tuple[Effect, ...]] = {}


def class_effects(cls: type) -> Tuple[Effect, ...]:
    """Apply-time effects of ``cls``: hot-path methods across the MRO
    (each defining class analyzed with its own module namespace), plus
    the same-class helpers those methods call, transitively."""
    got = _EFFECT_CACHE.get(cls)
    if got is not None:
        return got
    effects: List[Effect] = []
    for klass in cls.__mro__:
        if klass.__module__ in ("builtins",):
            continue
        defn = _class_defn(klass)
        if defn is None:
            continue
        node, lines = defn
        methods = {n.name: n for n in node.body
                   if isinstance(n, ast.FunctionDef)}
        module_name = klass.__module__
        mod = sys.modules.get(module_name)
        module_globals = vars(mod) if mod is not None else {}
        pending = [m for m in HOT_METHODS if m in methods]
        seen: Set[str] = set()
        while pending:
            name = pending.pop()
            if name in seen:
                continue
            seen.add(name)
            eff, helpers = _method_effects(
                klass.__name__, methods[name], lines,
                module_name, module_globals)
            effects.extend(eff)
            pending.extend(h for h in helpers
                           if h in methods and h not in seen)
    out = tuple(dict.fromkeys(effects))
    _EFFECT_CACHE[cls] = out
    return out


#: attribute names through which composite operators hold inner stages.
_COMPONENT_ATTRS = ("stages", "branches", "stage_specs")


def _components(op) -> List[Any]:
    """The operator plus every inner stage a composite holds (fused
    chains, gather stages, transformer chains) — a shared inner
    instance is just as racy as a shared outer one."""
    out: List[Any] = []
    seen: Set[int] = set()
    stack = [op]
    while stack:
        cur = stack.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        out.append(cur)
        for attr in _COMPONENT_ATTRS:
            val = getattr(cur, attr, None)
            if isinstance(val, (list, tuple)):
                stack.extend(
                    s for s in val if hasattr(s, "__class__")
                    and not isinstance(s, (str, int, float)))
    return out


def operator_effects(op) -> Dict[int, Tuple[Any, Tuple[Effect, ...]]]:
    """Per-component effect map of one operator instance:
    ``id(component) -> (component, effects)``, empty-effect components
    omitted."""
    out: Dict[int, Tuple[Any, Tuple[Effect, ...]]] = {}
    for comp in _components(op):
        eff = class_effects(type(comp))
        if eff:
            out[id(comp)] = (comp, eff)
    return out


# ------------------------------------------------------- interference


def interference_pass(graph) -> List[Diagnostic]:
    """KP511: pairs of effectful vertices the concurrent scheduler could
    force simultaneously while sharing mutable state. Callers gate on
    ``ExecutionConfig.concurrent_dispatch`` — with the scheduler off the
    serial depth-first force totally orders every pair and the race
    cannot occur."""
    from ..workflow.executor import concurrent_relation
    from .propagate import _label

    effectful = []
    for node in sorted(graph.operators, key=lambda n: n.id):
        op = graph.get_operator(node)
        try:
            eff = operator_effects(op)
        except Exception:
            continue  # inference must never break validation
        if eff:
            effectful.append((node, op, eff))
    if len(effectful) < 2:
        return []

    unordered = concurrent_relation(graph)
    diags: List[Diagnostic] = []
    for i in range(len(effectful)):
        for j in range(i + 1, len(effectful)):
            u, op_u, eff_u = effectful[i]
            v, op_v, eff_v = effectful[j]
            if not unordered(u, v):
                continue
            reasons: List[str] = []
            shared_ids = eff_u.keys() & eff_v.keys()
            for sid in sorted(shared_ids):
                comp, eff = eff_u[sid]
                reasons.append(
                    f"both force the same {type(comp).__name__} instance, "
                    f"which mutates itself at apply time ({eff[0]})")
            tgt_u = {e.shared_target for _, effs in eff_u.values()
                     for e in effs if e.shared_target}
            tgt_v = {e.shared_target for _, effs in eff_v.values()
                     for e in effs if e.shared_target}
            for tgt in sorted(tgt_u & tgt_v):
                reasons.append(f"both mutate process-global state {tgt}")
            if not reasons:
                continue
            diags.append(Diagnostic(
                "KP511", Severity.WARNING,
                f"effectful vertices {_label(graph, u)}@{u} and "
                f"{_label(graph, v)}@{v} have no dependency ordering, so "
                "the concurrent DAG scheduler may force them "
                f"simultaneously: {'; '.join(reasons)}. Order them "
                "explicitly, make the state per-instance (or memoize via "
                "self.__dict__), or revert to the serial force "
                "(KEYSTONE_CONCURRENT_DISPATCH=0)",
                vertex=v, label=_label(graph, v)))
    return diags
