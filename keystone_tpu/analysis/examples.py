"""Registry of statically-analyzable example pipelines.

Every example app in `keystone_tpu/pipelines/` exposes an
``analyzable()`` factory building its full predictor graph over abstract
placeholder data (`SpecDataset`) — no data loads, no fits run. The CLI
(`python -m keystone_tpu.analysis`) and the tier-1 parametrized test
validate each one, so a refactor that breaks an example's wiring or
shape contract fails the lint gate in milliseconds.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, Tuple

#: name -> (module, factory attr). Factories return (pipeline, source_spec).
EXAMPLES: Dict[str, Tuple[str, str]] = {
    "MnistRandomFFT": ("keystone_tpu.pipelines.mnist_random_fft", "analyzable"),
    "RandomPatchCifar": ("keystone_tpu.pipelines.random_patch_cifar", "analyzable"),
    "LinearPixels": ("keystone_tpu.pipelines.cifar_variants", "analyzable"),
    "TimitPipeline": ("keystone_tpu.pipelines.timit", "analyzable"),
    "NewsgroupsPipeline": ("keystone_tpu.pipelines.text_pipelines", "analyzable"),
    "VOCSIFTFisher": ("keystone_tpu.pipelines.voc_sift_fisher", "analyzable"),
    "ImageNetSiftLcsFV": ("keystone_tpu.pipelines.imagenet_sift_lcs_fv", "analyzable"),
}


def build_example(name: str):
    """Build one registered example: returns ``(pipeline, source_spec)``."""
    module, attr = EXAMPLES[name]
    factory: Callable = getattr(importlib.import_module(module), attr)
    return factory()
