"""Structural checks + abstract spec propagation over a lowered Graph.

Two tiers:

  - `structural_pass(graph)` — pure topology lints: cycles, arity,
    fit-before-use, delegate-without-estimator, dangling sources. Cheap
    (O(V+E)) and data-free; `GraphExecutor` runs it automatically before
    the first force so malformed plans fail in microseconds instead of
    minutes into a TPU job.
  - `spec_pass(graph, source_specs)` — walks the graph in topological
    order calling each operator's `abstract_eval` hook (default:
    `jax.eval_shape` over the per-item transform — zero data movement),
    assigning every vertex a spec and converting `SpecMismatchError`s
    into ERROR diagnostics anchored at the offending node.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .diagnostics import Diagnostic, Severity
from .specs import UNKNOWN, DataSpec, SpecMismatchError, TransformerSpec, is_known


def _label(graph: Graph, vid: GraphId) -> str:
    if isinstance(vid, NodeId):
        op = graph.get_operator(vid)
        try:
            return str(op.label)
        except Exception:
            return type(op).__name__
    return type(vid).__name__.replace("Id", "")


def toposort(graph: Graph) -> Tuple[List[GraphId], List[Diagnostic]]:
    """Kahn's algorithm over sources+nodes+sinks. Unlike `linearize`
    (depth-first, recursion-based) this cannot blow the stack and
    reports cycles as diagnostics instead of recursing forever."""
    indeg: Dict[GraphId, int] = {s: 0 for s in graph.sources}
    for n, deps in graph.dependencies.items():
        # distinct deps only: users_of dedupes repeated edges, so a node
        # depending twice on one vertex (CSE-merged gather branches)
        # receives a single decrement — counting multiplicity here would
        # report a false cycle
        indeg[n] = len(set(deps))
    for k in graph.sink_dependencies:
        indeg[k] = 1
    ready = deque(sorted((v for v, d in indeg.items() if d == 0),
                         key=lambda v: (type(v).__name__, v.id)))
    order: List[GraphId] = []
    while ready:
        v = ready.popleft()
        order.append(v)
        for u in graph.users_of(v):
            indeg[u] -= 1
            if indeg[u] == 0:
                ready.append(u)
    diags: List[Diagnostic] = []
    if len(order) != len(indeg):
        stuck = sorted(
            (v for v, d in indeg.items() if d > 0 and v not in set(order)),
            key=lambda v: (type(v).__name__, v.id),
        )
        diags.append(Diagnostic(
            "KP001", Severity.ERROR,
            f"dependency cycle through {', '.join(map(str, stuck))}",
            vertex=stuck[0] if stuck else None,
            label=_label(graph, stuck[0]) if stuck else "",
        ))
    return order, diags


def _produces_transformer(graph: Graph, dep) -> Optional[bool]:
    """Does vertex ``dep`` statically produce a TransformerExpression?
    True/False when provable, None when unknowable (e.g. a source)."""
    from ..workflow.expressions import TransformerExpression
    from ..workflow.operators import EstimatorOperator, ExpressionOperator

    if not isinstance(dep, NodeId):
        return None
    op = graph.get_operator(dep)
    if isinstance(op, EstimatorOperator):
        return True
    if isinstance(op, ExpressionOperator):
        return isinstance(op.expression, TransformerExpression)
    return False


def structural_pass(graph: Graph) -> List[Diagnostic]:
    from ..workflow.operators import (
        DelegatingOperator,
        EstimatorOperator,
        TransformerOperator,
    )

    _, diags = toposort(graph)

    for node in sorted(graph.operators, key=lambda n: n.id):
        op = graph.get_operator(node)
        deps = graph.get_dependencies(node)
        label = _label(graph, node)

        if isinstance(op, DelegatingOperator):
            if len(deps) < 2:
                diags.append(Diagnostic(
                    "KP002", Severity.ERROR,
                    f"DelegatingOperator needs a transformer dependency plus "
                    f"data, got {len(deps)} dependency(ies)",
                    vertex=node, label=label))
            elif _produces_transformer(graph, deps[0]) is False:
                diags.append(Diagnostic(
                    "KP004", Severity.ERROR,
                    f"first dependency {deps[0]} produces data, not a "
                    "transformer — the fit/apply wiring is inverted",
                    vertex=node, label=label))
        elif isinstance(op, TransformerOperator):
            if not deps:
                diags.append(Diagnostic(
                    "KP002", Severity.ERROR,
                    "TransformerOperator requires at least one data dependency",
                    vertex=node, label=label))
        elif isinstance(op, EstimatorOperator):
            if not deps:
                diags.append(Diagnostic(
                    "KP002", Severity.ERROR,
                    "EstimatorOperator requires training data dependencies",
                    vertex=node, label=label))

        # fit-before-use: an estimator's output is a transformer, not
        # data — only a consumer's declared ``estimator_positions``
        # (position 0 of a DelegatingOperator; the leading slots of a
        # fused super-node, workflow.fusion_rule.FusedChainOperator) may
        # consume it.
        if isinstance(op, EstimatorOperator):
            for user in graph.users_of(node):
                if isinstance(user, SinkId):
                    diags.append(Diagnostic(
                        "KP003", Severity.WARNING,
                        "estimator output bound to a sink: forcing it runs "
                        "the fit and returns the raw transformer",
                        vertex=node, label=label))
                    continue
                user_op = graph.get_operator(user)
                user_deps = graph.get_dependencies(user)
                est_positions = getattr(user_op, "estimator_positions", ())
                positions = [i for i, d in enumerate(user_deps) if d == node]
                if positions and len(positions) == 1 and \
                        positions[0] in est_positions:
                    continue
                diags.append(Diagnostic(
                    "KP003", Severity.ERROR,
                    f"estimator output consumed as data by "
                    f"{_label(graph, user)}@{user} — fit it through a "
                    "DelegatingOperator (`.with_data(...)`) first",
                    vertex=node, label=label))

    for source in sorted(graph.sources):
        if not graph.users_of(source):
            diags.append(Diagnostic(
                "KP005", Severity.WARNING,
                "source has no consumers; the pipeline ignores this input",
                vertex=source, label="Source"))

    return diags


def spec_pass(
    graph: Graph,
    source_specs: Optional[Dict[SourceId, Any]] = None,
    seeds: Optional[Dict[Any, Any]] = None,
) -> Tuple[Dict[GraphId, Any], List[Diagnostic]]:
    """Propagate abstract specs vertex-by-vertex in topological order.

    Zero device work: every default hook routes through `jax.eval_shape`
    (see `specs.trace_element`), and hooks that cannot tell return
    UNKNOWN. A `SpecMismatchError` raised by a hook becomes an ERROR
    diagnostic anchored at the node, and UNKNOWN flows downstream so one
    mismatch does not cascade into a wall of secondary errors.

    ``seeds`` maps interior vertices to *declared* boundary `DataSpec`s
    (the serving certifier's ingress declarations): a seed fills in a
    vertex whose propagated element is unknown — it NEVER overrides a
    spec propagation proved, so a declared boundary can only extend
    coverage, not contradict it."""
    source_specs = source_specs or {}
    seeds = seeds or {}
    order, cycle_diags = toposort(graph)
    diags: List[Diagnostic] = list(cycle_diags)
    specs: Dict[GraphId, Any] = {}

    for vid in order:
        if isinstance(vid, SourceId):
            specs[vid] = source_specs.get(vid, UNKNOWN)
        elif isinstance(vid, SinkId):
            specs[vid] = specs.get(graph.get_sink_dependency(vid), UNKNOWN)
        else:
            op = graph.get_operator(vid)
            in_specs = [specs.get(d, UNKNOWN) for d in graph.get_dependencies(vid)]
            try:
                out = op.abstract_eval(in_specs)
            except SpecMismatchError as e:
                diags.append(Diagnostic(
                    e.rule, Severity.ERROR, str(e),
                    vertex=vid, label=_label(graph, vid)))
                out = UNKNOWN
            except Exception as e:  # a buggy hook must not kill validation
                diags.append(Diagnostic(
                    "KP101", Severity.WARNING,
                    f"abstract_eval hook raised {type(e).__name__}: {e}",
                    vertex=vid, label=_label(graph, vid)))
                out = UNKNOWN
            if vid in seeds and not is_known(getattr(out, "element", None)):
                out = seeds[vid]
            specs[vid] = out
    return specs, diags
