"""Static per-node and peak live-memory estimation.

Follows the compile-time memory analysis of "Memory Safe Computations
with XLA Compiler" (arxiv 2206.14148): with every vertex's abstract spec
known (shape × dtype × count), walk the execution schedule and track the
live set — a vertex's output is resident from the step that produces it
until its last consumer has run. The peak of that walk is the static
HBM/host-RAM watermark, available in milliseconds before any data loads.

The overlap engine changes residency: a streaming stage never
materializes — at most ``2·prefetch_depth + 2`` chunks are in flight
(utils/batching.py's documented bound) — but prefetch *amplifies* the
chunk footprint by that same factor. Both effects are modeled: streaming
stages get the chunk-resident discount and a KP203 note when the
amplified footprint is a meaningful share of the budget.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .diagnostics import Diagnostic, Severity
from .propagate import _label, toposort
from .specs import DataSpec, element_nbytes, is_known

#: Historical default chunk row-count, kept as the documented fallback
#: for callers that pin an explicit number. The LIVE default is
#: `ExecutionConfig.chunk_size` (env ``KEYSTONE_CHUNK_SIZE``) — the same
#: knob `utils.batching.map_host_batched` dispatches with, resolved per
#: pass by `resolve_chunk_rows`, so this model can never assume a chunk
#: the runtime doesn't execute.
DEFAULT_CHUNK_ROWS = 256


def resolve_chunk_rows(chunk_rows: Optional[int]) -> int:
    """An explicit ``chunk_rows`` wins; None reads the shared
    resolution (`workflow.env.resolved_chunk_size`: the unified
    planner's enforced chunk decision when one is live, else the
    execution config's ``chunk_size``) — one number for the runtime
    dispatcher and the static memory model."""
    if chunk_rows is not None:
        return chunk_rows
    from ..workflow.env import resolved_chunk_size

    return resolved_chunk_size()


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _may_stream(op) -> bool:
    """Statically: could this operator's output arrive chunk-by-chunk
    under the overlap engine? True for declared stream producers
    (overridden ``apply_batch_stream``/``batch_transform_stream``) and
    chunk-passthrough stages (``chunkable``)."""
    if getattr(op, "chunkable", False):
        return True
    from ..workflow.pipeline import Transformer

    fn = getattr(type(op), "apply_batch_stream", None)
    return fn is not None and fn is not Transformer.apply_batch_stream


@dataclass
class MemoryEstimate:
    """Static memory picture of one graph."""

    per_node: Dict[NodeId, Optional[int]] = field(default_factory=dict)
    resident: Dict[NodeId, Optional[int]] = field(default_factory=dict)
    peak_bytes: int = 0
    peak_at: Optional[GraphId] = None
    unknown_nodes: int = 0
    #: per-device picture, filled in by `analysis.sharding.per_device_pass`
    #: when the sharding tier runs (level="full"): residency scaled by
    #: each node's actual shard counts. Empty/zero until then.
    per_device: Dict[NodeId, Optional[int]] = field(default_factory=dict)
    per_device_peak_bytes: int = 0
    per_device_peak_at: Optional[GraphId] = None

    def __repr__(self) -> str:
        return (
            f"MemoryEstimate(peak={_fmt_bytes(self.peak_bytes)} at "
            f"{self.peak_at}, {self.unknown_nodes} unknown node(s))"
        )


def live_set_walk(
    graph: Graph,
    order: List[GraphId],
    residents: Dict[NodeId, Optional[int]],
) -> Tuple[int, Optional[GraphId]]:
    """THE live-set walk, shared by the whole-fleet model here and the
    per-device model (`analysis.sharding.per_device_pass`): a vertex's
    output is live from production through its last consumer's schedule
    position, sinks pin their dependency forever. One implementation so
    the two pictures can never diverge semantically — they differ only
    in the residency numbers fed in. Returns ``(peak_bytes, peak_at)``."""
    sched_pos = {v: i for i, v in enumerate(order)}
    last_use: Dict[NodeId, int] = {}
    pinned: set = set()
    for vid in residents:
        users = graph.users_of(vid)
        if any(isinstance(u, SinkId) for u in users):
            pinned.add(vid)
        last_use[vid] = max(
            (sched_pos[u] for u in users if u in sched_pos),
            default=sched_pos.get(vid, 0),
        )

    live = 0
    peak = 0
    peak_at: Optional[GraphId] = None
    expiring: Dict[int, List[NodeId]] = {}
    for vid, end in last_use.items():
        expiring.setdefault(end, []).append(vid)
    for i, v in enumerate(order):
        if isinstance(v, NodeId) and residents.get(v) is not None:
            live += residents[v]
            if live > peak:
                peak, peak_at = live, v
        for dead in expiring.get(i, ()):
            if dead not in pinned and residents.get(dead) is not None:
                live -= residents[dead]
    return peak, peak_at


def memory_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    *,
    hbm_budget_bytes: Optional[int] = None,
    chunk_rows: Optional[int] = None,
    prefetch_depth: Optional[int] = None,
    overlap: Optional[bool] = None,
) -> Tuple[MemoryEstimate, List[Diagnostic]]:
    from ..workflow.env import execution_config

    cfg = execution_config()
    chunk_rows = resolve_chunk_rows(chunk_rows)
    if prefetch_depth is None:
        prefetch_depth = cfg.prefetch_depth
    if overlap is None:
        overlap = cfg.overlap
    if hbm_budget_bytes is None:
        hbm_budget_bytes = cfg.hbm_budget_bytes
    inflight_chunks = 2 * prefetch_depth + 2  # utils/batching.py bound

    order, _ = toposort(graph)
    est = MemoryEstimate()
    diags: List[Diagnostic] = []

    # Residency per produced vertex: full bytes, discounted for streaming.
    for vid in order:
        if not isinstance(vid, NodeId):
            continue
        spec = specs.get(vid)
        op = graph.get_operator(vid)
        full = spec.nbytes if isinstance(spec, DataSpec) else None
        est.per_node[vid] = full
        if full is None:
            est.unknown_nodes += 1
            est.resident[vid] = None
            continue
        resident = full
        # Host-tier residency: a host-placed CacheMarker output and an
        # out-of-core / spilled source live in host RAM — on device only
        # a bounded window (double-buffered chunk) is ever resident,
        # regardless of the overlap setting (the windowed reload path
        # streams even serially). This is the static model of the spill
        # tier the unified planner prices.
        host_tier = getattr(op, "placement", None) == "host"
        if not host_tier:
            ds = getattr(op, "dataset", None)
            host_tier = bool(getattr(ds, "is_out_of_core", False)
                             or getattr(ds, "is_spilled", False))
        if host_tier and isinstance(spec, DataSpec):
            per_elem = element_nbytes(spec.element)
            if per_elem is not None:
                window_bytes = per_elem * chunk_rows * 2
                if window_bytes < full:
                    resident = window_bytes
            est.resident[vid] = resident
            continue
        if overlap and isinstance(spec, DataSpec) and spec.kind == "dataset" \
                and (spec.streaming or _may_stream(op)):
            per_elem = element_nbytes(spec.element)
            if per_elem is not None:
                chunk_bytes = per_elem * chunk_rows * inflight_chunks
                if chunk_bytes < full:
                    resident = chunk_bytes
                    if hbm_budget_bytes and chunk_bytes > hbm_budget_bytes // 20:
                        diags.append(Diagnostic(
                            "KP203", Severity.INFO,
                            f"overlap amplification: {inflight_chunks} "
                            f"in-flight chunks × {_fmt_bytes(per_elem * chunk_rows)}"
                            f"/chunk = {_fmt_bytes(chunk_bytes)} resident "
                            f"(prefetch_depth={prefetch_depth})",
                            vertex=vid, label=_label(graph, vid)))
        # Megafused scan live-set: a whole-plan program holds its stacked
        # input (the dep's residency, priced at the producer) plus the
        # scan's per-trip carry — one chunk's largest stage boundary —
        # INSTEAD of materialized intermediates, which no longer exist as
        # graph nodes. The operator knows its own stage trail; price it.
        scan_hook = getattr(op, "scan_live_nbytes", None)
        if scan_hook is not None and full is not None:
            try:
                dep_specs = [specs.get(d)
                             for d in graph.get_dependencies(vid)]
                scan_live = scan_hook(dep_specs, chunk_rows)
            except Exception:
                scan_live = None
            if scan_live:
                resident += int(scan_live)
                if hbm_budget_bytes and scan_live > hbm_budget_bytes // 20:
                    diags.append(Diagnostic(
                        "KP204", Severity.INFO,
                        f"megafused scan live-set: "
                        f"{_fmt_bytes(int(scan_live))} of in-program "
                        f"per-trip carry (chunk_rows={chunk_rows}) rides "
                        "on top of the stacked input and output "
                        "residency",
                        vertex=vid, label=_label(graph, vid)))
        est.resident[vid] = resident

        if hbm_budget_bytes and full > hbm_budget_bytes:
            diags.append(Diagnostic(
                "KP201", Severity.WARNING,
                f"materialized output is {_fmt_bytes(full)}, over the "
                f"{_fmt_bytes(hbm_budget_bytes)} HBM budget"
                + (" (streams under overlap, resident "
                   f"{_fmt_bytes(resident)})" if resident < full else ""),
                vertex=vid, label=_label(graph, vid)))

    est.peak_bytes, est.peak_at = live_set_walk(graph, order, est.resident)

    if hbm_budget_bytes and est.peak_bytes > hbm_budget_bytes:
        diags.append(Diagnostic(
            "KP202", Severity.WARNING,
            f"peak live memory {_fmt_bytes(est.peak_bytes)} exceeds the "
            f"{_fmt_bytes(hbm_budget_bytes)} HBM budget (peak at "
            f"{_label(graph, est.peak_at)}@{est.peak_at})"
            + (f"; {est.unknown_nodes} node(s) unestimated"
               if est.unknown_nodes else ""),
            vertex=est.peak_at, label=_label(graph, est.peak_at)))
    return est, diags
