"""Mixed-precision policy pass: per-stage dtype as an optimizer decision.

The featurize hot path is bandwidth-bound while the MXU already ingests
bf16 (the fused conv kernel's numerics story, PERF.md; the bf16x3
precision discipline of arXiv 2112.09017). KeystoneML's thesis is that
pipeline-level choices should be made by cost models over the lowered
DAG (arXiv 1610.09451) — PR 9 made *placement* such a decision; this
module makes *precision* one: per stage boundary a legal dtype menu,
priced by the bytes the boundary actually moves, solved with the same
chain-DP + frontier-merge shape as `analysis.planner`, and enforced by
baking casts and matmul-precision scopes into fused/megafused programs
(`workflow.optimizer.PrecisionPlannerRule` is the enforcement shell).

The model:

  - **menu** — per stage boundary, the legal storage policies:
    ``bf16`` (bf16 storage, DEFAULT compute — halves every float32 byte
    the boundary moves), ``f32_bf16`` (f32 storage, bf16 matmul compute
    — a compute-only concession, byte-neutral, never chosen by the byte
    objective but available to explicit policies), and ``f32`` (f32
    storage, HIGHEST-fidelity compute — the reference policy, always
    legal, and exactly what runs today).
  - **legality** — flowed from per-operator ``precision_tolerance``
    declarations: solvers, moments/stats estimators, and label/index
    stages pin ``exact`` (their boundaries stay f32); elementwise and
    featurize stages declare ``tolerant``. Undeclared stages get an
    `jax.eval_shape`-based sensitivity probe: the stage is traced on a
    bf16 element — a trace that dies, or a non-floating output, pins
    the stage. Passthrough stages (`precision_passthrough` — Cacher,
    Identity, VectorCombiner) are *transparent*: the consumers behind
    them decide, so a cached feature matrix feeding an exact solver is
    pinned even though the cache itself tolerates anything. A boundary
    feeding a sink is the pipeline's visible output and stays f32.
  - **cost** — a boundary priced at the bytes its storage dtype
    implies: `policy_nbytes` halves float32 leaves under bf16 (ints and
    bools never change — the dtype-aware KP2xx story). Every storage
    flip along an edge carries a fixed cast penalty so a downcast that
    is immediately undone (KP702 cast-thrash) never wins on byte ties.
  - **solver** — min-cost DP over fan-out-free chains of choosable
    boundaries (each maximal run of bf16 boundaries pays two casts and
    saves its halved bytes), greedy freeze at fan-out/fan-in, one
    bounded descent sweep; chosen and default assignments are scored by
    the SAME function, and the plan degrades to the all-f32 default
    whenever it cannot strictly beat it — the kill switch
    (``KEYSTONE_PRECISION_PLANNER=0``) and every no-win case reproduce
    the PR-9 plan bit-for-bit.

Everything here is pure spec arithmetic — no data moves, no device
allocates.  Numeric safety is gated by the existing
allclose-vs-serial-unfused machinery (tests/test_precision.py, the
bench accuracy band): `shrink_to_band` discards a policy stage-by-stage
when an evaluation busts the declared tolerance band, so a policy that
cannot hold the band is never shipped.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..workflow.graph import Graph, GraphId, NodeId, SinkId, SourceId
from .diagnostics import Diagnostic, Severity
from .memory import _fmt_bytes, memory_pass
from .propagate import _label, toposort
from .specs import (
    UNKNOWN,
    DataSpec,
    TransformerSpec,
    is_known,
    trace_element,
)

# ------------------------------------------------------------------ policies

#: f32 storage + HIGHEST-fidelity compute — the reference policy; what
#: every boundary runs today, and what the kill switch reproduces.
POLICY_F32 = "f32"
#: f32 storage + bf16 matmul compute — byte-neutral, compute-only.
POLICY_F32_BF16 = "f32_bf16"
#: bf16 storage + DEFAULT compute — halves every f32 byte the boundary
#: moves; the policy the byte objective actually fights for.
POLICY_BF16 = "bf16"
POLICIES: Tuple[str, ...] = (POLICY_F32, POLICY_F32_BF16, POLICY_BF16)

#: `precision_tolerance` declaration values.
TOLERANT = "tolerant"   # bf16 storage AND bf16 compute acceptable
COMPUTE = "compute"     # f32 storage required; bf16 matmul acceptable
EXACT = "exact"         # f32 storage + HIGHEST compute, non-negotiable

#: the default per-pipeline tolerance band for policy-on outputs vs the
#: serial unfused f32 reference: ~2 bf16 roundings of relative error
#: plus an absolute floor for near-zero rectified values. Tests and the
#: bench accuracy gate both read these; `shrink_to_band` discards
#: policy stages until an evaluation fits inside them.
DEFAULT_BAND_RTOL = 2e-2
DEFAULT_BAND_ATOL = 5e-2

#: fixed per-cast penalty (bytes): every storage flip on an edge is a
#: convert_element_type the program would not otherwise contain, so a
#: single halved boundary sandwiched between f32 neighbours must save
#: more than two casts' worth of churn to win (the KP702 discipline,
#: priced into the objective instead of only linted after the fact).
CAST_PENALTY_BYTES = 2 << 10

_STORAGE = {POLICY_F32: "float32", POLICY_F32_BF16: "float32",
            POLICY_BF16: "bfloat16"}


def storage_dtype(policy: str) -> Optional[str]:
    """Boundary storage dtype name a policy implies for float32 leaves;
    None means 'leave the propagated dtype alone'."""
    name = _STORAGE[policy]
    return None if name == "float32" else name


def compute_precision(policy: str) -> Optional[str]:
    """`jax.default_matmul_precision` scope a policy implies, or None
    for the ambient default."""
    return "bfloat16" if policy == POLICY_F32_BF16 else None


# ----------------------------------------------------------------- tolerance


def declared_tolerance(op) -> Optional[str]:
    tol = getattr(op, "precision_tolerance", None)
    if tol in (TOLERANT, COMPUTE, EXACT):
        return tol
    return None


def _float32_leaves(element) -> List:
    if not is_known(element):
        return []
    return [l for l in jax.tree_util.tree_leaves(element)
            if getattr(l, "dtype", None) is not None
            and np.dtype(l.dtype) == np.float32]


def _bf16_element(element):
    """The element with every float32 leaf re-typed bf16 — the probe
    input for the sensitivity check and the storage spec under
    POLICY_BF16."""
    return cast_element(element, "bfloat16")


def cast_element(element, dtype_name: str):
    """Re-type every float32 leaf of an element pytree to ``dtype_name``
    (non-float leaves — labels, indices, masks — are never touched)."""
    if not is_known(element):
        return element

    def one(l):
        if getattr(l, "dtype", None) is not None \
                and np.dtype(l.dtype) == np.float32:
            return jax.ShapeDtypeStruct(tuple(l.shape), np.dtype(dtype_name))
        return l

    return jax.tree_util.tree_map(one, element)


def probe_tolerance(op, element) -> Tuple[str, str]:
    """``(tolerance, source)`` for one operator: the declared contract
    when present, else the eval_shape sensitivity probe — trace the
    stage's per-item transform on a bf16 element; a trace that dies or
    a non-floating output pins the stage. Conservative: anything the
    probe cannot prove tolerant is EXACT."""
    tol = declared_tolerance(op)
    if tol is not None:
        return tol, "declared"
    fn = getattr(op, "single_transform", None)
    if fn is None or not is_known(element) or not _float32_leaves(element):
        return EXACT, "pinned"
    try:
        out = trace_element(lambda x: fn([x]), (_bf16_element(element),))
    except Exception:
        return EXACT, "probe-pinned"
    if not is_known(out):
        return EXACT, "probe-pinned"
    leaves = jax.tree_util.tree_leaves(out)
    # jnp.issubdtype, not np: the probe input is bf16 so floating
    # outputs come back bf16, and numpy does not count ml_dtypes'
    # bfloat16 as np.floating — np.issubdtype here would pin every
    # undeclared stage and make the probe useless
    if leaves and all(
            jax.numpy.issubdtype(np.dtype(l.dtype), jax.numpy.floating)
            for l in leaves if getattr(l, "dtype", None) is not None):
        return TOLERANT, "probed"
    return EXACT, "probe-pinned"


# -------------------------------------------------------------- byte pricing


def policy_nbytes(spec: Any, policy: str,
                  nominal_count: int = 1024) -> Optional[int]:
    """Bytes one boundary materializes under ``policy`` — the
    dtype-aware KP2xx arithmetic: bf16 storage halves float32 leaves,
    every other dtype (uint8 loaders, int32 labels) keeps its real
    itemsize. Falls back to a nominal count when the spec carries
    none (apply-path boundaries)."""
    if not isinstance(spec, DataSpec) or not is_known(spec.element):
        return None
    sd = storage_dtype(policy)
    element = spec.element if sd is None else cast_element(spec.element, sd)
    total = 0
    for leaf in jax.tree_util.tree_leaves(element):
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            return None
        total += int(np.prod(leaf.shape, dtype=np.int64)) \
            * np.dtype(leaf.dtype).itemsize
    if spec.kind == "datum":
        return total
    count = spec.count if spec.count else nominal_count
    return total * int(count)


# ------------------------------------------------------------------ the plan


@dataclass
class PrecisionPlan:
    """The decision: per-stage boundary policies, the all-f32 default
    they were scored against, and both priced byte totals. When
    ``improved`` is False the policies ARE the default and nothing is
    enforced."""

    policies: Dict[GraphId, str]
    default_policies: Dict[GraphId, str]
    planned_cost_bytes: float
    default_cost_bytes: float
    planned_boundary: Dict[NodeId, int] = field(default_factory=dict)
    default_boundary: Dict[NodeId, int] = field(default_factory=dict)
    #: vid -> (tolerance, source) for every inspected stage
    tolerances: Dict[GraphId, Tuple[str, str]] = field(default_factory=dict)

    @property
    def improved(self) -> bool:
        return self.planned_cost_bytes < self.default_cost_bytes

    @property
    def savings_bytes(self) -> int:
        return max(0, int(self.default_cost_bytes - self.planned_cost_bytes))

    def changed_vertices(self) -> List[GraphId]:
        return [vid for vid, pol in sorted(
                    self.policies.items(),
                    key=lambda kv: getattr(kv[0], "id", -1))
                if self.default_policies.get(vid) != pol]

    def storage_for(self, vid) -> Optional[str]:
        """Chosen storage dtype name for a vertex's boundary, or None
        when it keeps its propagated dtype."""
        pol = self.policies.get(vid)
        return storage_dtype(pol) if pol else None

    def retyped_specs(self, specs: Dict[GraphId, Any]) -> Dict[GraphId, Any]:
        """The propagated specs with chosen storage dtypes baked into
        the elements — what the KP2xx/KP600 models price under this
        policy (bf16 halves residency exactly where chosen)."""
        out = dict(specs)
        for vid, pol in self.policies.items():
            sd = storage_dtype(pol)
            spec = specs.get(vid)
            if sd is None or not isinstance(spec, DataSpec):
                continue
            out[vid] = spec.with_element(cast_element(spec.element, sd))
        return out

    def rows(self, graph: Graph, specs: Dict[GraphId, Any]
             ) -> List[Dict[str, Any]]:
        """Per-stage chosen-dtype table (topo order), JSON-ready — the
        ``--explain-precision`` payload."""
        order, _ = toposort(graph)
        rows = []
        for vid in order:
            if not isinstance(vid, NodeId):
                continue
            spec = specs.get(vid)
            if not isinstance(spec, DataSpec):
                continue
            pol = self.policies.get(vid, POLICY_F32)
            tol, source = self.tolerances.get(vid, (EXACT, "pinned"))
            default_b = self.default_boundary.get(vid)
            planned_b = self.planned_boundary.get(vid)
            rows.append({
                "vertex": vid.id,
                "label": _label(graph, vid),
                "policy": pol,
                "dtype": storage_dtype(pol) or _elem_dtype_name(spec),
                "tolerance": tol,
                "tolerance_source": source,
                "default_bytes": default_b,
                "planned_bytes": planned_b,
                "bytes_saved": (default_b - planned_b)
                if default_b is not None and planned_b is not None else 0,
                "changed": pol != self.default_policies.get(vid, POLICY_F32),
            })
        return rows


def _elem_dtype_name(spec: DataSpec) -> str:
    leaves = jax.tree_util.tree_leaves(spec.element) if is_known(
        spec.element) else []
    names = sorted({np.dtype(l.dtype).name for l in leaves
                    if getattr(l, "dtype", None) is not None})
    if not names:
        return "?"
    return names[0] if len(names) == 1 else "+".join(names)


def format_plan(rows: List[Dict[str, Any]]) -> str:
    lines = [f"{'stage':<40} {'dtype':<10} {'tolerance':<18} {'Δbytes':>12}"]
    for r in rows:
        mark = "*" if r["changed"] else " "
        name = f"{r['label']}@{r['vertex']}"
        delta = r["bytes_saved"]
        col = f"-{delta:,d}" if delta else "—"
        lines.append(
            f"{name[:40]:<40} {mark}{r['dtype'][:9]:<9} "
            f"{(r['tolerance'] + '/' + r['tolerance_source'])[:18]:<18} "
            f"{col:>12}")
    return "\n".join(lines)


# ------------------------------------------------------------------- solver


class _PrecisionModel:
    """The priced view of one graph: per-vertex menus (legality flowed
    from tolerances through passthrough stages), dtype-aware boundary
    bytes, and a shared scorer — the DP's choice and the default's
    score come from literally the same arithmetic (the planner's
    `_CostModel` discipline)."""

    def __init__(self, graph: Graph, specs: Dict[GraphId, Any],
                 tolerances: Optional[Dict[GraphId, Tuple[str, str]]] = None):
        self.graph = graph
        self.specs = specs
        order, _ = toposort(graph)
        self.order = [v for v in order if not isinstance(v, SinkId)]
        known_counts = [
            s.count for s in specs.values()
            if isinstance(s, DataSpec) and s.kind == "dataset" and s.count
        ]
        self.nominal_count = max(known_counts, default=1024)
        # `tolerances` lets a caller holding an already-resolved map (a
        # PrecisionPlan's) skip the eval_shape sensitivity probe for
        # undeclared stages; only vertices it misses are probed fresh
        self.tolerances: Dict[GraphId, Tuple[str, str]] = {}
        for vid in self.order:
            if isinstance(vid, NodeId):
                if tolerances is not None and vid in tolerances:
                    self.tolerances[vid] = tolerances[vid]
                else:
                    self.tolerances[vid] = self._tolerance(vid)
        #: vid -> set of legal policies (only vertices with a real menu)
        self.menus: Dict[GraphId, Tuple[str, ...]] = {}
        for vid in self.order:
            menu = self._menu(vid)
            if len(menu) > 1:
                self.menus[vid] = menu

    # ---------------------------------------------------------- legality

    def _tolerance(self, vid: NodeId) -> Tuple[str, str]:
        op = self.graph.get_operator(vid)
        deps = self.graph.get_dependencies(vid)
        in_spec = next(
            (self.specs.get(d) for d in deps
             if isinstance(self.specs.get(d), DataSpec)), None)
        element = in_spec.element if isinstance(in_spec, DataSpec) \
            else UNKNOWN
        return probe_tolerance(op, element)

    def _effective_consumers(self, vid, _seen=None) -> List[GraphId]:
        """Users of ``vid`` with passthrough stages (Cacher, Identity,
        combiners) looked *through*: the stage that actually computes on
        the bytes decides whether reduced precision is tolerable."""
        _seen = _seen if _seen is not None else set()
        out: List[GraphId] = []
        for u in self.graph.users_of(vid):
            if u in _seen:
                continue
            _seen.add(u)
            if isinstance(u, NodeId) and getattr(
                    self.graph.get_operator(u),
                    "precision_passthrough", False):
                out.extend(self._effective_consumers(u, _seen))
            else:
                out.append(u)
        return out

    def _menu(self, vid) -> Tuple[str, ...]:
        if not isinstance(vid, NodeId):
            return (POLICY_F32,)
        spec = self.specs.get(vid)
        if not isinstance(spec, DataSpec) or spec.kind != "dataset" \
                or not spec.on_device or not is_known(spec.element) \
                or not _float32_leaves(spec.element):
            return (POLICY_F32,)
        tol, _ = self.tolerances.get(vid, (EXACT, "pinned"))
        if tol != TOLERANT:
            return (POLICY_F32,)
        for u in self._effective_consumers(vid):
            if isinstance(u, SinkId):
                return (POLICY_F32,)  # the pipeline's visible output
            if not isinstance(u, NodeId):
                return (POLICY_F32,)
            u_tol, _ = self.tolerances.get(u, (EXACT, "pinned"))
            if u_tol != TOLERANT:
                return (POLICY_F32,)
        return (POLICY_F32, POLICY_BF16)

    # ------------------------------------------------------------ pricing

    def vbytes(self, vid, policy: str) -> Optional[int]:
        return policy_nbytes(self.specs.get(vid), policy,
                             self.nominal_count)

    def score(self, policies: Dict[GraphId, str]) -> Tuple[
            float, Dict[NodeId, int]]:
        """``(objective, boundary)``: boundary bytes per vertex under
        the assignment plus a fixed cast penalty per storage flip edge.
        The SAME function scores the chosen plan and the all-f32
        default, so "planner ≤ default" is a property of the
        arithmetic, not of two models agreeing."""
        objective = 0.0
        boundary: Dict[NodeId, int] = {}

        def stor(v) -> str:
            return _STORAGE[policies.get(v, POLICY_F32)]

        for vid in self.order:
            if not isinstance(vid, NodeId):
                continue
            nbytes = self.vbytes(vid, policies.get(vid, POLICY_F32))
            if nbytes is not None and isinstance(
                    self.specs.get(vid), DataSpec):
                spec = self.specs.get(vid)
                if spec.kind == "dataset" and spec.on_device \
                        and is_known(spec.element):
                    objective += nbytes
                    boundary[vid] = int(nbytes)
            for d in self.graph.get_dependencies(vid):
                if isinstance(self.specs.get(d), DataSpec) \
                        and stor(d) != stor(vid) \
                        and (d in self.menus or vid in self.menus):
                    objective += CAST_PENALTY_BYTES
        return objective, boundary


def _plan_path(saved: List[Optional[int]], legal: List[bool]
               ) -> List[bool]:
    """Chain DP over one fan-out-free path of boundaries: choose bf16
    per boundary so that every maximal bf16 run's saved bytes exceed
    its two cast penalties (one down-cast entering the run, one up-cast
    leaving it). Returns the keep/drop decision per boundary. This is
    the exact chain solution — runs are independent, and a run is
    worth keeping iff sum(saved) > 2·CAST_PENALTY_BYTES."""
    out = [False] * len(saved)
    i = 0
    while i < len(saved):
        if not legal[i] or not saved[i]:
            i += 1
            continue
        j = i
        total = 0
        while j < len(saved) and legal[j] and saved[j]:
            total += saved[j]
            j += 1
        if total > 2 * CAST_PENALTY_BYTES:
            for k in range(i, j):
                out[k] = True
        i = j
    return out


def plan_precision(graph: Graph, specs: Dict[GraphId, Any]
                   ) -> Optional[PrecisionPlan]:
    """Choose a per-stage-boundary precision policy minimizing priced
    boundary bytes. Returns None when there is nothing to decide (no
    tolerant float boundary anywhere); otherwise the chain DP runs and
    the better of {optimum, all-f32 default} is returned — ``improved``
    says whether the policy actually beat the reference."""
    model = _PrecisionModel(graph, specs)
    if not model.menus:
        return None
    default = {vid: POLICY_F32 for vid in model.menus}
    default_obj, default_boundary = model.score(default)

    # chain decomposition: maximal fan-out-free runs of choosable
    # vertices (single choosable dep, single user), solved exactly by
    # the run DP; everything else freezes greedily at its own best
    users = {vid: [u for u in graph.users_of(vid)
                   if not isinstance(u, SinkId)]
             for vid in model.order}
    chosen: Dict[GraphId, str] = dict(default)
    visited: set = set()
    for vid in model.order:
        if vid not in model.menus or vid in visited:
            continue
        # walk up to the chain head
        head = vid
        while True:
            deps = [d for d in graph.get_dependencies(head)
                    if d in model.menus]
            if len(deps) == 1 and len(users.get(deps[0], ())) == 1 \
                    and deps[0] not in visited:
                head = deps[0]
            else:
                break
        chain = [head]
        cur = head
        while True:
            kids = [u for u in users.get(cur, ())
                    if isinstance(u, NodeId) and u in model.menus]
            if len(users.get(cur, ())) == 1 and len(kids) == 1 \
                    and kids[0] not in visited:
                chain.append(kids[0])
                cur = kids[0]
            else:
                break
        visited.update(chain)
        saved = []
        legal = []
        for v in chain:
            f32_b = model.vbytes(v, POLICY_F32)
            bf16_b = model.vbytes(v, POLICY_BF16)
            saved.append((f32_b - bf16_b)
                         if f32_b is not None and bf16_b is not None
                         else None)
            legal.append(POLICY_BF16 in model.menus[v])
        for v, keep in zip(chain, _plan_path(saved, legal)):
            if keep:
                chosen[v] = POLICY_BF16

    # bounded local descent: the frontier-merge repair sweep — try the
    # other policy at each vertex, keep strict improvements (scored by
    # the same function both sides use)
    best_obj, _ = model.score(chosen)
    for _sweep in range(2):
        changed = False
        for vid in model.menus:
            for pol in model.menus[vid]:
                if pol == chosen[vid]:
                    continue
                trial = dict(chosen)
                trial[vid] = pol
                trial_obj, _ = model.score(trial)
                if trial_obj < best_obj:
                    chosen, best_obj = trial, trial_obj
                    changed = True
        if not changed:
            break

    planned_obj, planned_boundary = model.score(chosen)
    if not planned_obj < default_obj:
        chosen = dict(default)  # no strict win: the plan IS the default
        planned_obj, planned_boundary = default_obj, default_boundary
    return PrecisionPlan(
        policies=chosen,
        default_policies=default,
        planned_cost_bytes=planned_obj,
        default_cost_bytes=default_obj,
        planned_boundary=planned_boundary,
        default_boundary=default_boundary,
        tolerances=dict(model.tolerances),
    )


# ----------------------------------------------- fused-program stage trails


def stage_tolerance(stage, graph: Graph = None, vid: NodeId = None,
                    slot_index: int = None) -> str:
    """Tolerance of one fused-program stage: a `_FitSlot` reads the
    declared tolerance of the estimator operator that fills it (solvers
    pin EXACT; an undeclared estimator is conservatively EXACT — a fit
    is a whole-dataset reduction), a plain stage its own declaration
    (undeclared fused members are EXACT: inside a program there is no
    probe spec to check against)."""
    from ..workflow.fusion_rule import _FitSlot

    if isinstance(stage, _FitSlot):
        if graph is None or vid is None:
            return EXACT
        deps = graph.get_dependencies(vid)
        if stage.index >= len(deps) or not isinstance(
                deps[stage.index], NodeId):
            return EXACT
        est_op = graph.get_operator(deps[stage.index])
        return declared_tolerance(est_op) or EXACT
    return declared_tolerance(stage) or EXACT


def stage_policy_menu(saved: List[Optional[int]],
                      legal: List[bool]) -> List[Dict[str, Any]]:
    """The priced candidate menu `_plan_path` decides over: one entry
    per maximal legal bf16 run of boundaries, carrying the bytes the
    run would save, the cast penalty it must clear, and whether the DP
    kept it. This is the decision core's own scoring made visible —
    the decision ledger records it as the alternatives the chosen
    policy beat (a rejected run IS a priced alternative: enabling it
    would cost ``2·CAST_PENALTY_BYTES − saved`` net bytes)."""
    menu: List[Dict[str, Any]] = []
    i = 0
    while i < len(saved):
        if not legal[i] or not saved[i]:
            i += 1
            continue
        j = i
        total = 0
        while j < len(saved) and legal[j] and saved[j]:
            total += saved[j]
            j += 1
        menu.append({
            "entry": f"bf16_boundaries_{i}..{j - 1}",
            "bytes_saved": int(total),
            "cast_penalty_bytes": 2 * CAST_PENALTY_BYTES,
            "kept": total > 2 * CAST_PENALTY_BYTES,
        })
        i = j
    return menu


def plan_stage_precision(
    graph: Graph,
    vid: NodeId,
    op,
    specs: Dict[GraphId, Any],
) -> Optional[Tuple[Tuple[Optional[str], ...], int, List[Dict[str, Any]]]]:
    """Per-internal-boundary storage policy for one fused/megafused
    program operator: ``(storage_names, savings_bytes, menu)`` where
    ``storage_names[i]`` is the dtype name stage ``i``'s output is cast
    to inside the program (None = untouched), aligned with the
    operator's PEEPHOLED stage list (the list `_build_program`
    executes), and ``menu`` is the `stage_policy_menu` of priced
    candidate runs the chain DP scored (kept and rejected — the
    decision ledger's alternatives). The program's final output
    boundary always stays untouched so downstream consumers see
    exactly the PR-9 dtypes. Returns None when the trail cannot be
    priced (unknown elements)."""
    from ..nodes.util.fusion import _peephole
    from ..workflow.fusion_rule import _FitSlot

    stage_specs = getattr(op, "stage_specs", None)
    if stage_specs is None:
        stage_specs = list(getattr(op, "stages", []))
    stages = _peephole(stage_specs)
    deps = graph.get_dependencies(vid)
    if not deps:
        return None
    # a chain's data input is its LAST dependency (est_0..est_k, data);
    # a plain fused transformer's its only one — deps[-1] serves both
    data_spec = specs.get(deps[-1])
    if not isinstance(data_spec, DataSpec) or not is_known(
            data_spec.element) or data_spec.kind != "dataset":
        return None
    count = data_spec.count or 1024
    t_specs = [specs.get(d) for d in deps[:-1]]

    elem = data_spec.element
    # saved_bytes[i]: bytes halving stage i's OUTPUT boundary saves
    # across the whole dataset (2 bytes per float32 element), None when
    # the boundary has no float32 leaves to halve. restore_names[i]: the
    # boundary's OWN single-leaf floating dtype name — the cast that
    # re-asserts the unplanned trail's dtype at that point — None when
    # the boundary is multi-leaf or non-float (unrestorable).
    saved_bytes: List[Optional[int]] = []
    restore_names: List[Optional[str]] = []
    tols: List[str] = []
    for s in stages:
        tols.append(stage_tolerance(s, graph, vid))
        if not is_known(elem):
            return None
        try:
            if isinstance(s, _FitSlot):
                ts = t_specs[s.index] if s.index < len(t_specs) else None
                elem = (ts.apply_element(elem)
                        if isinstance(ts, TransformerSpec) else UNKNOWN)
            else:
                elem = trace_element(
                    lambda x, s=s: s.single_transform([x]), (elem,))
        except Exception:
            return None
        if not is_known(elem):
            return None
        f32_leaves = _float32_leaves(elem)
        saved = sum(
            int(np.prod(l.shape, dtype=np.int64)) * 2 for l in f32_leaves)
        saved_bytes.append(saved * count if f32_leaves else None)
        leaves = jax.tree_util.tree_leaves(elem)
        restore_names.append(
            np.dtype(leaves[0].dtype).name
            if len(leaves) == 1 and np.issubdtype(
                np.dtype(leaves[0].dtype), np.floating) else None)

    # boundary i sits between stage i and stage i+1: it may be bf16
    # only when both sides tolerate it; the final boundary (the program
    # output) is never reduced
    n = len(stages)
    legal = [
        tols[i] == TOLERANT and tols[i + 1] == TOLERANT
        and saved_bytes[i] is not None
        for i in range(n - 1)
    ] + [False]
    keep = _plan_path(saved_bytes, legal)
    menu = stage_policy_menu(saved_bytes, legal)

    # Every kept bf16 run must be RESTORED at its exit boundary: the
    # fused stage bodies deliberately follow their input dtype (the
    # KJ011 discipline), so without an explicit up-cast the bf16 would
    # silently flow past the first f32 boundary into exact stages —
    # producing the very KP701 failure the menu legality priced out.
    # The exit entry re-asserts the trail's own dtype (the program
    # output entry serves as the exit for a run reaching the last
    # internal boundary); a run whose exit boundary is unrestorable
    # (multi-leaf / non-float) is dropped entirely.
    storage: List[Optional[str]] = [None] * n
    savings = 0
    i = 0
    while i < n - 1:
        if not keep[i]:
            i += 1
            continue
        j = i
        while j < n - 1 and keep[j]:
            j += 1
        exit_restore = restore_names[j]
        if exit_restore is not None:
            for k in range(i, j):
                storage[k] = "bfloat16"
                savings += saved_bytes[k] or 0
            storage[j] = exit_restore
        else:
            # the DP kept the run but the exit boundary cannot re-assert
            # its dtype: the run is dropped — the menu must say so, or
            # the ledger would record an alternative as chosen
            for entry in menu:
                if entry["entry"] == f"bf16_boundaries_{i}..{j - 1}":
                    entry["kept"] = False
                    entry["dropped"] = "unrestorable_exit_boundary"
        i = j
    # defensive: always re-assert the program's visible output dtype
    # when it is known (a same-dtype astype is an identity, so an
    # untouched trail compiles to exactly the PR-9 program)
    if storage[n - 1] is None:
        storage[n - 1] = restore_names[n - 1]
    if not savings:
        return None
    return tuple(storage), int(savings), menu


# ------------------------------------------------------------------- lints


def precision_pass(
    graph: Graph,
    specs: Dict[GraphId, Any],
    plan: Optional[PrecisionPlan] = None,
) -> List[Diagnostic]:
    """Lint a chosen (or externally supplied) precision policy:

      - KP701 (ERROR): a reduced-precision policy on a boundary whose
        producer or an effective consumer declares/probes EXACT — the
        legality contract the planner enforces, checked independently
        so a hand-written policy fails loudly;
      - KP702 (WARNING): cast-thrash — a bf16 boundary whose every
        consumer's own boundary is f32 and whose saved bytes do not
        cover the two casts the flip pair costs: the downcast is undone
        immediately downstream for nothing;
      - KP703 (INFO): dtype-dependent memory re-pricing — the stages
        whose KP2xx residency the chosen policy halves, old → new, so
        the static memory numbers visibly track the decided dtypes.
    """
    if plan is None:
        return []
    diags: List[Diagnostic] = []
    model = _PrecisionModel(graph, specs, tolerances=plan.tolerances)
    for vid, pol in sorted(plan.policies.items(),
                           key=lambda kv: getattr(kv[0], "id", -1)):
        if pol in (None, POLICY_F32) or not isinstance(vid, NodeId):
            continue
        label = _label(graph, vid)
        tol, source = model.tolerances.get(vid, (EXACT, "pinned"))
        bad = tol != TOLERANT
        bad_consumer = None
        # a compute-only policy (f32_bf16) leaves the boundary storage
        # f32, so consumers still see full precision — only the stage
        # computing under it must tolerate; a storage policy degrades
        # what every effective consumer RECEIVES, so both sides must
        if storage_dtype(pol) is not None:
            for u in model._effective_consumers(vid):
                if isinstance(u, SinkId) or not isinstance(u, NodeId):
                    bad_consumer = u
                    break
                u_tol, _ = model.tolerances.get(u, (EXACT, "pinned"))
                if u_tol != TOLERANT:
                    bad_consumer = u
                    break
        if bad or bad_consumer is not None:
            who = ("this stage declares/probes "
                   f"{tol!r} ({source})" if bad else
                   f"consumer {_label(graph, bad_consumer)}@{bad_consumer} "
                   "does not tolerate reduced precision")
            diags.append(Diagnostic(
                "KP701", Severity.ERROR,
                f"precision policy {pol!r} on an intolerant boundary: "
                f"{who}; the policy would silently degrade an exact "
                "stage's inputs",
                vertex=vid, label=label))
            continue
        if storage_dtype(pol) is None:
            continue  # compute-only policy: no boundary bytes to thrash
        f32_b = model.vbytes(vid, POLICY_F32)
        bf16_b = model.vbytes(vid, POLICY_BF16)
        saved = (f32_b - bf16_b) if f32_b and bf16_b else 0
        consumers = [u for u in model._effective_consumers(vid)
                     if isinstance(u, NodeId)]
        undone = consumers and all(
            storage_dtype(plan.policies.get(u, POLICY_F32)) is None
            for u in consumers)
        if undone and saved <= 2 * CAST_PENALTY_BYTES:
            diags.append(Diagnostic(
                "KP702", Severity.WARNING,
                f"cast-thrash: this boundary stores bf16 but every "
                f"consumer's boundary is f32 and the halving saves only "
                f"{_fmt_bytes(int(saved))} — less than the two "
                "convert_element_type casts the flip pair costs; drop "
                "the policy here",
                vertex=vid, label=label))
    return diags


def reprice_memory(
    graph: Graph,
    specs: Dict[GraphId, Any],
    plan: PrecisionPlan,
    **memory_kwargs,
) -> Tuple[Any, Any, List[Diagnostic]]:
    """Re-run the KP2xx memory model under the chosen policy's storage
    dtypes: ``(default_estimate, planned_estimate, diags)`` where the
    KP703 INFO diagnostics name each stage whose residency the policy
    changed (bf16 halves exactly the chosen float boundaries)."""
    est0, _ = memory_pass(graph, specs, **memory_kwargs)
    est1, _ = memory_pass(graph, plan.retyped_specs(specs),
                          **memory_kwargs)
    diags: List[Diagnostic] = []
    for vid in sorted(est0.resident, key=lambda v: v.id):
        a, b = est0.resident.get(vid), est1.resident.get(vid)
        if a and b and a != b:
            diags.append(Diagnostic(
                "KP703", Severity.INFO,
                f"dtype-aware re-pricing: residency {_fmt_bytes(a)} → "
                f"{_fmt_bytes(b)} under the chosen precision policy",
                vertex=vid, label=_label(graph, vid)))
    return est0, est1, diags


# ------------------------------------------------------------------ banding


def shrink_to_band(
    plan: PrecisionPlan,
    evaluate: Callable[[PrecisionPlan], bool],
    rescore: Optional[Callable[[Dict[GraphId, str]],
                               Tuple[float, Dict[NodeId, int]]]] = None,
) -> PrecisionPlan:
    """Discard a policy stage-by-stage until ``evaluate`` (the
    allclose-vs-serial-unfused band check) passes: the largest-savings
    reduced boundary is reverted first, so the policy sheds the most
    numerically aggressive halvings before giving up entirely. The
    all-f32 default always evaluates in band by construction, so this
    terminates with a shippable plan.

    ``rescore`` (a ``_PrecisionModel.score`` bound method) keeps the
    shrunk plan's cost EXACT — a revert can split a bf16 run and change
    the number of cast-penalty edges, which the byte-only fallback
    cannot see. Without it the adjustment restores boundary bytes only
    (an upper bound on the true objective), and a fully-reverted plan
    is clamped to the default's own cost."""
    current = plan
    while not evaluate(current):
        changed = current.changed_vertices()
        if not changed:
            return current  # already the default; the band check is
            # measuring something other than this policy
        worst = max(
            changed,
            key=lambda v: current.default_boundary.get(v, 0)
            - current.planned_boundary.get(v, 0))
        policies = dict(current.policies)
        policies[worst] = current.default_policies.get(worst, POLICY_F32)
        if rescore is not None:
            cost, planned_boundary = rescore(policies)
        else:
            cost = current.planned_cost_bytes + (
                current.default_boundary.get(worst, 0)
                - current.planned_boundary.get(worst, 0))
            planned_boundary = dict(current.planned_boundary)
            planned_boundary[worst] = current.default_boundary.get(worst, 0)
            if all(policies.get(v) == current.default_policies.get(v)
                   for v in policies):
                cost = current.default_cost_bytes
        current = PrecisionPlan(
            policies=policies,
            default_policies=current.default_policies,
            planned_cost_bytes=cost,
            default_cost_bytes=current.default_cost_bytes,
            planned_boundary=planned_boundary,
            default_boundary=current.default_boundary,
            tolerances=current.tolerances,
        )
    return current
