"""Registry-wide static operator contract auditor (the KP5xx family).

The whole PR 4–6 performance stack — fusion, megafusion, donation, the
concurrent DAG scheduler — rests on contracts operators *declare*
(``fusable``/``fuse()``, ``chunkable``, ``fusable_fit``,
``donates_deps``, ``fuse_masks_output``) and nothing verified: PR 6
found five stages declaring ``fusable`` without a ``fuse()``
decomposition, silently re-tracing every re-apply at ~5× cost. This
module makes those contracts *checked properties* (the
KeystoneML-soundness discipline of arXiv 1610.09451 — the optimizer is
only correct because capability declarations are truthful — enforced as
a compiler-level safety pass in the spirit of arXiv 2206.14148):

  KP501  fusable-without-structural-fuse: a stage declaring ``fusable``
         (or promised through an estimator's ``fusable_fit``) whose
         fused-program key path is id-keyed ("opaque") — detected by
         running the SAME decomposition the fusion builder uses
         (`nodes.util.fusion._stage_fuse`) and inspecting the static
         key, not by naming convention. Opaque keys mean every fused
         program containing the stage is cached per-instance and
         re-traced on every rebuilt pipeline — the PR-6 silent-retrace
         bug class.
  KP502  chunkable-non-distributive: ``chunkable = True`` whose batch
         path provably does not distribute over host chunks — the
         `jax.eval_shape` of the whole-batch form must agree with the
         concatenation of the chunk forms (`specs.trace_element`, zero
         data movement). A batch path that reduces over the example
         axis or grows a non-leading axis with n would return corrupt
         values the moment the overlap engine streams chunks through it.
  KP503  donation-not-implemented: ``donates_deps`` declared but no
         jitted step reachable from the operator's methods carries
         ``donate_argnums`` (or its donated indices exceed the step's
         signature) — the intra-operator complement of the graph-level
         KP301 hazard: the analyzer restricts the producer's consumers
         for a donation that never actually happens.
  KP504  unmasked-fused-stage: a ``fusable`` stage whose *unfused*
         batch path consumes the dataset's padded-row ``mask`` but
         which does not declare ``fuse_masks_output`` — inside a fused
         program the stage would stop re-zeroing padded rows and
         mask-less reductions downstream (`_moments`,
         `_normal_equations`) would read garbage: the padded-row
         corruption class PR 4's review caught by hand.

Two surfaces:

  - ``contract_pass(graph, specs)`` — instance-level checks over every
    operator in a lowered graph, run by ``validate(level="full")``.
  - ``audit_registry()`` / ``python -m keystone_tpu.analysis
    --audit-operators`` — sweeps EVERY registered Operator/Estimator
    subclass (probe instances where construction is known, class-level
    AST checks otherwise), so a new operator inherits the gate without
    ever appearing in an example pipeline.

Genuine exceptions suppress with a ``# keystone: ignore[KP50x]``
comment on the ``class`` line (mirroring jaxlint's line suppressions)
— never by silently skipping the check.
"""

from __future__ import annotations

import ast
import importlib
import inspect
import pkgutil
import re
import sys
import textwrap
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .diagnostics import Diagnostic, Severity
from .specs import DataSpec, is_known, shape_struct, trace_element

_IGNORE_RE = re.compile(r"#\s*keystone:\s*ignore\[([A-Z0-9,\s]+)\]")

#: modules swept for Operator subclasses — importing them registers
#: every built-in node class via ``__subclasses__``.
_REGISTRY_ROOTS = (
    "keystone_tpu.nodes",
    "keystone_tpu.workflow.pipeline",
    "keystone_tpu.workflow.operators",
    "keystone_tpu.workflow.fusion_rule",
)


# ---------------------------------------------------------------- registry


def _import_registry() -> None:
    for root in _REGISTRY_ROOTS:
        mod = importlib.import_module(root)
        if hasattr(mod, "__path__"):
            for info in pkgutil.walk_packages(mod.__path__, root + "."):
                try:
                    importlib.import_module(info.name)
                except Exception:
                    pass  # an optional-dep module must not kill the sweep


def _all_subclasses(cls: type) -> Iterable[type]:
    for sub in cls.__subclasses__():
        yield sub
        yield from _all_subclasses(sub)


def operator_registry() -> List[type]:
    """Every registered Operator subclass defined inside keystone_tpu,
    deterministically ordered."""
    from ..workflow.operators import Operator

    _import_registry()
    seen: Dict[type, None] = {}
    for cls in _all_subclasses(Operator):
        if cls.__module__.startswith("keystone_tpu."):
            seen.setdefault(cls)
    return sorted(seen, key=lambda c: (c.__module__, c.__qualname__))


# ------------------------------------------------------------------ probes

#: qualname -> zero-arg factory returning (instance, element_shapes).
#: Probes exist so classes whose constructors need arguments still get
#: instance-level checks (property-valued ``fusable``, fuse-key
#: inspection, the KP502 distributivity trace). A contract-bearing
#: class without a probe falls back to class-level checks only.
def _probe_factories() -> Dict[str, Any]:
    def conv():
        from ..nodes.images.core import Convolver

        return Convolver(
            np.ones((2, 3, 3, 3), np.float32), 8, 8, 3), [(8, 8, 3)]

    def conv_rect_pool():
        from ..nodes.images.core import Convolver
        from ..nodes.util.fusion import _ConvRectifyPoolStage

        c = Convolver(np.ones((2, 3, 3, 3), np.float32), 8, 8, 3)
        return _ConvRectifyPoolStage(c, 0.0, 0.0, 2, 2), [(8, 8, 3)]

    def fused_chain(cls_name):
        def make():
            import keystone_tpu.nodes.util.fusion as fz
            from ..nodes.stats.normalization import SignedHellingerMapper

            return getattr(fz, cls_name)([SignedHellingerMapper()]), [(6,)]

        return make

    table = {
        "Convolver": conv,
        "_ConvRectifyPoolStage": conv_rect_pool,
        "_RectifyPoolStage": lambda: (
            _cls("keystone_tpu.nodes.util.fusion", "_RectifyPoolStage")(
                0.0, 0.0, 2, 2), [(8, 8, 2)]),
        "Pooler": lambda: (
            _cls("keystone_tpu.nodes.images.core", "Pooler")(2, 2),
            [(8, 8, 3)]),
        "Cropper": lambda: (
            _cls("keystone_tpu.nodes.images.core", "Cropper")(0, 0, 4, 4),
            [(8, 8, 3)]),
        "ClassLabelIndicatorsFromInt": lambda: (
            _cls("keystone_tpu.nodes.util.basic",
                 "ClassLabelIndicatorsFromInt")(4), [()]),
        "ClassLabelIndicatorsFromIntArray": lambda: (
            _cls("keystone_tpu.nodes.util.basic",
                 "ClassLabelIndicatorsFromIntArray")(4), [(3,)]),
        "ColumnSampler": lambda: (
            _cls("keystone_tpu.nodes.stats.normalization",
                 "ColumnSampler")(4), [(8, 6)]),
        "CosineRandomFeatures": lambda: (
            _cls("keystone_tpu.nodes.stats.random_features",
                 "CosineRandomFeatures")(6, 8), [(6,)]),
        "RandomSignNode": lambda: (
            _cls("keystone_tpu.nodes.stats.random_features",
                 "RandomSignNode")(6), [(6,)]),
        "StandardScalerModel": lambda: (
            _cls("keystone_tpu.nodes.stats.scalers", "StandardScalerModel")(
                np.zeros(6, np.float32), np.ones(6, np.float32)), [(6,)]),
        "LinearMapper": lambda: (
            _cls("keystone_tpu.nodes.learning.linear", "LinearMapper")(
                np.ones((6, 3), np.float32)), [(6,)]),
        "BlockLinearMapper": lambda: (
            _cls("keystone_tpu.nodes.learning.block_ls",
                 "BlockLinearMapper")(np.ones((6, 3), np.float32)), [(6,)]),
        "BlockLeastSquaresEstimator": lambda: (
            _cls("keystone_tpu.nodes.learning.block_ls",
                 "BlockLeastSquaresEstimator")(4, 1), [(6,)]),
        "MatrixVectorizer": lambda: (
            _cls("keystone_tpu.nodes.util.basic", "MatrixVectorizer")(),
            [(4, 3)]),
        "_FunctionTransformer": lambda: (
            _cls("keystone_tpu.workflow.pipeline", "_FunctionTransformer")(
                lambda x: x), [(6,)]),
        "TransformerChain": lambda: (
            _cls("keystone_tpu.workflow.pipeline", "TransformerChain")(
                [_cls("keystone_tpu.nodes.stats.normalization",
                      "SignedHellingerMapper")()]), [(6,)]),
        "FusedBatchTransformer": fused_chain("FusedBatchTransformer"),
        "MegafusedBatchTransformer": fused_chain("MegafusedBatchTransformer"),
        "_GatherConcatStage": lambda: (
            _cls("keystone_tpu.nodes.util.fusion", "_GatherConcatStage")(
                [_cls("keystone_tpu.nodes.stats.normalization",
                      "SignedHellingerMapper")()]), [(6,)]),
    }
    return table


def _cls(module: str, name: str) -> type:
    return getattr(importlib.import_module(module), name)


#: element shapes tried when a probe declares none.
_DEFAULT_ELEMS: Tuple[Tuple[int, ...], ...] = ((6,), (8, 8, 3))


def probe_instance(cls: type):
    """Best-effort instance of ``cls`` for instance-level checks:
    ``(instance, element_shapes)`` or ``(None, ())`` when the class
    cannot be constructed without real state."""
    factory = _probe_factories().get(cls.__name__)
    if factory is not None:
        try:
            return factory()
        except Exception:
            return None, ()
    try:
        return cls(), list(_DEFAULT_ELEMS)
    except Exception:
        return None, ()


# --------------------------------------------------------- AST utilities


_MODULE_AST_CACHE: Dict[str, Optional[ast.Module]] = {}


def _module_ast(module_name: str) -> Optional[ast.Module]:
    tree = _MODULE_AST_CACHE.get(module_name, False)
    if tree is not False:
        return tree
    tree = None
    try:
        mod = sys.modules.get(module_name) or importlib.import_module(
            module_name)
        tree = ast.parse(inspect.getsource(mod))
    except Exception:
        tree = None
    _MODULE_AST_CACHE[module_name] = tree
    return tree


def _class_ast(cls: type) -> Optional[ast.ClassDef]:
    """The class's own ``ClassDef`` node (no source → None, e.g. for
    classes built dynamically with ``type(...)``)."""
    try:
        src = textwrap.dedent(inspect.getsource(cls))
    except Exception:
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
            return node
    return None


def suppressed_rules(cls: type) -> frozenset:
    """Rules suppressed with ``# keystone: ignore[KP50x]`` on (or right
    above) the ``class`` line — the explicit genuine-exception channel."""
    try:
        lines, _ = inspect.getsourcelines(cls)
    except Exception:
        return frozenset()
    head = []
    for line in lines:
        head.append(line)
        if line.lstrip().startswith("class ") and line.rstrip().endswith(":"):
            break
        if len(head) > 8:
            break
    out = set()
    for line in head:
        m = _IGNORE_RE.search(line)
        if m:
            out.update(r.strip() for r in m.group(1).split(","))
    return frozenset(out)


def _jit_donations(tree: ast.Module) -> Dict[str, Tuple[Optional[tuple], int]]:
    """Module-level jitted functions: name -> (donate_argnums tuple or
    None when the decorator declares none, positional arity). Recognizes
    ``@jax.jit``/``@jit``/``@partial(jax.jit, ...)`` decorators."""
    out: Dict[str, Tuple[Optional[tuple], int]] = {}
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            is_jit = (
                (isinstance(target, ast.Name) and target.id == "jit")
                or (isinstance(target, ast.Attribute) and target.attr == "jit")
                or (isinstance(dec, ast.Call)
                    and isinstance(dec.func, ast.Name)
                    and dec.func.id == "partial" and dec.args
                    and ((isinstance(dec.args[0], ast.Attribute)
                          and dec.args[0].attr == "jit")
                         or (isinstance(dec.args[0], ast.Name)
                             and dec.args[0].id == "jit")))
            )
            if not is_jit:
                continue
            donate: Optional[tuple] = None
            if isinstance(dec, ast.Call):
                for kw in dec.keywords:
                    if kw.arg == "donate_argnums":
                        try:
                            donate = tuple(ast.literal_eval(kw.value)) \
                                if not isinstance(kw.value, ast.Constant) \
                                else (ast.literal_eval(kw.value),)
                        except Exception:
                            donate = ()
            out[fn.name] = (donate, len(fn.args.args))
            break
    return out


def _called_names(cls_node: ast.ClassDef) -> set:
    names = set()
    for sub in ast.walk(cls_node):
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Name):
                names.add(sub.func.id)
            elif isinstance(sub.func, ast.Attribute):
                names.add(sub.func.attr)
    return names


def _batch_methods(cls_node: ast.ClassDef) -> List[ast.FunctionDef]:
    return [n for n in cls_node.body
            if isinstance(n, ast.FunctionDef)
            and n.name in ("apply_batch", "batch_transform")]


def _reads_mask(cls: type) -> bool:
    """Does the class's unfused batch path read a dataset ``.mask``
    (directly, or by passing it into a module-level jitted helper)?
    Walks the MRO: an INHERITED masking batch path re-inherits the
    padded-row contract just the same."""
    for klass in cls.__mro__:
        node = _class_ast(klass)
        if node is None:
            continue
        for fn in _batch_methods(node):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute) and sub.attr == "mask" \
                        and isinstance(sub.ctx, ast.Load):
                    return True
    return False


# ----------------------------------------------------------- rule checks


def _static_attr(cls: type, name: str):
    """Class attribute WITHOUT triggering properties: the raw descriptor
    for property-valued contracts, the plain value otherwise."""
    try:
        return inspect.getattr_static(cls, name)
    except AttributeError:
        return None


def _defines_fuse(cls: type) -> bool:
    return callable(getattr(cls, "fuse", None))


def _decompose(op) -> Tuple[Optional[Any], Any, Any, Optional[str]]:
    """The stage's fused-program decomposition via the SAME path the
    fusion builder uses — ``(key, params, fn, None)`` on success,
    ``(None, None, None, reason)`` when the decomposition itself fails.
    Computed once per audit and shared by KP501 (key inspection) and
    KP502 (distributivity trace)."""
    from ..nodes.util.fusion import _stage_fuse

    try:
        key, params, fn = _stage_fuse(op)
        return key, params, fn, None
    except Exception as e:
        return None, None, None, f"{type(e).__name__}: {e}"


def _kp501_instance(op, label: str, decomp=None,
                    vertex=None) -> List[Diagnostic]:
    from ..nodes.util.fusion import _contains_opaque

    if not getattr(op, "fusable", False):
        return []
    key, _, _, err = decomp if decomp is not None else _decompose(op)
    if err is not None:
        return [Diagnostic(
            "KP501", Severity.WARNING,
            f"fusable stage's fuse() decomposition failed ({err}); fused "
            "programs containing it cannot build",
            vertex=vertex, label=label)]
    if _contains_opaque(key):
        how = ("declares fusable but implements no fuse() decomposition"
               if not _defines_fuse(type(op))
               else "fuse() returns an id-keyed (opaque) component")
        return [Diagnostic(
            "KP501", Severity.WARNING,
            f"{how}: fused programs containing this stage are cached per "
            "instance and silently re-traced on every rebuilt pipeline "
            "(the PR-6 ~5x re-apply retrace class); implement a "
            "structural fuse() with params as traced arguments",
            vertex=vertex, label=label)]
    return []


def _elem_struct(shape) -> Any:
    return shape_struct(tuple(shape), np.float32)


def _kp502_instance(op, label: str, elems: Sequence[Any], decomp=None,
                    vertex=None) -> List[Diagnostic]:
    """Distributivity of the declared-chunkable batch path, proven (or
    refuted) shape-level: trace the whole-batch form and two chunk
    forms; concat of chunks must agree with the whole."""
    import jax

    if not getattr(op, "chunkable", False):
        return []
    _, params, fn, err = decomp if decomp is not None else _decompose(op)
    if err is not None:
        return []  # decomposition failure already reported by KP501

    for elem in elems:
        if not (hasattr(elem, "shape") and hasattr(elem, "dtype")):
            elem = _elem_struct(elem)
        shapes = {}
        failed = False
        for n in (3, 4, 7):
            xs = jax.ShapeDtypeStruct((n,) + tuple(elem.shape), elem.dtype)
            ms = jax.ShapeDtypeStruct((n,), np.bool_)
            try:
                out = trace_element(
                    lambda xb, mb: fn(params, xb, mb), (xs, ms))
            except Exception:
                # a shape complaint against a PROBE element only means
                # the probe guessed the wrong input shape — try the next
                # candidate; the pipeline-level pass uses real specs
                failed = True
                break
            if not is_known(out) or not (
                    hasattr(out, "shape") and hasattr(out, "dtype")):
                failed = True  # host code / pytree out: not provable
                break
            shapes[n] = (tuple(out.shape), np.dtype(out.dtype))
        if failed:
            continue
        (s3, d3), (s4, d4), (s7, d7) = shapes[3], shapes[4], shapes[7]
        # chunk outputs must concatenate into the whole-batch output:
        # identical tails/dtypes and leading axes that add up
        ok = (
            len(s3) == len(s4) == len(s7)
            and len(s3) >= 1
            and s3[1:] == s4[1:] == s7[1:]
            and d3 == d4 == d7
            and s3[0] + s4[0] == s7[0]
        )
        if not ok:
            return [Diagnostic(
                "KP502", Severity.ERROR,
                "declares chunkable but the batch path provably does not "
                f"distribute over chunks: eval_shape gives {s3}+{s4} for "
                f"chunks of 3+4 rows vs {s7} for the whole 7-row batch "
                "(f(concat(chunks)) != concat(f(chunks))); drop the "
                "chunkable declaration or make the batch path map-like "
                "in the example axis",
                vertex=vertex, label=label)]
        return []  # proven distributive on the first traceable element
    return []


def _kp503_class(cls: type) -> List[Diagnostic]:
    donates = _static_attr(cls, "donates_deps")
    if not isinstance(donates, tuple) or not donates:
        return []
    label = cls.__name__
    # walk the MRO: donates_deps resolves through inheritance, so the
    # jitted step that honors it may live in (and call into) any base
    # class's module — an empty-body subclass of an honest donor is
    # just as honest
    called: set = set()
    jitted: Dict[str, Tuple[Optional[tuple], int]] = {}
    any_source = False
    for klass in cls.__mro__:
        tree = _module_ast(klass.__module__)
        node = _class_ast(klass)
        if tree is None or node is None:
            continue
        any_source = True
        mod_jitted = _jit_donations(tree)
        jitted.update(
            {n: v for n, v in mod_jitted.items() if n not in jitted})
        called |= _called_names(node) & set(mod_jitted)
    if not any_source:
        return [Diagnostic(
            "KP503", Severity.WARNING,
            "declares donates_deps but its source is unavailable for the "
            "donate_argnums cross-check",
            label=label)]
    donated_steps = {n: jitted[n] for n in called if jitted[n][0]}
    if not called:
        return [Diagnostic(
            "KP503", Severity.WARNING,
            f"declares donates_deps={donates!r} but no jitted step is "
            "reachable from its methods; the promised buffer donation "
            "never happens (and KP301 restricts the producer's consumers "
            "for nothing)",
            label=label)]
    if not donated_steps:
        return [Diagnostic(
            "KP503", Severity.WARNING,
            f"declares donates_deps={donates!r} but none of its jitted "
            f"steps ({', '.join(sorted(called))}) carries donate_argnums; "
            "the dependency buffer is never actually donated",
            label=label)]
    bad = [
        f"{name}: donate_argnums={dn} exceeds its {arity} parameter(s)"
        for name, (dn, arity) in donated_steps.items()
        if any(i >= arity for i in dn)
    ]
    if bad:
        return [Diagnostic(
            "KP503", Severity.WARNING,
            "donate_argnums is mis-indexed against the step signature: "
            + "; ".join(sorted(bad)),
            label=label)]
    return []


def _kp504_class(cls: type) -> List[Diagnostic]:
    if not isinstance(_static_attr(cls, "fusable"), bool) \
            or not cls.fusable:
        # property-valued fusable classes are checked per instance
        if not isinstance(getattr(cls, "fusable", False), property):
            return []
    if bool(_static_attr(cls, "fuse_masks_output")):
        return []
    if not _reads_mask(cls):
        return []
    return [Diagnostic(
        "KP504", Severity.ERROR,
        "the unfused batch path masks padded rows (reads the dataset "
        "mask) but the class declares no fuse_masks_output — inside a "
        "fused program padded rows would stop being re-zeroed and "
        "mask-less reductions downstream would read corrupt values "
        "(the padded-row class PR 4's review caught by hand)",
        label=cls.__name__)]


def _mask_aware_fuse(op) -> bool:
    """A fuse() decomposition carrying the mask-aware sentinel threads
    the padded-row mask through its inner stages by construction — it
    cannot corrupt padded rows, so KP504 does not apply (the fusion
    machinery classes: FusedBatchTransformer, _GatherConcatStage)."""
    f = getattr(op, "fuse", None)
    if f is None:
        return False
    try:
        from ..nodes.util.fusion import _MASK_AWARE

        res = f()
        return len(res) == 4 and res[3] == _MASK_AWARE
    except Exception:
        return False


def _fit_return_classes(cls: type) -> List[type]:
    """Classes constructed in ``fit``/``fit_datasets`` return statements,
    resolved against the defining module's namespace — the static answer
    to 'what transformer does this estimator produce?'."""
    node = _class_ast(cls)
    if node is None:
        return []
    mod = sys.modules.get(cls.__module__)
    ns = vars(mod) if mod is not None else {}
    out: List[type] = []
    for fn in node.body:
        if not isinstance(fn, ast.FunctionDef) \
                or fn.name not in ("fit", "fit_datasets"):
            continue
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Return)
                    and isinstance(sub.value, ast.Call)):
                continue
            f = sub.value.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            got = ns.get(name)
            if isinstance(got, type):
                out.append(got)
    return out


def _kp501_estimator_class(cls: type) -> List[Diagnostic]:
    """``fusable_fit`` promises the fit yields a traceable transformer;
    the fitted class must therefore carry a structural fuse() or every
    fused chain absorbing this boundary re-traces per instance."""
    from ..workflow.operators import Operator

    if not bool(_static_attr(cls, "fusable_fit")):
        return []
    diags: List[Diagnostic] = []
    for fitted in _fit_return_classes(cls):
        if not (isinstance(fitted, type) and issubclass(fitted, Operator)):
            continue
        fus = _static_attr(fitted, "fusable")
        declared = (isinstance(fus, property)
                    or (isinstance(fus, bool) and fus))
        if declared and not _defines_fuse(fitted):
            diags.append(Diagnostic(
                "KP501", Severity.WARNING,
                f"fusable_fit promises a traceable fit, but the fitted "
                f"class {fitted.__name__} declares fusable without a "
                "structural fuse() — fused chains crossing this "
                "estimator boundary get id-keyed programs and re-trace "
                "on every re-apply",
                label=cls.__name__))
    return diags


# ------------------------------------------------------------- audit API


def audit_operator(op, elems: Sequence[Any] = (),
                   vertex=None) -> List[Diagnostic]:
    """Instance-level contract audit of one operator: KP501 (fuse-key
    inspection), KP502 (distributivity trace over ``elems``), and the
    class-level KP503/KP504 AST cross-checks. Honors the class-line
    ``# keystone: ignore[KP50x]`` suppression."""
    cls = type(op)
    label = getattr(op, "label", cls.__name__)
    decomp = _decompose(op)
    diags: List[Diagnostic] = []
    diags.extend(_kp501_instance(op, label, decomp, vertex=vertex))
    if elems:
        diags.extend(_kp502_instance(op, label, elems, decomp,
                                     vertex=vertex))
    kp504 = _kp504_class(cls)
    if kp504 and _mask_aware_fuse(op):
        kp504 = []
    for d in _kp503_class(cls) + kp504 + _kp501_estimator_class(cls):
        diags.append(Diagnostic(d.rule, d.severity, d.message,
                                vertex=vertex, label=label))
    sup = suppressed_rules(cls)
    return [d for d in diags if d.rule not in sup]


def audit_class(cls: type) -> Tuple[List[Diagnostic], bool]:
    """Registry-side audit of one operator class. Returns
    ``(diagnostics, probed)`` — ``probed`` False means only the
    class-level (AST) checks could run."""
    op, elems = probe_instance(cls)
    diags: List[Diagnostic] = []
    if op is not None:
        decomp = _decompose(op)
        diags.extend(_kp501_instance(op, cls.__name__, decomp))
        diags.extend(_kp502_instance(op, cls.__name__, elems, decomp))
    else:
        fus = _static_attr(cls, "fusable")
        if isinstance(fus, bool) and fus and not _defines_fuse(cls):
            diags.extend(_kp501_instance_classlevel(cls))
    diags.extend(_kp503_class(cls))
    kp504 = _kp504_class(cls)
    if kp504 and op is not None and _mask_aware_fuse(op):
        kp504 = []
    diags.extend(kp504)
    diags.extend(_kp501_estimator_class(cls))
    sup = suppressed_rules(cls)
    return [d for d in diags if d.rule not in sup], op is not None


def _kp501_instance_classlevel(cls: type) -> List[Diagnostic]:
    return [Diagnostic(
        "KP501", Severity.WARNING,
        "declares fusable but implements no fuse() decomposition: fused "
        "programs containing this stage are cached per instance and "
        "silently re-traced on every rebuilt pipeline (the PR-6 ~5x "
        "re-apply retrace class)",
        label=cls.__name__)]


def audit_registry() -> Tuple[List[Tuple[type, Diagnostic]], Dict[str, int]]:
    """Sweep every registered Operator/Estimator subclass. Returns the
    per-class findings plus sweep statistics."""
    findings: List[Tuple[type, Diagnostic]] = []
    probed = 0
    classes = operator_registry()
    for cls in classes:
        diags, was_probed = audit_class(cls)
        probed += bool(was_probed)
        findings.extend((cls, d) for d in diags)
    return findings, {"classes": len(classes), "probed": probed}


# ------------------------------------------------------------ graph pass


def _input_elems(graph, node, specs) -> List[Any]:
    """Known dataset element specs feeding this node — the KP502 trace
    runs against the pipeline's REAL propagated shapes when available."""
    elems = []
    for d in graph.get_dependencies(node):
        s = specs.get(d)
        if isinstance(s, DataSpec) and is_known(s.element) \
                and hasattr(s.element, "shape"):
            elems.append(s.element)
    return elems[:1]


def contract_pass(graph, specs: Optional[Dict] = None) -> List[Diagnostic]:
    """KP5xx contract audit over every operator instance in a lowered
    graph (the ``validate(level="full")`` surface). Input element specs
    come from the analyzer's propagation, so the KP502 distributivity
    trace uses the pipeline's actual shapes."""
    from .propagate import _label

    specs = specs or {}
    diags: List[Diagnostic] = []
    for node in sorted(graph.operators, key=lambda n: n.id):
        op = graph.get_operator(node)
        try:
            diags.extend(audit_operator(
                op, _input_elems(graph, node, specs), vertex=node))
        except Exception:
            continue  # the audit must never break validation
    # one finding per (rule, anchor): composite operators can repeat
    seen = set()
    out = []
    for d in diags:
        k = (d.rule, d.anchor, d.message)
        if k not in seen:
            seen.add(k)
            out.append(d)
    return out
