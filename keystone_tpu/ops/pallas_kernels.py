"""Pallas TPU kernels for the hot ops, with XLA fallbacks.

Two ops dominate HBM traffic in the flagship pipelines:

1. **Two-sided rectify + sum-pool** (RandomPatchCifar serving path,
   reference SymmetricRectifier.scala:7-32 then Pooler.scala:21-69).
   The XLA lowering materializes the channel-doubled rectified tensor
   (N·H·W·2K floats) in HBM before `reduce_window` shrinks it ~100×.
   The Pallas kernel reads the conv output once per batch block and
   writes only the pooled grid — one HBM pass instead of three.

2. **RBF kernel block** K(X, Yb) = exp(-γ‖x−y‖²) (reference
   KernelGenerator.scala:18-206), the inner op of kernel ridge
   regression. The Pallas kernel tiles the Gram GEMM onto the MXU with
   an f32 VMEM accumulator and applies the distance/exp epilogue before
   the (m, b) block ever leaves VMEM, instead of round-tripping the
   GEMM output through HBM for a separate elementwise kernel.

Every op has `*_reference` (pure jnp — the XLA path, also the CPU/test
oracle) and a dispatcher. Kernels are runnable in interpret mode on CPU
for unit tests.

**Measured on v5e (1 chip, round 4, 2026-07-30; fresh-valued chained
timing — the transport memoizes byte-identical executions, so earlier
repeat-same-values timings were unreliable):**

- rectify+pool: Pallas wins at EVERY measured shape —
  (2048,27,27,256): 23.2 vs 25.4 ms; (512,27,27,512): 8.3 vs 12.8 ms
  (1.54×); (4096,13,13,128): 6.3 vs 7.9 ms; (1024,54,54,64): 11.2 vs
  12.4 ms. → **default-ON on TPU** (`KEYSTONE_DISABLE_PALLAS_RECTIFY=1`
  reverts). Round 2's parity readings came from the memo-tainted
  methodology.
- RBF block: parity across shapes — (8192×2048,d=1024): 5.36 vs
  5.13 ms; (32768×1024,d=256): 4.85 vs 4.75; (4096×4096,d=2048): 10.4
  vs 11.0; (16384×512,d=64): 2.10 vs 2.12. → stays opt-in
  (`KEYSTONE_ENABLE_PALLAS=1`), kept because the VMEM-epilogue
  structure is the right shape for pods/toolchains where XLA's fusion
  regresses, with parity documented here.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _kernels_enabled() -> bool:
    """The ONE master switch over every Pallas kernel this library
    owns: `ExecutionConfig.pallas_kernels` (env
    ``KEYSTONE_CHAIN_KERNELS``, ledger-header recorded so ``--diff``
    names a kernel flip as the suspect kill switch). The per-kernel env
    knobs below remain as documented overrides UNDER this switch —
    their opt-in/opt-out defaults reflect each kernel's measured
    verdict, the master switch reflects trust in Pallas at all."""
    from ..workflow.env import execution_config

    return execution_config().pallas_kernels


def use_pallas() -> bool:
    """Trace-time gate for the RBF kernel: opt-in (measured XLA parity,
    module docstring) and TPU-only."""
    if not _kernels_enabled():
        return False
    if os.environ.get("KEYSTONE_ENABLE_PALLAS") != "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_rectify_pallas() -> bool:
    """Trace-time gate for the standalone rectify+pool kernel:
    default-ON on TPU (measured 1.1-1.54× over XLA's fusion at every
    shape point, module docstring); KEYSTONE_DISABLE_PALLAS_RECTIFY=1
    reverts to the XLA path."""
    if not _kernels_enabled():
        return False
    if os.environ.get("KEYSTONE_DISABLE_PALLAS_RECTIFY") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Fused two-sided rectify + sum pool
# ---------------------------------------------------------------------------


def rectify_pool_reference(x, alpha, max_val, pool: int, stride: int):
    """XLA path: SymmetricRectifier >> Pooler(sum) exactly as the
    unfused stages compute it. x: (N, H, W, K) → (N, GY, GX, 2K)."""
    cat = jnp.concatenate(
        [jnp.maximum(max_val, x - alpha), jnp.maximum(max_val, -x - alpha)],
        axis=-1,
    )
    return lax.reduce_window(
        cat, 0.0, lax.add,
        window_dimensions=(1, pool, pool, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def _rectify_pool_kernel(x_ref, o_ref, *, alpha, max_val, pool, stride, gy, gx, k):
    # windows overlap by at most pool−stride columns; recomputing the
    # rectification per window keeps VMEM at one input block + one
    # window slice instead of 3× the input block
    for iy in range(gy):
        for ix in range(gx):
            xw = x_ref[:, iy * stride : iy * stride + pool,
                       ix * stride : ix * stride + pool, :]
            pos = jnp.maximum(max_val, xw - alpha).sum(axis=(1, 2))
            neg = jnp.maximum(max_val, -xw - alpha).sum(axis=(1, 2))
            o_ref[:, iy, ix, 0:k] = pos
            o_ref[:, iy, ix, k : 2 * k] = neg


def rectify_pool_pallas(
    x, alpha: float, max_val: float, pool: int, stride: int,
    *, block_n: int = 8, interpret: bool = False,
):
    n, h, w, k = x.shape
    gy = (h - pool) // stride + 1
    gx = (w - pool) // stride + 1
    bn = min(block_n, n)
    n_pad = _round_up(n, bn)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0), (0, 0), (0, 0)))
    out = pl.pallas_call(
        partial(
            _rectify_pool_kernel,
            alpha=float(alpha), max_val=float(max_val),
            pool=pool, stride=stride, gy=gy, gx=gx, k=k,
        ),
        grid=(n_pad // bn,),
        in_specs=[
            pl.BlockSpec((bn, h, w, k), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, gy, gx, 2 * k), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, gy, gx, 2 * k), x.dtype),
        interpret=interpret,
    )(x)
    return out[:n]


def rectify_pool(x, alpha: float, max_val: float, pool: int, stride: int):
    """Dispatcher: Pallas on TPU (default-on), XLA elsewhere."""
    if use_rectify_pallas():
        # VMEM budget: the pipelined input block is double-buffered, and
        # tiling pads the sublane dim (W) to 8 and the lane dim (K) to
        # 128 — keep the nominal input block under ~3 MB of the 16 MB VMEM
        per_img = x.shape[1] * _round_up(x.shape[2], 8) * _round_up(x.shape[3], 128) * 4
        # conv-era standalone kernel: its working set is input-only (the
        # pooled output is negligible), so the 2x-double-buffer chain
        # formula over-reserves; the chain path's chooser covers the
        # fused RectifyPool>>Vectorizer form instead
        block_n = max(1, min(8, (3 << 20) // max(per_img, 1)))  # keystone: ignore[KJ017]
        return rectify_pool_pallas(x, alpha, max_val, pool, stride, block_n=block_n)
    return rectify_pool_reference(x, alpha, max_val, pool, stride)


# ---------------------------------------------------------------------------
# RBF kernel block: exp(-γ‖x−y‖²) with fused GEMM epilogue
# ---------------------------------------------------------------------------


def rbf_block_reference(X, Yb, gamma):
    """XLA path — the dot-product trick at full f32 precision."""
    with jax.default_matmul_precision("highest"):
        d2 = (
            jnp.sum(X * X, axis=1, keepdims=True)
            - 2.0 * X @ Yb.T
            + jnp.sum(Yb * Yb, axis=1)
        )
        return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _rbf_kernel(x_ref, y_ref, x2_ref, y2_ref, o_ref, acc_ref, *, gamma, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += lax.dot_general(
        x_ref[:], y_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        d2 = x2_ref[:] + y2_ref[:] - 2.0 * acc_ref[:]
        o_ref[:] = jnp.exp(-gamma * jnp.maximum(d2, 0.0)).astype(o_ref.dtype)


def rbf_block_pallas(
    X, Yb, gamma, *, bm: int = 512, bn: int = 512, bk: int = 512,
    interpret: bool = False,
):
    m, d = X.shape
    n = Yb.shape[0]
    bm, bn = min(bm, _round_up(m, 8)), min(bn, _round_up(n, 128))
    bk = min(bk, _round_up(d, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(d, bk)
    # f32 squared norms computed on the un-padded inputs (padding rows
    # are zero; their outputs are sliced off)
    with jax.default_matmul_precision("highest"):
        x2 = jnp.sum(X.astype(jnp.float32) ** 2, axis=1)
        y2 = jnp.sum(Yb.astype(jnp.float32) ** 2, axis=1)
    Xp = jnp.pad(X, ((0, mp - m), (0, kp - d)))
    Yp = jnp.pad(Yb, ((0, np_ - n), (0, kp - d)))
    x2p = jnp.pad(x2, (0, mp - m)).reshape(mp, 1)
    y2p = jnp.pad(y2, (0, np_ - n)).reshape(1, np_)
    k_steps = kp // bk
    out = pl.pallas_call(
        partial(_rbf_kernel, gamma=float(gamma), k_steps=k_steps),
        grid=(mp // bm, np_ // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bk), lambda i, j, kk: (j, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mp, np_), X.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(Xp, Yp, x2p, y2p)
    return out[:m, :n]


def rbf_block(X, Yb, gamma):
    """Dispatcher: Pallas on TPU, XLA elsewhere."""
    if use_pallas():
        return rbf_block_pallas(X, Yb, gamma)
    return rbf_block_reference(X, Yb, gamma)


# ---------------------------------------------------------------------------
# Fused conv + mean-correction + two-sided rectify + sum pool
# ---------------------------------------------------------------------------
#
# The featurizer's true bottleneck is not the conv FLOPs but the HBM
# round trips between conv, rectify, and pool: at 2048 CIFAR images /
# 256 filters the conv output (1.5 GB), the channel-doubled rectified
# tensor (3 GB written, 3 GB re-read by reduce_window) are all
# bandwidth, measured at 8.5 of the 9.7 ms per microbatch on v5e.
# This kernel keeps everything after the im2col in VMEM: one GEMM
# against the folded filter bank, the rank-1 patch-mean correction, the
# two-sided rectification, and sum-pooling expressed as a block-diagonal
# 0/1 matmul — only the (n, gy, gx, 2K) pooled grid is written back.
#
# Patches are fed to the MXU in bfloat16: at DEFAULT matmul precision
# the MXU truncates f32 operands to bf16 anyway, so this halves patch
# traffic with bit-for-bit-equivalent results vs the XLA conv path
# (measured max rel. disagreement 5.4e-4 — the same class as two
# DEFAULT-precision XLA convs of the same values).
#
# Measured on v5e (1 chip, 2026-07, chained-iteration timing): XLA path
# 9.0 ms vs fused kernel 4.0 ms per 2048-image microbatch (2.26x);
# 50 k-image featurize 219 ms -> 97 ms. Unlike the standalone
# rectify_pool kernel above, this one is ON by default on TPU
# (set KEYSTONE_DISABLE_FUSED_CONV=1 to force the XLA path).


def use_fused_conv() -> bool:
    if not _kernels_enabled():
        return False
    if os.environ.get("KEYSTONE_DISABLE_FUSED_CONV") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


class FusedConvIneligibleError(ValueError):
    """The fused conv kernel's block geometry cannot fit VMEM."""


def folded_conv_reference(images, kernel_hwio, colsum, bias, normalize: bool):
    """The folded conv: filter bank with ZCA pre-applied, patch-mean
    subtraction as a rank-1 correction via a uniform conv, plus bias.
    Single source of truth — nodes/images/core.py's Convolver and the
    fused peephole's fallback both call this.

    Mixed-precision contract: `lax.conv_general_dilated` requires both
    operands to share a dtype, so when the precision planner stores the
    activation boundary in bf16 the filter bank follows the activation
    dtype (bf16 inputs, f32 accumulation via `preferred_element_type` —
    the MXU discipline); the conv output is always f32."""
    if jnp.issubdtype(images.dtype, jnp.floating) \
            and kernel_hwio.dtype != images.dtype:
        kernel_hwio = kernel_hwio.astype(images.dtype)
    dn = lax.conv_dimension_numbers(
        images.shape, kernel_hwio.shape, ("NHWC", "HWIO", "NHWC")
    )
    out = lax.conv_general_dilated(
        images, kernel_hwio, (1, 1), "VALID", dimension_numbers=dn,
        preferred_element_type=jnp.float32,
    )
    if normalize:
        p, c = kernel_hwio.shape[0], kernel_hwio.shape[2]
        ones = jnp.ones((p, p, c, 1), images.dtype) / (p * p * c)
        means = lax.conv_general_dilated(
            images, ones, (1, 1), "VALID",
            dimension_numbers=lax.conv_dimension_numbers(
                images.shape, ones.shape, ("NHWC", "HWIO", "NHWC")
            ),
            preferred_element_type=jnp.float32,
        )
        out = out - means * colsum
    return out + bias


def conv_rectify_pool_reference(
    images, kernel_hwio, colsum, bias, alpha, max_val,
    pool: int, stride: int, normalize: bool,
):
    """XLA path: exactly the unfused Convolver >> SymmetricRectifier >>
    Pooler(sum) computation (see nodes/images/core.py)."""
    out = folded_conv_reference(images, kernel_hwio, colsum, bias, normalize)
    return rectify_pool_reference(out, alpha, max_val, pool, stride)


def hwio_to_cmajor(kernel_hwio):
    """(P,P,C,K) → the channel-major (C·P·P, K) feature layout the Pallas
    kernel consumes (conv_general_dilated_patches order)."""
    return kernel_hwio.transpose(2, 0, 1, 3).reshape(-1, kernel_hwio.shape[3])


_fused_conv_canary: dict = {}


def _fused_conv_canary_ok(h: int, w: int, c: int, k: int, pool: int,
                          stride: int, normalize: bool, patch: int) -> bool:
    """Compile-and-run the fused kernel ONCE per geometry on tiny data,
    eagerly. The dispatcher's trace-time try/except cannot see
    COMPILE-time failures (a scoped-vmem OOM, a Mosaic lowering reject)
    when the call sits inside an outer jit — they would surface when the
    enclosing program compiles and hard-fail the pipeline. The canary
    compiles the same kernel geometry (one n=1 call pads to one full
    image block) outside any enclosing trace, so a bad geometry demotes
    to the XLA path instead of crashing the run."""
    key = (h, w, c, k, pool, stride, bool(normalize), patch)
    # cached states: True (passed, permanent), False (failed,
    # permanent), 1 (one failed attempt — retried once on the next
    # call, so a transient device blip at first-trace time doesn't
    # demote a working geometry for the whole process)
    state = _fused_conv_canary.get(key)
    if state is True or state is False:
        return state
    multihost = jax.process_count() > 1
    try:
        import numpy as np

        got = conv_rectify_pool_pallas(
            jnp.zeros((1, h, w, c), jnp.float32),
            jnp.zeros((c * patch * patch, k), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            0.1, 0.0, pool, stride, normalize, patch,
        )
        ok = bool(np.isfinite(np.asarray(got)).all())
    except FusedConvIneligibleError:
        ok = False  # designed, silent fallback: the block geometry
        # cannot fit VMEM (deterministic in the geometry)
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "fused conv canary failed at geometry %s (%s: %s); "
            "using the XLA path for it", key, type(e).__name__, e)
        # Single-host: retry once (a transient device blip must not
        # demote a working geometry for the whole process). Multi-host:
        # no retry marker — the verdict is settled collectively below.
        ok = False if (multihost or state == 1) else 1
    if multihost:
        # Every process must compile the SAME program for the collective
        # launch, but a transient blip can hit only SOME hosts, leaving
        # them with different local verdicts (fused on one, XLA on the
        # rest → a wedged collective). Adopt process 0's verdict
        # everywhere: the canary runs at the same SPMD program point on
        # every process (same geometry key, same call site), so this
        # broadcast lines up like parallel.multihost.barrier() does.
        import numpy as np
        from jax.experimental import multihost_utils

        ok = bool(multihost_utils.broadcast_one_to_all(np.asarray(bool(ok))))
    _fused_conv_canary[key] = ok
    return ok is True


def conv_rectify_pool(
    images, kernel_hwio, colsum, bias, alpha, max_val,
    pool: int, stride: int, normalize: bool,
):
    """Dispatcher: fused Pallas kernel on TPU (default on), XLA
    elsewhere or when the block geometry cannot fit VMEM or fails its
    canary compile. The single entry point for
    Convolver>>Rectifier>>Pooler semantics — the fusion peephole and
    the driver graft entry both route through it."""
    # precision-planner boundaries may hand bf16 activations to an f32
    # filter bank: the kernel follows the activation dtype here so BOTH
    # paths (Pallas GEMM, XLA conv) see matching operand dtypes; the
    # accumulator stays f32 in each.
    if jnp.issubdtype(images.dtype, jnp.floating) \
            and kernel_hwio.dtype != images.dtype:
        kernel_hwio = kernel_hwio.astype(images.dtype)
    if use_fused_conv() and _fused_conv_canary_ok(
        images.shape[1], images.shape[2], images.shape[3],
        kernel_hwio.shape[3], pool, stride, normalize,
        kernel_hwio.shape[0],
    ):
        try:
            return conv_rectify_pool_pallas(
                images, hwio_to_cmajor(kernel_hwio), colsum, bias,
                alpha, max_val, pool, stride, normalize,
                kernel_hwio.shape[0],
            )
        except FusedConvIneligibleError:
            pass
        except Exception as e:  # trace failure on an unanticipated
            # geometry: degrade to the XLA path rather than hard-fail
            # the pipeline (compile-time failures are the canary's job)
            import logging

            logging.getLogger(__name__).warning(
                "fused conv Pallas path failed (%s: %s); falling back "
                "to XLA", type(e).__name__, e)
    return conv_rectify_pool_reference(
        images, kernel_hwio, colsum, bias, alpha, max_val, pool, stride,
        normalize,
    )


def _pool_matrix(pos_h: int, pos_w: int, posp: int,
                 pool: int, stride: int, g: int) -> "np.ndarray":
    """(R, g·posp) 0/1 sum-pool weights for ONE kernel loop iteration
    (g images, R = round_up(g·cells, 8)): block-diagonal over the g
    images, each block the (cells, posp) weights over that image's
    flattened (i·pos_w + j) position index. Applying it per small group
    instead of per full image-block keeps the pool GEMM's FLOPs linear
    in the block size — the whole-block block-diagonal form scaled them
    with b² (at the CIFAR geometry it out-FLOPed the conv GEMM ~3× at
    f32-HIGHEST) — while 8-row grouping keeps the dot and the store
    full-tile (a previous per-image variant with 4-row dots measured
    SLOWER than the b² form; module docstring history)."""
    import numpy as np

    gy = (pos_h - pool) // stride + 1
    gx = (pos_w - pool) // stride + 1
    cells = gy * gx
    M = np.zeros((_round_up(g * cells, 8), g * posp), np.float32)
    for im in range(g):
        for iy in range(gy):
            for ix in range(gx):
                r = im * cells + iy * gx + ix
                for i in range(iy * stride, iy * stride + pool):
                    for j in range(ix * stride, ix * stride + pool):
                        M[r, im * posp + i * pos_w + j] = 1.0
    return M


def _conv_rect_pool_kernel(
    pat_ref, g_ref, pmat_ref, colsum_ref, bias_ref, o_ref,
    *, alpha, max_val, d_real, k, normalize, b, posp, grp, rows,
):
    g = g_ref[:]                                       # (dp, k) bf16
    pm = pmat_ref[:]                                   # (rows, grp·posp)
    cs = colsum_ref[:]
    bs = bias_ref[:]

    def body(i, carry):
        # one iteration = one group of `grp` images (one 8-row output
        # tile when cells divides 8 — see _fused_conv_geometry)
        pat = pat_ref[pl.ds(i * grp * posp, grp * posp), :]  # bf16
        # precision pinned DEFAULT: bf16 operands under an ambient
        # default_matmul_precision("highest") context would ask Mosaic
        # for an fp32-contract bf16 matmul, which it rejects ("Bad lhs
        # type")
        z = jnp.dot(pat, g, preferred_element_type=jnp.float32,
                    precision=lax.Precision.DEFAULT)
        if normalize:
            means = jnp.sum(pat.astype(jnp.float32), axis=1,
                            keepdims=True) * (1.0 / d_real)
            z = z - means * cs
        out = z + bs
        # HIGHEST: the rectified activations would otherwise be
        # truncated to bf16 by the pool GEMM, a second rounding on top
        # of the documented bf16 patch feed; the 0/1 pm operand is
        # exact either way. Both the load and the store are
        # tile-aligned: posp % 16 == 0 and rows % 8 == 0.
        act = jnp.concatenate(
            [jnp.maximum(max_val, out - alpha),
             jnp.maximum(max_val, -out - alpha)],
            axis=1,
        )
        o_ref[pl.ds(i * rows, rows), :] = jnp.dot(
            pm, act, preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST)
        return carry

    # a SEQUENTIAL loop on purpose: per-group z/act transients are the
    # VMEM hogs, and fori_loop guarantees only one iteration's worth is
    # live — the block chooser's budget is structural, not a scheduling
    # guess (a Python-unrolled loop would let Mosaic keep several
    # groups' transients in flight)
    lax.fori_loop(0, b // grp, body, 0)


def _fused_conv_geometry(posp: int, dp: int, k: int,
                         cells: int) -> "tuple[int, int, int]":
    """(b, g, R): image block, images per kernel loop iteration, and
    output rows per iteration, chosen so the working set fits ~10 MB of
    VMEM. Groups are tried largest-first — g images per iteration share
    one pool dot/store whose 8-row tiles are fully used when g·cells is
    a multiple of 8 — and halved when a group's z/act transients (which
    scale with g) blow the budget, down to one image per iteration.
    b is always a multiple of g so the kernel's loop covers the block
    exactly; R is a multiple of 8 so stores stay tile-aligned."""
    if cells <= 0:  # pool window larger than the conv-position grid:
        # no pooled output exists; plainly ineligible, not a crash
        return 0, 1, 8
    kp = -(-k // 128) * 128
    k2p = -(-(2 * k) // 128) * 128
    g = 8 // cells if 8 % cells == 0 else 1
    while g >= 1:
        if g > 1 and (g * cells) % 8 != 0:
            # only TIGHT multi-image groups (or g=1): a padded group of
            # several images would interleave zero rows between groups,
            # breaking the per-image output reshape below
            g //= 2
            continue
        R = _round_up(g * cells, 8)
        best = 0
        cand = g
        while cand <= 32:
            # Mosaic pads the lane (minor) dimension to 128: every
            # (rows, k) f32 buffer really occupies
            # (rows, round_up(k, 128)) of VMEM — ignoring it produced a
            # real scoped-vmem OOM at k=16 (21.5 MB actual vs 8.9 MB
            # estimated). The conv/rectify intermediates (z, act) are
            # ONE group's worth by construction (sequential fori_loop
            # in the kernel), so they don't scale with the block; the
            # 10 MB cap of the 16 MB VMEM absorbs scheduling slop.
            bytes_needed = (
                2 * cand * posp * dp * 2         # patches, dbl-buf bf16
                + g * posp * kp * 4              # z (one group, f32)
                + g * posp * k2p * 4             # act = both signs
                + 2 * (cand // g) * R * k2p * 4  # pooled out, dbl-buf
                + R * g * posp * 4               # group pool matrix
                + dp * kp * 2
            )
            # grouped conv working set (patches + per-group z/act +
            # pooled out + pool matrix + filters) has no chain-formula
            # equivalent; its own live-chip canary gates it
            if bytes_needed > 10 * (1 << 20):  # keystone: ignore[KJ017]
                break
            best = cand
            cand += g
        if best > 0:
            return best, g, R
        g //= 2
    return 0, 1, _round_up(cells, 8)


def _fused_conv_block_images(posp: int, dp: int, k: int, cells: int) -> int:
    """Largest eligible image block (0 = the geometry cannot fit VMEM);
    see `_fused_conv_geometry`."""
    return _fused_conv_geometry(posp, dp, k, cells)[0]


def conv_rectify_pool_pallas(
    images, G_cmajor, colsum, bias, alpha, max_val,
    pool: int, stride: int, normalize: bool, patch: int,
    *, interpret: bool = False,
):
    """images (N,H,W,C) f32 → pooled (N,gy,gx,2K) f32.

    G_cmajor: (C·P·P, K) folded filter bank in the channel-major feature
    order of `conv_general_dilated_patches`.
    """
    n, h, w, c = images.shape
    d = c * patch * patch
    k = G_cmajor.shape[1]
    pos_h, pos_w = h - patch + 1, w - patch + 1
    npos = pos_h * pos_w
    # 16, not 8: the kernel takes per-group DYNAMIC row slices of the
    # bf16 patches ref at offsets i·g·posp, and the bf16 tile is (16,128)
    posp = _round_up(npos, 16)
    dp = _round_up(d, 128)
    gy = (pos_h - pool) // stride + 1
    gx = (pos_w - pool) // stride + 1
    cells = gy * gx

    b, g_img, rows = _fused_conv_geometry(posp, dp, k, cells)
    if b == 0:
        raise FusedConvIneligibleError("fused conv block does not fit VMEM")
    n_pad = _round_up(n, b)

    pat = lax.conv_general_dilated_patches(
        jnp.moveaxis(images, -1, 1), (patch, patch), (1, 1), "VALID"
    )  # (N, C·P·P, pos_h, pos_w), channel-major features
    pat = jnp.moveaxis(pat, 1, -1).reshape(n, npos, d)
    pat = jnp.pad(pat, ((0, n_pad - n), (0, posp - npos), (0, dp - d)))
    pat = pat.reshape(n_pad * posp, dp).astype(jnp.bfloat16)

    r_img = rows // g_img  # output rows per image (== cells when tight;
    # padded groups are g=1 only, so this stays exact)
    Gp = jnp.pad(G_cmajor, ((0, dp - d), (0, 0))).astype(jnp.bfloat16)
    pmat = jnp.asarray(_pool_matrix(pos_h, pos_w, posp, pool, stride, g_img))
    cs = jnp.asarray(colsum, jnp.float32).reshape(1, k)
    bs = jnp.asarray(bias, jnp.float32).reshape(1, k)

    grid = n_pad // b
    out = pl.pallas_call(
        partial(
            _conv_rect_pool_kernel,
            alpha=float(alpha), max_val=float(max_val),
            d_real=d, k=k, normalize=normalize, b=b, posp=posp,
            grp=g_img, rows=rows,
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((b * posp, dp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((dp, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, g_img * posp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b * r_img, 2 * k), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((grid * b * r_img, 2 * k),
                                       jnp.float32),
        interpret=interpret,
    )(pat, Gp, pmat, cs, bs)
    # tight grouping: r_img == cells and the slice below is a no-op
    return (out.reshape(n_pad, r_img, 2 * k)[:n, :cells]
            .reshape(n, gy, gx, 2 * k))
