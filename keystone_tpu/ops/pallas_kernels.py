"""Pallas TPU kernels for the hot ops, with XLA fallbacks.

Two ops dominate HBM traffic in the flagship pipelines:

1. **Two-sided rectify + sum-pool** (RandomPatchCifar serving path,
   reference SymmetricRectifier.scala:7-32 then Pooler.scala:21-69).
   The XLA lowering materializes the channel-doubled rectified tensor
   (N·H·W·2K floats) in HBM before `reduce_window` shrinks it ~100×.
   The Pallas kernel reads the conv output once per batch block and
   writes only the pooled grid — one HBM pass instead of three.

2. **RBF kernel block** K(X, Yb) = exp(-γ‖x−y‖²) (reference
   KernelGenerator.scala:18-206), the inner op of kernel ridge
   regression. The Pallas kernel tiles the Gram GEMM onto the MXU with
   an f32 VMEM accumulator and applies the distance/exp epilogue before
   the (m, b) block ever leaves VMEM, instead of round-tripping the
   GEMM output through HBM for a separate elementwise kernel.

Every op has `*_reference` (pure jnp — the XLA path, also the CPU/test
oracle) and a dispatcher. Kernels are runnable in interpret mode on CPU
for unit tests.

**Measured on v5e (1 chip, 2026-07):** XLA's own fusion already reaches
parity on both ops — rectify+pool (2048×27×27×256): XLA ~15 ms vs
Pallas ~15.8 ms per pass; RBF block (8192×2048, d=1024, HIGHEST):
XLA 8.04 ms vs Pallas 8.26 ms; end-to-end RandomPatchCifar bench is
~20 % *slower* with the Pallas featurizer path (the 4-image grid blocks
pay DMA overhead XLA's fused reduce_window avoids). The dispatchers
therefore default to the XLA paths; set `KEYSTONE_ENABLE_PALLAS=1` to
route to the Pallas kernels on TPU (e.g. to re-measure on larger pods
or future toolchains where the fusion trade-off may flip).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def use_pallas() -> bool:
    """Trace-time gate: Pallas kernels are opt-in (see module docstring
    for the measured XLA-parity rationale) and TPU-only."""
    if os.environ.get("KEYSTONE_ENABLE_PALLAS") != "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Fused two-sided rectify + sum pool
# ---------------------------------------------------------------------------


def rectify_pool_reference(x, alpha, max_val, pool: int, stride: int):
    """XLA path: SymmetricRectifier >> Pooler(sum) exactly as the
    unfused stages compute it. x: (N, H, W, K) → (N, GY, GX, 2K)."""
    cat = jnp.concatenate(
        [jnp.maximum(max_val, x - alpha), jnp.maximum(max_val, -x - alpha)],
        axis=-1,
    )
    return lax.reduce_window(
        cat, 0.0, lax.add,
        window_dimensions=(1, pool, pool, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def _rectify_pool_kernel(x_ref, o_ref, *, alpha, max_val, pool, stride, gy, gx, k):
    # windows overlap by at most pool−stride columns; recomputing the
    # rectification per window keeps VMEM at one input block + one
    # window slice instead of 3× the input block
    for iy in range(gy):
        for ix in range(gx):
            xw = x_ref[:, iy * stride : iy * stride + pool,
                       ix * stride : ix * stride + pool, :]
            pos = jnp.maximum(max_val, xw - alpha).sum(axis=(1, 2))
            neg = jnp.maximum(max_val, -xw - alpha).sum(axis=(1, 2))
            o_ref[:, iy, ix, 0:k] = pos
            o_ref[:, iy, ix, k : 2 * k] = neg


def rectify_pool_pallas(
    x, alpha: float, max_val: float, pool: int, stride: int,
    *, block_n: int = 8, interpret: bool = False,
):
    n, h, w, k = x.shape
    gy = (h - pool) // stride + 1
    gx = (w - pool) // stride + 1
    bn = min(block_n, n)
    n_pad = _round_up(n, bn)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0), (0, 0), (0, 0)))
    out = pl.pallas_call(
        partial(
            _rectify_pool_kernel,
            alpha=float(alpha), max_val=float(max_val),
            pool=pool, stride=stride, gy=gy, gx=gx, k=k,
        ),
        grid=(n_pad // bn,),
        in_specs=[
            pl.BlockSpec((bn, h, w, k), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, gy, gx, 2 * k), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, gy, gx, 2 * k), x.dtype),
        interpret=interpret,
    )(x)
    return out[:n]


def rectify_pool(x, alpha: float, max_val: float, pool: int, stride: int):
    """Dispatcher: Pallas on TPU, XLA elsewhere."""
    if use_pallas():
        # VMEM budget: the pipelined input block is double-buffered, and
        # tiling pads the sublane dim (W) to 8 and the lane dim (K) to
        # 128 — keep the nominal input block under ~3 MB of the 16 MB VMEM
        per_img = x.shape[1] * _round_up(x.shape[2], 8) * _round_up(x.shape[3], 128) * 4
        block_n = max(1, min(8, (3 << 20) // max(per_img, 1)))
        return rectify_pool_pallas(x, alpha, max_val, pool, stride, block_n=block_n)
    return rectify_pool_reference(x, alpha, max_val, pool, stride)


# ---------------------------------------------------------------------------
# RBF kernel block: exp(-γ‖x−y‖²) with fused GEMM epilogue
# ---------------------------------------------------------------------------


def rbf_block_reference(X, Yb, gamma):
    """XLA path — the dot-product trick at full f32 precision."""
    with jax.default_matmul_precision("highest"):
        d2 = (
            jnp.sum(X * X, axis=1, keepdims=True)
            - 2.0 * X @ Yb.T
            + jnp.sum(Yb * Yb, axis=1)
        )
        return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def _rbf_kernel(x_ref, y_ref, x2_ref, y2_ref, o_ref, acc_ref, *, gamma, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += lax.dot_general(
        x_ref[:], y_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _():
        d2 = x2_ref[:] + y2_ref[:] - 2.0 * acc_ref[:]
        o_ref[:] = jnp.exp(-gamma * jnp.maximum(d2, 0.0)).astype(o_ref.dtype)


def rbf_block_pallas(
    X, Yb, gamma, *, bm: int = 512, bn: int = 512, bk: int = 512,
    interpret: bool = False,
):
    m, d = X.shape
    n = Yb.shape[0]
    bm, bn = min(bm, _round_up(m, 8)), min(bn, _round_up(n, 128))
    bk = min(bk, _round_up(d, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(d, bk)
    # f32 squared norms computed on the un-padded inputs (padding rows
    # are zero; their outputs are sliced off)
    with jax.default_matmul_precision("highest"):
        x2 = jnp.sum(X.astype(jnp.float32) ** 2, axis=1)
        y2 = jnp.sum(Yb.astype(jnp.float32) ** 2, axis=1)
    Xp = jnp.pad(X, ((0, mp - m), (0, kp - d)))
    Yp = jnp.pad(Yb, ((0, np_ - n), (0, kp - d)))
    x2p = jnp.pad(x2, (0, mp - m)).reshape(mp, 1)
    y2p = jnp.pad(y2, (0, np_ - n)).reshape(1, np_)
    k_steps = kp // bk
    out = pl.pallas_call(
        partial(_rbf_kernel, gamma=float(gamma), k_steps=k_steps),
        grid=(mp // bm, np_ // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bk), lambda i, j, kk: (j, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mp, np_), X.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(Xp, Yp, x2p, y2p)
    return out[:m, :n]


def rbf_block(X, Yb, gamma):
    """Dispatcher: Pallas on TPU, XLA elsewhere."""
    if use_pallas():
        return rbf_block_pallas(X, Yb, gamma)
    return rbf_block_reference(X, Yb, gamma)
