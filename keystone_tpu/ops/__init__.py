"""Pallas TPU kernels for hot ops (with XLA fallbacks)."""

from .pallas_kernels import (
    rbf_block,
    rbf_block_pallas,
    rbf_block_reference,
    rectify_pool,
    rectify_pool_pallas,
    rectify_pool_reference,
    use_pallas,
)

__all__ = [
    "rbf_block",
    "rbf_block_pallas",
    "rbf_block_reference",
    "rectify_pool",
    "rectify_pool_pallas",
    "rectify_pool_reference",
    "use_pallas",
]
