"""Pallas TPU kernels for hot ops (with XLA fallbacks)."""

from .pallas_kernels import (
    FusedConvIneligibleError,
    conv_rectify_pool,
    conv_rectify_pool_pallas,
    conv_rectify_pool_reference,
    folded_conv_reference,
    hwio_to_cmajor,
    rbf_block,
    rbf_block_pallas,
    rbf_block_reference,
    rectify_pool,
    rectify_pool_pallas,
    rectify_pool_reference,
    use_fused_conv,
    use_pallas,
    use_rectify_pallas,
)

__all__ = [
    "FusedConvIneligibleError",
    "conv_rectify_pool",
    "conv_rectify_pool_pallas",
    "conv_rectify_pool_reference",
    "folded_conv_reference",
    "hwio_to_cmajor",
    "rbf_block",
    "rbf_block_pallas",
    "rbf_block_reference",
    "rectify_pool",
    "rectify_pool_pallas",
    "rectify_pool_reference",
    "use_fused_conv",
    "use_pallas",
    "use_rectify_pallas",
]
