"""Chain megakernels: lower a KP801 candidate's fused-stage trail to
ONE double-buffered Pallas kernel.

The fusion builder (nodes/util/fusion.py) composes stage bodies into a
single XLA program, but XLA still lowers the chain stage-at-a-time:
every boundary round-trips HBM (KP801 prices these — RandomPatchCifar's
rectify→pool→vectorize alone round-trips ~60 MB per sharded branch).
This module lowers an eligible sub-trail to one `pl.pallas_call` whose
grid streams batch blocks HBM→VMEM (the grid pipeline double-buffers
blocked operands), applies every stage body in VMEM, and writes only
the chain's final output — one HBM pass of in+out bytes instead of a
round-trip per boundary.

Two candidate families, matched on the same `_stage_fuse` static keys
the fusion builder and the KP501 auditor use:

- ``rectify_pool_vectorize``: the post-peephole ``RectifyPool >>
  ImageVectorizer`` trail of the conv pipelines. Reuses the proven
  rectify+pool kernel body (ops/pallas_kernels.py, 1.1-1.54x live) and
  appends the vectorize as a free contiguous reshape of the pooled
  block — the channel-doubled rectified tensor never leaves VMEM.
- ``elementwise_chain``: runs of shape-preserving-or-reshaping per-row
  stages (PixelScaler, GrayScaler, LinearRectifier, NormalizeRows,
  SignedHellingerMapper, RandomSign, StandardScaler, the vectorizers)
  on the FFT/patch paths. Each stage body executes on the VMEM block;
  ``fuse_masks_output`` stages keep re-zeroing padded rows at their
  original chain position via a streamed (block, 1) mask operand.

Every lowering has a pure-jnp ``*_reference`` oracle (the XLA path and
the CPU/test oracle — the SAME body functions applied outside Pallas),
a VMEM geometry chooser that returns 0 / raises
`ChainKernelIneligibleError` instead of compiling an OOM, and a canary
(the fused-conv discipline) so a Mosaic reject demotes to XLA instead
of crashing the enclosing program.

Gate: `use_chain_kernels()` — `ExecutionConfig.pallas_kernels` is the
master kill switch (env ``KEYSTONE_CHAIN_KERNELS``, ledger-header
recorded). Off-TPU the kernels are interpret-validated only: the
planner still prices and records the decision, but programs keep the
XLA body unless ``KEYSTONE_CHAIN_KERNELS=interpret`` forces the
interpret-mode swap (the e2e test hook). ``=0`` is the bit-for-bit
kill: the built program is exactly the pre-kernel XLA form.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import (
    _rectify_pool_kernel,
    _round_up,
    rectify_pool_reference,
)

#: the fused-conv budget discipline: leave ~6 MB of the 16 MB VMEM for
#: scheduling slop and double-buffer headroom
_VMEM_BUDGET = 10 * (1 << 20)

#: block-row ladders each family's chooser descends (largest first).
#: Shared with the KP1003 static proof (analysis/kernels.py) so the
#: prover walks the exact candidate set the runtime chooser walks.
_RECTIFY_BLOCK_LADDER = tuple(range(8, 0, -1))
_ELEMENTWISE_BLOCK_LADDER = (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)


def chain_vmem_bytes(bn: int, io_bytes: int, inter_bytes: int = 0,
                     param_bytes: int = 0) -> int:
    """THE chain-kernel VMEM working-set formula — the one shared
    arithmetic behind both families' block choosers AND the KP1003
    static proof (the `collective_cost`/`live_set_walk` precedent: one
    function, so the static verdict and the runtime demotion can never
    diverge). At batch block ``bn``: the grid pipeline double-buffers
    every streamed block (2× the in+out bytes), intermediates are
    single-buffered transients, closure params are resident once."""
    return 2 * bn * io_bytes + bn * inter_bytes + param_bytes


def chain_block_rows(io_bytes: int, inter_bytes: int = 0,
                     param_bytes: int = 0, *,
                     ladder=_ELEMENTWISE_BLOCK_LADDER,
                     budget=None) -> int:
    """Largest ladder block whose `chain_vmem_bytes` working set fits
    the budget (0 = the geometry cannot fit VMEM at any block)."""
    budget = _VMEM_BUDGET if budget is None else budget
    for bn in ladder:
        if chain_vmem_bytes(bn, io_bytes, inter_bytes, param_bytes) <= budget:
            return bn
    return 0


class ChainKernelIneligibleError(ValueError):
    """The chain kernel's block geometry cannot fit VMEM."""


def use_chain_kernels() -> bool:
    """Master gate for the planned chain megakernels:
    `ExecutionConfig.pallas_kernels` (env ``KEYSTONE_CHAIN_KERNELS``)
    AND a TPU backend — except ``KEYSTONE_CHAIN_KERNELS=interpret``,
    which enables the interpret-mode swap everywhere (tests, off-TPU
    validation)."""
    from ..workflow.env import execution_config

    if not execution_config().pallas_kernels:
        return False
    if chain_interpret_forced():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def chain_interpret_forced() -> bool:
    """``KEYSTONE_CHAIN_KERNELS=interpret``: run the kernels in
    interpret mode regardless of backend (the e2e swap-path hook)."""
    return os.environ.get("KEYSTONE_CHAIN_KERNELS", "").lower() == "interpret"


def chain_interpret() -> bool:
    """Interpret off-TPU (validated emulation), native on TPU."""
    if chain_interpret_forced():
        return True
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# Static-key matcher: which fused sub-trails lower, and why not
# ---------------------------------------------------------------------------

#: stages a chain kernel cannot absorb, with the NAMED reason the
#: lint.sh chain-kernel audit renders: a KP801 candidate containing
#: only these is suppressed (stays on XLA deliberately), anything else
#: unsupported is an open lowering gap the audit fails on.
SUPPRESSED_STAGES = {
    "ConvRectifyPool": "already ONE fused Pallas kernel "
                       "(ops.conv_rectify_pool, PR 11)",
    "PaddedFFT": "rfft has no Mosaic lowering; stays on the XLA path",
    "Pooler": "non-sum/pixel_fn pooling (the sum form peepholes into "
              "RectifyPool) stays on lax.reduce_window",
    "opaque": "id-keyed opaque stage: no static body to lower",
}

#: per-stage VMEM body builders for the elementwise family, keyed on
#: the `_stage_fuse` static-key head. Each entry:
#: ``prep(params) -> tuple of >=2-D operand arrays`` and
#: ``body(x, ops) -> y`` — pure jnp, used verbatim inside the kernel
#: and by the reference oracle (bit-identical bodies by construction).
_ELEMENTWISE = {}


def _register(head):
    def deco(builder):
        _ELEMENTWISE[head] = builder
        return builder
    return deco


def _scalar_ops(*vals):
    return tuple(jnp.asarray(v, jnp.float32).reshape(1, 1) for v in vals)


@_register("PixelScaler")
def _px(key, params):
    return (lambda p: (),
            lambda x, ops: jnp.asarray(x, jnp.float32) / 255.0)  # keystone: ignore[KJ011]


@_register("GrayScaler")
def _gray(key, params):
    # the NTSC weights ride as a kernel operand — Pallas kernels cannot
    # capture array constants
    def prep(p):
        return (jnp.asarray([0.299, 0.587, 0.114],  # keystone: ignore[KJ011]
                            jnp.float32).reshape(1, 3),)

    def body(x, ops):
        if x.shape[-1] == 1:
            return x
        return jnp.sum(jnp.asarray(x, jnp.float32) * ops[0],  # keystone: ignore[KJ011]
                       axis=-1, keepdims=True)

    return prep, body


@_register("ImageVectorizer")
@_register("MatrixVectorizer")
def _vec(key, params):
    return (lambda p: ()), (lambda x, ops: x.reshape(x.shape[0], -1))


@_register("LinearRectifier")
def _rect(key, params):
    def body(x, ops):
        mv, a = ops
        return jnp.maximum(mv[0, 0].astype(x.dtype),
                           x - a[0, 0].astype(x.dtype))

    return (lambda p: _scalar_ops(p[0], p[1])), body


@_register("NormalizeRows")
def _norm(key, params):
    def body(x, ops):
        (eps,) = ops
        axes = tuple(range(1, x.ndim))
        norms = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / jnp.maximum(norms, eps[0, 0].astype(x.dtype))

    return (lambda p: _scalar_ops(p[0])), body


@_register("SignedHellingerMapper")
def _hell(key, params):
    return (lambda p: ()), (lambda x, ops: jnp.sign(x) * jnp.sqrt(jnp.abs(x)))


@_register("RandomSignNode")
def _sign(key, params):
    def body(x, ops):
        (s,) = ops
        return x * s.astype(x.dtype)

    return (lambda p: (jnp.asarray(p[0]).reshape(1, -1),)), body


@_register("StandardScaler")
def _std(key, params):
    mode = key[1] if isinstance(key, tuple) and len(key) > 1 else "scale"
    if mode == "center":
        def body(x, ops):
            (m,) = ops
            return x - m.astype(x.dtype)

        return (lambda p: (jnp.asarray(p[0]).reshape(1, -1),)), body

    def body(x, ops):
        m, s = ops
        return (x - m.astype(x.dtype)) / s.astype(x.dtype)

    return (lambda p: (jnp.asarray(p[0]).reshape(1, -1),
                       jnp.asarray(p[1]).reshape(1, -1))), body


def _unwrap(key):
    """Strip `_stage_fuse`'s ``(key, "masked")`` wrapping; returns
    (inner_key, masked)."""
    masked = False
    while (isinstance(key, tuple) and len(key) == 2 and key[1] == "masked"):
        key, masked = key[0], True
    return key, masked


def _head(key):
    key, _ = _unwrap(key)
    if isinstance(key, tuple) and key:
        return key[0]
    return key


def stage_statics(stages):
    """The peepholed chain's fuse static keys — the matcher's input.
    Same decomposition the fusion builder derives its program key from;
    never builds or compiles a program."""
    from ..nodes.util.fusion import _peephole, _stage_fuse

    return tuple(_stage_fuse(s)[0] for s in _peephole(list(stages)))


def lowerability(statics) -> dict:
    """Verdict for a candidate chain's fuse statics: ``lowerable``
    (bool), ``family`` (str or None), ``reason`` (always rendered — why
    it lowers or why not), and ``suppressed`` (dict of stage → named
    reason, present only when EVERY blocker is a deliberate
    SUPPRESSED_STAGES entry — the lint.sh audit's escape hatch)."""
    statics = tuple(statics)
    heads = [_head(k) for k in statics]
    if len(statics) < 2:
        return {"lowerable": False, "family": None,
                "reason": "chain shorter than 2 fused stages"}
    if (len(statics) == 2 and heads[0] == "RectifyPool"
            and heads[1] in ("ImageVectorizer", "MatrixVectorizer")):
        return {"lowerable": True, "family": "rectify_pool_vectorize",
                "reason": "RectifyPool >> Vectorizer: one double-buffered "
                          "kernel writes only the pooled-flat output"}
    if all(h in _ELEMENTWISE for h in heads):
        return {"lowerable": True, "family": "elementwise_chain",
                "reason": "all stage bodies execute on the VMEM block: "
                          + " >> ".join(str(h) for h in heads)}
    blockers = sorted({str(h) for h in heads if h not in _ELEMENTWISE
                       and h != "RectifyPool"})
    out = {"lowerable": False, "family": None,
           "reason": "unsupported stage(s): " + ", ".join(blockers)}
    named = {b: SUPPRESSED_STAGES[b] for b in blockers
             if b in SUPPRESSED_STAGES}
    if blockers and len(named) == len(blockers):
        out["suppressed"] = named
    return out


# ---------------------------------------------------------------------------
# Family 1: rectify -> pool -> vectorize
# ---------------------------------------------------------------------------


def rectify_pool_vectorize_reference(x, alpha, max_val, pool, stride):
    """XLA oracle: SymmetricRectifier >> Pooler(sum) >> ImageVectorizer
    exactly as the unfused stages compute it. (N,H,W,K) → (N, gy·gx·2K)."""
    y = rectify_pool_reference(x, alpha, max_val, pool, stride)
    return y.reshape(y.shape[0], -1)


def _rectify_pool_vectorize_parts(h, w, k, pool, stride):
    """(io_bytes, inter_bytes, param_bytes, ladder) — the exact inputs
    this family's chooser feeds `chain_block_rows`, or None when the
    pool grid is empty. Input and pooled-output blocks both stream
    (double-buffered), with Mosaic's (8, 128) f32 tile padding on the
    two minor dims of each; no intermediates or closure params."""
    gy = (h - pool) // stride + 1
    gx = (w - pool) // stride + 1
    if gy <= 0 or gx <= 0:
        return None
    in_per = h * _round_up(w, 8) * _round_up(k, 128) * 4
    out_per = gy * _round_up(gx, 8) * _round_up(2 * k, 128) * 4
    return in_per + out_per, 0, 0, _RECTIFY_BLOCK_LADDER


def _rectify_pool_vectorize_block(h, w, k, pool, stride) -> int:
    """Largest eligible batch block (0 = the geometry cannot fit VMEM),
    chosen by the shared `chain_vmem_bytes` working-set formula."""
    parts = _rectify_pool_vectorize_parts(h, w, k, pool, stride)
    if parts is None:
        return 0
    io_bytes, inter, param_bytes, ladder = parts
    return chain_block_rows(io_bytes, inter, param_bytes, ladder=ladder)


def rectify_pool_vectorize_pallas(
    x, alpha, max_val, pool, stride, *, block_n=None, interpret=False,
):
    """One double-buffered kernel for the whole chain: the grid streams
    (bn, H, W, K) blocks into VMEM, the rectify+pool body writes the
    pooled grid per block, and the trailing vectorize is a contiguous
    row-major reshape of the kernel output (a bitcast, not a pass)."""
    n, h, w, k = x.shape
    bn = block_n or _rectify_pool_vectorize_block(h, w, k, pool, stride)
    if bn <= 0:
        raise ChainKernelIneligibleError(
            f"rectify_pool_vectorize block does not fit VMEM at "
            f"(h={h}, w={w}, k={k})")
    gy = (h - pool) // stride + 1
    gx = (w - pool) // stride + 1
    bn = min(bn, n)
    n_pad = _round_up(n, bn)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0), (0, 0), (0, 0)))
    out = pl.pallas_call(
        partial(
            _rectify_pool_kernel,
            alpha=float(alpha), max_val=float(max_val),
            pool=pool, stride=stride, gy=gy, gx=gx, k=k,
        ),
        grid=(n_pad // bn,),
        in_specs=[
            pl.BlockSpec((bn, h, w, k), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, gy, gx, 2 * k), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, gy, gx, 2 * k), x.dtype),
        interpret=interpret,
    )(x)
    return out[:n].reshape(n, gy * gx * 2 * k)


def rectify_pool_vectorize(x, alpha, max_val, pool, stride, *,
                           interpret=None):
    """Dispatcher: the chain kernel when the gate and geometry allow,
    the XLA oracle otherwise. A canary (the fused-conv discipline)
    settles native-compile eligibility per geometry so a Mosaic reject
    demotes instead of crashing the enclosing program."""
    if use_chain_kernels():
        n, h, w, k = x.shape
        interp = chain_interpret() if interpret is None else interpret
        bn = _rectify_pool_vectorize_block(h, w, k, pool, stride)
        if bn > 0 and (interp or _canary_ok(
            ("rectify_pool_vectorize", h, w, k, pool, stride),
            lambda: rectify_pool_vectorize_pallas(
                jnp.zeros((1, h, w, k), jnp.float32),
                0.1, 0.0, pool, stride),
        )):
            try:
                return rectify_pool_vectorize_pallas(
                    x, alpha, max_val, pool, stride, interpret=interp)
            except ChainKernelIneligibleError:
                pass
    return rectify_pool_vectorize_reference(x, alpha, max_val, pool, stride)


# ---------------------------------------------------------------------------
# Family 2: elementwise chains
# ---------------------------------------------------------------------------


def _compile_bodies(statics):
    """[(masked, prep, body)] per stage, or None when any stage's head
    has no registered VMEM body."""
    out = []
    for key in statics:
        inner, masked = _unwrap(key)
        head = inner[0] if isinstance(inner, tuple) and inner else inner
        builder = _ELEMENTWISE.get(head)
        if builder is None:
            return None
        prep, body = builder(inner, None)
        out.append((masked, prep, body))
    return out


def _run_bodies(bodies, ops, x, mask):
    """Apply the chain's bodies in order (pure jnp — shared by the
    reference oracle and shape/geometry probes). ``mask``: f32 (n, 1)
    valid-row column or None; masked stages re-zero padded rows at
    their original chain position (the `fuse_masks_output` contract)."""
    for (masked, _, body), o in zip(bodies, ops):
        x = body(x, o)
        if masked and mask is not None:
            x = x * mask.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    return x


def elementwise_chain_reference(statics, params, x, mask=None):
    """Pure-jnp oracle: the SAME stage bodies the kernel traces,
    applied outside Pallas. ``params``: one pytree per stage (the
    `_stage_fuse` params slice); ``mask``: bool (n,) or None."""
    bodies = _compile_bodies(statics)
    if bodies is None:
        raise ChainKernelIneligibleError(
            f"no elementwise lowering for {statics!r}")
    ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
    m = None
    if mask is not None:
        m = jnp.asarray(mask, jnp.float32).reshape(-1, 1)
    return _run_bodies(bodies, ops, x, m)


def _padded_item_bytes(shape, dtype) -> int:
    """Per-item VMEM bytes of one (block, *shape) buffer under Mosaic
    tile padding: lane (minor) dim to 128, sublane to 8."""
    itemsize = max(jnp.dtype(dtype).itemsize, 1)
    dims = list(shape)
    if not dims:
        return 128 * itemsize
    dims[-1] = _round_up(dims[-1], 128)
    if len(dims) >= 2:
        dims[-2] = _round_up(dims[-2], 8)
    total = 1
    for d in dims:
        total *= d
    return total * itemsize


def _elementwise_avals(bodies, ops, x):
    """Per-boundary avals of the chain at batch probe ``x`` (index 0 =
    the input, index i = after stage i) — `jax.eval_shape` only, shared
    by the geometry chooser and the KP1005 boundary check."""
    avals = [jax.eval_shape(lambda xx: xx, x)]
    cur = avals[0]
    for (_, _, body), o in zip(bodies, ops):
        cur = jax.eval_shape(lambda xx, oo: body(xx, oo), cur, o)
        avals.append(cur)
    return avals


def _elementwise_parts(bodies, ops, x):
    """(io_bytes, inter_bytes, param_bytes, ladder) — the exact inputs
    this family's chooser feeds `chain_block_rows`: in+out blocks
    stream (double-buffered), every internal boundary's transient is
    single-buffered, closure params are resident once."""
    avals = _elementwise_avals(bodies, ops, x)
    per_item = [_padded_item_bytes(a.shape[1:], a.dtype) for a in avals]
    io_bytes = per_item[0] + per_item[-1]
    inter = sum(per_item[1:-1])
    param_bytes = sum(_padded_item_bytes(a.shape, a.dtype)
                      for stage in ops for a in stage)
    return io_bytes, inter, param_bytes, _ELEMENTWISE_BLOCK_LADDER


def _elementwise_geometry(bodies, ops, x) -> int:
    """Largest batch block (0 = infeasible), chosen by the shared
    `chain_vmem_bytes` working-set formula."""
    io_bytes, inter, param_bytes, ladder = _elementwise_parts(bodies, ops, x)
    return chain_block_rows(io_bytes, inter, param_bytes, ladder=ladder)


def elementwise_chain_pallas(
    statics, params, x, mask=None, *, block_n=None, interpret=False,
):
    """ONE kernel for the whole elementwise run: the grid streams batch
    blocks HBM→VMEM double-buffered, applies every stage body on the
    block, and writes only the final output. Masked stages consume a
    streamed (bn, 1) valid-row column so padded rows stay exactly what
    the node-by-node path produces."""
    bodies = _compile_bodies(statics)
    if bodies is None:
        raise ChainKernelIneligibleError(
            f"no elementwise lowering for {statics!r}")
    ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
    n = x.shape[0]
    bn = block_n or _elementwise_geometry(bodies, ops, x)
    if bn <= 0:
        raise ChainKernelIneligibleError(
            f"elementwise chain block does not fit VMEM at {x.shape}")
    bn = min(bn, n)
    n_pad = _round_up(n, bn)
    needs_mask = any(masked for masked, _, _ in bodies)
    m = None
    if needs_mask:
        m = (jnp.ones((n,), jnp.float32) if mask is None
             else jnp.asarray(mask, jnp.float32)).reshape(-1, 1)
    if n_pad != n:
        x = jnp.pad(x, [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1))
        if m is not None:
            m = jnp.pad(m, ((0, n_pad - n), (0, 0)))
    out_aval = jax.eval_shape(
        lambda xx, oo: _run_bodies(bodies, oo, xx, None), x, ops)
    flat_ops = [a for stage in ops for a in stage]

    def kernel(*refs):
        x_refs = refs[: 2 if needs_mask else 1]
        p_refs = refs[len(x_refs):-1]
        o_ref = refs[-1]
        xb = x_refs[0][...]
        mb = x_refs[1][...] if needs_mask else None
        idx = 0
        for (masked, _, body), stage in zip(bodies, ops):
            loaded = tuple(p_refs[idx + t][...] for t in range(len(stage)))
            idx += len(stage)
            xb = body(xb, loaded)
            if masked:
                xb = xb * mb.reshape(
                    (-1,) + (1,) * (xb.ndim - 1)).astype(xb.dtype)
        o_ref[...] = xb.astype(o_ref.dtype)

    def _block(shape, ndim=None):
        nd = len(shape) if ndim is None else ndim
        return pl.BlockSpec(shape, lambda i, nd=nd: (i,) + (0,) * (nd - 1),
                            memory_space=pltpu.VMEM)

    in_specs = [_block((bn,) + x.shape[1:])]
    operands = [x]
    if needs_mask:
        in_specs.append(_block((bn, 1)))
        operands.append(m)
    for a in flat_ops:
        in_specs.append(pl.BlockSpec(
            a.shape, lambda i, nd=a.ndim: (0,) * nd,
            memory_space=pltpu.VMEM))
        operands.append(a)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=in_specs,
        out_specs=_block((bn,) + out_aval.shape[1:]),
        out_shape=jax.ShapeDtypeStruct((n_pad,) + out_aval.shape[1:],
                                       out_aval.dtype),
        interpret=interpret,
    )(*operands)
    return out[:n]


def elementwise_chain(statics, params, x, mask=None, *, interpret=None):
    """Dispatcher: the chain kernel when the gate and geometry allow,
    the pure-jnp oracle otherwise (same bodies either way)."""
    if use_chain_kernels():
        interp = chain_interpret() if interpret is None else interpret
        bodies = _compile_bodies(statics)
        if bodies is not None:
            ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
            bn = _elementwise_geometry(bodies, ops, x)
            geo = ("elementwise_chain", tuple(str(_head(k)) for k in statics),
                   tuple(x.shape[1:]), jnp.dtype(x.dtype).name)
            # canary operands are rebuilt from STATIC shapes (params may
            # be tracers inside the enclosing program trace) and filled
            # with ones, not zeros — a zero std/eps would NaN the probe
            # and falsely demote a working geometry
            canary_params = [
                jax.tree_util.tree_map(
                    lambda a: jnp.ones(jnp.shape(a), jnp.result_type(a)), p)
                for p in params
            ]
            if bn > 0 and (interp or _canary_ok(
                geo,
                lambda: elementwise_chain_pallas(
                    statics, canary_params,
                    jnp.zeros((1,) + tuple(x.shape[1:]), x.dtype)),
            )):
                try:
                    return elementwise_chain_pallas(
                        statics, params, x, mask, interpret=interp)
                except ChainKernelIneligibleError:
                    pass
    return elementwise_chain_reference(statics, params, x, mask)


# ---------------------------------------------------------------------------
# Canary + chain builder (the fusion swap's entry point)
# ---------------------------------------------------------------------------

_chain_canary: dict = {}


def _canary_ok(key, thunk) -> bool:
    """Compile-and-run a chain kernel ONCE per geometry on tiny data,
    eagerly — the fused-conv canary discipline: the dispatcher's
    trace-time try/except cannot see compile-time failures (scoped-vmem
    OOM, a Mosaic reject on an in-kernel reshape/reduce) when the call
    sits inside an outer jit. States: True/False permanent, 1 = one
    failed attempt (retried once, so a transient device blip doesn't
    demote a working geometry for the whole process). Multihost: every
    process adopts process 0's verdict so collective launches stay
    aligned (the `_fused_conv_canary_ok` broadcast)."""
    state = _chain_canary.get(key)
    if state is True or state is False:
        return state
    multihost = jax.process_count() > 1
    try:
        import numpy as np

        got = thunk()
        ok = bool(np.isfinite(np.asarray(got)).all())
    except ChainKernelIneligibleError:
        ok = False
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "chain kernel canary failed at geometry %s (%s: %s); "
            "using the XLA path for it", key, type(e).__name__, e)
        ok = False if (multihost or state == 1) else 1
    if multihost:
        import numpy as np
        from jax.experimental import multihost_utils

        ok = bool(multihost_utils.broadcast_one_to_all(np.asarray(bool(ok))))
    _chain_canary[key] = ok
    return ok is True


def build_chain_fn(statics, family=None, interpret=None):
    """The fusion swap's entry point: a ``fn(params_slice, xb, mb)``
    lowering the sub-trail to one kernel dispatch, or None when the
    slice doesn't match a family (a stale `planned_kernel` tag is
    ignored, never mis-lowered — the `planned_precision` discipline).
    ``family`` (from the plan tag) must agree with the matcher."""
    statics = tuple(statics)
    verdict = lowerability(statics)
    if not verdict["lowerable"]:
        return None
    if family is not None and family != verdict["family"]:
        return None
    if verdict["family"] == "rectify_pool_vectorize":
        inner, _ = _unwrap(statics[0])
        _, alpha, max_val, pool, stride = inner[:5]

        def fn(ps, xb, mb):
            return rectify_pool_vectorize(
                xb, alpha, max_val, pool, stride, interpret=interpret)

        return fn

    def fn(ps, xb, mb):
        return elementwise_chain(statics, ps, xb, mb, interpret=interpret)

    return fn


def chain_feasible(stages, item_shape, dtype=jnp.float32):
    """(ok, reason): probe the chain kernel's VMEM geometry at the
    per-item input shape without compiling anything. Used by the
    planner to price VMEM-infeasible tile geometries INF (clean
    demotion, never a crash). ``stages``: the raw (pre-peephole) stage
    objects of the candidate chain."""
    from ..nodes.util.fusion import _peephole, _stage_fuse

    try:
        fused = [_stage_fuse(s) for s in _peephole(list(stages))]
    except Exception as e:
        return False, f"stage decomposition failed: {type(e).__name__}"
    statics = tuple(f[0] for f in fused)
    params = [f[1] for f in fused]
    verdict = lowerability(statics)
    if not verdict["lowerable"]:
        return False, verdict["reason"]
    if verdict["family"] == "rectify_pool_vectorize":
        if len(item_shape) != 3:
            return False, f"expected (H, W, K) input, got {item_shape}"
        inner, _ = _unwrap(statics[0])
        _, _, _, pool, stride = inner[:5]
        h, w, k = item_shape
        bn = _rectify_pool_vectorize_block(h, w, k, pool, stride)
        if bn <= 0:
            return False, (f"VMEM: no feasible block at "
                           f"(h={h}, w={w}, k={k})")
        return True, f"block={bn}"
    bodies = _compile_bodies(statics)
    if bodies is None:
        return False, verdict["reason"]
    try:
        x = jax.ShapeDtypeStruct((8,) + tuple(item_shape), dtype)
        ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
        bn = _elementwise_geometry(bodies, ops, x)
    except Exception as e:
        return False, f"geometry probe failed: {type(e).__name__}"
    if bn <= 0:
        return False, f"VMEM: no feasible block at item shape {item_shape}"
    return True, f"block={bn}"
