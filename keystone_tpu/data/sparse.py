"""Host-side sparse dataset.

TPUs have no efficient native sparse GEMM path, so sparsity lives on the
host as scipy CSR (the reference keeps Breeze SparseVectors on the JVM,
nodes/util/Sparsify.scala) and crosses to the device as dense blocks.
`CommonSparseFeatures`-style top-K vocabulary selection (reference
nodes/util/CommonSparseFeatures.scala:19-64) is the intended path for
making NLP features dense enough to densify wholesale.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp

from .dataset import Dataset


class SparseDataset:
    """CSR-matrix-backed dataset (rows = examples)."""

    is_dataset = True

    def __init__(self, matrix: sp.spmatrix, mesh=None):
        self.matrix = sp.csr_matrix(matrix)
        self.mesh = mesh

    @property
    def count(self) -> int:
        return self.matrix.shape[0]

    @property
    def dim(self) -> int:
        return self.matrix.shape[1]

    @property
    def sparsity(self) -> float:
        """Fraction of nonzeros."""
        r, c = self.matrix.shape
        return self.matrix.nnz / max(r * c, 1)

    @property
    def per_shard_count(self) -> int:
        import jax

        return -(-self.count // max(1, len(jax.devices())))

    def map_rows(self, fn) -> "SparseDataset":
        return SparseDataset(fn(self.matrix), mesh=self.mesh)

    def densify(self, dtype=np.float32) -> Dataset:
        return Dataset(np.asarray(self.matrix.todense(), dtype=dtype), mesh=self.mesh)

    def sample_per_shard(self, k: int, seed: int = 0) -> "SparseDataset":
        import jax

        m = min(self.count, k * max(1, len(jax.devices())))
        idx = np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        return SparseDataset(self.matrix[idx], mesh=self.mesh)

    def cache(self) -> "SparseDataset":
        return self

    def numpy(self):
        return self.matrix

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (
            f"SparseDataset(count={self.count}, dim={self.dim}, "
            f"nnz={self.matrix.nnz})"
        )
