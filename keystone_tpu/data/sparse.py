"""Host-side sparse dataset.

TPUs have no efficient native sparse GEMM path, so sparsity lives on the
host as scipy CSR (the reference keeps Breeze SparseVectors on the JVM,
nodes/util/Sparsify.scala) and crosses to the device as dense blocks.
`CommonSparseFeatures`-style top-K vocabulary selection (reference
nodes/util/CommonSparseFeatures.scala:19-64) is the intended path for
making NLP features dense enough to densify wholesale.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp

from .dataset import Dataset


class SparseDataset:
    """CSR-matrix-backed dataset (rows = examples)."""

    is_dataset = True

    def __init__(self, matrix: sp.spmatrix, mesh=None):
        self.matrix = sp.csr_matrix(matrix)
        self.mesh = mesh

    @property
    def count(self) -> int:
        return self.matrix.shape[0]

    @property
    def dim(self) -> int:
        return self.matrix.shape[1]

    @property
    def sparsity(self) -> float:
        """Fraction of nonzeros."""
        r, c = self.matrix.shape
        return self.matrix.nnz / max(r * c, 1)

    @property
    def per_shard_count(self) -> int:
        import jax

        return -(-self.count // max(1, len(jax.devices())))

    def map_rows(self, fn) -> "SparseDataset":
        return SparseDataset(fn(self.matrix), mesh=self.mesh)

    def densify(self, dtype=np.float32) -> Dataset:
        return Dataset(np.asarray(self.matrix.todense(), dtype=dtype), mesh=self.mesh)

    def sample_per_shard(self, k: int, seed: int = 0) -> "SparseDataset":
        import jax

        m = min(self.count, k * max(1, len(jax.devices())))
        idx = np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        return SparseDataset(self.matrix[idx], mesh=self.mesh)

    def cache(self) -> "SparseDataset":
        return self

    def numpy(self):
        return self.matrix

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (
            f"SparseDataset(count={self.count}, dim={self.dim}, "
            f"nnz={self.matrix.nnz})"
        )


def padded_form_ok(n: int, w: int, nnz: int) -> bool:
    """Whether the width-padded (n, w) layout is a sane size for the
    data: a single outlier-dense row (a ones/bias column, one long
    document) turns O(nnz) into O(n·d) of padding. One predicate shared
    by the Gram and iterative sparse routes so their routing can't
    drift apart."""
    padded_bytes = 8.0 * n * w
    return padded_bytes <= 4e9 and not (
        padded_bytes > 32e6 and padded_bytes > 16.0 * 8.0 * max(nnz, 1)
    )


def pad_csr(matrix: sp.spmatrix):
    """Host CSR → width-padded (n, w) index/value arrays.

    Row r's nonzeros occupy slots [0, len_r); unused slots carry the
    sentinel column `dim` (so a (dim+1)-row gather table with a zero
    sentinel row makes padded slots contribute nothing) and value 0.
    This is the device-side sparse layout used by both the one-pass Gram
    reduction and the iterative matvec L-BFGS path.
    """
    X = sp.csr_matrix(matrix)
    n, d = X.shape
    lens = np.diff(X.indptr)
    w = max(1, int(lens.max()) if n else 1)
    row_ids = np.repeat(np.arange(n, dtype=np.int64), lens)
    pos_in_row = np.arange(X.nnz, dtype=np.int64) - np.repeat(
        X.indptr[:-1].astype(np.int64), lens
    )
    idx_pad = np.full((n, w), d, np.int32)
    val_pad = np.zeros((n, w), np.float32)
    idx_pad[row_ids, pos_in_row] = X.indices
    val_pad[row_ids, pos_in_row] = X.data
    return idx_pad, val_pad


class PaddedSparseDataset:
    """Device-resident width-padded sparse rows.

    The TPU-native sparse layout: `idx` (n, w) int32 column ids with
    sentinel `dim` marking padding, `val` (n, w) float32. Unlike
    `SparseDataset` (host scipy CSR), the arrays live on device, so
    solvers iterate over them with gathers/scatters and no host
    round-trips — the analog of the reference keeping partitioned
    SparseVectors resident in executor memory across L-BFGS iterations
    (LBFGS.scala:14-103).
    """

    is_dataset = True

    def __init__(self, idx, val, dim: int, mesh=None, nnz: Optional[int] = None,
                 cidx=None, cval=None):
        assert idx.shape == val.shape and idx.ndim == 2
        self.idx = idx
        self.val = val
        self.dim = int(dim)
        self.mesh = mesh
        # true nonzero count when known (sentinel slots excluded)
        self.nnz = int(nnz) if nnz is not None else int(idx.shape[0] * idx.shape[1])
        # optional column-oriented padding: cidx/cval (dim, wc) hold, per
        # feature column, the ROW ids containing it (sentinel = count).
        # With both orientations resident, Xᵀv is a gather over cidx just
        # like Xv is a gather over idx — no scatter ever runs in a solver
        # iteration loop (TPU scatter-adds into a small (d, k) table
        # serialize on index collisions; gathers don't collide).
        self.cidx = cidx
        self.cval = cval

    @classmethod
    def from_csr(cls, matrix: sp.spmatrix, mesh=None, column_form: bool = True,
                 max_col_pad_ratio: float = 16.0) -> "PaddedSparseDataset":
        import jax.numpy as jnp

        X = sp.csr_matrix(matrix)
        idx, val = pad_csr(X)
        cidx = cval = None
        if column_form and X.shape[1] > 0:
            col_lens = np.diff(X.tocsc().indptr)
            wc = max(1, int(col_lens.max()) if X.shape[1] else 1)
            # power-law columns (one ubiquitous token) can make the
            # column padding O(dim · n); skip it when padded size far
            # exceeds the data — the solver falls back to scatter
            if X.shape[1] * wc <= max(max_col_pad_ratio * max(X.nnz, 1), 1e6):
                # the column form IS the row padding of Xᵀ: (d, wc) row
                # ids per feature column, sentinel = Xᵀ's dim = n
                ci, cv = pad_csr(sp.csr_matrix(X.T))
                cidx, cval = jnp.asarray(ci), jnp.asarray(cv)
        return cls(jnp.asarray(idx), jnp.asarray(val), matrix.shape[1],
                   mesh=mesh, nnz=X.nnz, cidx=cidx, cval=cval)

    def with_column_form(self) -> "PaddedSparseDataset":
        """Build the column-oriented padding ON DEVICE — for
        device-generated data where no host CSR exists. One-time radix
        argsort of the flat column ids + unique-target scatters (the
        only scatters in the sparse stack, and they never collide);
        out-of-bounds positions from sentinel padding slots drop, which
        is JAX scatter semantics doing the masking for free."""
        if self.cidx is not None:
            return self
        import jax.numpy as jnp

        n, w = self.idx.shape
        d = self.dim
        flat = self.idx.reshape(-1)
        order = jnp.argsort(flat, stable=True)
        sorted_cols = flat[order]
        rows_sorted = (order // w).astype(jnp.int32)
        counts = jnp.bincount(flat, length=d + 1)
        wc = max(1, int(jnp.max(counts[:d]))) if d else 1
        starts = jnp.cumsum(counts) - counts  # exclusive prefix
        pos = jnp.arange(flat.shape[0]) - starts[sorted_cols]
        cidx = (
            jnp.full((d + 1, wc), n, jnp.int32)
            .at[sorted_cols, pos].set(rows_sorted)[:d]
        )
        cval = (
            jnp.zeros((d + 1, wc), jnp.float32)
            .at[sorted_cols, pos].set(self.val.reshape(-1)[order])[:d]
        )
        return PaddedSparseDataset(
            self.idx, self.val, d, mesh=self.mesh, nnz=self.nnz,
            cidx=cidx, cval=cval)

    @property
    def count(self) -> int:
        return self.idx.shape[0]

    @property
    def width(self) -> int:
        return self.idx.shape[1]

    @property
    def sparsity(self) -> float:
        return self.nnz / max(self.count * self.dim, 1)

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (
            f"PaddedSparseDataset(count={self.count}, dim={self.dim}, "
            f"width={self.width})"
        )
