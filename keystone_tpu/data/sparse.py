"""Host-side sparse dataset.

TPUs have no efficient native sparse GEMM path, so sparsity lives on the
host as scipy CSR (the reference keeps Breeze SparseVectors on the JVM,
nodes/util/Sparsify.scala) and crosses to the device as dense blocks.
`CommonSparseFeatures`-style top-K vocabulary selection (reference
nodes/util/CommonSparseFeatures.scala:19-64) is the intended path for
making NLP features dense enough to densify wholesale.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp

from .dataset import Dataset


class SparseDataset:
    """CSR-matrix-backed dataset (rows = examples)."""

    is_dataset = True

    def __init__(self, matrix: sp.spmatrix, mesh=None):
        self.matrix = sp.csr_matrix(matrix)
        self.mesh = mesh

    @property
    def count(self) -> int:
        return self.matrix.shape[0]

    @property
    def dim(self) -> int:
        return self.matrix.shape[1]

    @property
    def sparsity(self) -> float:
        """Fraction of nonzeros."""
        r, c = self.matrix.shape
        return self.matrix.nnz / max(r * c, 1)

    @property
    def per_shard_count(self) -> int:
        import jax

        return -(-self.count // max(1, len(jax.devices())))

    def map_rows(self, fn) -> "SparseDataset":
        return SparseDataset(fn(self.matrix), mesh=self.mesh)

    def densify(self, dtype=np.float32) -> Dataset:
        return Dataset(np.asarray(self.matrix.todense(), dtype=dtype), mesh=self.mesh)

    def sample_per_shard(self, k: int, seed: int = 0) -> "SparseDataset":
        import jax

        m = min(self.count, k * max(1, len(jax.devices())))
        idx = np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        return SparseDataset(self.matrix[idx], mesh=self.mesh)

    def cache(self) -> "SparseDataset":
        return self

    def numpy(self):
        return self.matrix

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (
            f"SparseDataset(count={self.count}, dim={self.dim}, "
            f"nnz={self.matrix.nnz})"
        )


def sublane_pad8(x: int) -> int:
    """Round a narrow leading-axis extent up to the TPU's 8 sublanes —
    the HBM cost of that axis in the (8, 128)-tiled slot-major layout.
    Shared by the routing predicate and the solvers' block budgets so
    the tile accounting cannot drift apart."""
    return -(-x // 8) * 8


def padded_form_ok(n: int, w: int, nnz: int) -> bool:
    """Whether the width-padded layout is a sane size for the data: a
    single outlier-dense row (a ones/bias column, one long document)
    turns O(nnz) into O(n·d) of padding. One predicate shared by the
    Gram and iterative sparse routes so their routing can't drift
    apart. Device cost counts the slot-major (w, n) layout (idx+val =
    8 B per sublane-padded slot); the 5e9 cap leaves room on a 16 GB
    chip for the similarly-sized column form plus solver transients."""
    padded_bytes = 8.0 * n * sublane_pad8(w)
    return padded_bytes <= 5e9 and not (
        padded_bytes > 32e6 and padded_bytes > 16.0 * 8.0 * max(nnz, 1)
    )


def pad_csr(matrix: sp.spmatrix):
    """Host CSR → slot-major width-padded (w, n) index/value arrays.

    Row r's nonzeros occupy slots [0, len_r) at [:, r]; unused slots
    carry the sentinel column `dim` (so a gather table with a zero
    sentinel entry makes padded slots contribute nothing) and value 0.
    This is the device-side sparse layout used by both the one-pass Gram
    reduction and the iterative matvec L-BFGS path; slot-major keeps
    the long n axis in the TPU's 128-lane minor tile dimension (see
    PaddedSparseDataset).
    """
    X = sp.csr_matrix(matrix)
    n, d = X.shape
    lens = np.diff(X.indptr)
    w = max(1, int(lens.max()) if n else 1)
    row_ids = np.repeat(np.arange(n, dtype=np.int64), lens)
    pos_in_row = np.arange(X.nnz, dtype=np.int64) - np.repeat(
        X.indptr[:-1].astype(np.int64), lens
    )
    idx_pad = np.full((w, n), d, np.int32)
    val_pad = np.zeros((w, n), np.float32)
    idx_pad[pos_in_row, row_ids] = X.indices
    val_pad[pos_in_row, row_ids] = X.data
    return idx_pad, val_pad


class PaddedSparseDataset:
    """Device-resident width-padded sparse rows, SLOT-MAJOR.

    The TPU-native sparse layout: `idx` (w, n) int32 column ids with
    sentinel `dim` marking padding, `val` (w, n) float32 — slot j of
    row r lives at [j, r]. The orientation is load-bearing on TPU: the
    default (8, 128) tiled layout pads the MINOR dimension to 128
    lanes, so a row-major (n, w) array with the natural small w (the
    reference's Amazon workload has w≈5 at d=1024) would occupy
    128/w ≈ 25× its logical bytes of HBM — slot-major instead puts the
    long n axis in lanes and pads w only up to 8 sublanes. Unlike
    `SparseDataset` (host scipy CSR), the arrays live on device, so
    solvers iterate over them with gathers/scatters and no host
    round-trips — the analog of the reference keeping partitioned
    SparseVectors resident in executor memory across L-BFGS iterations
    (LBFGS.scala:14-103).
    """

    is_dataset = True

    def __init__(self, idx, val, dim: int, mesh=None, nnz: Optional[int] = None,
                 cidx=None, cval=None):
        assert idx.shape == val.shape and idx.ndim == 2
        self.idx = idx
        self.val = val
        self.dim = int(dim)
        self.mesh = mesh
        # true nonzero count when known (sentinel slots excluded)
        self.nnz = int(nnz) if nnz is not None else int(idx.shape[0] * idx.shape[1])
        # optional column-oriented padding, also slot-major: cidx/cval
        # (wc, dim) hold, per feature column, the ROW ids containing it
        # (sentinel = count). With both orientations resident, Xᵀv is a
        # gather over cidx just like Xv is a gather over idx — no
        # scatter ever runs in a solver iteration loop (TPU
        # scatter-adds into a small gradient table serialize on index
        # collisions; gathers don't collide).
        self.cidx = cidx
        self.cval = cval

    @classmethod
    def from_csr(cls, matrix: sp.spmatrix, mesh=None, column_form: bool = True,
                 max_col_pad_ratio: float = 16.0) -> "PaddedSparseDataset":
        import jax.numpy as jnp

        X = sp.csr_matrix(matrix)
        idx, val = pad_csr(X)
        cidx = cval = None
        if column_form and X.shape[1] > 0:
            col_lens = np.diff(X.tocsc().indptr)
            wc = max(1, int(col_lens.max()) if X.shape[1] else 1)
            # power-law columns (one ubiquitous token) can make the
            # column padding O(dim · n); skip it when padded size far
            # exceeds the data — the solver falls back to scatter
            if X.shape[1] * wc <= max(max_col_pad_ratio * max(X.nnz, 1), 1e6):
                # the column form IS the slot padding of Xᵀ: (wc, d) row
                # ids per feature column, sentinel = Xᵀ's dim = n
                ci, cv = pad_csr(sp.csr_matrix(X.T))
                cidx, cval = jnp.asarray(ci), jnp.asarray(cv)
        return cls(jnp.asarray(idx), jnp.asarray(val), matrix.shape[1],
                   mesh=mesh, nnz=X.nnz, cidx=cidx, cval=cval)

    def with_column_form(self) -> "PaddedSparseDataset":
        """Build the column-oriented padding ON DEVICE — for
        device-generated data where no host CSR exists. One-time radix
        argsort of the flat column ids + unique-target scatters (the
        only scatters in the sparse stack, and they never collide);
        out-of-bounds positions from sentinel padding slots drop, which
        is JAX scatter semantics doing the masking for free. Column
        counts come from searchsorted over the sorted ids (a bincount
        here would be an nnz-sized colliding scatter-add)."""
        if self.cidx is not None:
            return self
        import jax.numpy as jnp

        w, n = self.idx.shape
        d = self.dim
        # slot-major flat index f = j*n + r → row id = f mod n
        flat = self.idx.reshape(-1)
        order = jnp.argsort(flat, stable=True)
        sorted_cols = flat[order]
        rows_sorted = (order % n).astype(jnp.int32)
        # exclusive prefix of per-column counts without a colliding
        # scatter: starts[c] = first position of column c in the sort
        starts_all = jnp.searchsorted(sorted_cols,
                                      jnp.arange(d + 1), side="left")
        counts = jnp.diff(jnp.concatenate(
            [starts_all, jnp.array([flat.shape[0]])]))
        wc = max(1, int(jnp.max(counts[:d]))) if d else 1
        pos = jnp.arange(flat.shape[0]) - starts_all[sorted_cols]
        # (wc, d+1) buffer: sentinel-column entries either overflow wc
        # (dropped by scatter semantics) or land in column d (sliced)
        cidx = (
            jnp.full((wc, d + 1), n, jnp.int32)
            .at[pos, sorted_cols].set(rows_sorted)[:, :d]
        )
        cval = (
            jnp.zeros((wc, d + 1), jnp.float32)
            .at[pos, sorted_cols].set(self.val.reshape(-1)[order])[:, :d]
        )
        return PaddedSparseDataset(
            self.idx, self.val, d, mesh=self.mesh, nnz=self.nnz,
            cidx=cidx, cval=cval)

    @property
    def count(self) -> int:
        return self.idx.shape[1]

    @property
    def width(self) -> int:
        return self.idx.shape[0]

    @property
    def sparsity(self) -> float:
        return self.nnz / max(self.count * self.dim, 1)

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (
            f"PaddedSparseDataset(count={self.count}, dim={self.dim}, "
            f"width={self.width})"
        )
