"""Distributed dataset handles — the TPU-native replacement for RDDs.

Two containers:

  - `Dataset` — a pytree of arrays with a leading example axis, padded to a
    multiple of the mesh's ``data`` axis and sharded over it. This is the
    analog of an `RDD[DenseVector]`/`RDD[Image]` with one shard per chip
    (SURVEY.md §2.7 'Data parallelism'). Zero-padding is deliberate: padded
    rows contribute nothing to Gram matrices, moment sums, or one-hot label
    sums, so reductions only need the true ``count`` for normalization.

  - `HostDataset` — a plain list of host objects (variable-size images,
    strings, token lists). The NLP stack and variable-shape image loaders
    run host-side, mirroring the reference's JVM-side per-item code, and
    convert to `Dataset` at the dense boundary via ``stack()``.

`Transformer.apply_batch`'s default path maps a per-item function over a
`Dataset` via ``jit(vmap(f))`` — the analog of `RDD.map` lowering to one
fused XLA program per shard (reference Transformer.scala:46).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as meshlib


def _pad_to(x, target: int):
    n = x.shape[0]
    if n == target:
        return x
    pad_widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths)
    return jnp.pad(x, pad_widths)


def leaf_sharding(mesh, shape) -> NamedSharding:
    """The sharding `Dataset` placement assigns a leaf of this shape:
    2-D (n, d) leaves shard their feature axis over 'model' when the
    mesh has one (the VectorSplitter analog), everything else is
    data-sharded on the leading axis. One function, used both by
    `Dataset.__init__`'s placement and by AOT plan warmup
    (`FusedBatchTransformer.warmup`) — the compiled-ahead executable
    must be lowered with exactly the shardings the runtime will pass.

    The leading axis must divide the mesh's data-shard count. `Dataset`
    placement always pads it first, but direct callers (AOT warmup over
    analyzer specs, ad-hoc `device_put`s) can hand in ragged leading
    axes — those fall back to a fully replicated placement with a
    warning instead of letting jax raise mid-force with an opaque
    uneven-sharding error (the KP604 lint flags the same condition
    statically)."""
    shards = mesh.shape.get(meshlib.DATA_AXIS, 1)
    if shape and shards > 1 and int(shape[0]) % shards != 0:
        import warnings

        warnings.warn(
            f"leaf_sharding: leading axis {shape[0]} does not divide the "
            f"{shards}-way {meshlib.DATA_AXIS!r} mesh axis; placing the "
            "value replicated instead (pad the leading axis to a "
            "multiple of the data-shard count to shard it)",
            stacklevel=2)
        return NamedSharding(mesh, P())
    if len(shape) == 2:
        feat = meshlib.feature_sharding(mesh, shape[1])
        if feat is not None:
            return feat
    return NamedSharding(mesh, P(meshlib.DATA_AXIS))


def sync_pull(leaf) -> None:
    """THE scalar-pull sync idiom, in one place: transfer one element of
    a (device) array to host. `jax.block_until_ready` does not actually
    block through the axon tunnel (PERF.md methodology), so every honest
    timing fence in the library routes through this helper.

    In a multi-process job a cross-host global array's element-0 slice is
    not addressable from every host, so np.asarray would raise; those
    leaves fall back to block_until_ready (the tunnel pathology is a
    single-host phenomenon — multihost runs use real local devices)."""
    if hasattr(leaf, "ndim") and hasattr(leaf, "dtype") and leaf.ndim > 0:
        if getattr(leaf, "is_fully_addressable", True):
            np.asarray(leaf[(0,) * leaf.ndim])
        else:
            jax.block_until_ready(leaf)


class Dataset:
    """Sharded device-resident dataset (leading axis = examples)."""

    is_dataset = True

    def __init__(self, data: Any, count: Optional[int] = None, mesh=None, _placed=False):
        self.mesh = mesh or meshlib.current_mesh()
        leaves = jax.tree_util.tree_leaves(data)
        if not leaves:
            raise ValueError("Dataset requires at least one array")
        n = leaves[0].shape[0]
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError("all leaves must share the leading axis length")
        self.count = int(count) if count is not None else n
        shards = self.mesh.shape.get(meshlib.DATA_AXIS, 1)
        padded = -(-self.count // shards) * shards if self.count else shards
        if _placed and n == padded:
            self.data = data
        else:
            if n < self.count:
                raise ValueError("count exceeds data length")
            data = jax.tree_util.tree_map(lambda x: _pad_to(x[: self.count], padded), data)
            # On a ('data', 'model') mesh, 2-D (n, d) leaves also shard
            # their feature axis over 'model' — the library-level analog
            # of the reference's VectorSplitter feature blocking. Other
            # ranks (images, label vectors of odd widths) stay data-only
            # and replicate over the model axis (see `leaf_sharding`).
            self.data = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    x, leaf_sharding(self.mesh, x.shape)),
                data)

    # ------------------------------------------------------------- factories

    @staticmethod
    def from_numpy(x, count: Optional[int] = None, mesh=None) -> "Dataset":
        return Dataset(np.asarray(x), count=count, mesh=mesh)

    # ---------------------------------------------------------------- views

    @property
    def array(self):
        """The padded, sharded pytree (single array in the common case)."""
        return self.data

    @property
    def padded_count(self) -> int:
        return jax.tree_util.tree_leaves(self.data)[0].shape[0]

    @property
    def n_shards(self) -> int:
        return self.mesh.shape.get(meshlib.DATA_AXIS, 1)

    @property
    def per_shard_count(self) -> int:
        """Max examples per shard (≈ reference `numPerPartition`,
        WorkflowUtils.scala:12-17)."""
        return self.padded_count // self.n_shards

    @property
    def mask(self):
        """Boolean validity mask over the padded leading axis (cached:
        eager re-dispatch per access costs a device round trip). Placed
        with the same leading-axis sharding as the data so programs
        consuming (data, mask) compile against ONE deterministic input
        layout — what AOT warmup lowers against."""
        m = self.__dict__.get("_mask_cache")
        if m is None:
            # built on host: an eager jnp.arange/lt pair compiles two
            # one-op XLA programs per DISTINCT padded count — cold
            # compiles the serving certifier's 0-cold-compile warm
            # ladder claim (KP902) cannot afford; device_put is a
            # transfer, not a compile
            m = np.arange(self.padded_count) < self.count
            sh = NamedSharding(self.mesh, P(meshlib.DATA_AXIS))
            if sh.is_fully_addressable:
                # multi-host meshes keep the host mask (a host array
                # can't device_put to a cross-process sharding);
                # AOT-warmed programs just fall back to the jit path
                m = jax.device_put(m, sh)
            self.__dict__["_mask_cache"] = m
        return m

    def numpy(self):
        """Unpadded host copy (≈ `collect`)."""
        return jax.tree_util.tree_map(lambda x: np.asarray(x)[: self.count], self.data)

    def __len__(self) -> int:
        return self.count

    # ------------------------------------------------------------ operations

    def map(self, fn: Callable, jitted: bool = True) -> "Dataset":
        """Apply a per-item function via vmap (≈ `RDD.map`). ``fn`` must be
        traceable; use `map_batches` for whole-batch functions."""
        batched = jax.vmap(fn)
        return self.map_batches(batched, jitted=jitted)

    def map_batches(self, fn: Callable, jitted: bool = True, count: Optional[int] = None) -> "Dataset":
        """Apply a whole-batch function to the padded sharded pytree. The
        result keeps the leading axis and sharding. One call = one
        executed XLA program — THE library-wide jitted call boundary, so
        it feeds the ``dispatch.programs_executed`` budget."""
        from ..telemetry import record_dispatch

        if jitted:
            fn = jax.jit(fn)
        record_dispatch()
        out = fn(self.data)
        return Dataset(out, count=count if count is not None else self.count,
                       mesh=self.mesh, _placed=True)

    def with_data(self, data: Any, count: Optional[int] = None) -> "Dataset":
        """New Dataset sharing this one's mesh/count, for already-sharded
        results of jitted computations."""
        return Dataset(data, count=count if count is not None else self.count,
                       mesh=self.mesh, _placed=True)

    def reshard(self, spec) -> "Dataset":
        """New Dataset with every leaf moved to ``spec`` (a batch-level
        `PartitionSpec`; entries beyond a leaf's rank are trimmed) via
        `parallel.collectives.reshard` — the explicit spelling of a
        placement decision, used by the sharding planner to seed plan
        inputs from the chosen plan instead of the static default.
        Leaves already laid out as ``spec`` are returned as-is (the
        identity short-circuit), so resharding to the current placement
        builds no program and moves nothing."""
        from ..parallel.collectives import reshard_tree

        return Dataset(reshard_tree(self.data, spec, mesh=self.mesh),
                       count=self.count, mesh=self.mesh, _placed=True)

    def cache(self) -> "Dataset":
        """Device arrays are already materialized (≈ `.cache()` + action).
        NOT a timing fence — production Cacher nodes call this on every
        run, and a host round trip here would defeat async dispatch
        overlap at every cache boundary; timing paths (autocache
        profiling, calibration) must use `sync()` instead."""
        jax.block_until_ready(self.data)
        return self

    def sync(self) -> "Dataset":
        """TRUE host sync: transfer one element per leaf.
        `jax.block_until_ready` does not actually block through the axon
        tunnel (see PERF.md methodology), so honest wall-clock timing —
        autocache profiling, calibration — must force a value transfer;
        a single-element device slice keeps the transfer tiny."""
        for leaf in jax.tree_util.tree_leaves(self.data):
            sync_pull(leaf)
        return self

    def spread_take(self, m: int):
        """Host copy of ≤ m valid examples at evenly spread indices —
        one device gather + one small transfer, never a full collect."""
        m = min(self.count, m)
        if m == 0:
            return jax.tree_util.tree_map(
                lambda x: np.asarray(x[:0]), self.data
            )
        idx = jnp.asarray(
            np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        )
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jnp.take(x, idx, axis=0)), self.data
        )

    def sample_per_shard(self, k: int, seed: int = 0) -> "Dataset":
        """Deterministic sample of ≤ k·n_shards valid examples, resharded
        (≈ SampleCollector's per-partition samples,
        NodeOptimizationRule.scala:145-197)."""
        m = min(self.count, k * self.n_shards)
        return Dataset(self.spread_take(m), count=m, mesh=self.mesh)

    def take(self, k: int):
        k = min(k, self.count)
        return jax.tree_util.tree_map(lambda x: np.asarray(x[:k]), self.data)

    def __repr__(self) -> str:
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), self.data)
        return f"Dataset(count={self.count}, shapes={shapes}, shards={self.n_shards})"


class HostDataset:
    """List-backed dataset of host objects (≈ RDD of JVM objects for the
    non-dense stages: strings, token lists, variable-size images)."""

    is_dataset = True

    def __init__(self, items: Sequence[Any]):
        self.items = list(items)

    @property
    def count(self) -> int:
        return len(self.items)

    @property
    def per_shard_count(self) -> int:
        return -(-len(self.items) // max(1, len(jax.devices())))

    def map(self, fn: Callable) -> "HostDataset":
        return HostDataset([fn(x) for x in self.items])

    def cache(self) -> "HostDataset":
        return self

    def sample_per_shard(self, k: int, seed: int = 0) -> "HostDataset":
        m = min(len(self.items), k * max(1, len(jax.devices())))
        if m == 0:
            return HostDataset([])
        idx = np.linspace(0, len(self.items) - 1, num=m, dtype=np.int64)
        return HostDataset([self.items[i] for i in idx])

    def stack(self, dtype=None, mesh=None, spec=None) -> Dataset:
        """Stack fixed-shape items into a device `Dataset`. ``spec``
        overrides the static `leaf_sharding` default at this
        host→device seam with an explicit batch-level `PartitionSpec`
        (the sharding planner's chosen placement for the stacked
        value). The host array is padded and placed DIRECTLY into the
        requested layout (one `collectives.reshard` device_put from
        host) — never staged through the default placement first."""
        from ..parallel.collectives import reshard_tree

        arr = np.stack([np.asarray(x, dtype=dtype) for x in self.items])
        if spec is None:
            return Dataset(arr, mesh=mesh)
        mesh = mesh or meshlib.current_mesh()
        count = arr.shape[0]
        shards = mesh.shape.get(meshlib.DATA_AXIS, 1)
        padded = -(-count // shards) * shards if count else shards
        placed = reshard_tree(_pad_to(arr, padded), spec, mesh=mesh)
        return Dataset(placed, count=count, mesh=mesh, _placed=True)

    def numpy(self):
        return self.items

    def take(self, k: int):
        return self.items[:k]

    def __len__(self) -> int:
        return len(self.items)

    def __iter__(self):
        return iter(self.items)

    def __repr__(self) -> str:
        return f"HostDataset(count={len(self.items)})"


class SpilledDataset:
    """Host-spilled dataset: the out-of-core tier's cache payload.

    A host-placed `workflow.autocache.CacheMarker` pulls its input off
    the device into one of these — an unpadded numpy pytree plus the
    true ``count`` — freeing the HBM the device copy pinned. Consumers
    re-enter the device through `utils.batching.stream_spill_windows`:
    bounded pow-2 row windows on the pad ladder, reload of window k+1
    overlapped with compute on window k. `rehydrate()` is the sanctioned
    full re-entry for consumers that genuinely need whole-batch
    residency (it re-counts the bytes as ``spill.bytes_in``).

    Deliberately does NOT expose ``.data`` or ``.items``: the telemetry
    byte estimator (`telemetry.instrument.estimate_bytes`) unwraps those
    attributes to count device payloads, and a spilled value must count
    as ~nothing against device residency — its whole point.
    """

    is_dataset = True
    is_spilled = True

    def __init__(self, host_data: Any, count: Optional[int] = None,
                 mesh=None, name: str = ""):
        self.mesh = mesh or meshlib.current_mesh()
        self.name = name
        leaves = jax.tree_util.tree_leaves(host_data)
        if not leaves:
            raise ValueError("SpilledDataset requires at least one array")
        n = int(leaves[0].shape[0])
        self.count = int(count) if count is not None else n
        if self.count > n:
            raise ValueError("count exceeds data length")
        # trim any device-side padding at spill time: host rows are the
        # TRUE rows, so windowed reload never re-uploads phantom rows
        self._host = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[: self.count], host_data)

    @staticmethod
    def spill(dataset: "Dataset", name: str = "") -> "SpilledDataset":
        """Pull a device `Dataset` to the host, counting the evicted
        bytes as ``spill.bytes_out`` — THE device→host spill seam."""
        from ..telemetry import counter

        host = dataset.numpy()
        counter("spill.bytes_out").inc(float(sum(
            a.nbytes for a in jax.tree_util.tree_leaves(host))))
        return SpilledDataset(host, count=dataset.count, mesh=dataset.mesh,
                              name=name)

    @property
    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in jax.tree_util.tree_leaves(self._host)))

    def row_loader(self, lo: int, hi: int):
        """Host rows [lo, hi) — the ``load`` callback
        `utils.batching.stream_spill_windows` stages from."""
        return jax.tree_util.tree_map(lambda x: x[lo:hi], self._host)

    def window_iter(self, window=None):
        """``(indices, device_window)`` pairs with bounded residency —
        see `utils.batching.stream_spill_windows`."""
        from ..utils.batching import USE_CONFIG_CHUNK, stream_spill_windows

        return stream_spill_windows(
            self.row_loader, self.count,
            USE_CONFIG_CHUNK if window is None else window)

    def rehydrate(self) -> "Dataset":
        """Sanctioned FULL re-entry: the whole spilled value back on
        device, counted as ``spill.bytes_in``. Consumers that can take
        windows should use `window_iter` instead."""
        from ..telemetry import counter

        counter("spill.bytes_in").inc(float(self.nbytes))
        return Dataset(self._host, count=self.count, mesh=self.mesh)

    def numpy(self):
        return self._host

    def take(self, k: int):
        k = min(k, self.count)
        return jax.tree_util.tree_map(lambda x: x[:k], self._host)

    def sample_per_shard(self, k: int, seed: int = 0) -> "Dataset":
        m = min(self.count, k * max(1, len(jax.devices())))
        if m == 0:
            return Dataset(jax.tree_util.tree_map(
                lambda x: x[:0], self._host), count=0, mesh=self.mesh)
        idx = np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        return Dataset(jax.tree_util.tree_map(
            lambda x: x[idx], self._host), count=m, mesh=self.mesh)

    def cache(self) -> "SpilledDataset":
        return self  # already materialized (on the host — that's the point)

    def sync(self) -> "SpilledDataset":
        return self  # host arrays: nothing in flight

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (f"SpilledDataset(count={self.count}, "
                f"host_bytes={self.nbytes})")


class OutOfCoreDataset:
    """On-demand sharded source for datasets ≫ HBM (the arXiv 1610.09451
    §5 out-of-core regime).

    Backed by per-shard loader callbacks — ``loaders[i]()`` returns
    shard i's host rows (array or pytree) with ``counts[i]`` rows — so
    nothing loads until a window asks for it, and device residency stays
    O(window) through `window_iter` / `utils.batching.map_spill_windows`
    instead of O(count). At most one loaded shard is kept (the window
    walk is sequential, so a shard is hot for exactly the windows that
    overlap it). `materialize()` is the sanctioned full drain for
    explicitly-unconstrained runs (the bench's reference arm); anything
    else draining one of these wholesale is what jaxlint KJ020 flags.

    Like `SpilledDataset`, deliberately exposes neither ``.data`` nor
    ``.items`` — see `telemetry.instrument.estimate_bytes`.
    """

    is_dataset = True
    is_out_of_core = True

    def __init__(self, loaders: Sequence[Callable[[], Any]],
                 counts: Sequence[int], mesh=None, name: str = "ooc"):
        if not loaders:
            raise ValueError("OutOfCoreDataset requires at least one shard")
        if len(loaders) != len(counts):
            raise ValueError("one count per shard loader required")
        self._loaders = list(loaders)
        self._counts = [int(c) for c in counts]
        if any(c <= 0 for c in self._counts):
            raise ValueError("shard counts must be positive")
        self._offsets = np.concatenate(([0], np.cumsum(self._counts)))
        self.count = int(self._offsets[-1])
        self.mesh = mesh or meshlib.current_mesh()
        self.name = name
        self._hot: Tuple[Optional[int], Any] = (None, None)

    def _shard(self, i: int):
        """Shard i's host rows, via the single-slot hot cache."""
        hot_i, hot_v = self._hot
        if hot_i != i:
            hot_v = self._loaders[i]()
            n = jax.tree_util.tree_leaves(hot_v)[0].shape[0]
            if int(n) != self._counts[i]:
                raise ValueError(
                    f"shard {i} loader returned {n} rows, declared "
                    f"{self._counts[i]}")
            self._hot = (i, hot_v)
        return hot_v

    def row_loader(self, lo: int, hi: int):
        """Host rows [lo, hi), concatenated across exactly the shards
        that overlap the range — the windowed prefetcher's ``load``
        callback. Sequential windows touch each shard once."""
        if not (0 <= lo <= hi <= self.count):
            raise IndexError(f"rows [{lo}, {hi}) out of range")
        first = int(np.searchsorted(self._offsets, lo, side="right")) - 1
        pieces = []
        i = first
        while i < len(self._loaders) and int(self._offsets[i]) < hi:
            base = int(self._offsets[i])
            shard = self._shard(i)
            a, b = max(lo - base, 0), min(hi - base, self._counts[i])
            pieces.append(jax.tree_util.tree_map(
                lambda x, a=a, b=b: x[a:b], shard))
            i += 1
        if len(pieces) == 1:
            return pieces[0]
        return jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *pieces)

    @property
    def nbytes(self) -> int:
        """Total host bytes, estimated from shard 0's per-row bytes —
        the figure the planner's live-set model scales by window/count."""
        shard0 = self._shard(0)
        per_row = sum(a.nbytes / max(1, a.shape[0])
                      for a in jax.tree_util.tree_leaves(shard0))
        return int(per_row * self.count)

    def window_iter(self, window=None):
        from ..utils.batching import USE_CONFIG_CHUNK, stream_spill_windows

        return stream_spill_windows(
            self.row_loader, self.count,
            USE_CONFIG_CHUNK if window is None else window)

    def map_windowed(self, fn: Callable, window=None):
        """``(indices, results)`` chunks of ``fn`` over reloaded device
        windows — `utils.batching.map_spill_windows` over this source."""
        from ..utils.batching import USE_CONFIG_CHUNK, map_spill_windows

        return map_spill_windows(
            self.row_loader, self.count, fn,
            USE_CONFIG_CHUNK if window is None else window)

    def materialize(self) -> "Dataset":
        """Sanctioned FULL materialization (the explicitly-unconstrained
        path: reference arms, tiny sources). Counts ``spill.bytes_in``
        like any other host→device re-entry."""
        from ..telemetry import counter

        host = self.row_loader(0, self.count)
        counter("spill.bytes_in").inc(float(sum(
            a.nbytes for a in jax.tree_util.tree_leaves(host))))
        return Dataset(host, count=self.count, mesh=self.mesh)

    def spill(self, name: str = "") -> "SpilledDataset":
        """Full host materialization as a `SpilledDataset` (no device
        trip) — for handing an on-demand source to the spill-cache tier."""
        return SpilledDataset(self.row_loader(0, self.count),
                              count=self.count, mesh=self.mesh,
                              name=name or self.name)

    def numpy(self):
        return self.row_loader(0, self.count)

    def take(self, k: int):
        return self.row_loader(0, min(k, self.count))

    def sample_per_shard(self, k: int, seed: int = 0) -> "Dataset":
        m = min(self.count, k * max(1, len(jax.devices())))
        if m == 0:
            return Dataset(jax.tree_util.tree_map(
                lambda x: x[:0], self._shard(0)), count=0, mesh=self.mesh)
        idx = np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        rows = [self.row_loader(int(j), int(j) + 1) for j in idx]
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *rows)
        return Dataset(stacked, count=m, mesh=self.mesh)

    def cache(self) -> "OutOfCoreDataset":
        return self  # caching an on-demand source is a planner decision

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        return (f"OutOfCoreDataset(count={self.count}, "
                f"shards={len(self._loaders)})")


def zip_datasets(datasets: List[Any]):
    """Elementwise zip of N aligned datasets into one dataset of tuples
    (≈ `RDD.zip`; used by the gather operator,
    GatherTransformerOperator.scala:9-18)."""
    if not datasets:
        raise ValueError("zip_datasets requires at least one dataset")
    if all(isinstance(d, HostDataset) for d in datasets):
        return HostDataset([list(t) for t in zip(*(d.items for d in datasets))])
    if all(isinstance(d, Dataset) for d in datasets):
        counts = {d.count for d in datasets}
        if len(counts) != 1:
            raise ValueError(f"zip of misaligned datasets: counts {counts}")
        return Dataset(
            tuple(d.data for d in datasets),
            count=datasets[0].count,
            mesh=datasets[0].mesh,
            _placed=True,
        )
    raise TypeError("zip_datasets requires all-device or all-host datasets")
