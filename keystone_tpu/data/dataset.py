"""Distributed dataset handles — the TPU-native replacement for RDDs.

Two containers:

  - `Dataset` — a pytree of arrays with a leading example axis, padded to a
    multiple of the mesh's ``data`` axis and sharded over it. This is the
    analog of an `RDD[DenseVector]`/`RDD[Image]` with one shard per chip
    (SURVEY.md §2.7 'Data parallelism'). Zero-padding is deliberate: padded
    rows contribute nothing to Gram matrices, moment sums, or one-hot label
    sums, so reductions only need the true ``count`` for normalization.

  - `HostDataset` — a plain list of host objects (variable-size images,
    strings, token lists). The NLP stack and variable-shape image loaders
    run host-side, mirroring the reference's JVM-side per-item code, and
    convert to `Dataset` at the dense boundary via ``stack()``.

`Transformer.apply_batch`'s default path maps a per-item function over a
`Dataset` via ``jit(vmap(f))`` — the analog of `RDD.map` lowering to one
fused XLA program per shard (reference Transformer.scala:46).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as meshlib


def _pad_to(x, target: int):
    n = x.shape[0]
    if n == target:
        return x
    pad_widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths)
    return jnp.pad(x, pad_widths)


def leaf_sharding(mesh, shape) -> NamedSharding:
    """The sharding `Dataset` placement assigns a leaf of this shape:
    2-D (n, d) leaves shard their feature axis over 'model' when the
    mesh has one (the VectorSplitter analog), everything else is
    data-sharded on the leading axis. One function, used both by
    `Dataset.__init__`'s placement and by AOT plan warmup
    (`FusedBatchTransformer.warmup`) — the compiled-ahead executable
    must be lowered with exactly the shardings the runtime will pass.

    The leading axis must divide the mesh's data-shard count. `Dataset`
    placement always pads it first, but direct callers (AOT warmup over
    analyzer specs, ad-hoc `device_put`s) can hand in ragged leading
    axes — those fall back to a fully replicated placement with a
    warning instead of letting jax raise mid-force with an opaque
    uneven-sharding error (the KP604 lint flags the same condition
    statically)."""
    shards = mesh.shape.get(meshlib.DATA_AXIS, 1)
    if shape and shards > 1 and int(shape[0]) % shards != 0:
        import warnings

        warnings.warn(
            f"leaf_sharding: leading axis {shape[0]} does not divide the "
            f"{shards}-way {meshlib.DATA_AXIS!r} mesh axis; placing the "
            "value replicated instead (pad the leading axis to a "
            "multiple of the data-shard count to shard it)",
            stacklevel=2)
        return NamedSharding(mesh, P())
    if len(shape) == 2:
        feat = meshlib.feature_sharding(mesh, shape[1])
        if feat is not None:
            return feat
    return NamedSharding(mesh, P(meshlib.DATA_AXIS))


def sync_pull(leaf) -> None:
    """THE scalar-pull sync idiom, in one place: transfer one element of
    a (device) array to host. `jax.block_until_ready` does not actually
    block through the axon tunnel (PERF.md methodology), so every honest
    timing fence in the library routes through this helper.

    In a multi-process job a cross-host global array's element-0 slice is
    not addressable from every host, so np.asarray would raise; those
    leaves fall back to block_until_ready (the tunnel pathology is a
    single-host phenomenon — multihost runs use real local devices)."""
    if hasattr(leaf, "ndim") and hasattr(leaf, "dtype") and leaf.ndim > 0:
        if getattr(leaf, "is_fully_addressable", True):
            np.asarray(leaf[(0,) * leaf.ndim])
        else:
            jax.block_until_ready(leaf)


class Dataset:
    """Sharded device-resident dataset (leading axis = examples)."""

    is_dataset = True

    def __init__(self, data: Any, count: Optional[int] = None, mesh=None, _placed=False):
        self.mesh = mesh or meshlib.current_mesh()
        leaves = jax.tree_util.tree_leaves(data)
        if not leaves:
            raise ValueError("Dataset requires at least one array")
        n = leaves[0].shape[0]
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError("all leaves must share the leading axis length")
        self.count = int(count) if count is not None else n
        shards = self.mesh.shape.get(meshlib.DATA_AXIS, 1)
        padded = -(-self.count // shards) * shards if self.count else shards
        if _placed and n == padded:
            self.data = data
        else:
            if n < self.count:
                raise ValueError("count exceeds data length")
            data = jax.tree_util.tree_map(lambda x: _pad_to(x[: self.count], padded), data)
            # On a ('data', 'model') mesh, 2-D (n, d) leaves also shard
            # their feature axis over 'model' — the library-level analog
            # of the reference's VectorSplitter feature blocking. Other
            # ranks (images, label vectors of odd widths) stay data-only
            # and replicate over the model axis (see `leaf_sharding`).
            self.data = jax.tree_util.tree_map(
                lambda x: jax.device_put(
                    x, leaf_sharding(self.mesh, x.shape)),
                data)

    # ------------------------------------------------------------- factories

    @staticmethod
    def from_numpy(x, count: Optional[int] = None, mesh=None) -> "Dataset":
        return Dataset(np.asarray(x), count=count, mesh=mesh)

    # ---------------------------------------------------------------- views

    @property
    def array(self):
        """The padded, sharded pytree (single array in the common case)."""
        return self.data

    @property
    def padded_count(self) -> int:
        return jax.tree_util.tree_leaves(self.data)[0].shape[0]

    @property
    def n_shards(self) -> int:
        return self.mesh.shape.get(meshlib.DATA_AXIS, 1)

    @property
    def per_shard_count(self) -> int:
        """Max examples per shard (≈ reference `numPerPartition`,
        WorkflowUtils.scala:12-17)."""
        return self.padded_count // self.n_shards

    @property
    def mask(self):
        """Boolean validity mask over the padded leading axis (cached:
        eager re-dispatch per access costs a device round trip). Placed
        with the same leading-axis sharding as the data so programs
        consuming (data, mask) compile against ONE deterministic input
        layout — what AOT warmup lowers against."""
        m = self.__dict__.get("_mask_cache")
        if m is None:
            # built on host: an eager jnp.arange/lt pair compiles two
            # one-op XLA programs per DISTINCT padded count — cold
            # compiles the serving certifier's 0-cold-compile warm
            # ladder claim (KP902) cannot afford; device_put is a
            # transfer, not a compile
            m = np.arange(self.padded_count) < self.count
            sh = NamedSharding(self.mesh, P(meshlib.DATA_AXIS))
            if sh.is_fully_addressable:
                # multi-host meshes keep the host mask (a host array
                # can't device_put to a cross-process sharding);
                # AOT-warmed programs just fall back to the jit path
                m = jax.device_put(m, sh)
            self.__dict__["_mask_cache"] = m
        return m

    def numpy(self):
        """Unpadded host copy (≈ `collect`)."""
        return jax.tree_util.tree_map(lambda x: np.asarray(x)[: self.count], self.data)

    def __len__(self) -> int:
        return self.count

    # ------------------------------------------------------------ operations

    def map(self, fn: Callable, jitted: bool = True) -> "Dataset":
        """Apply a per-item function via vmap (≈ `RDD.map`). ``fn`` must be
        traceable; use `map_batches` for whole-batch functions."""
        batched = jax.vmap(fn)
        return self.map_batches(batched, jitted=jitted)

    def map_batches(self, fn: Callable, jitted: bool = True, count: Optional[int] = None) -> "Dataset":
        """Apply a whole-batch function to the padded sharded pytree. The
        result keeps the leading axis and sharding. One call = one
        executed XLA program — THE library-wide jitted call boundary, so
        it feeds the ``dispatch.programs_executed`` budget."""
        from ..telemetry import record_dispatch

        if jitted:
            fn = jax.jit(fn)
        record_dispatch()
        out = fn(self.data)
        return Dataset(out, count=count if count is not None else self.count,
                       mesh=self.mesh, _placed=True)

    def with_data(self, data: Any, count: Optional[int] = None) -> "Dataset":
        """New Dataset sharing this one's mesh/count, for already-sharded
        results of jitted computations."""
        return Dataset(data, count=count if count is not None else self.count,
                       mesh=self.mesh, _placed=True)

    def reshard(self, spec) -> "Dataset":
        """New Dataset with every leaf moved to ``spec`` (a batch-level
        `PartitionSpec`; entries beyond a leaf's rank are trimmed) via
        `parallel.collectives.reshard` — the explicit spelling of a
        placement decision, used by the sharding planner to seed plan
        inputs from the chosen plan instead of the static default.
        Leaves already laid out as ``spec`` are returned as-is (the
        identity short-circuit), so resharding to the current placement
        builds no program and moves nothing."""
        from ..parallel.collectives import reshard_tree

        return Dataset(reshard_tree(self.data, spec, mesh=self.mesh),
                       count=self.count, mesh=self.mesh, _placed=True)

    def cache(self) -> "Dataset":
        """Device arrays are already materialized (≈ `.cache()` + action).
        NOT a timing fence — production Cacher nodes call this on every
        run, and a host round trip here would defeat async dispatch
        overlap at every cache boundary; timing paths (autocache
        profiling, calibration) must use `sync()` instead."""
        jax.block_until_ready(self.data)
        return self

    def sync(self) -> "Dataset":
        """TRUE host sync: transfer one element per leaf.
        `jax.block_until_ready` does not actually block through the axon
        tunnel (see PERF.md methodology), so honest wall-clock timing —
        autocache profiling, calibration — must force a value transfer;
        a single-element device slice keeps the transfer tiny."""
        for leaf in jax.tree_util.tree_leaves(self.data):
            sync_pull(leaf)
        return self

    def spread_take(self, m: int):
        """Host copy of ≤ m valid examples at evenly spread indices —
        one device gather + one small transfer, never a full collect."""
        m = min(self.count, m)
        if m == 0:
            return jax.tree_util.tree_map(
                lambda x: np.asarray(x[:0]), self.data
            )
        idx = jnp.asarray(
            np.linspace(0, self.count - 1, num=m, dtype=np.int64)
        )
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jnp.take(x, idx, axis=0)), self.data
        )

    def sample_per_shard(self, k: int, seed: int = 0) -> "Dataset":
        """Deterministic sample of ≤ k·n_shards valid examples, resharded
        (≈ SampleCollector's per-partition samples,
        NodeOptimizationRule.scala:145-197)."""
        m = min(self.count, k * self.n_shards)
        return Dataset(self.spread_take(m), count=m, mesh=self.mesh)

    def take(self, k: int):
        k = min(k, self.count)
        return jax.tree_util.tree_map(lambda x: np.asarray(x[:k]), self.data)

    def __repr__(self) -> str:
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), self.data)
        return f"Dataset(count={self.count}, shapes={shapes}, shards={self.n_shards})"


class HostDataset:
    """List-backed dataset of host objects (≈ RDD of JVM objects for the
    non-dense stages: strings, token lists, variable-size images)."""

    is_dataset = True

    def __init__(self, items: Sequence[Any]):
        self.items = list(items)

    @property
    def count(self) -> int:
        return len(self.items)

    @property
    def per_shard_count(self) -> int:
        return -(-len(self.items) // max(1, len(jax.devices())))

    def map(self, fn: Callable) -> "HostDataset":
        return HostDataset([fn(x) for x in self.items])

    def cache(self) -> "HostDataset":
        return self

    def sample_per_shard(self, k: int, seed: int = 0) -> "HostDataset":
        m = min(len(self.items), k * max(1, len(jax.devices())))
        if m == 0:
            return HostDataset([])
        idx = np.linspace(0, len(self.items) - 1, num=m, dtype=np.int64)
        return HostDataset([self.items[i] for i in idx])

    def stack(self, dtype=None, mesh=None, spec=None) -> Dataset:
        """Stack fixed-shape items into a device `Dataset`. ``spec``
        overrides the static `leaf_sharding` default at this
        host→device seam with an explicit batch-level `PartitionSpec`
        (the sharding planner's chosen placement for the stacked
        value). The host array is padded and placed DIRECTLY into the
        requested layout (one `collectives.reshard` device_put from
        host) — never staged through the default placement first."""
        from ..parallel.collectives import reshard_tree

        arr = np.stack([np.asarray(x, dtype=dtype) for x in self.items])
        if spec is None:
            return Dataset(arr, mesh=mesh)
        mesh = mesh or meshlib.current_mesh()
        count = arr.shape[0]
        shards = mesh.shape.get(meshlib.DATA_AXIS, 1)
        padded = -(-count // shards) * shards if count else shards
        placed = reshard_tree(_pad_to(arr, padded), spec, mesh=mesh)
        return Dataset(placed, count=count, mesh=mesh, _placed=True)

    def numpy(self):
        return self.items

    def take(self, k: int):
        return self.items[:k]

    def __len__(self) -> int:
        return len(self.items)

    def __iter__(self):
        return iter(self.items)

    def __repr__(self) -> str:
        return f"HostDataset(count={len(self.items)})"


def zip_datasets(datasets: List[Any]):
    """Elementwise zip of N aligned datasets into one dataset of tuples
    (≈ `RDD.zip`; used by the gather operator,
    GatherTransformerOperator.scala:9-18)."""
    if not datasets:
        raise ValueError("zip_datasets requires at least one dataset")
    if all(isinstance(d, HostDataset) for d in datasets):
        return HostDataset([list(t) for t in zip(*(d.items for d in datasets))])
    if all(isinstance(d, Dataset) for d in datasets):
        counts = {d.count for d in datasets}
        if len(counts) != 1:
            raise ValueError(f"zip of misaligned datasets: counts {counts}")
        return Dataset(
            tuple(d.data for d in datasets),
            count=datasets[0].count,
            mesh=datasets[0].mesh,
            _placed=True,
        )
    raise TypeError("zip_datasets requires all-device or all-host datasets")
