"""Pipeline launcher (the reference's bin/run-pipeline.sh: class name +
flags → spark-submit; here: pipeline name + flags → the app's argparse
main, reference bin/run-pipeline.sh:1-55).

    python -m keystone_tpu pipelines.images.cifar.RandomPatchCifar --num-filters 256
    python -m keystone_tpu MnistRandomFFT --num-ffts 4

Names accept the reference's fully-qualified form or the bare class name.
"""

from __future__ import annotations

import importlib
import sys

#: reference class name -> (module, main callable name)
REGISTRY = {
    "pipelines.images.mnist.MnistRandomFFT": ("keystone_tpu.pipelines.mnist_random_fft", "main"),
    "pipelines.images.cifar.RandomPatchCifar": ("keystone_tpu.pipelines.random_patch_cifar", "main"),
    "pipelines.images.cifar.LinearPixels": ("keystone_tpu.pipelines.cli_mains", "linear_pixels_main"),
    "pipelines.images.cifar.RandomCifar": ("keystone_tpu.pipelines.cli_mains", "random_cifar_main"),
    "pipelines.images.cifar.RandomPatchCifarKernel": ("keystone_tpu.pipelines.cli_mains", "cifar_kernel_main"),
    "pipelines.images.cifar.RandomPatchCifarAugmented": ("keystone_tpu.pipelines.cli_mains", "cifar_augmented_main"),
    "pipelines.images.cifar.RandomPatchCifarAugmentedKernel": ("keystone_tpu.pipelines.cli_mains", "cifar_augmented_kernel_main"),
    "pipelines.images.voc.VOCSIFTFisher": ("keystone_tpu.pipelines.voc_sift_fisher", "main"),
    "pipelines.images.imagenet.ImageNetSiftLcsFV": ("keystone_tpu.pipelines.imagenet_sift_lcs_fv", "main"),
    "pipelines.speech.TimitPipeline": ("keystone_tpu.pipelines.timit", "main"),
    "pipelines.text.NewsgroupsPipeline": ("keystone_tpu.pipelines.cli_mains", "newsgroups_main"),
    "pipelines.text.AmazonReviewsPipeline": ("keystone_tpu.pipelines.cli_mains", "amazon_main"),
    "pipelines.nlp.StupidBackoffPipeline": ("keystone_tpu.pipelines.cli_mains", "stupid_backoff_main"),
}

_SHORT = {name.rsplit(".", 1)[-1]: v for name, v in REGISTRY.items()}


def _pop_multihost_flags(argv):
    """Launcher-level multi-host flags (≈ the reference launcher's
    cluster args living outside the app's own scopt flags):

        python -m keystone_tpu --coordinator host:port --num-processes 4 \\
            --process-id $I pipelines.images.cifar.RandomPatchCifar ...
    """
    names = ("--coordinator", "--num-processes", "--process-id")
    opts, rest = {}, []
    it = iter(argv)
    for a in it:
        flag, eq, inline = a.partition("=")
        if flag in names:
            val = inline if eq else next(it, None)
            if not val:
                raise SystemExit(f"{flag} requires a value")
            opts[flag.lstrip("-").replace("-", "_")] = val
        else:
            rest.append(a)
    if opts:
        if "coordinator" not in opts:
            raise SystemExit(
                "--num-processes/--process-id require --coordinator "
                "(single-host runs need none of these flags)"
            )
        from .parallel import init_multihost

        init_multihost(
            coordinator_address=opts["coordinator"],
            num_processes=(
                int(opts["num_processes"]) if "num_processes" in opts else None
            ),
            process_id=int(opts["process_id"]) if "process_id" in opts else None,
        )
    return rest


def _normalize_flags(argv):
    """Accept the reference apps' scopt camelCase flags verbatim:
    `--numFFTs 4 --blockSize 2048` → `--num-ffts 4 --block-size 2048`
    (the reference CLI contract, e.g. MnistRandomFFT.scala:80-97)."""
    import re

    out = []
    for a in argv:
        if a.startswith("--"):
            flag, eq, val = a.partition("=")
            flag = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "-", flag).lower()
            a = flag + eq + val
        out.append(a)
    return out


def _pop_backend_flag(argv):
    """`--backend tpu|cpu` anywhere on the command line (the north-star
    launcher contract: run-pipeline.sh --backend=tpu) → KEYSTONE_BACKEND."""
    import os

    out = []
    it = iter(argv)
    for a in it:
        flag, eq, inline = a.partition("=")
        if flag == "--backend":
            val = inline if eq else next(it, None)
            if not val:
                raise SystemExit("--backend requires a value (tpu|cpu)")
            os.environ["KEYSTONE_BACKEND"] = val
        else:
            out.append(a)
    return out


def _apply_backend_env():
    """Honor KEYSTONE_BACKEND/KEYSTONE_CPU_DEVICES programmatically.

    jax.config updates are applied before any backend initializes, which
    keeps working even in environments where plugin site hooks consume
    or interfere with JAX_PLATFORMS/XLA_FLAGS env vars (the conftest
    uses the same pattern for the test mesh)."""
    import os

    if os.environ.get("KEYSTONE_BACKEND") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        n = os.environ.get("KEYSTONE_CPU_DEVICES")
        if n:
            jax.config.update("jax_num_cpu_devices", int(n))


def main(argv=None):
    argv = _pop_backend_flag(list(sys.argv[1:] if argv is None else argv))
    _apply_backend_env()
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("Available pipelines:")
        for name in sorted(REGISTRY):
            print(f"  {name}")
        return 0
    argv = _pop_multihost_flags(argv)
    name, rest = argv[0], _normalize_flags(argv[1:])
    entry = REGISTRY.get(name) or _SHORT.get(name)
    if entry is None:
        print(f"unknown pipeline {name!r}; run with --help to list", file=sys.stderr)
        return 2
    module, fn_name = entry
    fn = getattr(importlib.import_module(module), fn_name)
    fn(rest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
