"""Certificate-conformance watchdog: the live half of the KP9xx story.

`analysis.serving.serving_pass` proves, statically, that every ladder
shape's apply latency fits under a certified bound (KP903). Until now
that proof was only ever *audited* after the fact
(`reconcile_serving`). The watchdog closes the loop at runtime: arm it
with a fitted pipeline's certificate record (`ServingCertificate
.as_record()` — the exact ``keystone.serving`` trace payload) and every
live apply's wall-clock is checked against its padded-shape bound the
moment the request finishes. A breach:

  1. increments ``serving.slo_breaches`` (and every check increments
     ``serving.conformance_checks``);
  2. dumps the flight recorder (`flight.flight_snapshot`, tagged
     ``breach``) so the ring's context around the slow request is
     preserved;
  3. emits a ledger ``kind="conformance"`` record joining the static
     bound, the observed latency, and the dump artifact — renderable by
     ``--ledger`` and joined by `reconcile.reconcile_decisions` like
     any optimizer decision.

`request_scope` is the per-apply instrumentation the executor path
wraps around `FittedPipeline.apply`: it tags the request with its
padded ladder shape (`utils.batching._pad_target`, the same arithmetic
the dispatcher pads by, so live shapes join the certificate's shape
table exactly), feeds the streaming latency sketches, maintains the
``serving.inflight`` gauge, and runs the conformance check. With
``KEYSTONE_LIVE_TELEMETRY=0`` it is a no-op context manager — the
kill-switch bit-for-bit contract.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional

from .metrics import counter, gauge, histogram


def _live_enabled() -> bool:
    from ..workflow.env import execution_config

    try:
        return bool(execution_config().live_telemetry)
    except Exception:
        return True


class ConformanceWatchdog:
    """Per-shape bound table + breach policy for ONE armed pipeline.

    ``bounds`` maps padded ladder batch → certified seconds (the
    certificate's per-shape ``predicted_seconds``, i.e. the KP903
    bound). A live shape with no exact entry conservatively borrows the
    bound of the smallest certified batch that covers it (bounds are
    monotone in batch); shapes larger than every certified batch are
    out of envelope — counted (``serving.uncovered_shapes``), never
    breached, because the certificate makes no claim about them."""

    def __init__(self, pipeline: str, bounds: Dict[int, float],
                 slo_seconds: Optional[float] = None,
                 certified: bool = False):
        self.pipeline = str(pipeline)
        self.bounds = {int(k): float(v) for k, v in bounds.items()}
        self.slo_seconds = slo_seconds
        self.certified = bool(certified)
        self.checked = 0
        self.breaches = 0
        self._lock = threading.Lock()

    @classmethod
    def from_certificate(cls, record: Dict[str, Any],
                         pipeline: str = "pipeline",
                         ) -> Optional["ConformanceWatchdog"]:
        """Build from a `ServingCertificate.as_record()` payload (the
        ``keystone.serving`` trace metadata / `certify_example` report
        form). None when the record carries no priced shapes."""
        shapes = (record or {}).get("shapes") or []
        bounds = {}
        for s in shapes:
            try:
                bounds[int(s["batch"])] = float(s["predicted_seconds"])
            except (KeyError, TypeError, ValueError):
                continue
        if not bounds:
            return None
        return cls(pipeline, bounds,
                   slo_seconds=record.get("slo_seconds"),
                   certified=bool(record.get("certified")))

    def bound_for(self, chunk_shape: int) -> Optional[float]:
        chunk_shape = int(chunk_shape)
        b = self.bounds.get(chunk_shape)
        if b is not None:
            return b
        covering = [n for n in self.bounds if n >= chunk_shape]
        if covering:
            return self.bounds[min(covering)]
        return None

    def check(self, chunk_shape: int, seconds: float,
              batch: Optional[int] = None) -> bool:
        """Audit one finished apply; returns True when it breached.
        Breach handling (dump + ledger record) happens inline — it is
        cheap (ring copy + one JSON write) and only on the slow path."""
        bound = self.bound_for(chunk_shape)
        with self._lock:
            self.checked += 1
        counter("serving.conformance_checks").inc()
        if bound is None:
            counter("serving.uncovered_shapes").inc()
            return False
        if seconds <= bound:
            return False
        with self._lock:
            self.breaches += 1
        counter("serving.slo_breaches").inc()
        from .flight import flight_snapshot

        dump = flight_snapshot(tag="breach")
        from .ledger import record_decision

        record_decision(
            kind="conformance",
            rule="ConformanceWatchdog",
            vertices=[],
            labels=[self.pipeline, f"shape={int(chunk_shape)}"],
            chosen={
                "entry": "breach",
                "observed_seconds": float(seconds),
                "chunk_shape": int(chunk_shape),
                "batch": int(batch) if batch is not None else None,
                "flight_dump": dump,
            },
            alternatives=[{
                "entry": "within certified bound",
                "cost_seconds": float(bound),
            }],
            predicted={
                "bound_seconds": float(bound),
                "slo_seconds": self.slo_seconds,
                "certified": self.certified,
            },
            enforced=False,  # the watchdog observes; it does not gate
        )
        return True

    def describe(self) -> Dict[str, Any]:
        """JSON-ready digest for `streaming.health` / the --live CLI."""
        with self._lock:
            checked, breaches = self.checked, self.breaches
        return {
            "armed": True,
            "pipeline": self.pipeline,
            "certified": self.certified,
            "slo_seconds": self.slo_seconds,
            "shapes": {str(n): b for n, b in sorted(self.bounds.items())},
            "checked": checked,
            "breaches": breaches,
        }


# ----------------------------------------------------------- arm / disarm

_active_watchdog: Optional[ConformanceWatchdog] = None
_arm_lock = threading.Lock()


def active_watchdog() -> Optional[ConformanceWatchdog]:
    return _active_watchdog


def arm_watchdog(record: Dict[str, Any],
                 pipeline: str = "pipeline") -> Optional[ConformanceWatchdog]:
    """Arm (or re-arm) the process watchdog from a certificate record.
    Returns the watchdog, or None when the record has no shapes or the
    live telemetry plane is disabled."""
    global _active_watchdog
    if not _live_enabled():
        return None
    wd = ConformanceWatchdog.from_certificate(record, pipeline=pipeline)
    if wd is None:
        return None
    with _arm_lock:
        _active_watchdog = wd
    from .flight import ensure_flight

    ensure_flight()  # breach dumps need the ring recording already
    return wd


def disarm_watchdog() -> None:
    global _active_watchdog
    with _arm_lock:
        _active_watchdog = None


def maybe_arm_from_certificate(record: Optional[Dict[str, Any]],
                               pipeline: str = "pipeline") -> None:
    """Executor hook: when a run embeds its serving certificate
    (``KEYSTONE_SLO_MS`` armed → `_record_static_estimates` computes
    ``keystone.serving``), arm the watchdog against it so subsequent
    applies in the same process are conformance-checked. Never raises;
    an already-armed watchdog for the same pipeline is refreshed."""
    if not record:
        return
    try:
        arm_watchdog(record, pipeline=pipeline)
    except Exception:
        pass  # telemetry must never take down the measured run


# ------------------------------------------------------ per-request scope


def _padded_shape(batch: int) -> int:
    """The padded leading dim this request dispatches under — the SAME
    arithmetic the chunk planner uses (`_pad_target` with the resolved
    chunk rows), so live observations key into the certificate's ladder
    shape table exactly."""
    from ..analysis.memory import resolve_chunk_rows
    from ..utils.batching import _pad_target

    chunk = resolve_chunk_rows(None)
    return int(_pad_target(int(batch), chunk, int(batch)))


@contextmanager
def request_scope(batch: int, pipeline: str = "pipeline"):
    """Instrument one live apply request.

    Emits a ``cat="request"`` span (into the active tracer when one is
    scoped, else directly into the flight ring), maintains
    ``serving.requests`` / ``serving.inflight`` / the
    ``serving.apply_seconds`` histogram, feeds the per-shape streaming
    sketch, and runs the conformance check on exit. Exceptions
    propagate (marked on the span) — instrumentation never swallows
    the pipeline's own failure. No-op when
    ``KEYSTONE_LIVE_TELEMETRY=0``."""
    if not _live_enabled():
        yield None
        return
    batch = int(batch)
    chunk_shape = _padded_shape(batch)
    counter("serving.requests").inc()
    inflight = gauge("serving.inflight")
    inflight.add(1)
    from .flight import ensure_flight
    from .spans import current_tracer

    tracer = current_tracer()
    sink = tracer if tracer is not None else ensure_flight()
    t0 = sink.now() if sink is not None else 0.0
    error = False
    try:
        yield chunk_shape
    except BaseException:
        error = True
        raise
    finally:
        inflight.add(-1)
        if sink is not None:
            dur = sink.now() - t0
            sink.record_complete(
                "apply_request", "request", t0, dur, error=error,
                batch=batch, chunk_shape=chunk_shape, pipeline=pipeline)
        else:  # live plane on but flight creation failed: still time it
            dur = 0.0
        if not error and dur > 0.0:
            histogram("serving.apply_seconds").observe(dur)
            from .streaming import observe_apply

            observe_apply(pipeline, chunk_shape, dur)
            wd = active_watchdog()
            if wd is not None:
                try:
                    wd.check(chunk_shape, dur, batch=batch)
                except Exception:
                    pass  # a watchdog bug must never break serving
