"""Process-wide metrics registry: counters, gauges, histograms.

One trustworthy measurement substrate (KeystoneML's profile-guided
optimizer premise, PAPER.md §5): the executor, the overlap engine, and
the solver loops all report into the same named-metric namespace, so the
auto-cacher, user-facing profiler reports, and trace exports can never
disagree about what was observed.

Metric names are dotted and stable — they are part of the telemetry
contract documented in OBSERVABILITY.md:

  executor.node_forces / node_failures / memo_hits /
  executor.prefix_saves / prefix_reuse      (counters)
  executor.live_bytes                       (gauge; .max = observed peak)
  prefetch.queue_depth                      (gauge)
  prefetch.producer_stall_s / consumer_wait_s   (histograms, seconds)
  overlap.inflight_results / resident_chunks    (gauges)
  overlap.bytes_pulled / chunks_dispatched      (counters)
  solver.steps                              (counter)
  dispatch.programs_executed                (counter; one per jitted
                                             call boundary — see
                                             instrument.record_dispatch)
  dispatch.scheduler_runs / scheduled_tasks (counters; concurrent DAG
                                             scheduler activity)
  dispatch.programs_compiled                (counter; one per COLD XLA
                                             backend compile — see
                                             compile_events)
  dispatch.compile_cache_hits               (counter; persistent-cache
                                             retrievals, i.e. warm
                                             compiles)
  compile.cold_secs / warm_secs             (histograms, seconds of
                                             compile / retrieval wall)

Thread-safety: one process lock guards mutation — producer threads
(overlap engine) and the main thread share these. Updates are
chunk/force granular (hundreds per run, not millions), so contention is
irrelevant next to the work being measured.
"""

from __future__ import annotations

import random
import threading
import zlib
from typing import Dict, Optional

_LOCK = threading.Lock()


class Counter:
    """Monotonic accumulator."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with _LOCK:
            self.value += n

    def snapshot(self) -> Dict[str, float]:
        return {"value": self.value}


class Gauge:
    """Point-in-time level with a high-water mark. ``set``/``add`` also
    emit a counter sample into the active tracer (when one is installed)
    so the level is a time series in the Chrome trace, not just a max."""

    __slots__ = ("name", "value", "max")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self.max = 0.0

    def set(self, v: float) -> None:
        with _LOCK:
            self.value = v
            if v > self.max:
                self.max = v
        from .spans import current_tracer

        t = current_tracer()
        if t is not None:
            t.counter_sample(self.name, v)

    def add(self, d: float) -> float:
        with _LOCK:
            self.value += d
            v = self.value
            if v > self.max:
                self.max = v
        from .spans import current_tracer

        t = current_tracer()
        if t is not None:
            t.counter_sample(self.name, v)
        return v

    def snapshot(self) -> Dict[str, float]:
        return {"value": self.value, "max": self.max}


#: Histogram reservoir capacity. 512 float samples ≈ 4 KiB per metric —
#: a long-lived serving process holds a fixed few KiB per histogram no
#: matter how many observations arrive, yet p50/p99 stay readable
#: (standard error of a reservoir quantile at n=512 is ~2% at p50).
RESERVOIR_SIZE = 512


class Histogram:
    """Streaming count/sum/min/max plus a FIXED-SIZE uniform reservoir
    (Vitter's Algorithm R) so percentiles are readable without retaining
    samples unboundedly. The exact aggregates (count/total/min/max) are
    what reports and tests assert on; `percentile` answers from the
    reservoir — an unbiased uniform sample of everything observed —
    while memory stays O(RESERVOIR_SIZE) forever."""

    __slots__ = ("name", "count", "total", "min", "max",
                 "_reservoir", "_rng")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max = 0.0
        self._reservoir: list = []
        # deterministic per-name seed: reproducible snapshots in tests
        # without coupling separate histograms' sampling decisions
        self._rng = random.Random(zlib.crc32(name.encode()))

    def observe(self, v: float) -> None:
        with _LOCK:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if len(self._reservoir) < RESERVOIR_SIZE:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_SIZE:
                    self._reservoir[j] = v

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Reservoir-estimated q-quantile (q in [0, 1]); 0.0 when empty.
        Linear interpolation between order statistics."""
        with _LOCK:
            data = sorted(self._reservoir)
        if not data:
            return 0.0
        if len(data) == 1:
            return data[0]
        pos = max(0.0, min(1.0, q)) * (len(data) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(data) - 1)
        frac = pos - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "total": self.total,
            "min": self.min if self.min is not None else 0.0,
            "max": self.max,
            "mean": self.mean,
            "p50": self.percentile(0.50),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    """Name→metric table. ``counter``/``gauge``/``histogram`` create on
    first use; a name is one kind forever (a config bug, not a race —
    raise loudly)."""

    def __init__(self):
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def _get(self, table: Dict, name: str, cls):
        m = table.get(name)
        if m is None:
            for other in (self.counters, self.gauges, self.histograms):
                if other is not table and name in other:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(other[name]).__name__}"
                    )
            with _LOCK:
                m = table.setdefault(name, cls(name))
        return m

    def counter(self, name: str) -> Counter:
        return self._get(self.counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self.gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self.histograms, name, Histogram)

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """JSON-ready view: {counters: {...}, gauges: {...},
        histograms: {...}} — embedded verbatim in trace exports."""
        return {
            "counters": {k: v.snapshot() for k, v in sorted(self.counters.items())},
            "gauges": {k: v.snapshot() for k, v in sorted(self.gauges.items())},
            "histograms": {
                k: v.snapshot() for k, v in sorted(self.histograms.items())
            },
        }

    def reset(self) -> None:
        """Drop all metric state (tests; a fresh bench tier)."""
        with _LOCK:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


class MetricsDelta:
    """Counter deltas over one measured window, against the
    process-cumulative registry.

    Every per-example measurement used to hand-roll
    ``before = c.value; ...; c.value - before`` against the cumulative
    counters; this is that idiom, once::

        with metrics_delta() as d:
            predictor(test).get()
        programs = d.counter("dispatch.programs_executed")

    ``counter(name)`` is the window's increment (0.0 for a counter that
    did not exist or did not move); ``counters()`` is every nonzero
    delta. Gauges and histograms are cumulative-by-design (high-water
    marks, streaming totals) and are deliberately not delta'd here —
    read their snapshots directly. Reentrant and thread-compatible: the
    baseline is captured once at ``__enter__`` and never mutated."""

    def __init__(self, reg: Optional[MetricsRegistry] = None):
        self._registry = reg or _registry
        self._base: Dict[str, float] = {}

    def __enter__(self) -> "MetricsDelta":
        with _LOCK:
            self._base = {
                name: c.value for name, c in self._registry.counters.items()
            }
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def counter(self, name: str) -> float:
        c = self._registry.counters.get(name)
        current = c.value if c is not None else 0.0
        return current - self._base.get(name, 0.0)

    def counters(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with _LOCK:
            for name, c in self._registry.counters.items():
                d = c.value - self._base.get(name, 0.0)
                if d:
                    out[name] = d
        return out


def metrics_delta(reg: Optional[MetricsRegistry] = None) -> MetricsDelta:
    """Snapshot-delta context over the process-cumulative counter
    registry (see `MetricsDelta`)."""
    return MetricsDelta(reg)


def counter(name: str) -> Counter:
    return _registry.counter(name)


def gauge(name: str) -> Gauge:
    return _registry.gauge(name)


def histogram(name: str) -> Histogram:
    return _registry.histogram(name)
