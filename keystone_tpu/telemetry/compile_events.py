"""Compile accounting — programs *compiled* as a first-class metric.

PR 4 made programs *executed* per run a measured, minimized quantity;
this module does the same for programs compiled. Every XLA backend
compile the process performs is observed through `jax.monitoring`'s
event stream (no wrapping of jit call sites — the events fire inside
jax's own compile path, so nothing can dispatch a compile without being
counted):

  dispatch.programs_compiled   (counter) — COLD compiles: real XLA
                               backend work. THE quantity the
                               compile-bounded execution work minimizes;
                               a warm process/run holds this at 0.
  dispatch.compile_cache_hits  (counter) — persistent-cache retrievals
                               (`jax_compilation_cache_dir`, wired via
                               `ExecutionConfig.compile_cache_dir`): the
                               executable was deserialized, not rebuilt.
  compile.cold_secs            (histogram) — cold backend-compile wall
                               time.
  compile.warm_secs            (histogram) — warm retrieval wall time
                               (typically ~ms against multi-second
                               compiles — the win the persistent cache
                               and AOT warmup buy).

With a tracer active every compile additionally records a closed
``cat="compile"`` span (``cold``/``warm`` in args), so traces show
exactly WHERE compile time lands — including the AOT warmup pool's
background compiles, which appear on their own thread lane.

Event pairing: jax records ``/jax/compilation_cache/cache_hits`` (and a
retrieval-time duration) *before* the enclosing
``/jax/core/compile/backend_compile_duration`` event of the same
compile, on the same thread. A thread-local flag set by the hit event
and consumed by the next backend-compile event classifies that compile
as warm; compiles with no intervening hit are cold. Listener
registration is process-global and permanent (jax.monitoring has no
per-listener deregistration), installed once on first telemetry import.
"""

from __future__ import annotations

import threading

from .metrics import counter, histogram
from .spans import current_tracer

#: duration-event suffix jax records around every backend compile
#: (cache hit or miss) — jax 0.4.x name: /jax/core/compile/...
_BACKEND_COMPILE = "backend_compile_duration"
#: event recorded on a persistent-compilation-cache retrieval
_CACHE_HIT = "/jax/compilation_cache/cache_hits"

_local = threading.local()
_installed = False
_install_lock = threading.Lock()


def _on_event(event: str, **kwargs) -> None:
    if event == _CACHE_HIT:
        _local.pending_hit = True
        counter("dispatch.compile_cache_hits").inc()


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if not event.endswith(_BACKEND_COMPILE):
        return
    warm = getattr(_local, "pending_hit", False)
    _local.pending_hit = False
    if warm:
        histogram("compile.warm_secs").observe(duration)
    else:
        counter("dispatch.programs_compiled").inc()
        from .instrument import process_dim

        dim = process_dim()
        if dim is not None:
            # multi-host: every process compiles its own executables, so
            # pod-level compile accounting carries a per-process axis
            counter(f"dispatch.programs_compiled.{dim}").inc()
        histogram("compile.cold_secs").observe(duration)
    tracer = current_tracer()
    if tracer is not None:
        now = tracer.now()
        tracer.record_complete(
            "xla_compile", "compile", max(0.0, now - duration), duration,
            cold=not warm, seconds=round(duration, 6))


def install_compile_listeners() -> bool:
    """Register the monitoring listeners (idempotent). Returns whether
    the hooks are live — False only when jax.monitoring is absent, in
    which case compile counters simply stay at zero."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        # pre-register the compile metrics so they appear in every
        # snapshot/trace from the moment the hooks are live — a fully
        # warm run's "0 cold compiles" is a headline number, and it must
        # be distinguishable from a pre-accounting trace (where the
        # counters are absent entirely)
        counter("dispatch.programs_compiled")
        counter("dispatch.compile_cache_hits")
        histogram("compile.cold_secs")
        histogram("compile.warm_secs")
        _installed = True
        return True


def compiles_snapshot() -> dict:
    """Point-in-time compile accounting (the compile bench's delta
    primitive): cold compiles, cache hits, and their wall-clock totals."""
    cold = histogram("compile.cold_secs").snapshot()
    warm = histogram("compile.warm_secs").snapshot()
    return {
        "programs_compiled": int(
            counter("dispatch.programs_compiled").value),
        "compile_cache_hits": int(
            counter("dispatch.compile_cache_hits").value),
        "cold_compile_secs": round(cold["total"], 4),
        "warm_retrieval_secs": round(warm["total"], 4),
    }
