"""Trace summary + decision ledger CLI.

    python -m keystone_tpu.telemetry run.json [--top N] [--json]
    python -m keystone_tpu.telemetry --ledger <run> [--json]
    python -m keystone_tpu.telemetry --ledger <run> --emit-calibration <path>
    python -m keystone_tpu.telemetry --diff <run_a> <run_b> [--json]
    python -m keystone_tpu.telemetry --flight <dump> [--top N] [--json]
    python -m keystone_tpu.telemetry --live [--json]

The trace form prints the span digest (top nodes by self-time, solver
iteration and stream-chunk totals), overlap queue-stall totals, bytes
moved, and — when the trace carries the static analyzer's estimates —
the static-vs-observed memory reconciliation table that calibrates the
KP2xx model.

``--ledger`` renders a run's decision ledger (a ``KEYSTONE_LEDGER``
JSONL file or a trace whose metadata embeds the decisions): one row per
optimizer decision — chosen entry, best-priced runner-up, predicted
cost — joined, when the run's trace is reachable, with the observed
values and residuals (`analysis.reconcile.reconcile_decisions`) plus
the cost-model drift report (`cost_model_drift`).

``--emit-calibration`` (with ``--ledger``) closes the
trace-bytes-in/plan-out loop: the run's cost-model drift report is
persisted as a ``tpu_calibration.json``-schema file
(`reconcile.drift_cost_weights` → `calibrate.write_calibration`), and
pointing ``KEYSTONE_COST_CALIBRATION`` at it makes
`calibrate.machine_rates()` — hence every roofline classification and
every unified-planner menu price — prefer the trace-implied rates
whenever the recorded platform matches the live backend.

``--flight`` renders a flight-recorder dump (`flight.flight_snapshot`
/ SIGUSR2 / a watchdog breach artifact): the ring-window header
(capacity, spans held, evictions, in-flight-at-dump count) followed by
the ordinary trace digest — a dump IS a Chrome trace, so every other
consumer (``--ledger``, reconcile, ``perf_table.py --trace``) accepts
it unchanged.

``--live`` renders this process's live-health view
(`streaming.health`): per-(pipeline, padded-shape) apply-latency
percentiles from the streaming sketches, throughput, in-flight depth,
conformance check/breach counters, and the armed watchdog's
certificate digest. (Meaningful in-process — e.g. from a serving
wrapper's debug hook; a fresh CLI process reports an empty table.)

``--diff`` is run-over-run regression detection between two runs'
ledgers: config kill-switch flips are named by env var (an injected
``KEYSTONE_MEGAFUSION=0`` reads as exactly that), removed/added
decisions, prediction drift, and observed regressions from the two
reconciliations. Exit code 1 when any regression is reported — the
lint-gate contract (a run diffed against itself exits 0).

See OBSERVABILITY.md; rule catalog in ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import sys

from .export import aggregate_spans, load_trace, summarize


def _read_run(path: str):
    from .ledger import read_ledger

    try:
        return read_ledger(path)
    except (OSError, ValueError) as e:
        print(f"error: {path}: {e}", file=sys.stderr)
        return None


def _reconcile(run):
    if not run.get("trace"):
        return None
    try:
        from ..analysis.reconcile import reconcile_decisions

        return reconcile_decisions(run)
    except Exception:
        return None


def _emit_calibration(run, out_path: str, ledger_path: str) -> int:
    """Persist the run's drift-implied `CostWeights` in the
    ``tpu_calibration.json`` schema (the `machine_rates` round-trip)."""
    if not run.get("trace"):
        print("error: --emit-calibration needs a run whose trace "
              "artifact is reachable (the drift report is computed "
              "from observed span timings)", file=sys.stderr)
        return 2
    from ..analysis.reconcile import drift_cost_weights
    from ..nodes.learning.calibrate import write_calibration

    weights = drift_cost_weights(run["trace"])
    provenance = {"source": "drift_cost_weights", "ledger": ledger_path}
    # the weights are implied by the TRACED run's measurements: its
    # recorded platform owns the provenance — emitting from a
    # different host must not relabel TPU-implied weights as CPU ones
    run_platform = (run.get("header") or {}).get("platform")
    assumed = ""
    if run_platform:
        provenance["platform"] = run_platform
    else:
        assumed = (" [platform assumed from THIS host — the run's "
                   "ledger predates the header platform field]")
    payload = write_calibration(out_path, weights, provenance=provenance)
    print(f"wrote {out_path}: cpu_weight={payload['cpu_weight']:.3e} "
          f"mem_weight={payload['mem_weight']:.3e} "
          f"(platform={payload['provenance'].get('platform')}{assumed}); "
          "point KEYSTONE_COST_CALIBRATION at it to recalibrate "
          "machine_rates()")
    return 0


def _ledger_main(path: str, as_json: bool,
                 emit_calibration: str = None) -> int:
    from .ledger import render_ledger

    run = _read_run(path)
    if run is None:
        return 2
    if emit_calibration:
        return _emit_calibration(run, emit_calibration, path)
    rec = _reconcile(run)
    drift = None
    if run.get("trace"):
        try:
            from ..analysis.reconcile import cost_model_drift

            drift = cost_model_drift(run["trace"])
        except Exception:
            drift = None
    if as_json:
        json.dump({
            "header": run["header"],
            "decisions": run["decisions"],
            "reconciliation": rec,
            "cost_model_drift": drift,
        }, sys.stdout, indent=1, default=str)
        print()
        return 0
    print(render_ledger(run, reconciliation=rec))
    if rec is not None:
        from ..analysis.reconcile import format_decision_reconciliation

        print()
        print(format_decision_reconciliation(rec))
    if drift is not None:
        from ..analysis.reconcile import format_drift

        print()
        print(format_drift(drift))
    return 0


def _diff_main(path_a: str, path_b: str, as_json: bool) -> int:
    from .ledger import diff_runs, format_diff

    run_a = _read_run(path_a)
    run_b = _read_run(path_b)
    if run_a is None or run_b is None:
        return 2
    diff = diff_runs(run_a, run_b,
                     reconciliation_a=_reconcile(run_a),
                     reconciliation_b=_reconcile(run_b))
    if as_json:
        json.dump(diff, sys.stdout, indent=1, default=str)
        print()
    else:
        print(format_diff(diff))
    return 1 if diff["regressions"] else 0


def _flight_main(path: str, top: int, as_json: bool) -> int:
    try:
        trace = load_trace(path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    meta = trace.get("keystone", {}).get("flight") or {}
    incomplete = sum(
        1 for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("args", {}).get("incomplete"))
    if as_json:
        json.dump({
            "flight": meta,
            "incomplete_spans": incomplete,
            "metrics": trace.get("keystone", {}).get("metrics", {}),
            "spans": aggregate_spans(trace),
        }, sys.stdout, indent=1)
        print()
        return 0
    if meta:
        dropped = int(meta.get("dropped_spans", 0))
        print(f"flight dump: {int(meta.get('spans_held', 0))}/"
              f"{int(meta.get('capacity', 0))} span(s) in ring, "
              f"{dropped} evicted before dump, "
              f"{incomplete} in-flight at dump")
        print()
    print(summarize(trace, top=top))
    return 0


def _live_main(as_json: bool) -> int:
    from .streaming import format_health, health

    h = health()
    if as_json:
        json.dump(h, sys.stdout, indent=1, default=str)
        print()
    else:
        print(format_health(h))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu.telemetry",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("trace", nargs="?",
                   help="Chrome trace JSON written by trace_run / "
                        "KEYSTONE_TRACE")
    p.add_argument("--top", type=int, default=15,
                   help="rows per section (default 15)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable digest (perf_table.py input)")
    p.add_argument("--ledger", metavar="RUN",
                   help="render a run's decision ledger (JSONL file or "
                        "decision-carrying trace) with the "
                        "predicted-vs-observed reconciliation")
    p.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                   help="run-over-run regression detection between two "
                        "runs' ledgers (exit 1 on any regression)")
    p.add_argument("--flight", metavar="DUMP",
                   help="render a flight-recorder dump: ring-window "
                        "header (capacity / evictions / in-flight "
                        "spans) followed by the trace digest")
    p.add_argument("--live", action="store_true",
                   help="render this process's live health view "
                        "(streaming latency percentiles, throughput, "
                        "conformance counters, armed watchdog)")
    p.add_argument("--emit-calibration", metavar="PATH",
                   help="with --ledger: persist the run's drift-implied "
                        "cost weights as a tpu_calibration.json-schema "
                        "file; KEYSTONE_COST_CALIBRATION=<PATH> then "
                        "recalibrates machine_rates() when the platform "
                        "matches")
    args = p.parse_args(argv)
    if args.emit_calibration and not args.ledger:
        p.error("--emit-calibration requires --ledger")
    if args.diff:
        return _diff_main(args.diff[0], args.diff[1], args.as_json)
    if args.ledger:
        return _ledger_main(args.ledger, args.as_json,
                            emit_calibration=args.emit_calibration)
    if args.live:
        return _live_main(args.as_json)
    if args.flight:
        return _flight_main(args.flight, args.top, args.as_json)
    if not args.trace:
        p.error("a trace path, --ledger, --diff, --flight, or --live "
                "is required")
    try:
        trace = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        digest = {
            "nodes": aggregate_spans(trace, "node"),
            "steps": aggregate_spans(trace, "step"),
            "chunks": aggregate_spans(trace, "chunk"),
            "metrics": trace.get("keystone", {}).get("metrics", {}),
        }
        try:
            from ..analysis.reconcile import reconcile_trace

            digest["memory_reconciliation"] = reconcile_trace(trace)
        except Exception:
            pass
        json.dump(digest, sys.stdout, indent=1)
        print()
    else:
        print(summarize(trace, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
