"""Trace summary CLI.

    python -m keystone_tpu.telemetry run.json [--top N] [--json]

Prints the span digest (top nodes by self-time, solver iteration and
stream-chunk totals), overlap queue-stall totals, bytes moved, and —
when the trace carries the static analyzer's estimates — the
static-vs-observed memory reconciliation table that calibrates the
KP2xx model (see OBSERVABILITY.md; rule catalog in ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from .export import aggregate_spans, load_trace, summarize


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m keystone_tpu.telemetry",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("trace", help="Chrome trace JSON written by trace_run / "
                                 "KEYSTONE_TRACE")
    p.add_argument("--top", type=int, default=15,
                   help="rows per section (default 15)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable digest (perf_table.py input)")
    args = p.parse_args(argv)
    try:
        trace = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        digest = {
            "nodes": aggregate_spans(trace, "node"),
            "steps": aggregate_spans(trace, "step"),
            "chunks": aggregate_spans(trace, "chunk"),
            "metrics": trace.get("keystone", {}).get("metrics", {}),
        }
        try:
            from ..analysis.reconcile import reconcile_trace

            digest["memory_reconciliation"] = reconcile_trace(trace)
        except Exception:
            pass
        json.dump(digest, sys.stdout, indent=1)
        print()
    else:
        print(summarize(trace, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
