"""Hierarchical span tracer.

A `Tracer` collects closed `SpanRecord`s — named, categorized intervals
with parent attribution — from every layer of a run:

    pipeline run (trace_run)          cat="pipeline"
      optimizer phase                 cat="phase"
        node force (executor)         cat="node"
          stream chunk (batching)     cat="chunk"
          solver iteration            cat="step"

Nesting is structural, not declared: each thread keeps a span stack per
tracer, so a node force that pulls its dependency inside its own thunk
automatically becomes that dependency's parent, and the overlap engine's
producer thread gets its own root lane (its tid separates it in the
Chrome trace view).

Activation, cheapest-first:

  - no tracer installed → `span(...)` returns a shared no-op context
    manager; the hot path costs one global read;
  - ``with trace_run("out.json"):`` scopes a tracer and writes Chrome
    trace JSON on exit;
  - ``KEYSTONE_TRACE=out.json`` (or `ExecutionConfig.trace_path`)
    installs an ambient process tracer on first use and writes the file
    at interpreter exit — so ANY entry point (`python -m
    keystone_tpu.pipelines ...`, bench.py, pytest) produces a trace with
    zero code changes.

Timestamps use `time.perf_counter()` relative to the tracer's epoch
(KJ004 discipline); the wall-clock epoch is recorded once in metadata
for cross-run alignment.
"""

from __future__ import annotations

import atexit
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

_capabilities: Dict[str, Dict[str, Any]] = {}


def record_capability(name: str, available: bool, reason: str = "") -> None:
    """Record an environment capability probe outcome (e.g. a skipped
    test's reason). Exported in every trace's metadata so bench/trace
    artifacts carry which capabilities were absent for the run."""
    _capabilities[name] = {"available": bool(available), "reason": reason}


def capabilities() -> Dict[str, Dict[str, Any]]:
    return dict(_capabilities)


class SpanRecord:
    """One closed span. ``t0``/``dur`` are seconds relative to the
    tracer epoch; ``sid``/``parent`` link the hierarchy."""

    __slots__ = ("name", "cat", "t0", "dur", "tid", "sid", "parent",
                 "args", "error")

    def __init__(self, name: str, cat: str, t0: float, tid: int, sid: int,
                 parent: Optional[int], args: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.dur = 0.0
        self.tid = tid
        self.sid = sid
        self.parent = parent
        self.args = args
        self.error = False


class Tracer:
    """Span + counter-sample collector. Append-only lists mutated under
    the GIL (list.append is atomic); per-thread span stacks live in a
    `threading.local` so producer threads nest independently."""

    def __init__(self):
        self.epoch = time.perf_counter()
        self.wall_epoch = time.time()  # keystone: ignore[KJ004] — wall-clock anchor, not a duration
        self.spans: List[SpanRecord] = []
        self.counter_samples: List[tuple] = []  # (name, t, value, tid)
        self.metadata: Dict[str, Any] = {}
        self._ids = itertools.count(1)
        self._local = threading.local()
        # sid → still-open SpanRecord, so a dump/export racing an open
        # span can emit it as incomplete-but-parseable instead of
        # dropping it (dict add/pop are atomic under the GIL)
        self._open: Dict[int, SpanRecord] = {}

    # ------------------------------------------------------------ spans

    def _stack(self) -> List[SpanRecord]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def start(self, name: str, cat: str = "span", **args) -> SpanRecord:
        st = self._stack()
        rec = SpanRecord(
            name,
            cat,
            time.perf_counter() - self.epoch,
            threading.get_ident(),
            next(self._ids),
            st[-1].sid if st else None,
            args,
        )
        st.append(rec)
        self._open[rec.sid] = rec
        return rec

    def end(self, rec: SpanRecord, error: bool = False, **args) -> None:
        rec.dur = time.perf_counter() - self.epoch - rec.t0
        rec.error = error
        if args:
            rec.args.update(args)
        st = self._stack()
        # tolerate exception-path unwinding that skipped inner ends
        while st and st[-1] is not rec:
            st.pop()
        if st:
            st.pop()
        self._open.pop(rec.sid, None)
        self.spans.append(rec)
        if _TEES:
            _tee_span(self, rec)

    def record_complete(self, name: str, cat: str, t0: float, dur: float,
                        error: bool = False, **args) -> SpanRecord:
        """Append an already-closed span without touching the stack —
        for measurements whose lifetime does not nest cleanly (a
        streamed stage's drain interleaves with its consumer). Parent is
        whatever span is open on this thread right now. ``t0`` is
        seconds relative to this tracer's epoch."""
        st = self._stack()
        rec = SpanRecord(
            name, cat, t0, threading.get_ident(), next(self._ids),
            st[-1].sid if st else None, args,
        )
        rec.dur = dur
        rec.error = error
        self.spans.append(rec)
        if _TEES:
            _tee_span(self, rec)
        return rec

    def now(self) -> float:
        """Seconds since this tracer's epoch (for `record_complete`)."""
        return time.perf_counter() - self.epoch

    def open_spans(self) -> List[SpanRecord]:
        """Snapshot of the spans still open right now (dump/export use:
        each is emitted as an incomplete-but-parseable event). The list
        is a copy; the records themselves are live."""
        return list(self._open.values())

    def counter_sample(self, name: str, value: float) -> None:
        t = time.perf_counter() - self.epoch
        tid = threading.get_ident()
        self.counter_samples.append((name, t, value, tid))
        if _TEES:
            _tee_counter(self, name, t, value, tid)

    # ------------------------------------------------- live-set tracking

    def add_live_bytes(self, nbytes: float) -> None:
        """Per-run observed live-set accounting: node outputs are
        memoized for their executor's lifetime, so the running sum's
        high-water mark is THIS run's observed peak (the process-global
        `executor.live_bytes` gauge is cumulative across runs)."""
        live = self.metadata.get("observed_live_bytes", 0.0) + nbytes
        self.metadata["observed_live_bytes"] = live
        if live > self.metadata.get("observed_live_peak_bytes", 0.0):
            self.metadata["observed_live_peak_bytes"] = live


class _SpanCtx:
    """Context manager binding one span to one tracer. Exceptions close
    the span (marked ``error``) and propagate."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_rec")

    def __init__(self, tracer: Tracer, name: str, cat: str, args: Dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._rec = None

    def __enter__(self) -> SpanRecord:
        self._rec = self._tracer.start(self._name, self._cat, **self._args)
        return self._rec

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._tracer.end(self._rec, error=exc_type is not None)
        return False


class _NoopSpan:
    """Shared do-nothing context manager for the untraced hot path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def __bool__(self) -> bool:  # `if span_ctx:` idiom in instrumentation
        return False


_NOOP = _NoopSpan()

# ------------------------------------------------------------------ tees
#
# A tee is a passive sink (the flight recorder) that receives a copy of
# every CLOSED span and counter sample any tracer records — so the
# always-on ring stays populated even while a scoped `trace_run` tracer
# owns the active slot. The registry is an immutable tuple swapped
# whole-sale (read is one global load; the hot path pays a falsy check
# when no tee is installed). A tee that is itself a Tracer never
# receives its own records.

_TEES: tuple = ()


def add_tee(sink) -> None:
    """Register ``sink`` (needs ``tee_span(src, rec)`` and
    ``tee_counter(src, name, t, value, tid)``) to receive copies of all
    closed spans / counter samples from every tracer. Idempotent."""
    global _TEES
    if sink not in _TEES:
        _TEES = _TEES + (sink,)


def remove_tee(sink) -> None:
    global _TEES
    _TEES = tuple(s for s in _TEES if s is not sink)


def _tee_span(src: Tracer, rec: SpanRecord) -> None:
    for sink in _TEES:
        if sink is src:
            continue
        try:
            sink.tee_span(src, rec)
        except Exception:
            pass  # telemetry must never take down the measured run


def _tee_counter(src: Tracer, name: str, t: float, value: float,
                 tid: int) -> None:
    for sink in _TEES:
        if sink is src:
            continue
        try:
            sink.tee_counter(src, name, t, value, tid)
        except Exception:
            pass


# ---------------------------------------------------------------- active

_active: Optional[Tracer] = None
_ambient_checked = False


def _env_trace_path() -> Optional[str]:
    from ..workflow.env import execution_config

    return execution_config().trace_path


def _flush_ambient(path: str) -> None:
    global _active
    t = _active
    if t is not None:
        from .export import write_trace

        try:
            write_trace(t, path)
        except OSError:
            pass


def current_tracer() -> Optional[Tracer]:
    """The installed tracer, or None. On first call, honors
    ``KEYSTONE_TRACE``/`ExecutionConfig.trace_path` by installing an
    ambient tracer flushed at process exit."""
    global _active, _ambient_checked
    if _active is None and not _ambient_checked:
        _ambient_checked = True
        try:
            path = _env_trace_path()
        except Exception:
            path = None
        if path:
            _active = Tracer()
            atexit.register(_flush_ambient, path)
    return _active


def telemetry_active() -> bool:
    return current_tracer() is not None


def span(name: str, cat: str = "span", **args):
    """Open a span under the active tracer; a shared no-op when tracing
    is off (one global read, zero allocation)."""
    t = current_tracer()
    if t is None:
        return _NOOP
    return _SpanCtx(t, name, cat, args)


class trace_run:
    """Scope a tracer (and optionally write its Chrome trace on exit):

        with trace_run("run.json") as tracer:
            pipeline(data).get()

    ``path=None`` falls back to `ExecutionConfig.trace_path` (the
    ``KEYSTONE_TRACE`` env var); with neither, the trace is only held in
    memory on the yielded tracer. Nests: the previous tracer is restored
    on exit. Opens a root ``cat="pipeline"`` span so every run has a
    top-level interval."""

    def __init__(self, path: Optional[str] = None, name: str = "pipeline_run"):
        self._path = path
        self._name = name
        self._prev: Optional[Tracer] = None
        self._root = None
        self.tracer = Tracer()

    def __enter__(self) -> Tracer:
        global _active
        self._prev = _active
        _active = self.tracer
        self._root = self.tracer.start(self._name, cat="pipeline")
        return self.tracer

    def __exit__(self, exc_type, exc, tb) -> bool:
        global _active
        self.tracer.end(self._root, error=exc_type is not None)
        _active = self._prev
        path = self._path
        if path is None:
            try:
                path = _env_trace_path()
            except Exception:
                path = None
        if path:
            from .export import write_trace

            write_trace(self.tracer, path)
        return False


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Install ``tracer`` process-wide (None uninstalls). `trace_run` is
    the structured form; this exists for hosts that manage lifecycle
    themselves (bench child processes)."""
    global _active
    _active = tracer
