"""Always-on bounded flight recorder.

A serving process cannot afford an unbounded tracer, but when an SLO
breach fires the question is always "what was happening RIGHT BEFORE?".
The flight recorder answers it with black-box semantics: a fixed-
capacity ring of the most recent closed spans (plus counter samples),
O(1) memory forever, populated passively by the span tee
(`spans.add_tee`) so it rides along whether or not a scoped `trace_run`
tracer is active — and dumped as a fully valid Chrome trace on demand.

Three dump triggers:

  - ``flight_snapshot(path)`` — programmatic (tests, a serving wrapper's
    debug endpoint);
  - ``SIGUSR2`` — operator-initiated, installed by `ensure_flight` when
    running on the main thread (``kill -USR2 <pid>`` never interrupts
    the serving loop: the handler only copies the ring and writes JSON);
  - watchdog breach — `watchdog.ConformanceWatchdog` calls
    `flight_snapshot` automatically so every conformance ledger record
    names a dump artifact.

Dumps are ordinary Chrome traces: `reconcile`, ``--ledger``,
``perf_table.py --trace``, and the telemetry CLI consume them unchanged
(``--flight <dump>`` is a convenience alias for the summary view).
Spans that are still open when a dump fires are exported as
incomplete-but-parseable events (``args.incomplete``), including the
active scoped tracer's in-flight spans — a snapshot taken mid-
``megafused_program`` still shows that program on the timeline.

Ring capacities default to `DEFAULT_CAPACITY` spans / counter samples
(``KEYSTONE_FLIGHT_CAPACITY`` overrides); overflow evicts oldest-first
and counts evictions in the dump metadata (``flight.dropped_spans``),
so a dump is honest about its window. The whole plane is kill-switched
by ``KEYSTONE_LIVE_TELEMETRY=0`` (`ensure_flight` returns None and no
tee is installed — PR-17 behavior bit-for-bit).
"""

from __future__ import annotations

import itertools
import os
import signal
import tempfile
import threading
from typing import Any, Dict, List, Optional

from .spans import SpanRecord, Tracer, add_tee, current_tracer, remove_tee

#: default ring capacity (spans and counter samples each). 4096 spans
#: at ~200 B/record ≈ under 1 MiB resident — hours of serving context
#: at per-request span granularity.
DEFAULT_CAPACITY = 4096


class _Ring:
    """Fixed-capacity append ring. A lock (not a bare deque) because
    dumps iterate while worker threads append — `collections.deque`
    raises "mutated during iteration" under exactly that race.
    ``dropped`` counts evictions so dumps can report their window
    honestly."""

    __slots__ = ("_cap", "_buf", "_start", "_lock", "dropped")

    def __init__(self, capacity: int):
        self._cap = max(1, int(capacity))
        self._buf: List[Any] = []
        self._start = 0  # index of the oldest element (circular)
        self._lock = threading.Lock()
        self.dropped = 0

    def append(self, item: Any) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(item)
            else:
                self._buf[self._start] = item
                self._start = (self._start + 1) % self._cap
                self.dropped += 1

    def snapshot(self) -> List[Any]:
        """Oldest-first copy, safe against concurrent appends."""
        with self._lock:
            return self._buf[self._start:] + self._buf[:self._start]

    def __iter__(self):
        return iter(self.snapshot())

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf = []
            self._start = 0
            self.dropped = 0


class FlightRecorder(Tracer):
    """A `Tracer` whose span / counter stores are bounded rings, fed
    two ways: directly (the watchdog's request spans when no scoped
    tracer is active) and via the span tee (copies of every closed span
    any other tracer records). It is never installed as the ACTIVE
    tracer — `spans.span()`'s no-op fast path and `telemetry_active()`
    stay exactly as they were (the kill-switch bit-for-bit contract).

    Teed records keep their original span ids (hierarchy among them
    survives); the recorder's own ids start at 10**9 so the two spaces
    cannot collide. Timestamps are re-anchored to the recorder's epoch.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        super().__init__()
        self.capacity = max(1, int(capacity))
        self.spans = _Ring(self.capacity)  # type: ignore[assignment]
        self.counter_samples = _Ring(self.capacity)  # type: ignore[assignment]
        self._ids = itertools.count(10 ** 9)
        self.metadata["flight"] = {"capacity": self.capacity}

    # ------------------------------------------------------------- tee

    def tee_span(self, src: Tracer, rec: SpanRecord) -> None:
        offset = src.epoch - self.epoch
        cp = SpanRecord(rec.name, rec.cat, rec.t0 + offset, rec.tid,
                        rec.sid, rec.parent, dict(rec.args))
        cp.dur = rec.dur
        cp.error = rec.error
        self.spans.append(cp)

    def tee_counter(self, src: Tracer, name: str, t: float, value: float,
                    tid: int) -> None:
        self.counter_samples.append(
            (name, t + (src.epoch - self.epoch), value, tid))

    # ----------------------------------------------------------- dumps

    def open_spans(self) -> List[SpanRecord]:
        """The recorder's own in-flight spans PLUS the active scoped
        tracer's (re-anchored copies) — a dump racing an open
        ``megafused_program`` span still shows it."""
        out = super().open_spans()
        src = current_tracer()
        if src is not None and src is not self:
            offset = src.epoch - self.epoch
            for rec in src.open_spans():
                cp = SpanRecord(rec.name, rec.cat, rec.t0 + offset,
                                rec.tid, rec.sid, rec.parent,
                                dict(rec.args))
                out.append(cp)
        return out

    def dump(self, path: str) -> str:
        """Write the ring as a Chrome trace (atomic rename, same as any
        trace export). Ring metadata rides in ``keystone.flight``."""
        self.metadata["flight"] = {
            "capacity": self.capacity,
            "spans_held": len(self.spans),
            "dropped_spans": self.spans.dropped,
            "counter_samples_held": len(self.counter_samples),
            "dropped_counter_samples": self.counter_samples.dropped,
        }
        from .export import write_trace

        return write_trace(self, path)


# ---------------------------------------------------------- module state

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()
_signal_installed = False
_dump_seq = itertools.count(1)


def _env_capacity() -> int:
    try:
        return max(1, int(os.environ.get(
            "KEYSTONE_FLIGHT_CAPACITY", str(DEFAULT_CAPACITY))))
    except ValueError:
        return DEFAULT_CAPACITY


def _live_enabled() -> bool:
    from ..workflow.env import execution_config

    try:
        return bool(execution_config().live_telemetry)
    except Exception:
        return True


def ensure_flight() -> Optional[FlightRecorder]:
    """The process flight recorder, creating (and installing its tee +
    SIGUSR2 handler) on first call. None when the live telemetry plane
    is disabled (``KEYSTONE_LIVE_TELEMETRY=0``) — in that case nothing
    is installed and the process behaves exactly as before this module
    existed."""
    global _recorder
    if not _live_enabled():
        return None
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                rec = FlightRecorder(capacity=_env_capacity())
                add_tee(rec)
                _recorder = rec
                _install_signal_handler()
    return _recorder


def flight_recorder() -> Optional[FlightRecorder]:
    """The current recorder without creating one."""
    return _recorder


def reset_flight() -> None:
    """Tear down the process recorder (tests). The SIGUSR2 handler
    stays installed — it no-ops without a recorder."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            remove_tee(_recorder)
            _recorder = None


def _default_dump_path(tag: str = "") -> str:
    base = os.environ.get("KEYSTONE_FLIGHT_DIR") or tempfile.gettempdir()
    label = f"_{tag}" if tag else ""
    name = (f"keystone_flight_{os.getpid()}"
            f"_{next(_dump_seq)}{label}.json")
    return os.path.join(base, name)


def flight_snapshot(path: Optional[str] = None, tag: str = "") -> Optional[str]:
    """Dump the flight ring as a Chrome trace; returns the written path
    or None when the plane is disabled. ``path=None`` writes under
    ``KEYSTONE_FLIGHT_DIR`` (default: the system temp dir) with a
    pid-and-sequence-stamped name; ``tag`` labels the file (e.g.
    ``"breach"``)."""
    rec = ensure_flight()
    if rec is None:
        return None
    if path is None:
        path = _default_dump_path(tag)
    try:
        return rec.dump(path)
    except OSError:
        return None  # an unwritable dir must never break serving


def _on_sigusr2(signum, frame) -> None:
    rec = _recorder
    if rec is not None:
        try:
            rec.dump(_default_dump_path("sigusr2"))
        except Exception:
            pass  # a signal handler must never raise into the main loop


def _install_signal_handler() -> None:
    """SIGUSR2 → dump. Only from the main thread (CPython restriction),
    only once, and never on platforms without SIGUSR2 (Windows)."""
    global _signal_installed
    if _signal_installed or not hasattr(signal, "SIGUSR2"):
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
        _signal_installed = True
    except (ValueError, OSError):
        pass  # embedded interpreters may refuse; dumps stay programmatic


def flight_health() -> Dict[str, Any]:
    """Ring occupancy digest for `streaming.health` consumers."""
    rec = _recorder
    if rec is None:
        return {"armed": False}
    return {
        "armed": True,
        "capacity": rec.capacity,
        "spans_held": len(rec.spans),
        "dropped_spans": rec.spans.dropped,
    }
