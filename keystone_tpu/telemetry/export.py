"""Chrome trace-event JSON export and trace summarization.

The export is the Trace Event Format's object form — a ``traceEvents``
list of complete (``"ph": "X"``) events plus counter (``"ph": "C"``)
samples — loadable in ``chrome://tracing`` / Perfetto unchanged. Keystone
extras ride in a top-level ``"keystone"`` object Chrome ignores:
the metrics-registry snapshot, environment capability probes, and the
static analyzer's per-node memory estimates (what `analysis.reconcile`
diffs against the observed bytes).

Span hierarchy survives the export: every event's ``args`` carries
``span_id`` and (when nested) ``parent_id``, so summaries can compute
*self* time — a span's duration minus its direct children — which is the
per-node attribution the auto-cacher and PERF rounds care about.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from .metrics import registry
from .spans import Tracer, capabilities


def to_chrome_trace(tracer: Tracer) -> Dict[str, Any]:
    """Render ``tracer`` (+ the current metrics registry and capability
    probes) as a Chrome trace object."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "keystone_tpu"},
    }]
    now = tracer.now()
    closed = list(tracer.spans)  # snapshot: appends may race the export
    seen = {id(s) for s in closed}
    # In-flight spans export as complete events running to "now", marked
    # ``args.incomplete`` — a dump racing an open span (flight snapshot,
    # atexit flush mid-run) stays fully parseable instead of silently
    # dropping the span that was on the CPU when the dump fired.
    open_spans = [s for s in tracer.open_spans() if id(s) not in seen]
    for s, incomplete in ([(s, False) for s in closed]
                          + [(s, True) for s in open_spans]):
        args = dict(s.args)
        args["span_id"] = s.sid
        if s.parent is not None:
            args["parent_id"] = s.parent
        if s.error:
            args["error"] = True
        dur = s.dur
        if incomplete:
            args["incomplete"] = True
            dur = max(0.0, now - s.t0)
        events.append({
            "name": s.name,
            "cat": s.cat,
            "ph": "X",
            "ts": round(s.t0 * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": s.tid,
            "args": args,
        })
    for name, t, value, tid in list(tracer.counter_samples):
        events.append({
            "name": name,
            "ph": "C",
            "ts": round(t * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": {"value": value},
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "keystone": {
            "wall_epoch": tracer.wall_epoch,
            "metrics": registry().snapshot(),
            "capabilities": capabilities(),
            **tracer.metadata,
        },
    }


def write_trace(tracer: Tracer, path: str) -> str:
    trace = to_chrome_trace(tracer)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)  # atomic: a killed process never leaves half a trace
    return path


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        trace = json.load(f)
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError(f"{path} is not a Chrome trace object (no traceEvents)")
    return trace


# ------------------------------------------------------------- summaries


def _complete_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]


def self_times(trace: Dict[str, Any]) -> Dict[int, float]:
    """span_id → self-time µs (duration minus direct children)."""
    events = _complete_events(trace)
    child_dur: Dict[int, float] = {}
    for e in events:
        parent = e.get("args", {}).get("parent_id")
        if parent is not None:
            child_dur[parent] = child_dur.get(parent, 0.0) + e.get("dur", 0.0)
    out: Dict[int, float] = {}
    for e in events:
        sid = e.get("args", {}).get("span_id")
        if sid is not None:
            out[sid] = max(0.0, e.get("dur", 0.0) - child_dur.get(sid, 0.0))
    return out


def aggregate_spans(
    trace: Dict[str, Any], cat: Optional[str] = None
) -> Dict[str, Dict[str, float]]:
    """name → {count, total_s, self_s, bytes} over complete events,
    optionally restricted to one category."""
    selfs = self_times(trace)
    agg: Dict[str, Dict[str, float]] = {}
    for e in _complete_events(trace):
        if cat is not None and e.get("cat") != cat:
            continue
        a = agg.setdefault(e["name"], {
            "count": 0, "total_s": 0.0, "self_s": 0.0, "bytes": 0.0,
        })
        a["count"] += 1
        a["total_s"] += e.get("dur", 0.0) / 1e6
        sid = e.get("args", {}).get("span_id")
        a["self_s"] += selfs.get(sid, e.get("dur", 0.0)) / 1e6
        a["bytes"] += float(e.get("args", {}).get("out_bytes", 0.0) or 0.0)
    return agg


def _per_process_counts(counters: Dict[str, Any], base: str) -> str:
    """``" ; per-process: p0=12 p1=11"`` when the trace carries a
    multi-host breakdown of ``base`` (``<base>.p<i>`` counters — see
    `instrument.process_dim`), empty otherwise."""
    prefix = base + ".p"
    rows = [(name[len(base) + 1:], v.get("value", 0))
            for name, v in counters.items() if name.startswith(prefix)]
    if not rows:
        return ""

    def idx(dim: str):
        # numeric process order (p10 after p2, not lexicographic)
        try:
            return (0, int(dim[1:]))
        except ValueError:
            return (1, 0)

    rows.sort(key=lambda r: (idx(r[0]), r[0]))
    return " ; per-process: " + " ".join(
        f"{dim}={int(v)}" for dim, v in rows)


def dispatch_summary(trace: Dict[str, Any]) -> Optional[str]:
    """One-line per-run dispatch digest from a trace's metrics snapshot
    (programs executed, node forces, concurrent-scheduler activity —
    plus the per-process program counts when the trace came from a
    multi-host mesh), or None when the trace predates the dispatch
    counters. Shared by the trace CLI and `scripts/perf_table.py` so
    the two reports cannot drift."""
    counters = trace.get("keystone", {}).get("metrics", {}).get("counters", {})
    programs = counters.get("dispatch.programs_executed", {}).get("value")
    if not programs:
        return None
    sched = counters.get("dispatch.scheduler_runs", {}).get("value", 0)
    tasks = counters.get("dispatch.scheduled_tasks", {}).get("value", 0)
    forces = counters.get("executor.node_forces", {}).get("value", 0)
    line = (f"programs executed: {int(programs)} "
            f"(node forces {int(forces)}; concurrent scheduler ran "
            f"{int(sched)}x over {int(tasks)} task(s))")
    mega = counters.get("megafusion.programs", {}).get("value", 0)
    if mega:
        trips = counters.get("megafusion.scan_trips", {}).get("value", 0)
        line += (f"; megafused: {int(mega)} program(s), "
                 f"{int(trips)} in-program scan trip(s)")
    line += _per_process_counts(counters, "dispatch.programs_executed")
    return line


def dispatch_plan_breakdown(trace: Dict[str, Any]) -> List[str]:
    """Per-plan apply-run program rows from the trace metadata the
    dispatch bench embeds (``keystone.dispatch_plans``): one line per
    example, ``serial_unfused/legacy/optimized/megafused`` columns — the
    2→1 reduction readable straight off ``perf_table.py --trace`` / the
    telemetry CLI. Empty when the trace predates the breakdown."""
    plans_meta = trace.get("keystone", {}).get("dispatch_plans") or {}
    per_example = plans_meta.get("apply_run_programs") or {}
    plans = plans_meta.get("plans") or []
    lines = []
    for example in sorted(per_example):
        row = per_example[example]
        cols = " ".join(
            f"{p}={row[p]}" for p in (plans or sorted(row)) if p in row)
        lines.append(f"apply programs/run [{example}]: {cols}")
    return lines


def compile_summary(trace: Dict[str, Any]) -> Optional[str]:
    """One-line compile digest from a trace's metrics snapshot: cold
    compiles vs persistent-cache hits and their wall-clock totals, or
    None when the trace predates compile accounting. The accounting
    layer pre-registers its counters when the hooks install
    (`compile_events.install_compile_listeners`), so a fully warm run's
    "0 cold" reports instead of vanishing — that zero IS the headline
    number. Shared by the trace CLI and `scripts/perf_table.py`."""
    metrics = trace.get("keystone", {}).get("metrics", {})
    counters = metrics.get("counters", {})
    hists = metrics.get("histograms", {})
    if ("dispatch.programs_compiled" not in counters
            and "dispatch.compile_cache_hits" not in counters):
        return None  # pre-accounting trace
    cold_n = int(counters.get(
        "dispatch.programs_compiled", {}).get("value", 0))
    hits = int(counters.get(
        "dispatch.compile_cache_hits", {}).get("value", 0))
    cold_s = hists.get("compile.cold_secs", {}).get("total", 0.0)
    warm_s = hists.get("compile.warm_secs", {}).get("total", 0.0)
    return (f"programs compiled: {cold_n} cold ({cold_s:.3f}s) + "
            f"{hits} cache hit(s) ({warm_s:.3f}s retrieval)"
            + _per_process_counts(counters, "dispatch.programs_compiled"))


def decision_summary(trace: Dict[str, Any]) -> Optional[str]:
    """One-line digest of the optimizer decisions embedded in the trace
    metadata (`telemetry.ledger.record_decision` appends them under
    ``keystone.decisions``): per-kind counts plus the predicted savings
    totals, ending with the CLI pointer that renders the full
    per-decision table. None when the trace carries no decisions."""
    decisions = trace.get("keystone", {}).get("decisions") or []
    if not decisions:
        return None
    from .ledger import decision_key

    # dedup by (kind, labels): each optimizer invocation (fit graph,
    # apply graph, plan sweeps) re-records the same decision — counting
    # raw records would inflate the digest vs reconcile_decisions
    unique: Dict = {}
    for d in decisions:
        unique.setdefault(decision_key(d), d)
    kinds: Dict[str, int] = {}
    bytes_saved = 0
    for d in unique.values():
        k = str(d.get("kind"))
        kinds[k] = kinds.get(k, 0) + 1
        pred = d.get("predicted") or {}
        for key in ("boundary_bytes_saved", "policy_bytes_saved"):
            v = pred.get(key)
            if isinstance(v, (int, float)):
                bytes_saved += int(v)
    parts = [f"{kinds[k]} {k}" for k in sorted(kinds)]
    line = (f"optimizer decisions: {len(unique)} distinct "
            f"({', '.join(parts)}; {len(decisions)} record(s))")
    if bytes_saved:
        line += f", {_fmt_bytes(bytes_saved)} predicted saved"
    return line + " — `--ledger` renders the per-decision table"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return f"{n}B"


def summarize(trace: Dict[str, Any], top: int = 15) -> str:
    """Human-readable trace digest: top spans by self-time per category,
    prefetch stall totals, bytes moved, and (when the trace carries the
    analyzer's static estimates) the static-vs-observed memory
    reconciliation table."""
    lines: List[str] = []
    events = _complete_events(trace)
    n_events = len(events)
    n_open = sum(1 for e in events
                 if e.get("args", {}).get("incomplete"))
    open_note = f" ({n_open} in-flight at dump)" if n_open else ""
    lines.append(f"{n_events} span(s){open_note}")

    for cat, title in (("node", "top node forces by self-time"),
                       ("step", "solver iterations"),
                       ("chunk", "stream chunks")):
        agg = aggregate_spans(trace, cat)
        if not agg:
            continue
        lines.append(f"\n== {title} ==")
        lines.append(f"{'name':<44} {'self s':>9} {'total s':>9} "
                     f"{'count':>6} {'bytes':>12}")
        rows = sorted(agg.items(), key=lambda kv: -kv[1]["self_s"])
        for name, a in rows[:top]:
            lines.append(
                f"{name[:44]:<44} {a['self_s']:>9.4f} {a['total_s']:>9.4f} "
                f"{int(a['count']):>6} {_fmt_bytes(a['bytes']):>12}"
            )

    ks = trace.get("keystone", {})
    hist = ks.get("metrics", {}).get("histograms", {})
    stall = hist.get("prefetch.producer_stall_s")
    wait = hist.get("prefetch.consumer_wait_s")
    if stall or wait:
        lines.append("\n== overlap queue stalls ==")
        if stall:
            lines.append(
                f"producer stall: {stall['total']:.4f}s total over "
                f"{int(stall['count'])} put(s) (max {stall['max']:.4f}s)")
        if wait:
            lines.append(
                f"consumer wait:  {wait['total']:.4f}s total over "
                f"{int(wait['count'])} get(s) (max {wait['max']:.4f}s)")
    counters = ks.get("metrics", {}).get("counters", {})
    dispatch = dispatch_summary(trace)
    compiles = compile_summary(trace)
    breakdown = dispatch_plan_breakdown(trace)
    if dispatch or compiles or breakdown:
        lines.append("\n== dispatch ==")
        if dispatch:
            lines.append(dispatch)
        lines.extend(breakdown)
        if compiles:
            lines.append(compiles)
    decisions = decision_summary(trace)
    if decisions:
        lines.append("\n== decisions ==")
        lines.append(decisions)
    moved = counters.get("overlap.bytes_pulled", {}).get("value")
    if moved:
        lines.append(f"\nbytes pulled off device: {_fmt_bytes(moved)}")
    live = ks.get("observed_live_peak_bytes") or (
        ks.get("metrics", {}).get("gauges", {})
        .get("executor.live_bytes", {}).get("max"))
    if live:
        lines.append(f"observed peak live set: {_fmt_bytes(live)}")

    try:
        from ..analysis.reconcile import format_reconciliation, reconcile_trace

        rec = reconcile_trace(trace)
        if rec["rows"]:
            lines.append("")
            lines.append(format_reconciliation(rec))
    except Exception as e:  # a malformed trace must still summarize
        lines.append(f"\n(memory reconciliation unavailable: {e})")

    try:
        from ..analysis.reconcile import reconcile_roofline

        roof = reconcile_roofline(trace)
        if roof["stages_joined"]:
            lines.append(
                f"\n== roofline (predicted vs observed seconds) ==")
            lines.append(
                f"{roof['stages_joined']} stage(s) joined: predicted "
                f"{roof['predicted_seconds']:.4f}s, observed "
                f"{roof['observed_seconds']:.4f}s, flops residual "
                f"{roof['flops_residual_seconds']:+.4f}s")
    except Exception:
        pass  # advisory: partial traces summarize without it

    try:
        from ..analysis.reconcile import (
            format_serving_reconciliation,
            reconcile_serving,
        )

        serving = reconcile_serving(trace)
        if serving["rows"]:
            lines.append("")
            lines.append(format_serving_reconciliation(serving))
        elif trace.get("keystone", {}).get("serving"):
            cert = trace["keystone"]["serving"]
            verdict = "certified" if cert.get("certified") else "UNCERTIFIED"
            lines.append(
                f"\nserving certificate: {verdict}, "
                f"{len(cert.get('shapes', []))} ladder shape(s), SLO "
                f"{(cert.get('slo_seconds') or 0) * 1e3:.0f}ms (no "
                "observed percentiles — run scripts/serving_latency.py "
                "to join)")
    except Exception:
        pass  # advisory: partial traces summarize without it

    caps = ks.get("capabilities") or {}
    absent = {k: v for k, v in caps.items() if not v.get("available", True)}
    if absent:
        lines.append("\n== absent capabilities ==")
        for name, v in sorted(absent.items()):
            reason = v.get("reason", "")
            lines.append(f"{name}: {reason}" if reason else name)
    return "\n".join(lines)
