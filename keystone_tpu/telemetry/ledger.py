"""Decision ledger — every optimizer choice recorded, priced, and
auditable.

PRs 4–10 made the optimizer a decision-maker: fusion shape, whole-plan
megafusion, placement, and storage dtype are priced choices. Their
predictions (boundary bytes saved, programs eliminated, bytes halved)
were scattered across lint tables and CLI output and never checked
against what a run actually did. KeystoneML's thesis is that cost-based
whole-pipeline optimization is only as good as its measurements
(arXiv 1610.09451 §5); this module is the measurement's other half —
ONE auditable record per decision of what was decided, what the priced
alternatives were, and what it was predicted to cost, in the shared
cost units (`parallel.mesh.collective_cost` bytes/seconds,
`analysis.precision.policy_nbytes`, programs-per-run, cold compiles).

A decision record is a plain JSON dict:

    {"seq": n, "t": <wall>, "kind": "fusion" | "megafusion" |
     "placement" | "precision", "rule": "<Rule class>",
     "vertices": [...], "labels": [...],
     "chosen": {...},                    # the entry the rule enforced
     "alternatives": [{...}, ...],       # the priced menu it beat
     "predicted": {<metric>: value},     # shared cost units
     "enforced": true}

Destinations, cheapest-first:

  - an in-memory session list is ALWAYS appended (decisions are
    per-optimize rare, so this costs nothing) — `session_mark()` /
    `session_since()` let the dispatch bench and tests audit the
    decisions of one measured window without any file I/O;
  - with a tracer active, records are embedded in the trace metadata
    (``keystone.decisions`` + a ``keystone.ledger_run`` header), so a
    single trace artifact carries decisions AND observations;
  - with a ledger path armed (``KEYSTONE_LEDGER`` /
    `ExecutionConfig.ledger_path`, default derived alongside the trace
    artifact), each record is appended as one JSONL line — a killed run
    leaves a parseable prefix. The first line is a run header carrying
    the optimizer-config snapshot (megafusion / sharding_planner /
    precision_planner / concurrent_dispatch and their env-var names),
    which is what lets ``--diff`` name an injected
    ``KEYSTONE_MEGAFUSION=0`` flip instead of just observing its
    fallout.

Reconciliation against the live run (predicted vs observed programs,
bytes, casts, and the cost-model drift report) lives in
`analysis.reconcile`; the CLI surface is
``python -m keystone_tpu.telemetry --ledger <run>`` and ``--diff
<run_a> <run_b>`` (see OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

LEDGER_VERSION = 1

#: decision kinds the optimizer rules emit — plus "conformance", the
#: runtime watchdog's record kind: a live apply that breached its KP903
#: certified bound (bound vs observed vs flight-dump artifact).
KINDS = ("fusion", "megafusion", "placement", "precision", "chunk",
         "cache", "kernel", "spill", "conformance")

#: the config fields a run header snapshots, with the env var that
#: flips each — the channel by which ``--diff`` names a kill-switch
#: flip ("KEYSTONE_MEGAFUSION flipped 1 -> 0") instead of only
#: observing its fallout.
CONFIG_ENV = {
    "megafusion": "KEYSTONE_MEGAFUSION",
    "sharding_planner": "KEYSTONE_SHARDING_PLANNER",
    "precision_planner": "KEYSTONE_PRECISION_PLANNER",
    "unified_planner": "KEYSTONE_UNIFIED_PLANNER",
    "concurrent_dispatch": "KEYSTONE_CONCURRENT_DISPATCH",
    "pad_chunks": "KEYSTONE_PAD_CHUNKS",
    "aot_warmup": "KEYSTONE_AOT_WARMUP",
    "overlap": "KEYSTONE_OVERLAP",
    "pallas_kernels": "KEYSTONE_CHAIN_KERNELS",
    "live_telemetry": "KEYSTONE_LIVE_TELEMETRY",
    "serving_coalesce": "KEYSTONE_SERVING_COALESCE",
    "ooc_spill": "KEYSTONE_OOC_SPILL",
}

_LOCK = threading.Lock()
_SESSION: List[Dict[str, Any]] = []
_SESSION_CAP = 100_000  # runaway backstop; decisions are per-optimize rare
_seq = 0
_started_paths: set = set()
#: last config snapshot written to each JSONL path — when a later
#: decision runs under a different scoped config (a bench sweeping
#: plans via config_override), a fresh header line marks the boundary
#: so the file never claims one config for decisions made under another
_path_configs: Dict[str, Any] = {}
_suppress = threading.local()
#: header snapshot taken at the session's FIRST decision — the config
#: the decisions were actually made under (a scoped config_override
#: must be visible in the header, or --diff could not name the flip).
_session_header: Optional[Dict[str, Any]] = None


# ------------------------------------------------------------- activation


def resolve_ledger_path() -> Optional[str]:
    """The armed JSONL path: explicit `ExecutionConfig.ledger_path`
    (env ``KEYSTONE_LEDGER``) wins; otherwise a traced run defaults to
    a ledger alongside the trace artifact (``<trace>.ledger.jsonl``) so
    the two halves of one run travel together; None when neither is
    configured (records still reach the session list and any active
    tracer)."""
    try:
        from ..workflow.env import execution_config

        cfg = execution_config()
    except Exception:
        return None
    if cfg.ledger_path:
        return cfg.ledger_path
    if cfg.trace_path:
        return cfg.trace_path + ".ledger.jsonl"
    return None


def ledger_active() -> bool:
    """Whether records reach a durable destination (trace metadata or a
    JSONL file). The in-memory session list is always on."""
    from .spans import current_tracer

    return current_tracer() is not None or resolve_ledger_path() is not None


@contextmanager
def suppressed():
    """Scope in which `record_decision` is a no-op — for analysis-side
    callers that re-run optimizer rules on throwaway graphs
    (`fusion_rule.megafusion_blockers`) and must not pollute the run's
    ledger with decisions no executor will enforce."""
    prev = getattr(_suppress, "on", False)
    _suppress.on = True
    try:
        yield
    finally:
        _suppress.on = prev


# ------------------------------------------------------------ the header


def run_header() -> Dict[str, Any]:
    """The run-level header: ledger version, pid, wall epoch, the trace
    path (when armed), and the optimizer-config snapshot with env-var
    names — the diff channel for kill-switch flips."""
    config: Dict[str, Any] = {}
    trace_path = None
    platform = None
    try:
        from ..workflow.env import execution_config

        cfg = execution_config()
        trace_path = cfg.trace_path
        for field in CONFIG_ENV:
            config[field] = bool(getattr(cfg, field, False))
    except Exception:
        pass
    try:
        # the platform the run's measurements were taken on — what a
        # later --emit-calibration must stamp into provenance (emitting
        # from a different host must not relabel TPU-implied weights
        # as CPU ones). Never initializes a backend.
        from ..nodes.learning.cost_model import _live_platform_no_init

        platform = _live_platform_no_init()
    except Exception:
        pass
    return {
        "ledger_version": LEDGER_VERSION,
        "pid": os.getpid(),
        "wall_epoch": time.time(),  # keystone: ignore[KJ004] — wall-clock anchor, not a duration
        "trace_path": trace_path,
        "platform": platform,
        "config": config,
        "config_env": dict(CONFIG_ENV),
    }


# ------------------------------------------------------------- recording


def _jsonable(obj):
    """Deep-convert a decision payload to JSON-safe primitives: specs,
    NodeIds, dtypes, and anything else exotic degrade to ``str``."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in obj]
    return str(obj)


def _session_run_header() -> Dict[str, Any]:
    """The session's header: snapshotted at the first decision (the
    config the decisions ran under), freshly derived otherwise."""
    global _session_header
    with _LOCK:
        if _session_header is not None:
            return dict(_session_header)
    return run_header()


def _append_jsonl(path: str, record: Dict[str, Any],
                  header: Dict[str, Any]) -> None:
    first = False
    write_header = False
    with _LOCK:
        if path not in _started_paths:
            _started_paths.add(path)
            first = True
        if first or _path_configs.get(path) != header.get("config"):
            # a config change mid-file (scoped config_override sweeps,
            # e.g. the dispatch bench's plan matrix) gets its own
            # header line: decisions are never filed under a config
            # they were not made with
            _path_configs[path] = header.get("config")
            write_header = True
    mode = "w" if first else "a"
    with open(path, mode) as f:
        if write_header:
            f.write(json.dumps(header) + "\n")
        f.write(json.dumps(record) + "\n")


def record_decision(
    kind: str,
    rule: str,
    vertices: List[int],
    labels: List[str],
    chosen: Dict[str, Any],
    alternatives: List[Dict[str, Any]],
    predicted: Dict[str, Any],
    enforced: bool = True,
) -> Optional[Dict[str, Any]]:
    """Record one optimizer decision. Never raises — a ledger bug must
    not break optimization — and returns the recorded dict (None when
    suppressed)."""
    if getattr(_suppress, "on", False):
        return None
    global _seq, _session_header
    try:
        header = run_header()
        with _LOCK:
            _seq += 1
            seq = _seq
            if _session_header is None:
                _session_header = header
        rec = {
            "seq": seq,
            "t": time.time(),  # keystone: ignore[KJ004] — wall-clock anchor, not a duration
            "kind": str(kind),
            "rule": str(rule),
            "vertices": _jsonable(list(vertices)),
            "labels": _jsonable(list(labels)),
            "chosen": _jsonable(chosen),
            "alternatives": _jsonable(list(alternatives)),
            "predicted": _jsonable(predicted),
            "enforced": bool(enforced),
        }
        with _LOCK:
            _SESSION.append(rec)
            if len(_SESSION) > _SESSION_CAP:
                del _SESSION[: len(_SESSION) - _SESSION_CAP]
        from .spans import current_tracer

        tracer = current_tracer()
        if tracer is not None:
            tracer.metadata.setdefault("ledger_run", header)
            headers = tracer.metadata.setdefault("ledger_headers", [header])
            if headers[-1].get("config") != header.get("config"):
                headers.append(header)  # config changed mid-trace
            tracer.metadata.setdefault("decisions", []).append(rec)
        path = resolve_ledger_path()
        if path:
            try:
                _append_jsonl(path, rec, header)
            except OSError:
                pass  # an unwritable path must never break optimization
        return rec
    except Exception:
        return None


# ---------------------------------------------------------- session audit


def session_mark() -> int:
    """Opaque cursor into the in-memory session list; pair with
    `session_since` to slice the decisions of one measured window."""
    with _LOCK:
        return len(_SESSION)


def session_since(mark: int) -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_SESSION[mark:])


def session_decisions() -> List[Dict[str, Any]]:
    with _LOCK:
        return list(_SESSION)


def clear_session() -> None:
    """Drop the in-memory session records (tests; a fresh bench tier).
    JSONL files and trace metadata are untouched."""
    global _seq, _session_header
    with _LOCK:
        _SESSION.clear()
        _seq = 0
        _session_header = None


def write_session(path: str, decisions: Optional[List[Dict]] = None,
                  header: Optional[Dict[str, Any]] = None) -> str:
    """Write a complete ledger file (header + decisions) in one shot —
    the explicit-flush form for tests and hosts that manage lifecycle
    themselves (the ambient JSONL path appends incrementally instead).
    The default header is the session's first-decision snapshot, so a
    scoped config override active during the run is what the file
    records; callers slicing one window out of a longer session pass
    the `run_header()` they captured inside that window."""
    with open(path, "w") as f:
        f.write(json.dumps(_jsonable(
            _session_run_header() if header is None else header)) + "\n")
        for rec in (session_decisions() if decisions is None else decisions):
            f.write(json.dumps(_jsonable(rec)) + "\n")
    return path


# --------------------------------------------------------------- reading


def read_ledger(path: str) -> Dict[str, Any]:
    """Load a run's decisions from either artifact form:

      - a ledger JSONL (header line + one record per line), or
      - a Chrome trace JSON whose ``keystone`` metadata embeds
        ``ledger_run`` + ``decisions`` (and, as a bonus, the
        observations reconciliation needs).

    Returns ``{"path", "header", "headers", "decisions", "trace"}`` —
    ``header`` is the run's first header, ``headers`` every header line
    (a run whose config changed mid-file — scoped overrides sweeping
    plans — carries one per config), and ``trace`` is the parsed trace
    object when one is available (the trace form itself, or the
    header's ``trace_path`` when that file exists), else None. A
    truncated final JSONL line (a run killed mid-append) is dropped:
    the parseable prefix IS the contract; corruption anywhere else
    still raises."""
    with open(path) as f:
        text = f.read()
    header: Dict[str, Any] = {}
    headers: List[Dict[str, Any]] = []
    decisions: List[Dict[str, Any]] = []
    trace = None
    parsed = None
    try:
        parsed = json.loads(text)
    except ValueError:
        parsed = None
    if isinstance(parsed, dict) and "traceEvents" in parsed:
        ks = parsed.get("keystone", {})
        header = ks.get("ledger_run") or {}
        headers = list(ks.get("ledger_headers") or ([header] if header
                                                    else []))
        decisions = list(ks.get("decisions") or [])
        trace = parsed
    else:
        lines = [ln.strip() for ln in text.splitlines()]
        lines = [ln for ln in lines if ln]
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    break  # truncated tail from a killed run
                raise
            if "ledger_version" in rec and "kind" not in rec:
                headers.append(rec)
            else:
                decisions.append(rec)
        header = headers[0] if headers else {}
        tp = header.get("trace_path")
        if tp and os.path.exists(tp):
            try:
                from .export import load_trace

                trace = load_trace(tp)
            except (OSError, ValueError):
                trace = None
    return {"path": path, "header": header, "headers": headers,
            "decisions": decisions, "trace": trace}


# ------------------------------------------------------------- rendering


def runner_up(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The best-priced alternative the chosen entry beat: lowest value
    of the first ``cost_*`` field present, else the first alternative."""
    alts = record.get("alternatives") or []
    if not alts:
        return None
    cost_keys = [k for k in alts[0] if str(k).startswith("cost_")]
    if cost_keys:
        key = cost_keys[0]
        priced = [a for a in alts if isinstance(a.get(key), (int, float))]
        if priced:
            return min(priced, key=lambda a: a[key])
    return alts[0]


def _short(d: Optional[Dict[str, Any]], width: int = 34) -> str:
    if not d:
        return "—"
    entry = d.get("entry")
    if entry is None:
        entry = ", ".join(f"{k}={v}" for k, v in sorted(d.items())
                          if not isinstance(v, (dict, list)))
    return str(entry)[:width]


def render_ledger(run: Dict[str, Any],
                  reconciliation: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable per-decision table: chosen / runner-up /
    predicted — plus observed / residual columns when a reconciliation
    (from `analysis.reconcile.reconcile_decisions`) is supplied."""
    lines: List[str] = []
    header = run.get("header") or {}
    cfg = header.get("config") or {}
    if cfg:
        flags = " ".join(f"{k}={'1' if v else '0'}"
                         for k, v in sorted(cfg.items()))
        lines.append(f"run config: {flags}")
    decisions = run.get("decisions") or []
    lines.append(f"{len(decisions)} decision(s)")
    obs_by_seq: Dict[Any, Dict[str, Any]] = {}
    if reconciliation:
        for row in reconciliation.get("rows", []):
            obs_by_seq[row.get("seq")] = row
    head = (f"{'kind':<11} {'decision':<34} {'chosen':<26} "
            f"{'runner-up':<26} {'predicted':<30}")
    if reconciliation:
        head += f" {'observed':<24} {'residual':<18}"
    lines.append(head)
    for d in decisions:
        labels = d.get("labels") or []
        name = (labels[0] if labels else "?")
        if len(labels) > 1:
            name += f" (+{len(labels) - 1})"
        pred = d.get("predicted") or {}
        pred_s = " ".join(
            f"{k}={_fmt_val(v)}" for k, v in sorted(pred.items())
            if not isinstance(v, (dict, list)))
        line = (f"{d.get('kind', '?'):<11} {name[:34]:<34} "
                f"{_short(d.get('chosen'), 26):<26} "
                f"{_short(runner_up(d), 26):<26} {pred_s[:30]:<30}")
        if reconciliation:
            row = obs_by_seq.get(d.get("seq")) or {}
            obs = row.get("observed") or {}
            res = row.get("residuals") or {}
            obs_s = " ".join(f"{k}={_fmt_val(v)}"
                             for k, v in sorted(obs.items()))
            res_s = " ".join(f"{k}={_fmt_val(v)}"
                             for k, v in sorted(res.items()))
            line += f" {obs_s[:24]:<24} {res_s[:18]:<18}"
        lines.append(line)
    return "\n".join(lines)


def _fmt_val(v) -> str:
    if isinstance(v, float) and v == int(v):
        v = int(v)
    if isinstance(v, int) and abs(v) >= 10_000:
        return f"{v:,}"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


# ------------------------------------------------------------------ diff


def decision_key(record: Dict[str, Any]) -> Tuple[str, str]:
    """Run-over-run identity of a decision: its kind plus its label
    trail (vertex ids are per-graph and shift between runs; labels are
    the stable anchor, matching the reconcile-table convention)."""
    return (str(record.get("kind")),
            ";".join(str(x) for x in record.get("labels") or []))


#: relative tolerance for "the prediction drifted" (predictions are
#: priced integers; a 1% wobble from a count change is not drift).
DRIFT_RTOL = 0.01


def diff_runs(
    run_a: Dict[str, Any],
    run_b: Dict[str, Any],
    reconciliation_a: Optional[Dict[str, Any]] = None,
    reconciliation_b: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Run-over-run regression detection. Returns a dict with:

      - ``config_flips`` — optimizer-config fields (and their env-var
        names) that changed between the two run headers: an injected
        ``KEYSTONE_MEGAFUSION=0`` is named here directly;
      - ``decisions_removed`` / ``decisions_added`` — decision keys
        present in one run only (a kill switch removes its rule's
        decisions; a new rule adds some);
      - ``prediction_drift`` — same decision key, numeric predicted
        values differing beyond `DRIFT_RTOL`;
      - ``observed_regressions`` — per shared observed metric of the
        two reconciliations, run B strictly worse than run A (programs
        and bytes are both better-smaller);
      - ``regressions`` — the total count the CLI exits nonzero on.
    """
    header_a = run_a.get("header") or {}
    cfg_a = _stable_config(run_a)
    cfg_b = _stable_config(run_b)
    env_names = dict(CONFIG_ENV)
    env_names.update(header_a.get("config_env") or {})
    config_flips = []
    for field in sorted(set(cfg_a) | set(cfg_b)):
        va, vb = cfg_a.get(field), cfg_b.get(field)
        if va != vb and va is not None and vb is not None:
            config_flips.append({
                "field": field,
                "env": env_names.get(field, field),
                "a": va, "b": vb,
            })

    by_key_a: Dict[Tuple[str, str], Dict] = {}
    by_key_b: Dict[Tuple[str, str], Dict] = {}
    for rec in run_a.get("decisions") or []:
        by_key_a.setdefault(decision_key(rec), rec)
    for rec in run_b.get("decisions") or []:
        by_key_b.setdefault(decision_key(rec), rec)

    removed = [
        {"kind": k[0], "labels": k[1],
         "suspect_env": _suspect_env(k[0], config_flips)}
        for k in sorted(set(by_key_a) - set(by_key_b))
    ]
    added = [{"kind": k[0], "labels": k[1]}
             for k in sorted(set(by_key_b) - set(by_key_a))]

    drift = []
    for key in sorted(set(by_key_a) & set(by_key_b)):
        pa = by_key_a[key].get("predicted") or {}
        pb = by_key_b[key].get("predicted") or {}
        for metric in sorted(set(pa) & set(pb)):
            va, vb = pa[metric], pb[metric]
            if not isinstance(va, (int, float)) \
                    or not isinstance(vb, (int, float)):
                continue
            tol = DRIFT_RTOL * max(abs(va), abs(vb), 1.0)
            if abs(va - vb) > tol:
                drift.append({
                    "kind": key[0], "labels": key[1], "metric": metric,
                    "a": va, "b": vb,
                })

    observed_regressions = _observed_regressions(
        reconciliation_a, reconciliation_b)

    regressions = (len(config_flips) + len(removed) + len(drift)
                   + len(observed_regressions))
    return {
        "config_flips": config_flips,
        "decisions_removed": removed,
        "decisions_added": added,
        "prediction_drift": drift,
        "observed_regressions": observed_regressions,
        "regressions": regressions,
    }


def _stable_config(run: Dict[str, Any]) -> Dict[str, Any]:
    """The config fields that held ONE value for the whole run. A file
    whose config changed mid-run (scoped overrides sweeping plans)
    carries several headers; a field that varied within the run cannot
    be flip-compared against another run, so it is dropped here — only
    genuinely run-constant fields feed ``config_flips``."""
    headers = run.get("headers") or []
    if not headers and run.get("header"):
        headers = [run["header"]]
    configs = [h.get("config") or {} for h in headers]
    configs = [c for c in configs if c]
    if not configs:
        return {}
    stable = dict(configs[0])
    for cfg in configs[1:]:
        for field in list(stable):
            if cfg.get(field, object()) != stable[field]:
                del stable[field]
    return stable


#: which config kill-switch FIELDS own which decision kind — how a
#: removed decision is attributed to the flip that removed it (fusion
#: has no env switch of its own: only the optimizer construction
#: changes it). Placement and precision decisions have TWO possible
#: owners since PR 15: the sequential rule's own switch, and the
#: unified planner that enforces the same kinds jointly when it wins.
_KIND_FIELDS = {
    "megafusion": ("megafusion",),
    "placement": ("sharding_planner", "unified_planner"),
    "precision": ("precision_planner", "unified_planner"),
    "chunk": ("unified_planner",),
    "cache": ("unified_planner",),
    "kernel": ("pallas_kernels", "unified_planner"),
    "spill": ("ooc_spill", "unified_planner"),
    "conformance": ("live_telemetry",),
}


def _suspect_env(kind: str, config_flips: List[Dict]) -> Optional[str]:
    """The kill switch to blame for a removed decision — only when an
    owning config field ACTUALLY flipped between the runs; a decision
    that vanished under identical config (pipeline edit, savings floor)
    names no suspect."""
    fields = _KIND_FIELDS.get(kind)
    if not fields:
        return None
    for field in fields:
        for flip in config_flips:
            if flip.get("field") == field:
                return flip.get("env", field)
    return None


#: observed metrics where smaller is better (a B>A move is a
#: regression); everything else is reported as drift only. Names match
#: `analysis.reconcile.reconcile_decisions`'s observed keys.
_SMALLER_BETTER = (
    "programs_executed", "programs_compiled", "megafused_programs",
    "boundary_bytes", "out_bytes", "casts_baked",
)


def _observed_regressions(rec_a, rec_b) -> List[Dict[str, Any]]:
    if not rec_a or not rec_b:
        return []

    def totals(rec):
        out: Dict[str, float] = {}
        for row in rec.get("rows", []):
            for metric, v in (row.get("observed") or {}).items():
                if isinstance(v, (int, float)):
                    out[metric] = out.get(metric, 0.0) + v
        # run-level observations live on the reconciliation itself
        for metric, v in (rec.get("run_observed") or {}).items():
            if isinstance(v, (int, float)):
                out.setdefault(metric, v)
        return out

    ta, tb = totals(rec_a), totals(rec_b)
    out = []
    for metric in sorted(set(ta) & set(tb)):
        if metric not in _SMALLER_BETTER:
            continue
        if tb[metric] > ta[metric]:
            out.append({"metric": metric, "a": ta[metric], "b": tb[metric]})
    return out


def format_diff(diff: Dict[str, Any]) -> str:
    lines: List[str] = []
    for f in diff["config_flips"]:
        lines.append(
            f"CONFIG FLIP: {f['env']} ({f['field']}) "
            f"{'1' if f['a'] else '0'} -> {'1' if f['b'] else '0'}")
    for d in diff["decisions_removed"]:
        sus = f" (suspect: {d['suspect_env']})" if d.get("suspect_env") \
            else ""
        lines.append(
            f"DECISION REMOVED: {d['kind']} [{d['labels'][:60]}]{sus}")
    for d in diff["decisions_added"]:
        lines.append(f"decision added: {d['kind']} [{d['labels'][:60]}]")
    for d in diff["prediction_drift"]:
        lines.append(
            f"PREDICTION DRIFT: {d['kind']} [{d['labels'][:40]}] "
            f"{d['metric']}: {_fmt_val(d['a'])} -> {_fmt_val(d['b'])}")
    for d in diff["observed_regressions"]:
        lines.append(
            f"OBSERVED REGRESSION: {d['metric']} "
            f"{_fmt_val(d['a'])} -> {_fmt_val(d['b'])} (worse)")
    lines.append(f"{diff['regressions']} regression(s)")
    return "\n".join(lines)
