"""Node-force instrumentation — the one wrapper every profile consumer
shares.

`GraphExecutor` wraps each node's lazy Expression through
`instrument_node_force`; the wrapper times the real force (try/finally,
so a thunk that raises still reports its elapsed time and bumps the
failure counter), estimates output bytes ONCE per force with the
module-level `estimate_bytes` (no per-force import — the old
`ExecutionProfiler.wrap` re-imported it inside the thunk on every
force), opens a ``cat="node"`` span under the active tracer, feeds the
observed live-set accounting, and notifies the attached profiler.
Streaming expressions — which downstream consumers drain through
``iter_chunks()`` without ever running the memoized thunk — are
instrumented at the chunk generator instead (`_instrument_stream`), so
they too appear in spans, profiles, and reconciliation. Because
`utils.profiling.ExecutionProfiler` and `workflow.autocache.profile_nodes`
both consume these span completions, cache decisions and user-facing
profile reports can never disagree about a measurement.

Timing semantics: with a profiler attached the forced value is
``.sync()``-ed (scalar pull) so device compute is honestly attributed to
the producing node — the contract `profile_nodes` and
`profile_execution` always had. Under pure tracing no sync is injected:
a trace must observe the overlap engine, not serialize it, so node spans
measure dispatch+materialization and the *stall* time shows up where it
is actually paid (chunk drains, consumer waits).
"""

from __future__ import annotations

from time import perf_counter
from typing import Optional

from .metrics import counter, gauge
from .spans import current_tracer


#: cached per-process metric dimension: "" on single-process jobs (no
#: extra counter), "p<index>" under a multi-host mesh, None = unresolved.
#: Tests reset this to None to re-probe after monkeypatching.
_proc_dim_cache: Optional[str] = None


def process_dim() -> Optional[str]:
    """The per-process dispatch/compile accounting dimension: ``p<i>``
    when this is process ``i`` of a multi-host job, None on single-host
    jobs (where a second counter would just duplicate the total).
    Resolved once — `jax.process_index()` is constant for the life of a
    process — and never initializes a backend that isn't already the
    caller's problem (dispatch implies an initialized backend)."""
    global _proc_dim_cache
    if _proc_dim_cache is None:
        try:
            import jax

            _proc_dim_cache = (
                f"p{jax.process_index()}" if jax.process_count() > 1
                else "")
        except Exception:
            _proc_dim_cache = ""
    return _proc_dim_cache or None


def record_dispatch(n: int = 1) -> None:
    """Count ``n`` executed XLA programs against
    ``dispatch.programs_executed`` — THE per-run dispatch budget the
    round-4 profiling proved the headline path is bounded by (PERF.md
    "execution count, not bandwidth": trivial stages cost 65–95 ms of
    tunnel RTT each at ~1.5 ms of theoretical HBM time).

    Call sites are the library's jitted call boundaries: every
    `Dataset.map_batches`, every fused-chain program launch
    (`FusedBatchTransformer.apply_batch`), every solver step
    (`_bcd_epoch` / `_krr_step` / `_lbfgs_step`), every overlap-engine
    chunk dispatch, and the node-level module jits that bypass
    `map_batches` (scalers, label indicators, random features, normal
    equations). Always on (not gated on tracing): the `dispatch_count`
    bench tier and the scheduler tests read the counter directly.

    Under a multi-host mesh each count also lands on
    ``dispatch.programs_executed.p<i>`` — every host dispatches its own
    SPMD program launches, so a pod-level trace must say which process
    executed what (the telemetry CLI's dispatch summary and
    ``perf_table.py --trace`` render the per-process breakdown)."""
    counter("dispatch.programs_executed").inc(n)
    dim = process_dim()
    if dim is not None:
        counter(f"dispatch.programs_executed.{dim}").inc(n)


def estimate_bytes(value) -> float:
    """Estimated host/device bytes of a forced value: array leaves by
    ``nbytes``, strings/bytes by length, opaque leaves at a nominal 64.
    Canonical home of the estimator previously private to
    `workflow.autocache` (which still re-exports it). Dataset-likes
    unwrap to their payload: ``.data`` (device `Dataset`) or ``.items``
    (`HostDataset` — summed per item, so a host stage's output is its
    real residency, not one opaque-leaf placeholder)."""
    import jax

    payload = getattr(value, "data", None)
    if payload is None:
        payload = getattr(value, "items", None)
    if payload is None:
        payload = value
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(payload):
        if hasattr(leaf, "nbytes"):
            total += float(leaf.nbytes)
        elif isinstance(leaf, (bytes, str)):
            total += len(leaf)
        else:
            total += 64.0
    return total


def _record_node(label, vertex, profiler, dt, nbytes, failed,
                 t0_rel=None, streamed=False):
    """Shared completion bookkeeping for both force paths."""
    counter("executor.node_forces").inc()
    if failed:
        counter("executor.node_failures").inc()
    elif nbytes:
        # memoized outputs stay live for the executor's lifetime: the
        # running sum's high-water mark is the observed live-set peak
        # the static KP2xx model reconciles against (per-run copy on the
        # tracer; the registry gauge is cumulative across runs)
        gauge("executor.live_bytes").add(nbytes)
        tracer = current_tracer()
        if tracer is not None:
            tracer.add_live_bytes(nbytes)
    if streamed:
        tracer = current_tracer()
        if tracer is not None and t0_rel is not None:
            # ts is the FIRST-pull timestamp (the drain window's start,
            # not the completion time the record is written at) and dur
            # stays the cumulative pull time — the consumer's
            # between-chunk work is excluded from the stage's cost, so
            # self-time math holds; drain_window_s carries the real
            # first-pull→exhaustion extent for timeline readers
            tracer.record_complete(
                f"force {label}", "node", t0_rel, dt, error=failed,
                vertex=vertex, out_bytes=nbytes, seconds=round(dt, 6),
                drain_window_s=round(max(0.0, tracer.now() - t0_rel), 6),
                streamed=True)
    if profiler is not None:
        profiler.on_force(label, dt, nbytes, failed=failed, vertex=vertex)


def _instrument_stream(label, expr, vertex, profiler):
    """Streamed stages are drained through ``iter_chunks()`` — the
    memoized ``_thunk`` never runs on that path, so wrap the chunk
    generator instead. Per-pull timing keeps the consumer's
    between-chunk work OUT of this stage's duration (drains interleave
    with downstream compute by design); on exhaustion one closed
    ``cat="node"`` span is recorded via `Tracer.record_complete`
    (``streamed=True``, ``dur`` = cumulative pull time) and the profiler
    is notified — so streamed stages appear in profiles, reconciliation,
    and live-set accounting instead of silently folding into their
    consumer. Early close (`GeneratorExit`) records nothing: the stream
    is resumable and will complete (and report) later."""
    orig_chunks = expr._chunks_thunk

    def chunks():
        it = orig_chunks()
        total = 0.0
        nbytes = 0.0
        t0_rel = None
        while True:
            t0 = perf_counter()
            if t0_rel is None:
                tracer = current_tracer()
                t0_rel = tracer.now() if tracer is not None else 0.0
            try:
                item = next(it)
            except StopIteration:
                total += perf_counter() - t0
                _record_node(label, vertex, profiler, total, nbytes,
                             failed=False, t0_rel=t0_rel, streamed=True)
                return
            except GeneratorExit:
                raise  # early close: resumable, not a completion
            except BaseException:
                total += perf_counter() - t0
                _record_node(label, vertex, profiler, total, 0.0,
                             failed=True, t0_rel=t0_rel, streamed=True)
                raise
            total += perf_counter() - t0
            try:
                nbytes += estimate_bytes(item[1])
            except Exception:
                pass
            yield item

    expr._chunks_thunk = chunks
    return expr


def instrument_node_force(
    label: str,
    expr,
    vertex: Optional[int] = None,
    profiler=None,
):
    """Wrap ``expr`` so its force reports spans + metrics + profiler
    completions. Streaming expressions get their chunk generator wrapped
    (see `_instrument_stream`); plain expressions get their thunk
    wrapped. Already-forced expressions pass through untouched. Safe to
    call with neither tracer nor profiler active — but the executor
    guards the call, so the untraced hot path never even reaches here."""
    if getattr(expr, "_chunks_thunk", None) is not None \
            and not expr.is_forced:
        return _instrument_stream(label, expr, vertex, profiler)
    orig_thunk = expr._thunk
    if orig_thunk is None:  # already forced; nothing to time
        return expr

    def forced():
        tracer = current_tracer()
        rec = None
        if tracer is not None:
            rec = tracer.start(f"force {label}", cat="node", vertex=vertex)
        t0 = perf_counter()
        value = None
        failed = False
        try:
            value = orig_thunk()
            if profiler is not None and hasattr(value, "sync"):
                value.sync()  # scalar-pull sync so device time lands on
                # this node (block_until_ready is a no-op through the
                # axon tunnel); tracing alone never injects a sync — it
                # must observe the overlap engine, not serialize it
            return value
        except BaseException:
            failed = True
            raise
        finally:
            dt = perf_counter() - t0
            nbytes = 0.0
            if not failed and value is not None:
                try:
                    nbytes = estimate_bytes(value)
                except Exception:
                    nbytes = 0.0
            if rec is not None:
                tracer.end(rec, error=failed, out_bytes=nbytes,
                           seconds=round(dt, 6))
            _record_node(label, vertex, profiler, dt, nbytes, failed)

    expr._thunk = forced
    return expr
