"""Unified runtime telemetry: hierarchical spans, a process-wide metrics
registry, Chrome trace-event export, and the instrumentation hooks the
executor / overlap engine / solver loops report through.

Span hierarchy (structural, via per-thread stacks):

    pipeline run → optimizer phase → node force → stream chunk
                                                → solver iteration

Quick start:

    from keystone_tpu.telemetry import trace_run
    with trace_run("run.json"):
        pipeline(data).get()
    # -> run.json loads in chrome://tracing / Perfetto

    KEYSTONE_TRACE=run.json python -m keystone_tpu.pipelines MnistRandomFFT
    python -m keystone_tpu.telemetry run.json   # summarize

Metric names, the span model, and the static-vs-observed memory
reconciliation workflow are documented in OBSERVABILITY.md.
"""

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsDelta,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    metrics_delta,
    registry,
)
from . import ledger
from .spans import (
    SpanRecord,
    Tracer,
    capabilities,
    current_tracer,
    record_capability,
    set_tracer,
    span,
    telemetry_active,
    trace_run,
)
from .export import (
    aggregate_spans,
    compile_summary,
    dispatch_plan_breakdown,
    dispatch_summary,
    load_trace,
    self_times,
    summarize,
    to_chrome_trace,
    write_trace,
)
from .instrument import estimate_bytes, instrument_node_force, record_dispatch
from .compile_events import compiles_snapshot, install_compile_listeners
from .flight import (
    FlightRecorder,
    ensure_flight,
    flight_recorder,
    flight_snapshot,
    reset_flight,
)
from .streaming import QuantileSketch, format_health, health, reset_live
from .watchdog import (
    ConformanceWatchdog,
    active_watchdog,
    arm_watchdog,
    disarm_watchdog,
    request_scope,
)

# Compile accounting is armed with the package: the monitoring hooks are
# passive (they fire only inside jax's own compile path), and installing
# here means no compile anywhere in the process escapes
# `dispatch.programs_compiled` — the same always-on discipline as
# `record_dispatch`.
install_compile_listeners()

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsDelta", "MetricsRegistry",
    "counter", "gauge", "histogram", "ledger", "metrics_delta",
    "registry",
    "SpanRecord", "Tracer", "capabilities", "current_tracer",
    "record_capability", "set_tracer", "span", "telemetry_active",
    "trace_run",
    "aggregate_spans", "compile_summary", "dispatch_plan_breakdown",
    "dispatch_summary", "load_trace", "self_times",
    "summarize", "to_chrome_trace", "write_trace",
    "estimate_bytes", "instrument_node_force", "record_dispatch",
    "compiles_snapshot", "install_compile_listeners",
    "FlightRecorder", "ensure_flight", "flight_recorder",
    "flight_snapshot", "reset_flight",
    "QuantileSketch", "format_health", "health", "reset_live",
    "ConformanceWatchdog", "active_watchdog", "arm_watchdog",
    "disarm_watchdog", "request_scope",
]
