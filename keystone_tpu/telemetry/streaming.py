"""Streaming quantile sketches and the live serving-latency table.

A long-lived serving process must answer "what is my apply-latency p99
for batch shape 256 right now?" without retaining samples: this module
keeps one `QuantileSketch` per (pipeline, padded ladder shape) — a
fixed-memory histogram sketch in the Ben-Haim/Yom-Tov streaming style
(the same family as t-digest / Hive's NumericHistogram) — plus
queue-depth and throughput gauges, all surfaced through `health()` and
the ``python -m keystone_tpu.telemetry --live`` CLI rendering.

Sketch properties:

  - fixed memory: at most ``max_bins`` (centroid, count) pairs, ~1 KiB
    per sketch at the default 64 bins, regardless of observation count;
  - mergeable: ``merge`` combines two sketches bin-wise then re-compacts
    — per-thread or per-process sketches can be unioned for a fleet
    view without sample exchange;
  - exact count / sum / min / max ride alongside, so totals and worst
    cases are never approximated — only interior quantiles are, with
    error shrinking as mass concentrates (unimodal latency
    distributions, the serving case, resolve p50/p99 to well under the
    bin width).

The table itself is process-global and lock-guarded (observations are
per-apply, not per-element — contention is irrelevant), reset by
`reset_live()` (tests; a fresh bench tier), and fed by
`watchdog.request_scope` so it populates exactly when the live
telemetry plane is armed (``KEYSTONE_LIVE_TELEMETRY`` — see
`workflow.env.ExecutionConfig`).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: default sketch width: 64 (centroid, count) bins ≈ 1 KiB — interior
#: quantile error for unimodal latency data is well under one bin width
DEFAULT_MAX_BINS = 64

_LOCK = threading.Lock()


class QuantileSketch:
    """Fixed-memory streaming quantile sketch (Ben-Haim/Yom-Tov
    streaming-parallel decision-tree histogram): keep at most
    ``max_bins`` weighted centroids sorted by value; inserting past
    capacity merges the two closest adjacent centroids (weighted mean).
    Quantiles interpolate the cumulative weight curve. All mutation is
    caller-locked (the module table holds one lock) or single-threaded.
    """

    __slots__ = ("max_bins", "count", "total", "min", "max", "_bins")

    def __init__(self, max_bins: int = DEFAULT_MAX_BINS):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = int(max_bins)
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._bins: List[List[float]] = []  # [value, weight], sorted

    # ---------------------------------------------------------- update

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        values = [b[0] for b in self._bins]
        i = bisect.bisect_left(values, v)
        if i < len(self._bins) and self._bins[i][0] == v:
            self._bins[i][1] += 1.0
        else:
            self._bins.insert(i, [v, 1.0])
            self._compact()

    def _compact(self) -> None:
        while len(self._bins) > self.max_bins:
            # merge the closest adjacent pair (weighted mean) — O(bins)
            # per insert past capacity, bins is a small constant
            best_i = 0
            best_gap = float("inf")
            for i in range(len(self._bins) - 1):
                gap = self._bins[i + 1][0] - self._bins[i][0]
                if gap < best_gap:
                    best_gap = gap
                    best_i = i
            a, b = self._bins[best_i], self._bins[best_i + 1]
            w = a[1] + b[1]
            self._bins[best_i] = [(a[0] * a[1] + b[0] * b[1]) / w, w]
            del self._bins[best_i + 1]

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (bin-wise union, then re-compact).
        Exact aggregates add; returns self for chaining."""
        for v, w in other._bins:
            values = [b[0] for b in self._bins]
            i = bisect.bisect_left(values, v)
            if i < len(self._bins) and self._bins[i][0] == v:
                self._bins[i][1] += w
            else:
                self._bins.insert(i, [v, w])
        self._compact()
        self.count += other.count
        self.total += other.total
        if other.min is not None and (self.min is None or other.min < self.min):
            self.min = other.min
        if other.max is not None and (self.max is None or other.max > self.max):
            self.max = other.max
        return self

    # ----------------------------------------------------------- query

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]); 0.0 when empty. The
        cumulative-weight curve is interpolated between centroids;
        extremes clamp to the exact observed min/max."""
        if not self._bins or self.count == 0:
            return 0.0
        q = max(0.0, min(1.0, q))
        target = q * self.count
        if target <= self._bins[0][1] * 0.5:
            return self.min if self.min is not None else self._bins[0][0]
        cum = 0.0
        for i, (v, w) in enumerate(self._bins):
            mid = cum + w * 0.5
            if target <= mid:
                if i == 0:
                    prev_v = self.min if self.min is not None else v
                    prev_mid = 0.0
                else:
                    pv, pw = self._bins[i - 1]
                    prev_v = pv
                    prev_mid = cum - pw * 0.5
                denom = mid - prev_mid
                frac = (target - prev_mid) / denom if denom > 0 else 1.0
                return prev_v + (v - prev_v) * frac
            cum += w
        return self.max if self.max is not None else self._bins[-1][0]

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "total": self.total,
            "min": self.min if self.min is not None else 0.0,
            "max": self.max if self.max is not None else 0.0,
            "mean": self.mean,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "bins": len(self._bins),
        }


# ---------------------------------------------------------------- table
#
# (pipeline, padded chunk shape) → QuantileSketch of apply-latency
# seconds, plus process throughput/in-flight accounting. Keys are
# strings so the health dict is JSON-ready.

_sketches: Dict[Tuple[str, int], QuantileSketch] = {}
_started: Optional[float] = None
_last_request: Optional[float] = None


def observe_apply(pipeline: str, chunk_shape: int, seconds: float) -> None:
    """Record one live apply latency under its padded ladder shape."""
    global _started, _last_request
    key = (str(pipeline), int(chunk_shape))
    now = time.time()  # keystone: ignore[KJ004] — wall anchor for throughput, not a duration
    with _LOCK:
        sk = _sketches.get(key)
        if sk is None:
            sk = _sketches[key] = QuantileSketch()
        sk.observe(seconds)
        if _started is None:
            _started = now
        _last_request = now


def latency_sketch(pipeline: str, chunk_shape: int) -> Optional[QuantileSketch]:
    with _LOCK:
        return _sketches.get((str(pipeline), int(chunk_shape)))


def reset_live() -> None:
    """Drop all live sketch state (tests; a fresh bench tier)."""
    global _started, _last_request
    with _LOCK:
        _sketches.clear()
        _started = None
        _last_request = None


def health() -> Dict[str, Any]:
    """JSON-ready live-health view: per-(pipeline, shape) latency
    percentiles from the sketches, request totals and throughput, the
    in-flight/queue-depth gauges, breach counters, and — when a
    watchdog is armed — its certificate digest. This is the payload the
    ``--live`` CLI renders and a serving wrapper would export."""
    from .metrics import registry

    with _LOCK:
        rows = [
            {
                "pipeline": pipe,
                "chunk_shape": shape,
                **sk.snapshot(),
            }
            for (pipe, shape), sk in sorted(_sketches.items())
        ]
        started = _started
        last = _last_request
    total = sum(r["count"] for r in rows)
    window = (last - started) if (started is not None and last is not None
                                  and last > started) else 0.0
    reg = registry()
    gauges = {name: g.snapshot() for name, g in sorted(reg.gauges.items())
              if name.startswith(("serving.", "prefetch.", "overlap."))}
    counters = {name: c.snapshot()
                for name, c in sorted(reg.counters.items())
                if name.startswith("serving.")}
    histograms = {name: hg.snapshot()
                  for name, hg in sorted(reg.histograms.items())
                  if name.startswith("serving.")}
    out: Dict[str, Any] = {
        "requests": total,
        "throughput_rps": (total - 1) / window if window > 0 and total > 1
        else 0.0,
        "latency": rows,
        "gauges": gauges,
        "counters": counters,
        "histograms": histograms,
    }
    from .watchdog import active_watchdog

    wd = active_watchdog()
    if wd is not None:
        out["watchdog"] = wd.describe()
    return out


def format_health(h: Dict[str, Any]) -> str:
    """Human rendering of a `health()` dict (the ``--live`` CLI)."""
    lines: List[str] = []
    lines.append(
        f"live telemetry: {int(h.get('requests', 0))} request(s), "
        f"{h.get('throughput_rps', 0.0):.2f} req/s")
    rows = h.get("latency") or []
    if rows:
        lines.append("")
        lines.append(f"{'pipeline':<28} {'shape':>7} {'count':>7} "
                     f"{'p50 ms':>9} {'p90 ms':>9} {'p99 ms':>9} "
                     f"{'max ms':>9}")
        for r in rows:
            lines.append(
                f"{str(r['pipeline'])[:28]:<28} {int(r['chunk_shape']):>7} "
                f"{int(r['count']):>7} {r['p50'] * 1e3:>9.2f} "
                f"{r['p90'] * 1e3:>9.2f} {r['p99'] * 1e3:>9.2f} "
                f"{r['max'] * 1e3:>9.2f}")
    counters = h.get("counters") or {}
    breaches = counters.get("serving.slo_breaches", {}).get("value", 0)
    checked = counters.get("serving.conformance_checks", {}).get("value", 0)
    if checked or breaches:
        lines.append("")
        lines.append(f"conformance: {int(checked)} check(s), "
                     f"{int(breaches)} breach(es)")
    gauges = h.get("gauges") or {}
    inflight = gauges.get("serving.inflight")
    if inflight:
        lines.append(f"in-flight: {int(inflight.get('value', 0))} "
                     f"(peak {int(inflight.get('max', 0))})")
    depth = gauges.get("serving.queue_depth")
    shed = counters.get("serving.shed_total", {}).get("value", 0)
    dispatches = counters.get("serving.dispatches", {}).get("value", 0)
    if depth or shed or dispatches:
        lines.append(
            f"serving runtime: {int(dispatches)} dispatch(es), queue "
            f"depth {int((depth or {}).get('value', 0))} "
            f"(peak {int((depth or {}).get('max', 0))}), "
            f"{int(shed)} shed")
    coalesced = (h.get("histograms") or {}).get("serving.coalesced_batch")
    if coalesced and coalesced.get("count"):
        lines.append(
            f"coalesced batch: mean {coalesced.get('mean', 0.0):.1f} "
            f"p50 {coalesced.get('p50', 0.0):.0f} "
            f"p99 {coalesced.get('p99', 0.0):.0f} "
            f"max {coalesced.get('max', 0.0):.0f} "
            f"(over {int(coalesced['count'])} dispatch(es))")
    wd = h.get("watchdog")
    if wd:
        state = "armed" if wd.get("armed") else "disarmed"
        shapes = wd.get("shapes") or {}
        lines.append("")
        lines.append(
            f"watchdog: {state} [{wd.get('pipeline', '?')}], "
            f"{len(shapes)} certified shape(s), SLO "
            f"{(wd.get('slo_seconds') or 0) * 1e3:.0f}ms")
        for shape in sorted(shapes, key=int):
            lines.append(f"  shape {shape}: bound "
                         f"{shapes[shape] * 1e3:.2f}ms")
    return "\n".join(lines)
