#!/usr/bin/env python
"""jaxlint — repo-specific static JAX lints for keystone_tpu.

Pure-AST (no imports of the linted code, no jax required), so it runs in
milliseconds as a pre-test gate (`scripts/lint.sh`) and as a tier-1
pytest (tests/test_jaxlint.py). Rules encode project discipline the type
system cannot (see ANALYSIS.md for the full catalog):

  KJ001  jnp-loop-accumulation (under ``nodes/``): a raw ``jnp.*`` call
         feeding a loop-carried accumulation inside a Python for/while.
         Each iteration dispatches its own XLA program and the loop-
         carried value forces a dependency chain — use `lax.scan`/
         `lax.fori_loop`, or a jitted step function (the donated-buffer
         epoch pattern in nodes/learning).
  KJ002  numpy-inside-jit: a ``np.*``/``numpy.*`` *call* in the body of
         a ``jax.jit``-decorated function. NumPy calls on tracers either
         crash (TracerArrayConversionError) or silently constant-fold at
         trace time. Attribute reads (``np.float32``, ``np.pi``) are
         fine — only calls are flagged.
  KJ003  missing-donate (under ``nodes/learning/``): a jitted function
         named ``*_step``/``*_epoch``/``*_sweep`` — the solver-loop
         naming convention for steps that rebuild O(model)-sized state —
         must declare ``donate_argnums`` so XLA reuses the state buffers
         instead of allocating fresh HBM every iteration.
  KJ004  wall-clock-duration: a ``time.time()`` call inside
         ``keystone_tpu/``. Wall-clock is NTP-steppable and coarse;
         every duration measurement (profiler, telemetry spans, stall
         histograms) must use ``time.perf_counter()``. Genuine
         wall-clock timestamps (trace epoch anchors, file-mtime
         comparisons) suppress with the standard comment.
  KJ005  blocking-host-pull (under ``workflow/`` and ``nodes/``): a
         ``.block_until_ready()`` call, or ``np.asarray(...)`` over a
         device value (a ``jnp.*`` call result, or a dataset payload
         attribute ``.array``/``.data``), in a hot path. Both serialize
         the async dispatch queue — `block_until_ready` is additionally
         a NO-OP through the axon tunnel, so it doesn't even fence
         honestly. Pulls that must happen route through
         ``data.dataset.sync_pull`` (one-element transfer) or
         ``Dataset.sync()``; sanctioned drains (the overlap engine's
         in-order result pulls) carry the suppression comment.

  KJ006  fresh-jit-per-call (under ``workflow/`` and ``nodes/``):
         ``jax.jit`` applied to a freshly constructed closure or lambda
         inside a loop or per-call scope. jit caches by function-object
         identity, so each call constructs a new callable, misses the
         cache, and silently re-traces + recompiles — the exact compile
         tax the compile-bounded execution work (ISSUE 5) eliminates.
         Cache the jitted fn at module level, on the instance
         (``self.__dict__['_jitted']``), or in an explicit program
         cache keyed on structure (``nodes/util/fusion``).
  KJ007  scan-carry-realloc (under ``workflow/`` and ``nodes/``): a
         ``lax.scan``/``lax.fori_loop`` body that rebuilds a carried
         buffer with an allocating/copying jnp call (``concatenate``,
         ``stack``, ``pad``, ``tile``, ...) and no in-place update
         pattern. XLA donates the scan carry between trips ONLY when
         the body updates it in place (``lax.dynamic_update_slice``,
         ``.at[...].set``) — a grow/copy carry silently doubles
         O(model) state every trip, exactly what the megafused
         single-program apply path must never do. Scan-invariant model
         state belongs in the closure, not the carry.

  KJ008  hot-path-state-write (under ``workflow/`` and ``nodes/``): an
         assignment to ``self.*`` or a module global — or an in-place
         mutation of a module-level container — inside an operator's
         ``apply``/``apply_batch``/``_chunk_loop``. The concurrent DAG
         scheduler (PR 4, default on) may force two vertices
         simultaneously, making the write interleaving schedule-
         dependent (the KP511 race class, see
         ``keystone_tpu/analysis/effects.py`` for the graph-level
         pass). The ``self.__dict__[...]`` instance-memo idiom and
         module-level structure-keyed caches (``*CACHE*``/``*PENDING*``
         names) are sanctioned.

  KJ009  hard-coded-mesh-axis / bare-device-put: a bare ``"data"`` /
         ``"model"`` string literal used as a mesh axis name in a
         sharding construction or collective call under ``nodes/`` /
         ``workflow/`` (the canonical names live in
         ``parallel/mesh.py`` — import ``DATA_AXIS``/``MODEL_AXIS`` so
         a mesh relayout stays a one-place change), and — under
         ``parallel/`` / ``data/`` — ``jax.device_put`` without an
         explicit sharding/device argument (defaults to device 0,
         silently un-sharding whatever flows through a mesh hot path).

  KJ010  output-layout-leak (under ``workflow/`` and ``nodes/``): a
         ``jax.jit``/``pjit`` call passing ``in_shardings`` but
         omitting ``out_shardings``. Pinning only the input layout
         leaves the OUTPUT layout to XLA's partitioner — the caller
         gets whatever placement compilation happened to pick, and the
         next stage pays an unpriced reshard to recover the layout the
         plan expected (exactly the implicit boundary move KP601 lints
         and the sharding planner prices). A jit that constrains its
         inputs must say where its outputs land.

  KJ011  literal-precision-cast (under ``workflow/`` and ``nodes/``):
         a literal ``jnp.float32(...)`` / ``.astype(jnp.float32)`` /
         ``asarray(..., jnp.float32)`` inside a ``fuse()``,
         ``_chunk_loop``, or ``_build_program`` body. Fused-program
         code runs under the
         mixed-precision policy pass (analysis/precision.py): a pinned
         f32 cast — or an f32 scalar param, which jnp promotion
         silently widens a bf16 tensor against — re-promotes a halved
         boundary back to f32 and defeats the policy without any
         diagnostic. Match the input dtype
         (``jnp.asarray(c, x.dtype)``) instead; genuine kernel
         constraints (RFFT accepts only f32/f64, uint8 pixel decode)
         carry an explicit suppression.

  KJ012  dynamic-metric-name (under ``workflow/`` and ``nodes/``):
         ``telemetry.counter/gauge/histogram(...)`` called with a
         non-literal name (f-string, ``%``/``+`` formatting,
         ``.format()``, or a variable) in hot-path code. The metrics
         registry is process-wide and created-on-first-use: a name
         formatted per vertex/label/chunk mints a NEW counter per
         distinct value — unbounded cardinality that grows the
         registry (and every trace's embedded snapshot) for the life
         of the process. Use one literal name and carry the dimension
         in a span arg instead; the sanctioned low-cardinality case
         (per-process ``dispatch.*.p<i>`` accounting) lives in
         ``telemetry/instrument.py``, outside this rule's scope, and
         any genuine in-scope exception carries a suppression.

  KJ013  transpose-then-reshape (under ``workflow/`` and ``nodes/``): a
         ``.reshape(...)`` whose receiver (or ``jnp.reshape`` whose
         argument) contains a transpose — ``.T``/``.mT``,
         ``transpose(...)``, ``swapaxes``/``moveaxis`` — inside a
         ``fuse()``, ``_chunk_loop``, or ``_build_program`` body. A
         transpose feeding a reshape cannot stay a free layout
         relabeling: XLA must materialize the permuted buffer before
         re-flattening it, so the fused program pays a full
         write+read of the tensor that the roofline's boundary-bytes
         model (analysis/roofline.py) cannot see — the in-body twin of
         the KP802 movement-dominance lint. Reorder the computation
         (reshape first, or keep the axis order end-to-end); genuine
         layout contracts (kernel-required NHWC flips) carry a
         suppression with the rationale.

  KJ014  blocking-host-io (under ``workflow/`` and ``nodes/``):
         ``time.sleep(...)``, blocking file reads (``open(...)`` /
         ``Path.read_text/read_bytes``), or network calls
         (``urllib.request.urlopen``, ``requests.get/post/...``,
         ``socket.create_connection``) inside an operator hot-path
         method (``apply``/``apply_batch``/``_chunk_loop``/...). The
         KJ005 companion for non-device blocking: a host stall on the
         apply path gates EVERY request behind the full I/O latency,
         is invisible to the roofline's time model, and busts the
         KP903 serving latency bound without any static trace of why.
         Hoist the I/O to construction or fit time (weights, vocab
         files), or pre-load at the serving ingress; a genuinely
         per-request external lookup carries a suppression naming why
         it cannot be batched ahead of the request.

  KJ015  manual-chunk-knob (under ``workflow/`` and ``nodes/``): a
         direct ``.chunk_size`` config-attribute read or a
         ``KEYSTONE_CHUNK_SIZE`` environment read outside the
         sanctioned resolution sites. The chunk size is an OPTIMIZER
         decision since PR 15: the unified planner's chosen chunk
         flows through ``workflow.env.resolved_chunk_size`` into the
         host batcher (``utils/batching.py``) and the KP2xx/KP8xx
         models (``analysis/memory.resolve_chunk_rows``) from one
         place. A hot-path module reading the raw knob bypasses the
         planner's decision — the analyzer then models a chunking the
         runtime doesn't execute. Call ``resolved_chunk_size()`` (or
         take an explicit parameter) instead; the config definition
         site (``workflow/env.py``) is sanctioned by path.

  KJ016  pallas-call-outside-ops (everywhere except ``ops/``): a
         ``pl.pallas_call`` (or bare ``pallas_call``) invocation in a
         module outside ``keystone_tpu/ops/``. Kernels live in one
         place so the chain-kernel audit (scripts/lint.sh), the
         interpret-mode test oracles, the live-chip canary
         (scripts/kernel_live_check.py), and the
         ``KEYSTONE_CHAIN_KERNELS`` kill switch cover every kernel the
         runtime can dispatch. A pallas_call minted elsewhere dodges
         all four: no ``*_reference`` oracle, no canary record, no
         gate. Move the kernel into ``ops/`` (with its pure-jnp
         reference) and call the builder, or suppress with a rationale
         naming why this one cannot live there.

  KJ017  hard-coded-kernel-geometry (``ops/`` only): a literal VMEM
         byte budget (a ``<< 20`` MiB shift or a >=1 MiB integer
         constant) outside the one sanctioned definition site
         (``chain_kernels._VMEM_BUDGET``), or a literal leading
         block-row count baked into a ``pl.BlockSpec`` shape. The
         KP1003 static VMEM proof and `chain_feasible`'s runtime
         chooser share ONE working-set formula
         (``chain_kernels.chain_vmem_bytes`` /
         ``chain_block_rows``) precisely so the verifier's verdict
         and the dispatched geometry can never diverge; an inline
         byte cap or a pinned block size reintroduces a second,
         unverified arithmetic the static tier cannot see. Route the
         geometry through the shared chooser, or suppress with a
         rationale naming the kernel-specific working set.

  KJ018  trace-time-telemetry (under ``workflow/`` and ``nodes/``):
         a span or metric emission (``span(...)``, ``counter/gauge/
         histogram(...).inc/observe/...``) lexically inside a fused-
         program body — a ``fuse()``/``_chunk_loop`` body, or a
         nested closure of ``_build_program`` (its host prologue is
         build-time code; only the traced ``chunk_fn``/``per_shard``
         closures become program body). Those bodies execute at TRACE
         time: the emission fires once per compile, not once per run,
         so the recorded "latency" is trace-time, live percentile
         sketches ingest garbage, and re-runs of the warm program
         emit nothing at all. Instrument at the dispatch boundary
         (the executor / instrument layer) instead, or suppress with
         a rationale naming why the call is host-side.

  KJ019  unbounded-request-buffer (under ``serving/`` and
         ``workflow/``): a ``queue.Queue()`` (or LifoQueue/
         PriorityQueue) constructed with no maxsize — or a literal
         maxsize ≤ 0, which the stdlib treats as infinite — and, under
         ``serving/`` only, a ``SimpleQueue()`` (unbounded by
         construction) or a bare ``list.append`` onto a receiver named
         like a request buffer (queue/pending/requests/backlog/inbox/
         buffer). Every serving queue must be BOUNDED: a full queue is
         the load-shed signal (`serving.shed_total` + a flight dump),
         so an unbounded buffer silently converts overload into
         unbounded memory growth and unbounded queueing delay — the
         p99 dies long before the OOM does. Size the queue from
         ``execution_config().serving_queue_depth`` (the
         ``KEYSTONE_SERVING_QUEUE_DEPTH`` knob), or suppress with a
         rationale naming why the producer is statically bounded.

  KJ020  ooc-whole-dataset-drain (under ``data/`` and ``workflow/``): a
         whole-dataset materialization of an out-of-core source — a
         name bound from ``OutOfCoreDataset(...)``,
         ``SpilledDataset(...)``, or an ``out_of_core_*``/
         ``synthetic_out_of_core`` loader fed to ``np.asarray``/
         ``np.array``/``np.stack``/``np.concatenate`` or drained via
         ``list()``/``tuple()``. The entire point of the spill tier is
         bounded device residency through the windowed prefetcher
         (``window_iter()``/``map_windowed()``); an ad-hoc full drain
         reintroduces the dataset-sized allocation the planner promised
         away. The sanctioned full drains are the methods the classes
         themselves expose (``materialize()``/``rehydrate()``/
         ``numpy()``) at call sites that own that decision — suppress
         with a rationale when a full drain is genuinely intended.

Suppression: append ``# keystone: ignore[KJ001]`` (comma-separate for
several rules) to the flagged line, or to the ``def`` line for KJ003.

Usage: python scripts/jaxlint.py [--list-rules] [--json] [paths...]
Exit code 1 when findings remain. ``--json`` emits machine-readable
findings for CI annotation.
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from pathlib import Path
from typing import Iterator, List, NamedTuple, Optional, Set

RULES = {
    "KJ001": "raw jnp.* call in a Python-loop accumulation (use lax.scan "
             "or a jitted step fn)",
    "KJ002": "numpy call inside a jax.jit-decorated function",
    "KJ003": "jitted solver step mutating O(model) state lacks "
             "donate_argnums",
    "KJ004": "time.time() used where a duration is measured (use "
             "time.perf_counter())",
    "KJ005": "blocking host pull on a device value in a hot path "
             "(route through data.dataset.sync_pull / Dataset.sync)",
    "KJ006": "jax.jit of a freshly constructed closure/lambda in a loop "
             "or per-call scope (recompiles every call; cache the "
             "jitted fn)",
    "KJ007": "lax.scan/fori_loop carry rebuilt by an allocating jnp call "
             "with no in-place update (dynamic_update_slice / .at[].set) "
             "— the carry buffer reallocates O(model) state every trip",
    "KJ008": "state write in an operator hot path: assignment to self.* "
             "or a module global inside apply/apply_batch/_chunk_loop — "
             "the concurrent scheduler may force two such vertices "
             "simultaneously (use the self.__dict__ memo idiom or a "
             "structure-keyed cache)",
    "KJ009": "hard-coded mesh axis name ('data'/'model') in a sharding or "
             "collective call (use meshlib.DATA_AXIS/MODEL_AXIS), or a "
             "jax.device_put without an explicit sharding in a "
             "parallel-adjacent hot path (placement must be deliberate "
             "on a mesh)",
    "KJ010": "jax.jit/pjit with in_shardings but no out_shardings: the "
             "output layout leaks to XLA's partitioner and the caller "
             "re-shards downstream (declare out_shardings so the "
             "boundary layout is a decision, not an accident)",
    "KJ011": "literal float32 cast inside a fuse()/_chunk_loop body: a "
             "pinned jnp.float32/astype(jnp.float32) in fused-program "
             "code silently promotes bf16 boundaries back to f32 and "
             "defeats any precision policy (match the input dtype, or "
             "suppress with a kernel-constraint rationale)",
    "KJ012": "telemetry counter/gauge/histogram called with a "
             "dynamically formatted name in a hot path: the registry "
             "is process-wide and created-on-first-use, so a per-"
             "vertex/label name mints unbounded metric cardinality "
             "(use one literal name; carry the dimension in a span "
             "arg)",
    "KJ013": "transpose-then-reshape chain inside a fused-program body "
             "(fuse()/_chunk_loop/_build_program): the permuted buffer "
             "must materialize before the reshape, a full write+read "
             "the roofline's boundary-bytes model cannot see — reorder "
             "the computation or keep the axis order end-to-end",
    "KJ014": "blocking host I/O in an operator hot path: time.sleep, "
             "file reads (open/Path.read_*), or network calls "
             "(urllib/requests/socket) inside apply/apply_batch/"
             "_chunk_loop stall every request for the full host-call "
             "latency — the non-device twin of KJ005 (hoist the I/O to "
             "construction/fit time, or pre-load at ingress)",
    "KJ015": "manual chunk knob: a direct config .chunk_size read or a "
             "KEYSTONE_CHUNK_SIZE env read outside the sanctioned "
             "batcher/memory-model resolution sites bypasses the "
             "unified planner's chunk decision (read "
             "workflow.env.resolved_chunk_size() instead)",
    "KJ016": "pallas_call outside keystone_tpu/ops/: kernels live in "
             "one audited home so the chain-kernel audit, the "
             "interpret-mode oracles, the live-chip canary, and the "
             "KEYSTONE_CHAIN_KERNELS kill switch cover every kernel "
             "the runtime can dispatch — move the kernel (and its "
             "pure-jnp reference) into ops/ and call the builder",
    "KJ017": "hard-coded kernel geometry in ops/: a literal VMEM byte "
             "budget outside chain_kernels._VMEM_BUDGET, or a literal "
             "leading block-row count in a pl.BlockSpec shape — the "
             "static KP1003 proof and the runtime chooser share one "
             "formula (chain_vmem_bytes/chain_block_rows); inline "
             "byte caps and pinned block sizes dodge it",
    "KJ018": "span/metric emission inside a fused-program body "
             "(fuse()/_chunk_loop, or a _build_program closure): the "
             "body runs at trace time, so the emission records "
             "compile-time not run-time and corrupts live latency "
             "percentiles — instrument at the dispatch boundary",
    "KJ019": "unbounded request buffer in a serving hot path: a "
             "queue.Queue() with no (or a non-positive literal) "
             "maxsize, a SimpleQueue, or a bare list-append request "
             "buffer — a full BOUNDED queue is the load-shed signal; "
             "an unbounded one converts overload into unbounded "
             "memory and queueing delay (size it from "
             "serving_queue_depth)",
    "KJ020": "whole-dataset drain of an out-of-core source: an "
             "OutOfCoreDataset/SpilledDataset-bound name fed to "
             "np.asarray/np.array/np.stack/np.concatenate or "
             "list()/tuple() — stream it through "
             "window_iter()/map_windowed() (or call the class's own "
             "materialize()/rehydrate() where a full drain is the "
             "sanctioned decision)",
}

_IGNORE_RE = re.compile(r"#\s*keystone:\s*ignore\[([A-Z0-9,\s]+)\]")

#: numpy module aliases recognized in Attribute roots.
_NUMPY_NAMES = {"np", "numpy", "onp"}
_JNP_NAMES = {"jnp"}
#: names whose calls are harmless inside jit (dtype casts of constants).
_NUMPY_CALL_ALLOWLIST = {"dtype"}
#: jnp attrs that are scalar casts / wrappers, not compute — a loop that
#: only casts its chunk counters while accumulating through a *jitted*
#: step function is the approved donated-buffer pattern, not a smell.
_JNP_CAST_ALLOWLIST = {
    "asarray", "array", "int8", "int16", "int32", "int64", "uint8",
    "uint16", "uint32", "uint64", "float16", "float32", "float64",
    "bfloat16", "bool_", "dtype",
}
_STEP_NAME_RE = re.compile(r"_(step|epoch|sweep)$")


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _attr_root(node: ast.AST) -> Optional[str]:
    """Root name of an attribute chain: ``np.linalg.svd`` → ``np``."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _calls_rooted_at(
    tree: ast.AST, roots: Set[str], skip_attrs: Set[str] = frozenset()
) -> Iterator[ast.Call]:
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if _attr_root(sub.func) in roots \
                    and sub.func.attr not in skip_attrs:
                yield sub


def _names_loaded(tree: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(tree)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _jit_decorator(fn: ast.FunctionDef) -> Optional[ast.AST]:
    """The decorator node if ``fn`` is jitted: ``@jax.jit``, ``@jit``,
    ``@jax.jit(...)``, or ``@partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "jit":
            return dec
        if isinstance(target, ast.Attribute) and target.attr == "jit" \
                and _attr_root(target) == "jax":
            return dec
        if isinstance(dec, ast.Call) and isinstance(dec.func, ast.Name) \
                and dec.func.id == "partial" and dec.args:
            inner = dec.args[0]
            if isinstance(inner, ast.Attribute) and inner.attr == "jit" \
                    and _attr_root(inner) == "jax":
                return dec
            if isinstance(inner, ast.Name) and inner.id == "jit":
                return dec
    return None


def _decorator_kwargs(dec: ast.AST) -> Set[str]:
    if isinstance(dec, ast.Call):
        return {kw.arg for kw in dec.keywords if kw.arg}
    return set()


# ---------------------------------------------------------------- rules


def _check_loop_accumulation(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ001: inside for/while bodies, flag (a) augmented assignment
    whose value calls jnp directly, (b) ``x = f(x, ...jnp call...)``
    self-assignment with a direct jnp call, (c) ``list.append(<jnp
    call>)`` — all loop-carried per-iteration XLA dispatch patterns."""
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for stmt in loop.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.AugAssign):
                    if any(True for _ in _calls_rooted_at(sub.value, _JNP_NAMES, _JNP_CAST_ALLOWLIST)):
                        yield Finding(
                            path, sub.lineno, "KJ001",
                            "augmented assignment accumulates a jnp result "
                            "inside a Python loop")
                elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    t = sub.targets[0].id
                    if t in _names_loaded(sub.value) and any(
                            True for _ in _calls_rooted_at(sub.value, _JNP_NAMES, _JNP_CAST_ALLOWLIST)):
                        yield Finding(
                            path, sub.lineno, "KJ001",
                            f"`{t}` is rebuilt from itself with a raw jnp "
                            "call each iteration")
                elif isinstance(sub, ast.Expr) and isinstance(sub.value, ast.Call):
                    call = sub.value
                    if isinstance(call.func, ast.Attribute) \
                            and call.func.attr == "append" and call.args:
                        if any(True for _ in _calls_rooted_at(
                                call.args[0], _JNP_NAMES, _JNP_CAST_ALLOWLIST)):
                            yield Finding(
                                path, sub.lineno, "KJ001",
                                "appending a per-iteration jnp result; "
                                "each append dispatches its own program")


def _check_numpy_in_jit(tree: ast.AST, path: str) -> Iterator[Finding]:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _jit_decorator(fn) is None:
            continue
        for call in _calls_rooted_at(fn, _NUMPY_NAMES):
            func = call.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _NUMPY_CALL_ALLOWLIST:
                continue
            yield Finding(
                path, call.lineno, "KJ002",
                f"numpy call `{ast.unparse(func)}` inside jitted "
                f"`{fn.name}` — constant-folds at trace time or crashes "
                "on tracers")


def _check_wall_clock_duration(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ004: `time.time()` calls (module-attribute form, plus the bare
    `time()` form when the file does `from time import time`). Anything
    timing-shaped in keystone_tpu/ must use the monotonic
    `time.perf_counter()`; real wall-clock timestamps are rare enough to
    carry an explicit suppression."""
    bare_time_imported = any(
        isinstance(n, ast.ImportFrom) and n.module == "time"
        and any(a.name == "time" and (a.asname or a.name) == "time"
                for a in n.names)
        for n in ast.walk(tree)
    )
    for sub in ast.walk(tree):
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        hit = (
            isinstance(func, ast.Attribute) and func.attr == "time"
            and isinstance(func.value, ast.Name) and func.value.id == "time"
        ) or (
            bare_time_imported
            and isinstance(func, ast.Name) and func.id == "time"
        )
        if hit:
            yield Finding(
                path, sub.lineno, "KJ004",
                "time.time() is wall-clock (steppable, coarse); durations "
                "must use time.perf_counter()")


#: dataset-payload attribute names whose np.asarray() is a device pull.
_DEVICE_PAYLOAD_ATTRS = {"array", "data"}


def _check_blocking_host_pull(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ005: `.block_until_ready()` anywhere (it serializes dispatch
    and is a no-op through the axon tunnel), and `np.asarray(...)` whose
    argument is provably device-resident — a direct ``jnp.*`` call
    result or a dataset payload attribute (``.array`` / ``.data``).
    Heuristic by design: a plain ``np.asarray(x)`` over host items stays
    legal, while the two patterns that reliably mean "pull a device
    value mid-pipeline" are flagged."""

    def _device_arg(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                    and _attr_root(sub.func) in _JNP_NAMES:
                return True
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in _DEVICE_PAYLOAD_ATTRS \
                    and isinstance(sub.ctx, ast.Load):
                return True
        return False

    for sub in ast.walk(tree):
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        if isinstance(func, ast.Attribute) and func.attr == "block_until_ready":
            yield Finding(
                path, sub.lineno, "KJ005",
                "block_until_ready() serializes async dispatch and is a "
                "no-op through the axon tunnel; fence with "
                "data.dataset.sync_pull / Dataset.sync() instead")
        elif isinstance(func, ast.Attribute) and func.attr == "asarray" \
                and _attr_root(func) in _NUMPY_NAMES and sub.args \
                and _device_arg(sub.args[0]):
            yield Finding(
                path, sub.lineno, "KJ005",
                "np.asarray over a device value blocks the dispatch "
                "queue mid-pipeline; pull through data.dataset.sync_pull "
                "or defer to the overlap engine's in-order drain")


def _is_jit_call(func: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` as a CALL (decorators live in
    decorator_list and are evaluated once at def time — not flagged)."""
    if isinstance(func, ast.Name):
        return func.id == "jit"
    return (isinstance(func, ast.Attribute) and func.attr == "jit"
            and _attr_root(func) == "jax")


def _check_fresh_jit(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ006: jit caches compiled executables by FUNCTION OBJECT
    identity, so ``jax.jit`` over a freshly constructed callable — a
    lambda, or a function defined in the same (per-call) scope — misses
    that cache on every call and silently re-traces + recompiles each
    time. Two patterns are flagged in ``workflow/``/``nodes/``:

      (a) any ``jax.jit(...)`` call inside a ``for``/``while`` body —
          one compile per iteration, the worst case;
      (b) ``jax.jit(<lambda or same-scope def>)`` inside a function
          body — one compile per CALL of the enclosing function.

    The sanctioned fixes are module-level jits, instance-memoized jits
    (the ``self.__dict__['_jitted']`` idiom — its argument is a call
    expression, so it is not flagged), or an explicit program cache
    (``nodes/util/fusion._PROGRAM_CACHE``, which suppresses)."""
    # (a) jit calls under a loop
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call) and _is_jit_call(sub.func):
                yield Finding(
                    path, sub.lineno, "KJ006",
                    "jax.jit inside a loop body compiles a fresh program "
                    "every iteration; hoist and cache the jitted fn")

    # (b) jit of a lambda / same-scope def inside a function body
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local_fns: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not fn:
                local_fns.add(sub.name)
            elif isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Lambda):
                local_fns.update(
                    t.id for t in sub.targets if isinstance(t, ast.Name))
        # one aliasing hop: `g = local_def; ... jax.jit(g)`
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in local_fns:
                local_fns.update(
                    t.id for t in sub.targets if isinstance(t, ast.Name))
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call) and _is_jit_call(call.func)
                    and call.args):
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Lambda) or (
                    isinstance(arg, ast.Name) and arg.id in local_fns):
                name = ("lambda" if isinstance(arg, ast.Lambda)
                        else arg.id)
                yield Finding(
                    path, call.lineno, "KJ006",
                    f"jax.jit over per-call-scope callable `{name}` in "
                    f"`{fn.name}` recompiles on every call; cache the "
                    "jitted fn (module level, instance memo, or an "
                    "explicit program cache)")


#: jnp calls that ALLOCATE a fresh (usually grown or copied) buffer —
#: a carry rebuilt through one of these reallocates every scan trip.
_CARRY_ALLOC_CALLS = {
    "concatenate", "stack", "vstack", "hstack", "dstack", "append",
    "pad", "tile", "repeat", "copy",
}
#: in-place carry-update spellings that let XLA donate the carry buffer
#: between trips.
_INPLACE_UPDATE_ATTRS = {
    "dynamic_update_slice", "dynamic_update_index_in_dim", "set", "add",
}


def _scope_walk(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk a scope's own statements WITHOUT descending into nested
    function/lambda bodies (the nested defs themselves are yielded, so
    callers can collect them as this scope's local names)."""
    stack = (list(scope.body)
             if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module))
             else list(ast.iter_child_nodes(scope)))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _scan_bodies(tree: ast.AST) -> Iterator:
    """Yield ``(call_node, body_fn_node, carry_param_index)`` for every
    ``lax.scan(body, ...)`` / ``lax.fori_loop(lo, hi, body, init)`` call
    whose body resolves to a lambda or a ``def``/lambda bound in the
    call's own scope (nearest-scope resolution — two solver steps may
    both name their body ``body``)."""
    scopes = [tree] + [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        own = list(_scope_walk(scope))
        defs = {n.name: n for n in own if isinstance(n, ast.FunctionDef)}
        lambdas = {}
        for n in own:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Lambda):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        lambdas[t.id] = n.value
        for call in own:
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)):
                continue
            root = _attr_root(call.func)
            attr = call.func.attr
            if attr == "scan" and root in {"lax", "jax"}:
                body_arg, carry_idx = (
                    call.args[0] if call.args else None), 0
            elif attr == "fori_loop" and root in {"lax", "jax"}:
                body_arg, carry_idx = (
                    call.args[2] if len(call.args) > 2 else None), 1
            else:
                continue
            if isinstance(body_arg, ast.Lambda):
                yield call, body_arg, carry_idx
            elif isinstance(body_arg, ast.Name):
                fn = defs.get(body_arg.id) or lambdas.get(body_arg.id)
                if fn is not None:
                    yield call, fn, carry_idx


def _check_scan_carry_realloc(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ007: a scan/fori body whose carried value is rebuilt through an
    allocating jnp call (``jnp.concatenate(carry, ...)`` and friends)
    with no in-place update pattern anywhere in the body. XLA only
    reuses the carry buffer across trips when the body writes it in
    place; a grow/copy carry allocates a fresh O(carry) buffer per trip
    — O(model) state silently doubled inside the one program the
    megafused apply path is supposed to be."""
    for call, body, carry_idx in _scan_bodies(tree):
        # carry names: the carry parameter itself plus one unpacking hop
        # (`a, b = carry` — the solver idiom)
        args = body.args.args
        if len(args) <= carry_idx:
            continue
        carry_names = {args[carry_idx].arg}
        body_stmts = (body.body if isinstance(body.body, list)
                      else [ast.Expr(body.body)])
        for sub in ast.walk(ast.Module(body=body_stmts, type_ignores=[])):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in carry_names:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        carry_names.add(t.id)
                    elif isinstance(t, ast.Tuple):
                        carry_names.update(
                            e.id for e in t.elts if isinstance(e, ast.Name))

        has_inplace = False
        offender = None
        for sub in ast.walk(ast.Module(body=body_stmts, type_ignores=[])):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in _INPLACE_UPDATE_ATTRS:
                has_inplace = True
            elif isinstance(func, ast.Attribute) \
                    and func.attr in _CARRY_ALLOC_CALLS \
                    and _attr_root(func) in _JNP_NAMES:
                touched = {
                    n.id for n in ast.walk(sub)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                }
                if touched & carry_names and offender is None:
                    offender = (sub.lineno, func.attr)
        if offender is not None and not has_inplace:
            line, name = offender
            yield Finding(
                path, line, "KJ007",
                f"scan/fori_loop carry rebuilt via jnp.{name} every trip "
                "with no in-place update; use lax.dynamic_update_slice / "
                ".at[].set so XLA donates the carry buffer (scan-invariant "
                "model state belongs in the closure, not the carry)")


#: operator methods the concurrent scheduler may run simultaneously
#: across vertices — writes to shared state inside them are races.
#: Kept in lockstep with `analysis/effects.py`'s HOT_METHODS (the
#: graph-level KP511 pass over the same discipline).
_HOT_PATH_METHODS = {
    "apply", "apply_batch", "apply_batch_stream", "single_transform",
    "batch_transform", "batch_transform_stream", "batch_fn", "fuse",
    "_chunk_loop",
}
#: in-place container mutators.
_MUTATOR_CALLS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear",
}
#: module-level names matching the sanctioned structure-keyed cache
#: idiom (program caches, pending-future registries, locks).
_SANCTIONED_GLOBAL_RE = re.compile(r"(CACHE|PENDING|LOCK|REGISTRY)", re.I)


def _chain_root(node: ast.AST) -> ast.AST:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


def _is_self_dict(node: ast.AST) -> bool:
    """``self.__dict__`` — the sanctioned instance-memo root."""
    return (isinstance(node, ast.Attribute) and node.attr == "__dict__"
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _is_self_dict_chain(node: ast.AST) -> bool:
    """``self.__dict__`` or ``self.__dict__[...]`` — a mutator call on
    either (``self.__dict__.setdefault``, ``self.__dict__['k'].append``)
    is the sanctioned memo idiom, not shared-state mutation."""
    if _is_self_dict(node):
        return True
    return isinstance(node, ast.Subscript) and _is_self_dict(node.value)


def _check_hot_path_state_write(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ008: apply-time state writes under ``nodes/``/``workflow/`` —
    assignment to ``self.*`` or to a declared ``global``, and in-place
    mutation (subscript assignment or a mutator-method call) of a
    module-level container, inside an operator's hot-path methods
    (``apply``/``apply_batch``/``_chunk_loop``). The concurrent DAG
    scheduler (default on) may force two vertices simultaneously, so
    any such write is schedule-dependent — the KP511 race class,
    policed here at the file level with zero imports. Sanctioned:
    the ``self.__dict__[...]`` instance-memo idiom and module-level
    structure-keyed caches (``*CACHE*``/``*PENDING*``/``*LOCK*``)."""
    module_names = {
        t.id
        for stmt in (tree.body if isinstance(tree, ast.Module) else [])
        if isinstance(stmt, (ast.Assign, ast.AnnAssign))
        for t in (stmt.targets if isinstance(stmt, ast.Assign)
                  else [stmt.target])
        if isinstance(t, ast.Name)
    }

    def flagged_global(name: str) -> bool:
        return name in module_names and not _SANCTIONED_GLOBAL_RE.search(name)

    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name not in _HOT_PATH_METHODS:
                continue
            declared_globals: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Global):
                    declared_globals.update(sub.names)
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for t in targets:
                        elts = t.elts if isinstance(t, ast.Tuple) else [t]
                        for e in elts:
                            root = _chain_root(e)
                            if isinstance(e, ast.Name) \
                                    and e.id in declared_globals:
                                yield Finding(
                                    path, sub.lineno, "KJ008",
                                    f"`{fn.name}` writes module global "
                                    f"`{e.id}`; two concurrently forced "
                                    "vertices would race on it")
                            elif isinstance(root, ast.Name) \
                                    and root.id == "self":
                                if isinstance(e, ast.Subscript) \
                                        and _is_self_dict(e.value):
                                    continue  # sanctioned memo idiom
                                yield Finding(
                                    path, sub.lineno, "KJ008",
                                    f"`{fn.name}` assigns instance state "
                                    f"`self.{_attr_name(e)}` at apply "
                                    "time; shared instances race under "
                                    "the concurrent scheduler (memoize "
                                    "via self.__dict__[...] instead)")
                            elif isinstance(e, (ast.Subscript, ast.Attribute)) \
                                    and isinstance(root, ast.Name) \
                                    and flagged_global(root.id):
                                yield Finding(
                                    path, sub.lineno, "KJ008",
                                    f"`{fn.name}` mutates module-level "
                                    f"container `{root.id}` at apply time")
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _MUTATOR_CALLS \
                        and not _is_self_dict_chain(sub.func.value):
                    root = _chain_root(sub.func.value)
                    if isinstance(root, ast.Name) and flagged_global(root.id):
                        yield Finding(
                            path, sub.lineno, "KJ008",
                            f"`{fn.name}` calls `{root.id}."
                            f"{sub.func.attr}(...)` on a module-level "
                            "container at apply time")
                    elif isinstance(root, ast.Name) and root.id == "self" \
                            and isinstance(sub.func.value,
                                           (ast.Attribute, ast.Subscript)):
                        # self.attr.append(...) mutates shared instance
                        # state exactly like self.attr[k] = v does; a
                        # direct self.add(...) METHOD call is not a
                        # container mutation (the receiver must be an
                        # attribute/subscript chain, as in effects.py)
                        yield Finding(
                            path, sub.lineno, "KJ008",
                            f"`{fn.name}` calls `self."
                            f"{_attr_name(sub.func.value)}."
                            f"{sub.func.attr}(...)` at apply time; "
                            "shared instances race under the concurrent "
                            "scheduler (memoize via self.__dict__[...] "
                            "instead)")


#: the library's two mesh axis names — the canonical constants live in
#: parallel/mesh.py (DATA_AXIS/MODEL_AXIS); everything else must import
#: them, so a mesh rename (or a 3-axis pod layout) is a one-line change.
_MESH_AXIS_LITERALS = {"data", "model"}
#: call names whose arguments are axis names / partition specs.
_SHARDING_CALL_NAMES = {
    "P", "PartitionSpec", "NamedSharding", "Mesh", "make_mesh",
}
#: collective ops taking a positional axis-name argument.
_COLLECTIVE_ATTRS = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "psum_scatter", "axis_index", "ppermute", "pshuffle",
}
#: kwarg names that carry mesh axis names.
_AXIS_KWARGS = {"axis", "axis_name", "axis_names"}


def _call_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _axis_literals_in(node: ast.AST) -> Iterator[ast.Constant]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and sub.value in _MESH_AXIS_LITERALS:
            yield sub


def _check_axis_literals(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ009 (axis-literal half, under ``nodes/``/``workflow/``): a bare
    ``"data"``/``"model"`` string in a sharding construction
    (`P`/`PartitionSpec`/`NamedSharding`/`Mesh`), a collective call's
    axis argument (`lax.psum(x, "data")`), an ``axis=``/``axis_name(s)=``
    kwarg, or a ``mesh.shape.get("data")`` lookup. Axis names are mesh
    *configuration*: hard-coding them in node/workflow code silently
    desynchronizes from `parallel.mesh.DATA_AXIS`/`MODEL_AXIS` the day
    the mesh layout changes. Plain string data (NLP word lists, dict
    keys) never matches — only these call contexts are inspected."""
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        name = _call_name(call.func)
        contexts: List[ast.AST] = []
        if name in _SHARDING_CALL_NAMES or name in _COLLECTIVE_ATTRS:
            contexts.extend(call.args)
        if name == "get" and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Attribute) \
                and call.func.value.attr == "shape":
            contexts.extend(call.args)
        for kw in call.keywords:
            if kw.arg in _AXIS_KWARGS:
                contexts.append(kw.value)
        seen_lines = set()
        for ctx in contexts:
            for lit in _axis_literals_in(ctx):
                if lit.lineno in seen_lines:
                    continue
                seen_lines.add(lit.lineno)
                yield Finding(
                    path, lit.lineno, "KJ009",
                    f"hard-coded mesh axis name {lit.value!r} in "
                    f"`{name}(...)`; import meshlib.DATA_AXIS/MODEL_AXIS "
                    "so the axis layout stays a one-place decision")


def _check_bare_device_put(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ009 (device_put half, under ``parallel/``/``data/``): a
    ``jax.device_put(x)`` with no sharding/device argument in the layers
    that own placement. The default placement is device 0 — on a mesh
    that silently un-shards (and un-overlaps) whatever flows through;
    placement decisions in the parallel-adjacent hot paths must be
    explicit (`NamedSharding`, `leaf_sharding`, `mesh` helpers)."""
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        is_dput = (
            isinstance(func, ast.Attribute) and func.attr == "device_put"
            and _attr_root(func) == "jax"
        ) or (isinstance(func, ast.Name) and func.id == "device_put")
        if not is_dput:
            continue
        if len(call.args) >= 2 or any(
                kw.arg in {"device", "sharding", "dst_sharding"} or
                kw.arg is None
                for kw in call.keywords):
            continue
        yield Finding(
            path, call.lineno, "KJ009",
            "jax.device_put without an explicit sharding defaults to "
            "device 0; parallel-layer placements must name their "
            "sharding (NamedSharding / data.dataset.leaf_sharding)")


def _check_output_layout_leak(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ010 (under ``workflow/``/``nodes/``): a ``jax.jit``/``pjit``
    call with an ``in_shardings=`` keyword but no ``out_shardings=``.
    Half-constrained jits hand the output layout to XLA's partitioner:
    whatever placement compilation picks, the caller inherits — and the
    next stage boundary pays an implicit reshard to get back to the
    layout the plan expected. A call deliberate enough to pin its input
    layout must pin (or explicitly delegate) its output layout too."""
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in {"jit", "pjit"}:
            continue
        kwargs = {kw.arg for kw in call.keywords}
        if "in_shardings" in kwargs and "out_shardings" not in kwargs:
            yield Finding(
                path, call.lineno, "KJ010",
                f"`{name}(...)` passes in_shardings but no out_shardings; "
                "the output layout leaks to XLA's partitioner and "
                "downstream consumers re-shard implicitly — declare "
                "out_shardings")


def _is_f32_literal(node: ast.AST) -> bool:
    """`jnp.float32` / `np.float32` attribute, bare `float32`, or the
    string constant "float32"."""
    if isinstance(node, ast.Attribute) and node.attr == "float32" \
            and isinstance(node.value, ast.Name) \
            and node.value.id in (_NUMPY_NAMES | _JNP_NAMES):
        return True
    if isinstance(node, ast.Name) and node.id == "float32":
        return True
    return isinstance(node, ast.Constant) and node.value == "float32"


def _check_literal_precision_cast(tree: ast.AST, path: str
                                  ) -> Iterator[Finding]:
    """KJ011 (under ``workflow/``/``nodes/``): literal f32 casts inside
    ``fuse()`` / ``_chunk_loop`` bodies — the code that becomes part of
    a fused XLA program. Three forms: ``x.astype(jnp.float32)``,
    a direct ``jnp.float32(...)`` call (an f32 scalar param silently
    promotes a bf16 tensor), and ``asarray(..., jnp.float32)`` /
    ``dtype=jnp.float32`` call arguments. ``_build_program`` counts as
    a fused body too — its nested chunk_fn/per_shard closures are
    traced into the same XLA program the planner tags. Dtype literals
    OUTSIDE fused bodies (loaders, abstract_eval specs, host decode
    paths) are not this rule's business."""
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef) \
                or fn.name not in {"fuse", "_chunk_loop", "_build_program"}:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Attribute) and func.attr == "astype" \
                    and sub.args and _is_f32_literal(sub.args[0]):
                yield Finding(
                    path, sub.lineno, "KJ011",
                    "literal .astype(float32) in a fused-program body "
                    "defeats the precision policy; cast to the input's "
                    "dtype instead")
                continue
            if _is_f32_literal(func):
                yield Finding(
                    path, sub.lineno, "KJ011",
                    "literal float32(...) scalar in a fused-program "
                    "body: jnp promotion widens bf16 tensors against "
                    "f32 scalars — build the scalar from the input "
                    "dtype instead")
                continue
            literal_args = [a for a in sub.args if _is_f32_literal(a)]
            literal_kwargs = [kw for kw in sub.keywords
                              if kw.arg == "dtype"
                              and _is_f32_literal(kw.value)]
            if literal_args or literal_kwargs:
                name = _call_name(func) or "?"
                line = (literal_args[0].lineno if literal_args
                        else literal_kwargs[0].value.lineno)
                yield Finding(
                    path, line, "KJ011",
                    f"literal float32 dtype in `{name}(...)` inside a "
                    "fused-program body defeats the precision policy; "
                    "derive the dtype from the input instead")


#: attribute spellings that mean "transpose" on an array expression.
_TRANSPOSE_ATTRS = {"T", "mT"}
#: call names that permute axes (method or jnp.* form).
_TRANSPOSE_CALLS = {"transpose", "swapaxes", "moveaxis", "permute_dims"}


def _contains_transpose(node: ast.AST) -> Optional[int]:
    """Line number of a transpose buried in an expression — a ``.T`` /
    ``.mT`` attribute read, or a ``transpose``/``swapaxes``/
    ``moveaxis`` call — or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _TRANSPOSE_ATTRS \
                and isinstance(sub.ctx, ast.Load):
            return sub.lineno
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _TRANSPOSE_CALLS:
            return sub.lineno
    return None


def _check_transpose_reshape(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ013 (under ``workflow/``/``nodes/``): a transpose-then-reshape
    chain inside a ``fuse()`` / ``_chunk_loop`` / ``_build_program``
    body — the code that becomes part of a fused XLA program. Two
    spellings are matched: ``<expr with transpose>.reshape(...)``
    (method chain, ``x.T.reshape(...)`` included) and
    ``jnp.reshape(<expr with transpose>, ...)``. A reshape over a
    permuted view forces the permuted buffer to materialize — a full
    write+read of the tensor invisible to the roofline's boundary
    bytes; the stage shows up as KP802 movement dominance at the graph
    level, and here at the file level with zero imports."""
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef) \
                or fn.name not in {"fuse", "_chunk_loop", "_build_program"}:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Attribute) and func.attr == "reshape":
                root = _attr_root(func)
                if root in _JNP_NAMES:
                    target = sub.args[0] if sub.args else None
                else:
                    target = func.value
                if target is not None and _contains_transpose(target):
                    yield Finding(
                        path, sub.lineno, "KJ013",
                        "transpose-then-reshape in a fused-program body: "
                        "the permuted buffer materializes before the "
                        "reshape (a full write+read the roofline's "
                        "boundary-bytes model cannot see); reorder the "
                        "computation or keep the axis order end-to-end")


#: the telemetry metric factories whose name argument KJ012 audits
#: (alias-tolerant: ``from ..telemetry import counter as _counter`` is
#: still the same registry entry point).
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _check_dynamic_metric_name(tree: ast.AST, path: str
                               ) -> Iterator[Finding]:
    """KJ012 (under ``workflow/``/``nodes/``): a
    ``counter/gauge/histogram`` call whose metric name is not a string
    literal. The registry is process-wide and created-on-first-use: a
    name formatted from a vertex id, label, or chunk index mints a new
    metric per distinct value — unbounded cardinality that grows the
    registry (and every trace's embedded metrics snapshot) for the
    life of the process. Both the module-level factories and
    registry/attribute forms (``telemetry.counter``,
    ``registry().gauge``) are matched; leading-underscore import
    aliases too. The attribute form is matched only on telemetry
    receivers (``telemetry.*`` / ``metrics.*`` modules, ``registry()``
    calls) so numeric APIs sharing a name — ``np.histogram``,
    ``jnp.histogram`` — never false-positive. A literal first argument
    (or ``name=`` literal) is the pass condition — constant-folding of
    f-strings is deliberately NOT attempted: an f-string with no
    placeholders is still a smell worth normalizing."""
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        if isinstance(func, ast.Name):
            fname = func.id
        elif isinstance(func, ast.Attribute):
            fname = func.attr
            # the receiver must be the telemetry layer: a module whose
            # dotted name ends in telemetry/metrics, or a registry()
            # call — np.histogram / jnp.histogram are not metrics
            recv = func.value
            if isinstance(recv, ast.Call):
                rf = recv.func
                rname = (rf.id if isinstance(rf, ast.Name)
                         else rf.attr if isinstance(rf, ast.Attribute)
                         else "")
                if rname.lstrip("_") != "registry":
                    continue
            else:
                last = (recv.attr if isinstance(recv, ast.Attribute)
                        else recv.id if isinstance(recv, ast.Name)
                        else "")
                if last.lstrip("_") not in ("telemetry", "metrics"):
                    continue
        else:
            continue
        if fname.lstrip("_") not in _METRIC_FACTORIES:
            continue
        arg = call.args[0] if call.args else None
        if arg is None:
            for kw in call.keywords:
                if kw.arg == "name":
                    arg = kw.value
                    break
        if arg is None:
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            continue
        yield Finding(
            path, call.lineno, "KJ012",
            f"`{fname}(...)` with a dynamically formatted metric name "
            "in a hot path: per-value names mint unbounded registry "
            "cardinality — use one literal name and carry the "
            "dimension in a span arg")


def _kj018_emission_name(call: ast.Call):
    """The telemetry emission a call expresses — ``span``, a metric
    factory (``counter``/``gauge``/``histogram``), or a tracer
    ``counter_sample`` — or None. Attribute forms require a telemetry
    receiver (``telemetry.*`` / ``metrics.*`` / ``spans.*`` modules, a
    ``registry()``/``current_tracer()`` call, or a ``tracer`` object)
    so unrelated APIs sharing a name never false-positive."""
    func = call.func
    if isinstance(func, ast.Name):
        base = func.id.lstrip("_")
        if base == "span" or base in _METRIC_FACTORIES:
            return base
        return None
    if isinstance(func, ast.Attribute):
        base = func.attr.lstrip("_")
        if base != "span" and base != "counter_sample" \
                and base not in _METRIC_FACTORIES:
            return None
        recv = func.value
        if isinstance(recv, ast.Call):
            rf = recv.func
            rname = (rf.id if isinstance(rf, ast.Name)
                     else rf.attr if isinstance(rf, ast.Attribute)
                     else "")
            if rname.lstrip("_") in ("registry", "current_tracer"):
                return base
            return None
        last = (recv.attr if isinstance(recv, ast.Attribute)
                else recv.id if isinstance(recv, ast.Name)
                else "")
        if last.lstrip("_") in ("telemetry", "metrics", "spans", "tracer"):
            return base
    return None


def _check_trace_time_telemetry(tree: ast.AST, path: str
                                ) -> Iterator[Finding]:
    """KJ018 (under ``workflow/``/``nodes/``): a span or metric
    emission lexically inside a fused-program body. ``fuse()`` and
    ``_chunk_loop`` bodies are traced wholesale; ``_build_program`` is
    different — its top level is host build code (a build-time counter
    there is legitimate), but its nested ``chunk_fn``/``per_shard``
    closures ARE the traced program body, so only nested defs/lambdas
    are scanned there. An emission in traced code fires once per
    COMPILE, not once per run: the recorded latency is trace-time, the
    live percentile sketches ingest garbage, and warm re-runs emit
    nothing — the non-obvious twin of KJ002's numpy-under-jit."""
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if fn.name in ("fuse", "_chunk_loop"):
            scopes = [fn]
        elif fn.name == "_build_program":
            scopes = [n for n in ast.walk(fn)
                      if isinstance(n, (ast.FunctionDef, ast.Lambda))
                      and n is not fn]
        else:
            continue
        for scope in scopes:
            for sub in ast.walk(scope):
                if not isinstance(sub, ast.Call):
                    continue
                name = _kj018_emission_name(sub)
                if name:
                    yield Finding(
                        path, sub.lineno, "KJ018",
                        f"`{name}(...)` inside a fused-program body "
                        "executes at trace time, not per run — the "
                        "emission records compile-time and corrupts "
                        "live percentiles; instrument at the dispatch "
                        "boundary instead")


def _attr_name(node: ast.AST) -> str:
    names = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            names.append(node.attr)
        node = node.value
    return names[-1] if names else "?"


_BOUNDED_QUEUE_CLASSES = {"Queue", "LifoQueue", "PriorityQueue"}
#: receiver names that mark a list as a request buffer (KJ019): the
#: serving vocabulary for "work waiting to be dispatched".
_REQUEST_BUFFER_RE = re.compile(
    r"(queue|pending|request|backlog|inbox|buffer)s?$", re.IGNORECASE)


def _kj019_queue_call(call: ast.Call) -> Optional[str]:
    """The queue class name when ``call`` constructs a stdlib queue
    (``queue.Queue(...)`` or a bare imported ``Queue(...)``), else
    None. Receiver-filtered like KJ012: ``multiprocessing.Queue`` et
    al. resolve through the same names, which is fine — the bounding
    discipline is identical."""
    func = call.func
    if isinstance(func, ast.Name):
        name = func.id
    elif isinstance(func, ast.Attribute) and isinstance(func.value,
                                                        ast.Name):
        name = func.attr
    else:
        return None
    if name in _BOUNDED_QUEUE_CLASSES or name == "SimpleQueue":
        return name
    return None


def _kj019_unbounded(call: ast.Call) -> bool:
    """Is this bounded-capable queue construction provably unbounded?
    No maxsize argument at all, or a literal maxsize ≤ 0 (the stdlib's
    'infinite' spelling). A non-literal maxsize expression is accepted
    — the capacity is a decision, which is all the rule demands."""
    args = list(call.args)
    maxsize: Optional[ast.AST] = args[0] if args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            maxsize = kw.value
        elif kw.arg is None:
            return False  # **kwargs splat: cannot prove
    if maxsize is None:
        return True
    if isinstance(maxsize, ast.Constant) and isinstance(
            maxsize.value, (int, float)):
        return maxsize.value <= 0
    if isinstance(maxsize, ast.UnaryOp) and isinstance(maxsize.op,
                                                       ast.USub):
        return True  # a negative literal, however spelled
    return False


def _check_unbounded_request_buffer(tree: ast.AST, path: str,
                                    serving: bool) -> Iterator[Finding]:
    """KJ019: unbounded ``queue.Queue()`` constructions (serving/ and
    workflow/), plus — under serving/ only — ``SimpleQueue()`` and bare
    list-appends onto request-buffer-named receivers. The load-shed
    discipline: a serving queue must be able to say no."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            cls = _kj019_queue_call(node)
            if cls == "SimpleQueue":
                if serving:
                    yield Finding(
                        path, node.lineno, "KJ019",
                        "`SimpleQueue()` is unbounded by construction "
                        "— a serving queue must be bounded so a full "
                        "queue sheds (use queue.Queue(maxsize=execution"
                        "_config().serving_queue_depth))")
                continue
            if cls is not None and _kj019_unbounded(node):
                yield Finding(
                    path, node.lineno, "KJ019",
                    f"`{cls}()` without a positive maxsize is an "
                    "unbounded request buffer — overload becomes "
                    "unbounded memory and queueing delay instead of a "
                    "shed; size it (serving_queue_depth is the "
                    "sanctioned knob)")
            continue
        if not serving:
            continue
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "append"):
            recv = node.value.func.value
            recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name)
                         else None)
            if recv_name and _REQUEST_BUFFER_RE.search(
                    recv_name.lstrip("_")):
                yield Finding(
                    path, node.lineno, "KJ019",
                    f"bare list-append onto `{recv_name}` grows a "
                    "request buffer without bound — route requests "
                    "through a bounded queue.Queue so overload sheds "
                    "instead of accumulating")


def _check_missing_donate(tree: ast.AST, path: str) -> Iterator[Finding]:
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if not _STEP_NAME_RE.search(fn.name):
            continue
        dec = _jit_decorator(fn)
        if dec is None:
            continue
        if "donate_argnums" not in _decorator_kwargs(dec):
            yield Finding(
                path, fn.lineno, "KJ003",
                f"jitted solver step `{fn.name}` has no donate_argnums; "
                "its state buffers reallocate every iteration")


#: call receivers whose attribute calls block on the network.
_NETWORK_RECEIVERS = {"urllib", "requests", "socket", "http", "httplib"}
#: attribute names that read/block regardless of receiver spelling
#: (urllib.request.urlopen, socket.create_connection).
_BLOCKING_ATTRS = {"urlopen", "create_connection", "getaddrinfo"}
#: Path read methods — Path(...).read_text() in a hot method is file
#: I/O just like open().read().
_PATH_READ_ATTRS = {"read_text", "read_bytes"}


def _check_blocking_host_io(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ014 (under ``workflow/``/``nodes/``): blocking host I/O inside
    an operator hot-path method — ``time.sleep``, ``open(...)`` /
    ``Path.read_*`` file reads, or urllib/requests/socket network
    calls. The non-device companion of KJ005's blocking-host-pull rule:
    a sleep or synchronous read on the apply path stalls every request
    for the full host-call latency, invisibly to the roofline time
    model that prices the KP903 serving bound."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name not in _HOT_PATH_METHODS:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                func = sub.func
                offense = None
                if isinstance(func, ast.Name):
                    if func.id == "open":
                        offense = "`open(...)` file I/O"
                    elif func.id in ("urlopen", "sleep"):
                        offense = f"`{func.id}(...)`"
                elif isinstance(func, ast.Attribute):
                    root = _chain_root(func)
                    root_id = root.id if isinstance(root, ast.Name) else ""
                    if func.attr == "sleep" and root_id == "time":
                        offense = "`time.sleep(...)`"
                    elif func.attr in _BLOCKING_ATTRS:
                        offense = f"`{root_id or '...'}.{func.attr}(...)`"
                    elif root_id in _NETWORK_RECEIVERS:
                        offense = f"`{root_id}.{func.attr}(...)` network call"
                    elif func.attr in _PATH_READ_ATTRS:
                        offense = f"`.{func.attr}()` file read"
                    elif func.attr == "read" and isinstance(
                            func.value, ast.Call) and isinstance(
                            func.value.func, ast.Name) \
                            and func.value.func.id == "open":
                        offense = "`open(...).read()`"
                if offense is not None:
                    yield Finding(
                        path, sub.lineno, "KJ014",
                        f"{offense} in hot-path method `{fn.name}`: "
                        "blocking host I/O stalls every request for the "
                        "full call latency and is invisible to the "
                        "KP903 serving latency bound — hoist it to "
                        "construction/fit time or the serving ingress")


def _check_manual_chunk_knob(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ015 (under ``workflow/``/``nodes/``, the config definition
    site ``workflow/env.py`` excluded by the dispatcher): a direct
    ``<config>.chunk_size`` attribute read, or any expression carrying
    the ``"KEYSTONE_CHUNK_SIZE"`` env-key literal. Since PR 15 the
    chunk size is an optimizer decision — the planner's chosen chunk
    reaches the host batcher and the KP2xx/KP8xx static models through
    ONE resolution (`workflow.env.resolved_chunk_size`); a module
    reading the raw knob executes (or models) a chunking the planner
    did not decide."""
    def config_receiver(node) -> bool:
        # cfg.chunk_size / config.chunk_size / execution_config().chunk_size
        if isinstance(node, ast.Name):
            return node.id in ("cfg", "config", "exec_config",
                               "execution_config")
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            return name == "execution_config"
        return False

    for sub in ast.walk(tree):
        if isinstance(sub, ast.Attribute) and sub.attr == "chunk_size" \
                and isinstance(sub.ctx, ast.Load) \
                and config_receiver(sub.value):
            yield Finding(
                path, sub.lineno, "KJ015",
                "direct `.chunk_size` config read bypasses the unified "
                "planner's chunk decision — call "
                "workflow.env.resolved_chunk_size() (or take an "
                "explicit parameter) instead")
        elif isinstance(sub, ast.Constant) \
                and sub.value == "KEYSTONE_CHUNK_SIZE":
            yield Finding(
                path, sub.lineno, "KJ015",
                "direct KEYSTONE_CHUNK_SIZE env read bypasses the "
                "unified planner's chunk decision — the env knob is "
                "resolved once by ExecutionConfig; read "
                "workflow.env.resolved_chunk_size() instead")


def _check_pallas_outside_ops(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ016 (everywhere except ``ops/``): a ``pl.pallas_call`` /
    ``pallas.pallas_call`` / bare ``pallas_call`` invocation outside
    the one audited kernel home. Comments and docstrings naming the
    API do not trip this — only a real call expression does."""
    for sub in ast.walk(tree):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name == "pallas_call":
            yield Finding(
                path, sub.lineno, "KJ016",
                "pallas_call outside keystone_tpu/ops/ — kernels live "
                "in ops/ (with a pure-jnp *_reference oracle) so the "
                "lint.sh chain-kernel audit, the live-chip canary, and "
                "the KEYSTONE_CHAIN_KERNELS kill switch cover them; "
                "move the kernel there and call the builder")


def _check_hardcoded_kernel_geometry(tree: ast.AST,
                                     path: str) -> Iterator[Finding]:
    """KJ017 (``ops/`` only): a hard-coded VMEM byte budget (a
    ``<< 20`` MiB shift or a >=1 MiB integer constant) outside the one
    sanctioned ``_VMEM_BUDGET`` definition, or a literal leading
    block-row count in a ``pl.BlockSpec`` shape tuple. A leading
    literal of 1 is a broadcast/scalar block dimension, not a chosen
    batch block — only literals > 1 trip."""
    sanctioned: Set[int] = set()
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_VMEM_BUDGET"
                for t in sub.targets):
            sanctioned.update(id(inner) for inner in ast.walk(sub))
    mib = 1 << 20
    for sub in ast.walk(tree):
        if id(sub) in sanctioned:
            continue
        if (isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.LShift)
                and isinstance(sub.right, ast.Constant)
                and isinstance(sub.right.value, int)
                and sub.right.value >= 20):
            yield Finding(
                path, sub.lineno, "KJ017",
                "hard-coded VMEM byte budget (MiB shift) outside "
                "chain_kernels._VMEM_BUDGET — route the geometry "
                "through the shared chooser "
                "(chain_vmem_bytes/chain_block_rows) so the KP1003 "
                "static proof covers it")
        elif (isinstance(sub, ast.Constant) and isinstance(sub.value, int)
                and not isinstance(sub.value, bool) and sub.value >= mib):
            yield Finding(
                path, sub.lineno, "KJ017",
                "hard-coded >=1 MiB byte constant outside "
                "chain_kernels._VMEM_BUDGET — a second inline VMEM "
                "arithmetic the KP1003 static proof cannot see")
        elif isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name == "BlockSpec" and sub.args:
                shape = sub.args[0]
                if (isinstance(shape, (ast.Tuple, ast.List)) and shape.elts
                        and isinstance(shape.elts[0], ast.Constant)
                        and isinstance(shape.elts[0].value, int)
                        and not isinstance(shape.elts[0].value, bool)
                        and shape.elts[0].value > 1):
                    yield Finding(
                        path, shape.elts[0].lineno, "KJ017",
                        "literal leading block-row count in a "
                        "pl.BlockSpec shape — the batch block is the "
                        "shared chooser's decision "
                        "(chain_block_rows), not a constant; a pinned "
                        "block dodges the KP1003 VMEM proof")


# ----------------------------------------------------------------- driver


#: constructors/loaders whose result is an out-of-core (host-tier)
#: dataset — the names KJ020 tracks assignments from
_OOC_CONSTRUCTORS = {"OutOfCoreDataset", "SpilledDataset",
                     "out_of_core_from_shards", "out_of_core_npy_loader",
                     "synthetic_out_of_core"}

#: numpy-level whole-array drains (np.<attr> / numpy.<attr>)
_OOC_NP_DRAINS = {"asarray", "array", "stack", "concatenate"}


def _check_ooc_whole_drain(tree: ast.AST, path: str) -> Iterator[Finding]:
    """KJ020 (under ``data/``/``workflow/``): whole-dataset
    materialization of an out-of-core source. Names bound from the
    out-of-core constructors/loaders are tracked per module; feeding a
    tracked name to a numpy whole-array drain or ``list()``/``tuple()``
    defeats the bounded-residency contract the windowed prefetcher
    provides. The classes' own ``materialize()``/``rehydrate()``/
    ``numpy()`` methods are not flagged — they ARE the sanctioned,
    greppable full-drain decision points."""
    tracked: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        func = node.value.func
        name = (func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None)
        if name in _OOC_CONSTRUCTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tracked.add(tgt.id)
    if not tracked:
        return
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        func = call.func
        drain = None
        if isinstance(func, ast.Attribute) \
                and func.attr in _OOC_NP_DRAINS \
                and _attr_root(func) in {"np", "numpy"}:
            drain = f"np.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in {"list", "tuple"}:
            drain = func.id
        if drain is None:
            continue
        hit = next((a.id for a in call.args
                    if isinstance(a, ast.Name) and a.id in tracked), None)
        if hit is None:
            continue
        yield Finding(
            path, call.lineno, "KJ020",
            f"{drain}({hit}) drains an out-of-core dataset whole — "
            "stream it (window_iter()/map_windowed()) or make the full "
            f"drain explicit ({hit}.materialize()/.numpy())")


def lint_file(path: Path, repo_root: Optional[Path] = None) -> List[Finding]:
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Finding(str(path), e.lineno or 0, "KJ000",
                        f"syntax error: {e.msg}")]
    rel = str(path if repo_root is None else path.relative_to(repo_root))
    findings: List[Finding] = []
    findings.extend(_check_numpy_in_jit(tree, rel))
    findings.extend(_check_wall_clock_duration(tree, rel))
    posix = rel.replace("\\", "/") + "/"
    if "nodes/" in posix:
        findings.extend(_check_loop_accumulation(tree, rel))
    if "nodes/learning" in posix:
        findings.extend(_check_missing_donate(tree, rel))
    if "workflow/" in posix or "nodes/" in posix:
        findings.extend(_check_blocking_host_pull(tree, rel))
        findings.extend(_check_fresh_jit(tree, rel))
        findings.extend(_check_scan_carry_realloc(tree, rel))
        findings.extend(_check_hot_path_state_write(tree, rel))
        findings.extend(_check_axis_literals(tree, rel))
        findings.extend(_check_output_layout_leak(tree, rel))
        findings.extend(_check_literal_precision_cast(tree, rel))
        findings.extend(_check_dynamic_metric_name(tree, rel))
        findings.extend(_check_trace_time_telemetry(tree, rel))
        findings.extend(_check_transpose_reshape(tree, rel))
        findings.extend(_check_blocking_host_io(tree, rel))
        if not posix.endswith("workflow/env.py/"):
            # env.py IS the knob's definition + resolution site
            findings.extend(_check_manual_chunk_knob(tree, rel))
    if "serving/" in posix or "workflow/" in posix:
        findings.extend(_check_unbounded_request_buffer(
            tree, rel, serving="serving/" in posix))
    if "parallel/" in posix or "data/" in posix:
        findings.extend(_check_bare_device_put(tree, rel))
    if "data/" in posix or "workflow/" in posix:
        findings.extend(_check_ooc_whole_drain(tree, rel))
    if "ops/" not in posix:
        findings.extend(_check_pallas_outside_ops(tree, rel))
    else:
        findings.extend(_check_hardcoded_kernel_geometry(tree, rel))

    # nested loops make ast.walk revisit inner statements: keep one
    # finding per (line, rule)
    findings = list(dict.fromkeys(findings))

    # per-line suppression: # keystone: ignore[KJ001,KJ002]
    lines = src.splitlines()
    kept = []
    for f in findings:
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        m = _IGNORE_RE.search(line)
        if m and f.rule in {r.strip() for r in m.group(1).split(",")}:
            continue
        kept.append(f)
    return kept


def iter_py_files(paths: List[str]) -> Iterator[Path]:
    for p in paths:
        path = Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=["keystone_tpu"])
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings (CI annotation)")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0
    repo_root = Path(__file__).resolve().parent.parent
    findings: List[Finding] = []
    for f in iter_py_files(args.paths or ["keystone_tpu"]):
        root = repo_root if f.resolve().is_relative_to(repo_root) else None
        findings.extend(lint_file(f.resolve() if root else f, repo_root=root))
    if args.json:
        import json

        print(json.dumps({
            "findings": [f._asdict() for f in findings],
            "total": len(findings),
        }, indent=2))
        return 1 if findings else 0
    for finding in findings:
        print(finding)
    if findings:
        print(f"jaxlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
