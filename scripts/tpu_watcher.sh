#!/usr/bin/env bash
# TPU tunnel watcher: probe the device periodically; the moment a healthy
# window opens, run the full bench and archive the record. Keeps looping so
# later code improvements get re-measured in subsequent healthy windows.
#
# Usage: scripts/tpu_watcher.sh [out_dir]   (default /tmp/bench_live)
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="${1:-/tmp/bench_live}"
mkdir -p "$OUT"
cd "$REPO"
PY="$(command -v python3 || command -v python)"

probe() {
  timeout 90 "$PY" -u -c "
import jax, jax.numpy as jnp
print('probe_sum', float(jnp.ones((2,2)).sum()))
" >/dev/null 2>&1
}

i=0
while true; do
  i=$((i+1))
  ts=$(date +%Y%m%d_%H%M%S)
  if probe; then
    echo "[watcher] $ts probe OK — running bench (iter $i)" | tee -a "$OUT/watcher.log"
    "$PY" bench.py --attempts 2 --deadline 2400 --run-timeout 1800 \
      > "$OUT/bench_$ts.json" 2> "$OUT/bench_$ts.err"
    echo "[watcher] bench rc=$? -> $OUT/bench_$ts.json" | tee -a "$OUT/watcher.log"
    tail -c 400 "$OUT/bench_$ts.json" >> "$OUT/watcher.log"
    echo >> "$OUT/watcher.log"
    sleep 600
  else
    echo "[watcher] $ts probe failed (tunnel wedged), sleeping 240s" >> "$OUT/watcher.log"
    sleep 240
  fi
done
