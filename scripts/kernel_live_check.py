"""Live (on-chip) validation + timing of the Pallas kernels after a
geometry/structure change: the fused conv+rectify+pool kernel and the
two chain-megakernel families (`ops/chain_kernels.py` — the
elementwise chain and rectify→pool→vectorize, the KP801 lowerings the
unified planner's kernel axis prices).

Three gates per kernel, in order (each is a prerequisite for trusting
the next):

1. COMPILE: the kernel at the flagship geometry (conv: CIFAR k=256 at
   the largest VMEM block; chains: the bench-tier item shapes) must
   compile at a ragged batch (2·block+3, forcing a padded tail block)
   — a scoped-vmem OOM or Mosaic reject here is the failure class
   interpret-mode tests cannot see.
2. NUMERICS: on-chip agreement vs the XLA reference path at the same
   geometry (conv tolerance: the documented bf16-patch-feed class,
   ~5e-4 relative pooled over 196-element windows; chains: the same
   2e-3 gate — they are pure f32 so the observed error should sit at
   float roundoff).
3. TIMING: chained fresh-valued reps inside one program, R vs R/2
   differenced so tunnel RTT/dispatch cancels (PERF.md methodology) —
   prints per-rep seconds and kernel-only images/sec for the Pallas
   path and the XLA reference path at the bench tier's batch.

Run from the repo root on the live chip: python scripts/kernel_live_check.py
``--interpret`` runs the chain-kernel gates 1+2 in Pallas interpret
mode (CPU smoke of this script's own harness; not a chip verdict).
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def _static_refutation(stages, item_shape):
    """KP10xx pre-flight: the static kernel verifier's refuting rule
    code (and message) when it proves this geometry unsafe/infeasible —
    the live check skips such geometries rather than burning TPU time
    on a lowering the unified planner prices to INF anyway. Returns
    None when the lowering verifies (or the verifier can't run)."""
    from keystone_tpu.analysis.kernels import verify_lowering

    try:
        proof, _ = verify_lowering(stages, item_shape)
    except Exception:
        return None  # verifier unavailable: the live gates decide
    code = proof.get("refuted_by")
    if code is None:
        code = next((r for r, v in (proof.get("rules") or {}).items()
                     if str(v).startswith("REFUTED")), None)
    if code is None:
        return None
    return code, (proof.get("rules") or {}).get(code, "")


def _timing_gate(name, fn_one, xb, reps=120):
    """Gate 3: differenced chained-rep timing (R vs R/2 inside one
    program so tunnel RTT/dispatch cancels) — shared by the conv
    canary and both chain families."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chained(r):
        @jax.jit
        def run(x, seed):
            def body(i, acc):
                key = jax.random.fold_in(seed, i)
                xp = x * (1.0 + 1e-6 * jax.random.uniform(key))
                y = fn_one(xp)
                return acc + y.reshape(x.shape[0], -1)[:, :8].sum()

            return lax.fori_loop(0, r, body, jnp.float32(0.0))

        return run

    seconds = {}
    for r in (reps // 2, reps):
        run = chained(r)
        float(run(xb, jax.random.PRNGKey(0)))  # compile+warm
        t0 = time.perf_counter()
        s = float(run(xb, jax.random.PRNGKey(1)))
        seconds[r] = time.perf_counter() - t0
        assert np.isfinite(s)
    per_rep = (seconds[reps] - seconds[reps // 2]) / (reps - reps // 2)
    print(f"{name}: full={seconds[reps]:.3f}s half={seconds[reps//2]:.3f}s "
          f"per_rep={per_rep*1e3:.2f}ms "
          f"kernel_only={xb.shape[0]/per_rep:,.0f} img/s", flush=True)


def check_chain_elementwise(interpret=False, timing=True):
    """Chain family 1: the elementwise megakernel at the LinearPixels
    geometry (PixelScaler >> GrayScaler >> ImageVectorizer on 32×32×3)
    — the exact stage trail the unified planner tags `planned_kernel`
    on that example's fused operator."""
    import jax.numpy as jnp

    from keystone_tpu.nodes.images import (
        GrayScaler,
        ImageVectorizer,
        PixelScaler,
    )
    from keystone_tpu.nodes.util.fusion import _peephole, _stage_fuse
    from keystone_tpu.ops.chain_kernels import (
        _compile_bodies,
        _elementwise_geometry,
        elementwise_chain_pallas,
        elementwise_chain_reference,
    )

    stages = [PixelScaler(), GrayScaler(), ImageVectorizer()]
    item = (32, 32, 3)
    refuted = _static_refutation(stages, item)
    if refuted:
        code, msg = refuted
        print(f"elementwise_chain SKIPPED (statically refuted {code}): "
              f"{msg}", flush=True)
        return
    fused = [_stage_fuse(s) for s in _peephole(stages)]
    statics = tuple(f[0] for f in fused)
    params = [f[1] for f in fused]

    rng = np.random.default_rng(1)
    bodies = _compile_bodies(statics)
    assert bodies is not None, "elementwise trail no longer lowers"
    ops = [prep(p) for (_, prep, _), p in zip(bodies, params)]
    probe = jnp.zeros((8,) + item, jnp.float32)
    b = _elementwise_geometry(bodies, ops, probe)
    assert b > 0, f"gate 1 FAILED: no VMEM block at item {item}"
    print(f"elementwise_chain block chooser at item={item}: b={b}",
          flush=True)

    # gates 1+2: compile at a ragged batch (padded tail block) + numerics
    n_small = 2 * b + 3
    x = jnp.asarray(rng.random((n_small,) + item).astype(np.float32))
    got = np.asarray(elementwise_chain_pallas(
        statics, params, x, interpret=interpret))
    want = np.asarray(elementwise_chain_reference(statics, params, x))
    scale = max(np.abs(want).max(), 1e-12)
    err = np.abs(got - want).max() / scale
    assert err < 2e-3, f"gate 2 FAILED: max rel err {err:.2e}"
    print(f"elementwise_chain gate 1+2 ok: compiled at b={b}, n={n_small}; "
          f"max rel err vs XLA = {err:.2e}", flush=True)

    if timing:
        batch = 16384
        xb = jnp.asarray(rng.random((batch,) + item).astype(np.float32))
        _timing_gate("elementwise_chain pallas",
                     lambda xp: elementwise_chain_pallas(statics, params, xp),
                     xb)
        _timing_gate("elementwise_chain xla",
                     lambda xp: elementwise_chain_reference(
                         statics, params, xp),
                     xb)


def check_chain_rectify_pool(interpret=False, timing=True):
    """Chain family 2: rectify→pool→vectorize at the RandomPatchCifar
    conv-output geometry (27×27 positions, k=256 filters, 14/13
    pooling) — the highest-priced KP801 family on that example."""
    import jax.numpy as jnp

    from keystone_tpu.ops.chain_kernels import (
        _rectify_pool_vectorize_block,
        rectify_pool_vectorize_pallas,
        rectify_pool_vectorize_reference,
    )

    h = w = 27
    k, pool, stride, alpha = 256, 14, 13, 0.25
    from keystone_tpu.nodes.images import ImageVectorizer
    from keystone_tpu.nodes.util.fusion import _RectifyPoolStage

    refuted = _static_refutation(
        [_RectifyPoolStage(alpha, 0.0, pool, stride), ImageVectorizer()],
        (h, w, k))
    if refuted:
        code, msg = refuted
        print(f"rectify_pool_vectorize SKIPPED (statically refuted "
              f"{code}): {msg}", flush=True)
        return
    b = _rectify_pool_vectorize_block(h, w, k, pool, stride)
    assert b > 0, f"gate 1 FAILED: no VMEM block at (h={h}, w={w}, k={k})"
    print(f"rectify_pool_vectorize block chooser at (h={h}, w={w}, k={k}): "
          f"b={b}", flush=True)

    rng = np.random.default_rng(2)
    n_small = 2 * b + 3
    x = jnp.asarray(rng.standard_normal((n_small, h, w, k)).astype(np.float32))
    got = np.asarray(rectify_pool_vectorize_pallas(
        x, alpha, 0.0, pool, stride, interpret=interpret))
    want = np.asarray(rectify_pool_vectorize_reference(
        x, alpha, 0.0, pool, stride))
    scale = max(np.abs(want).max(), 1e-12)
    err = np.abs(got - want).max() / scale
    assert err < 2e-3, f"gate 2 FAILED: max rel err {err:.2e}"
    print(f"rectify_pool_vectorize gate 1+2 ok: compiled at b={b}, "
          f"n={n_small}; max rel err vs XLA = {err:.2e}", flush=True)

    if timing:
        batch = 2048
        xb = jnp.asarray(
            rng.standard_normal((batch, h, w, k)).astype(np.float32))
        _timing_gate("rectify_pool_vectorize pallas",
                     lambda xp: rectify_pool_vectorize_pallas(
                         xp, alpha, 0.0, pool, stride),
                     xb)
        _timing_gate("rectify_pool_vectorize xla",
                     lambda xp: rectify_pool_vectorize_reference(
                         xp, alpha, 0.0, pool, stride),
                     xb)


def main():
    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops import (
        conv_rectify_pool_pallas,
        conv_rectify_pool_reference,
        hwio_to_cmajor,
    )
    from keystone_tpu.ops.pallas_kernels import _fused_conv_block_images

    interpret = "--interpret" in sys.argv[1:]
    if interpret:
        # CPU smoke of the chain-kernel harness only — not a chip verdict
        check_chain_elementwise(interpret=True, timing=False)
        check_chain_rectify_pool(interpret=True, timing=False)
        print("interpret-mode chain smoke ok (no chip verdict)", flush=True)
        return

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", flush=True)

    k, patch, c, h, w = 256, 6, 3, 32, 32
    pool, stride, alpha = 14, 13, 0.25
    # derive the chooser inputs from the geometry above (must match the
    # kernel's own internal computation in conv_rectify_pool_pallas)
    pos_h, pos_w = h - patch + 1, w - patch + 1
    posp = -(-(pos_h * pos_w) // 16) * 16
    dp = -(-(c * patch * patch) // 128) * 128
    cells = ((pos_h - pool) // stride + 1) * ((pos_w - pool) // stride + 1)
    b = _fused_conv_block_images(posp, dp, k, cells)
    print(f"block chooser at posp={posp} dp={dp} cells={cells} k={k}: "
          f"b={b}", flush=True)

    rng = np.random.default_rng(0)
    kern = jnp.asarray(rng.normal(size=(patch, patch, c, k)).astype(np.float32))
    g = hwio_to_cmajor(kern)
    colsum = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))

    # --- gate 1+2: compile at the chosen block and check numerics ------
    n_small = 2 * b + 3  # forces a padded tail block too
    x = jnp.asarray(rng.random((n_small, h, w, c)).astype(np.float32))
    got = np.asarray(conv_rectify_pool_pallas(
        x, g, colsum, bias, alpha, 0.0, pool, stride, True, patch))
    want = np.asarray(conv_rectify_pool_reference(
        x, kern, colsum, bias, alpha, 0.0, pool, stride, True))
    scale = np.abs(want).max()
    err = np.abs(got - want).max() / scale
    assert err < 2e-3, f"gate 2 FAILED: max rel err {err:.2e}"
    print(f"gate 1+2 ok: compiled at b={b}, n={n_small}; "
          f"max rel err vs XLA on-chip = {err:.2e}", flush=True)

    # --- gate 3: differenced chained-rep timing ------------------------
    batch = 16384
    xb = jnp.asarray(rng.random((batch, h, w, c)).astype(np.float32))

    def pallas_one(xp):
        return conv_rectify_pool_pallas(
            xp, g, colsum, bias, alpha, 0.0, pool, stride, True, patch)

    def ref_one(xp):
        return conv_rectify_pool_reference(
            xp, kern, colsum, bias, alpha, 0.0, pool, stride, True)

    _timing_gate("pallas", pallas_one, xb)
    _timing_gate("xla", ref_one, xb)

    # --- chain megakernels (ops/chain_kernels.py) ----------------------
    check_chain_elementwise()
    check_chain_rectify_pool()


if __name__ == "__main__":
    main()
