"""Live (on-chip) validation + timing of the fused conv+rectify+pool
Pallas kernel after a geometry/structure change.

Three gates, in order (each is a prerequisite for trusting the next):

1. COMPILE: the kernel at the CIFAR flagship geometry (k=256, the
   largest block the VMEM chooser picks) must compile — a scoped-vmem
   OOM here is the failure class interpret-mode tests cannot see.
2. NUMERICS: on-chip agreement vs the XLA reference path at the same
   geometry (tolerance: the documented bf16-patch-feed class, ~5e-4
   relative, pooled over 196-element windows).
3. TIMING: chained fresh-valued reps inside one program, R vs R/2
   differenced so tunnel RTT/dispatch cancels (PERF.md methodology) —
   prints per-rep seconds and kernel-only images/sec for the Pallas
   path and the XLA reference path at the bench tier's batch.

Run from the repo root on the live chip: python scripts/kernel_live_check.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from keystone_tpu.ops import (
        conv_rectify_pool_pallas,
        conv_rectify_pool_reference,
        hwio_to_cmajor,
    )
    from keystone_tpu.ops.pallas_kernels import _fused_conv_block_images

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", flush=True)

    k, patch, c, h, w = 256, 6, 3, 32, 32
    pool, stride, alpha = 14, 13, 0.25
    # derive the chooser inputs from the geometry above (must match the
    # kernel's own internal computation in conv_rectify_pool_pallas)
    pos_h, pos_w = h - patch + 1, w - patch + 1
    posp = -(-(pos_h * pos_w) // 16) * 16
    dp = -(-(c * patch * patch) // 128) * 128
    cells = ((pos_h - pool) // stride + 1) * ((pos_w - pool) // stride + 1)
    b = _fused_conv_block_images(posp, dp, k, cells)
    print(f"block chooser at posp={posp} dp={dp} cells={cells} k={k}: "
          f"b={b}", flush=True)

    rng = np.random.default_rng(0)
    kern = jnp.asarray(rng.normal(size=(patch, patch, c, k)).astype(np.float32))
    g = hwio_to_cmajor(kern)
    colsum = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(k,)).astype(np.float32))

    # --- gate 1+2: compile at the chosen block and check numerics ------
    n_small = 2 * b + 3  # forces a padded tail block too
    x = jnp.asarray(rng.random((n_small, h, w, c)).astype(np.float32))
    got = np.asarray(conv_rectify_pool_pallas(
        x, g, colsum, bias, alpha, 0.0, pool, stride, True, patch))
    want = np.asarray(conv_rectify_pool_reference(
        x, kern, colsum, bias, alpha, 0.0, pool, stride, True))
    scale = np.abs(want).max()
    err = np.abs(got - want).max() / scale
    assert err < 2e-3, f"gate 2 FAILED: max rel err {err:.2e}"
    print(f"gate 1+2 ok: compiled at b={b}, n={n_small}; "
          f"max rel err vs XLA on-chip = {err:.2e}", flush=True)

    # --- gate 3: differenced chained-rep timing ------------------------
    batch, reps = 16384, 120

    def chained(fn_one, r):
        @jax.jit
        def run(xb, seed):
            def body(i, acc):
                key = jax.random.fold_in(seed, i)
                xp = xb * (1.0 + 1e-6 * jax.random.uniform(key))
                y = fn_one(xp)
                return acc + y.reshape(xb.shape[0], -1)[:, :8].sum()

            return lax.fori_loop(0, r, body, jnp.float32(0.0))

        return run

    xb = jnp.asarray(rng.random((batch, h, w, c)).astype(np.float32))

    def pallas_one(xp):
        return conv_rectify_pool_pallas(
            xp, g, colsum, bias, alpha, 0.0, pool, stride, True, patch)

    def ref_one(xp):
        return conv_rectify_pool_reference(
            xp, kern, colsum, bias, alpha, 0.0, pool, stride, True)

    for name, fn_one in (("pallas", pallas_one), ("xla", ref_one)):
        seconds = {}
        for r in (reps // 2, reps):
            run = chained(fn_one, r)
            float(run(xb, jax.random.PRNGKey(0)))  # compile+warm
            t0 = time.perf_counter()
            s = float(run(xb, jax.random.PRNGKey(1)))
            seconds[r] = time.perf_counter() - t0
            assert np.isfinite(s)
        per_rep = (seconds[reps] - seconds[reps // 2]) / (reps - reps // 2)
        print(f"{name}: full={seconds[reps]:.3f}s half={seconds[reps//2]:.3f}s "
              f"per_rep={per_rep*1e3:.2f}ms "
              f"kernel_only={batch/per_rep:,.0f} img/s", flush=True)


if __name__ == "__main__":
    main()
