"""Featurizer microbatch sweep: time the fused featurization (the
dominant pipeline stage) across microbatch sizes to pick the default.

One JSON line per point; tunnel-safe timing (fresh-valued inputs +
scalar-pull fence, see data.dataset.sync_pull).

Usage: python scripts/featurize_sweep.py [--n 50000] [--filters 256]
       [--quick]  # tiny CPU smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=50_000)
    p.add_argument("--filters", type=int, default=256)
    p.add_argument("--microbatches", type=int, nargs="+",
                   default=[1024, 2048, 4096, 8192])
    p.add_argument("--quick", action="store_true")
    args = p.parse_args()
    if os.environ.get("KEYSTONE_BACKEND") == "cpu" or args.quick:
        import jax

        jax.config.update("jax_platforms", "cpu")
        if args.quick:
            args.n, args.filters = 1024, 64
            args.microbatches = [256, 512]

    from bench import BENCH_CONFUSION, BENCH_NOISE
    from keystone_tpu.data.dataset import sync_pull
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        learn_filters,
        make_featurizer,
    )

    train, _ = synthetic_cifar(args.n, 64, noise=BENCH_NOISE,
                               confusion=BENCH_CONFUSION)
    config = RandomPatchCifarConfig(num_filters=args.filters)
    filters, whitener = learn_filters(train.data, config)
    h, w, c = train.data.array.shape[1:]
    rng = np.random.default_rng()
    best = None
    for mb in args.microbatches:
        feat = make_featurizer(filters, whitener, h, w, c, config,
                               microbatch=mb)

        def run_once():
            eps = float(rng.random()) * 1e-6
            d2 = train.data.map_batches(lambda x: x * (1.0 + eps)).sync()
            t0 = time.perf_counter()
            out = feat.apply_batch(d2)
            sync_pull(out.array)
            return time.perf_counter() - t0

        run_once()  # compile
        secs = min(run_once() for _ in range(3))
        row = {
            "microbatch": mb, "n": args.n, "filters": args.filters,
            "featurize_seconds": round(secs, 4),
            "images_per_sec": round(args.n / secs, 1),
        }
        print(json.dumps(row), flush=True)
        if best is None or secs < best[1]:
            best = (mb, secs)
    print(json.dumps({"best_microbatch": best[0],
                      "best_seconds": round(best[1], 4)}), flush=True)


if __name__ == "__main__":
    main()
