#!/usr/bin/env bash
# Fast pre-test lint gate: AST-level JAX lints + static validation of
# every example pipeline. Runs in seconds with no data and no devices
# beyond the CPU backend (the pipeline validator traces with
# jax.eval_shape only). Mirrored in tier-1 by the `lint` pytest marker
# (tests/test_jaxlint.py, tests/test_analysis.py).
#
#   scripts/lint.sh              # whole gate
#   scripts/lint.sh --list-rules # rule catalog
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--list-rules" ]]; then
    python scripts/jaxlint.py --list-rules
    JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --list-rules
    exit 0
fi

echo "== jaxlint (AST rules) =="
python scripts/jaxlint.py keystone_tpu

echo "== pipeline validation (abstract specs) =="
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis "$@"

echo "lint: OK"
