#!/usr/bin/env bash
# Fast pre-test lint gate: AST-level JAX lints + static validation of
# every example pipeline. Runs in seconds with no data and no devices
# beyond the CPU backend (the pipeline validator traces with
# jax.eval_shape only). Mirrored in tier-1 by the `lint` pytest marker
# (tests/test_jaxlint.py, tests/test_analysis.py).
#
#   scripts/lint.sh              # whole gate
#   scripts/lint.sh --list-rules # rule catalog
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--list-rules" ]]; then
    python scripts/jaxlint.py --list-rules
    JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --list-rules
    exit 0
fi

echo "== jaxlint (AST rules) =="
python scripts/jaxlint.py keystone_tpu

echo "== pipeline validation (abstract specs) =="
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis "$@"

echo "== operator contract audit (registry-wide KP5xx) =="
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --audit-operators

echo "== sharding audit (per-stage placement over every example, 8-device mesh) =="
# Every analyzable() example's propagated partition table on a forced
# 8-device CPU mesh: the CLI exits 1 on ANY unsuppressed KP6xx finding
# (implicit reshard, oversized replication, host all-gather,
# mesh-indivisible counts) — placement regressions fail here in seconds.
SHARDING_JSON="$(mktemp /tmp/keystone_sharding_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m keystone_tpu.analysis --explain-sharding --json > "$SHARDING_JSON"
python - "$SHARDING_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
assert payload["devices"] == 8, payload["devices"]
examples = payload["examples"]
assert len(examples) >= 7, [e["example"] for e in examples]
for e in examples:
    assert "build_error" not in e, e
    assert e["findings"] == [], e["findings"]
    assert e["stages"], e["example"]
stages = sum(len(e["stages"]) for e in examples)
print(f"sharding audit: {len(examples)} example(s), {stages} stage rows, "
      "0 KP6xx findings OK")
PY

echo "== planner audit (chosen vs default placement over every example, 2x4 mesh) =="
# The sharding planner's decision gate: on an 8-device CPU mesh arranged
# 2 (data) x 4 (model), run the planner over every analyzable() example
# and assert (1) the chosen placement's priced boundary bytes never
# exceed the default placement's, (2) the planner strictly wins on at
# least 2 examples, and (3) zero unsuppressed KP6xx findings UNDER the
# chosen plan — the decided placement is clean, not just the default.
PLANNER_JSON="$(mktemp /tmp/keystone_planner_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m keystone_tpu.analysis --explain-sharding --plan --mesh-shape 2x4 \
    --json > "$PLANNER_JSON"
python - "$PLANNER_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
assert payload["devices"] == 8, payload["devices"]
examples = payload["examples"]
assert len(examples) >= 7, [e["example"] for e in examples]
strict = 0
for e in examples:
    assert "build_error" not in e, e
    assert e["findings"] == [], (e["example"], e["findings"])
    planner = e.get("planner")
    if planner is None:
        continue  # nothing to decide (host-only pipeline)
    assert planner["planned_cost_bytes"] <= planner["default_cost_bytes"], e
    if planner["planned_cost_bytes"] < planner["default_cost_bytes"]:
        strict += 1
assert strict >= 2, f"planner strictly beat the default on only {strict} example(s)"
saved = sum((e.get("planner") or {}).get("savings_bytes", 0) for e in examples)
print(f"planner audit: {len(examples)} example(s), strict wins on {strict}, "
      f"{saved:,} boundary bytes saved, 0 KP6xx under chosen plans OK")
PY

echo "== precision audit (chosen per-stage dtypes over every example) =="
# The mixed-precision policy planner's decision gate: run the planner
# over every analyzable() example and assert (1) the chosen policy's
# priced boundary bytes never exceed the all-f32 default's, (2) the
# planner strictly wins on at least 2 examples, and (3) zero
# unsuppressed WARNING/ERROR KP7xx findings under the chosen policies —
# the decided dtypes are clean, not just the f32 reference.
PRECISION_JSON="$(mktemp /tmp/keystone_precision_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON"' EXIT
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --explain-precision \
    --json > "$PRECISION_JSON"
python - "$PRECISION_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
examples = payload["examples"]
assert len(examples) >= 7, [e["example"] for e in examples]
strict = 0
for e in examples:
    assert "build_error" not in e, e
    gate = [f for f in e["findings"] if f["severity"] != "INFO"]
    assert gate == [], (e["example"], gate)
    planner = e.get("planner")
    if planner is None:
        continue  # nothing to decide (no tolerant float boundary)
    assert planner["planned_cost_bytes"] <= planner["default_cost_bytes"], e
    if planner["planned_cost_bytes"] < planner["default_cost_bytes"]:
        strict += 1
assert strict >= 2, f"precision planner strictly won on only {strict} example(s)"
saved = sum((e.get("planner") or {}).get("savings_bytes", 0) for e in examples)
print(f"precision audit: {len(examples)} example(s), strict wins on {strict}, "
      f"{saved:,} boundary bytes saved, 0 KP7xx under chosen policies OK")
PY

echo "== roofline audit (per-stage flops/bytes/intensity over every example) =="
# The static roofline analyzer's gate: price every analyzable() example
# on the calibrated machine balance and assert (1) zero unsuppressed
# ERROR-severity KP8xx findings (the tier is advisory — KP801/KP803
# candidates and re-pricings are INFO), (2) the device-featurize
# examples actually price (stage rows with flops/bytes/intensity/
# predicted-seconds present), and (3) the KP801 Pallas-candidate list
# is non-empty — the Pallas megakernel backend (ROADMAP) needs a
# statically identified bandwidth-bound chain to target.
ROOFLINE_JSON="$(mktemp /tmp/keystone_roofline_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON"' EXIT
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --explain-roofline \
    --json > "$ROOFLINE_JSON"
python - "$ROOFLINE_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
machine = payload["machine"]
assert machine and machine["peak_flops"] > 0 and machine["peak_bw"] > 0
examples = payload["examples"]
assert len(examples) >= 7, [e["example"] for e in examples]
candidates = 0
priced = 0
for e in examples:
    assert "build_error" not in e, e
    errors = [f for f in e["findings"] if f["severity"] == "ERROR"]
    assert errors == [], (e["example"], errors)
    for s in e["stages"]:
        assert s["flops"] >= 0 and s["hbm_bytes"] > 0, (e["example"], s)
        assert s["bound"] in ("compute", "bandwidth"), s
        assert s["predicted_seconds"] > 0, s
    priced += len(e["stages"])
    candidates += len(e["candidates"])
assert priced > 0, "no example priced a single stage"
assert candidates >= 1, "KP801 found no Pallas-candidate chain anywhere"
print(f"roofline audit: {len(examples)} example(s), {priced} priced stage "
      f"rows, {candidates} KP801 pallas candidate(s), 0 KP8xx errors OK")
PY

echo "== chain-kernel audit (every KP801 candidate lowers, prices worse, or is suppressed) =="
# The chain-megakernel backend's gate (ops/chain_kernels.py): every
# KP801 Pallas candidate the roofline finds must resolve one of three
# ways — (1) it LOWERS (a lowerable verdict naming the kernel family,
# with a finite kernel-seconds price), (2) it prices WORSE than the XLA
# chain with the reason rendered, or (3) it carries a NAMED suppression
# (chain_kernels.SUPPRESSED_STAGES — each blocker states why it stays
# on XLA deliberately). An unlowerable candidate with no named
# suppression is an open lowering gap: exit 1. At least 2 candidates
# must lower with a winning price (the PR-16 acceptance floor).
python - "$ROOFLINE_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
total = wins = worse = suppressed = 0
gaps = []
for e in payload["examples"]:
    for c in e.get("candidates", []):
        total += 1
        v = c.get("lowerable")
        anchor = f"{e['example']}:{c['vertices']}"
        assert v is not None and v.get("reason"), (
            f"{anchor}: KP801 candidate carries no lowerability verdict")
        ks, cs = c.get("kernel_seconds"), c.get("chain_seconds")
        if v.get("lowerable"):
            assert ks is not None and ks == ks and ks != float("inf"), (
                f"{anchor}: lowerable but kernel price is not finite")
            if ks < cs:
                wins += 1
            else:
                worse += 1  # priced worse, reason rendered in the verdict
        elif v.get("suppressed"):
            suppressed += 1
        else:
            gaps.append(f"{anchor}: {v.get('reason')}")
if gaps:
    print("chain-kernel audit: open lowering gap(s) with no named "
          "suppression:", file=sys.stderr)
    for g in gaps:
        print(f"  {g}", file=sys.stderr)
    sys.exit(1)
assert wins >= 2, f"only {wins} candidate(s) lower with a winning price"
print(f"chain-kernel audit: {total} KP801 candidate(s) — {wins} lower and "
      f"win, {worse} price worse (reason rendered), {suppressed} carry "
      "named suppressions, 0 open gaps OK")
PY

echo "== kernel-verifier audit (KP10xx: every registered lowering statically proved) =="
# The static Pallas kernel verifier (analysis/kernels.py): every
# lowerable KP801 candidate must carry a full KP1001-KP1005 proof —
# grid coverage, ragged-tail bounds, VMEM working set (the SAME
# arithmetic as chain_feasible's runtime chooser), mask discipline,
# and abstract oracle equivalence — or a named
# `# keystone: ignore[KP100x]` suppression. An unsuppressed KP10xx
# finding means a lowering could dispatch without a static safety
# proof: exit 1.
KERNELS_JSON="$(mktemp /tmp/keystone_kernels_audit.XXXXXX.json)"
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --audit-kernels \
    --json > "$KERNELS_JSON"
python - "$KERNELS_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
assert not payload["build_errors"], payload["build_errors"]
findings = payload["findings"]
if findings:
    print("kernel-verifier audit: unsuppressed KP10xx finding(s):",
          file=sys.stderr)
    for f in findings:
        print(f"  {f['example']}:{f['lowering']}: {f['rule']} "
              f"{f['message']}", file=sys.stderr)
    sys.exit(1)
verified, total = payload["verified_lowerings"], payload["total_lowerings"]
assert total >= 6, f"only {total} registered lowering(s) audited"
assert verified == total, (
    f"only {verified}/{total} lowerings statically verified")
print(f"kernel-verifier audit: {payload['audited_examples']} example(s) "
      f"swept, {verified}/{total} lowerings statically verified, "
      f"{len(payload['suppressed'])} suppression(s), "
      "0 unsuppressed KP10xx OK")
PY
rm -f "$KERNELS_JSON"

echo "== unified-planner audit (joint decision IR vs sequential passes, 2x4 mesh) =="
# The unified plan optimizer's decision gate: on an 8-device CPU mesh
# arranged 2 (data) x 4 (model), solve the joint {placement x dtype x
# chunk x cache} IR over every analyzable() example and assert (1) the
# joint plan's predicted seconds never exceed the sequential PR-13
# composition's (both scored by the same time model), (2) the joint
# plan strictly wins on at least 2 examples, and (3) zero unsuppressed
# WARNING/ERROR KP6xx/KP7xx/KP8xx findings UNDER the chosen plans —
# the jointly decided placement/dtypes/chunk are clean, not just the
# sequential reference.
UNIFIED_JSON="$(mktemp /tmp/keystone_unified_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python -m keystone_tpu.analysis --explain-unified --mesh-shape 2x4 \
    --json > "$UNIFIED_JSON"
python - "$UNIFIED_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
assert payload["devices"] == 8, payload["devices"]
examples = payload["examples"]
assert len(examples) >= 7, [e["example"] for e in examples]
strict = 0
for e in examples:
    assert "build_error" not in e, e
    gate = [f for f in e["findings"] if f["severity"] != "INFO"]
    assert gate == [], (e["example"], gate)
    planner = e.get("planner")
    if planner is None:
        continue  # nothing to decide (host-only pipeline)
    assert planner["joint_seconds"] <= planner["sequential_seconds"], e
    if planner["joint_seconds"] < planner["sequential_seconds"]:
        strict += 1
assert strict >= 2, f"joint plan strictly won on only {strict} example(s)"
saved = sum((e.get("planner") or {}).get("savings_seconds", 0.0)
            for e in examples)
print(f"unified audit: {len(examples)} example(s), strict wins on {strict}, "
      f"{saved:.3e} predicted seconds saved, 0 KP6xx/KP7xx/KP8xx under "
      "chosen plans OK")
PY

echo "== serving audit (KP9xx readiness certificate over every example) =="
# The serving-readiness certifier's gate: certify every analyzable()
# example against the default envelope (batch [1,64], 1s SLO) and
# assert (1) the CLI exits 0 — zero UNSUPPRESSED ERROR-severity KP9xx
# findings anywhere, (2) at least 5 examples certify clean, and (3)
# every example that cannot certify carries NAMED suppressions
# (serving.SERVING_SUPPRESSIONS — each states the stage and the fix),
# so the audit says exactly what is uncertified and why instead of
# silently passing.
SERVING_JSON="$(mktemp /tmp/keystone_serving_audit.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON"' EXIT
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --certify-serving \
    --json > "$SERVING_JSON"
python - "$SERVING_JSON" <<'PY'
import json, sys
payload = json.load(open(sys.argv[1]))
examples = payload["examples"]
assert len(examples) >= 7, [e.get("example") for e in examples]
certified = 0
for e in examples:
    assert "build_error" not in e, e
    assert e["unsuppressed_errors"] == 0, (e["example"], e["findings"])
    if e["certified"]:
        certified += 1
        assert e["certificate"]["shapes"], e["example"]
        assert all(s["predicted_seconds"] > 0
                   for s in e["certificate"]["shapes"]), e["example"]
    else:
        assert e["suppressions"], (
            f"{e['example']} is uncertified with NO named suppression")
assert certified >= 5, f"only {certified} example(s) certified clean"
suppressed = sum(1 for e in examples if e["suppressions"])
print(f"serving audit: {len(examples)} example(s), {certified} certified "
      f"clean, {suppressed} carrying named suppressions, 0 unsuppressed "
      "KP9xx errors OK")
PY

echo "== telemetry smoke (trace a tiny pipeline, validate the JSON) =="
TRACE_TMP="$(mktemp /tmp/keystone_trace_smoke.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_SMOKE_TRACE="$TRACE_TMP" python - <<'PY'
import json, os
import numpy as np
from keystone_tpu import Dataset, Transformer
from keystone_tpu.telemetry import trace_run

path = os.environ["KEYSTONE_SMOKE_TRACE"]
with trace_run(path):
    pipe = Transformer.from_function(lambda x: x * 2.0).to_pipeline()
    pipe(Dataset.from_numpy(np.ones((8, 4), np.float32))).get()
trace = json.load(open(path))
events = trace["traceEvents"]
assert isinstance(events, list) and events, "empty traceEvents"
for e in events:
    assert "ph" in e and "name" in e and "pid" in e, e
assert any(e.get("cat") == "node" for e in events), "no node-force spans"
assert "keystone" in trace and "metrics" in trace["keystone"]
print(f"telemetry smoke: {len(events)} events OK")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry "$TRACE_TMP" >/dev/null

echo "== dispatch smoke (example pipeline under the concurrent scheduler) =="
DISPATCH_TRACE="$(mktemp /tmp/keystone_dispatch_smoke.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP" "$DISPATCH_TRACE"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_TRACE="$DISPATCH_TRACE" KEYSTONE_CONCURRENT_DISPATCH=1 \
python - <<'PY'
# One example pipeline (the dispatch-bench MnistRandomFFT instance) run
# end-to-end under the concurrent DAG scheduler with tracing armed: the
# trace must parse and the run must have executed (and counted) real
# XLA programs through dispatch.programs_executed.
import json, os
from keystone_tpu.dispatch_bench import measure_example

res = measure_example("MnistRandomFFT", "optimized")
assert res["fit_run_programs"] > 0 and res["apply_run_programs"] > 0, res

import keystone_tpu.telemetry.spans as spans
from keystone_tpu.telemetry.export import write_trace
tracer = spans.current_tracer()
assert tracer is not None, "KEYSTONE_TRACE did not arm the ambient tracer"
write_trace(tracer, os.environ["KEYSTONE_TRACE"])

trace = json.load(open(os.environ["KEYSTONE_TRACE"]))
assert trace["traceEvents"], "empty traceEvents"
programs = (trace["keystone"]["metrics"]["counters"]
            .get("dispatch.programs_executed", {}).get("value", 0))
assert programs > 0, "programs_executed not counted"
print(f"dispatch smoke: {int(programs)} program(s), "
      f"{res['apply_run_programs']} on the apply run OK")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry "$DISPATCH_TRACE" >/dev/null

echo "== compile smoke (warm second run performs 0 cold compiles) =="
COMPILE_CACHE="$(mktemp -d /tmp/keystone_compile_smoke.XXXXXX)"
COMPILE_TRACE="$(mktemp /tmp/keystone_compile_smoke.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP" "$DISPATCH_TRACE" "$COMPILE_TRACE"; rm -rf "$COMPILE_CACHE"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_COMPILE_CACHE="$COMPILE_CACHE" \
KEYSTONE_TRACE="$COMPILE_TRACE" python - <<'PY'
# One example pipeline run TWICE against a fresh persistent-cache dir
# with tracing armed: the second (rebuilt-from-scratch) run must perform
# zero cold compiles — everything served warm from the persistent cache
# or the in-process program caches — and the trace must parse and carry
# the compile accounting.
import json, os
from keystone_tpu.dispatch_bench import measure_example
from keystone_tpu.telemetry import compiles_snapshot
from keystone_tpu.workflow.executor import drain_warmups

measure_example("MnistRandomFFT", "optimized")
drain_warmups()  # background AOT compiles count against THIS run
first = compiles_snapshot()
measure_example("MnistRandomFFT", "optimized")
drain_warmups()
second = compiles_snapshot()
new_cold = second["programs_compiled"] - first["programs_compiled"]
assert new_cold == 0, (
    f"second identical run performed {new_cold} cold compile(s): "
    f"{first} -> {second}")

import keystone_tpu.telemetry.spans as spans
from keystone_tpu.telemetry.export import compile_summary, write_trace
tracer = spans.current_tracer()
assert tracer is not None, "KEYSTONE_TRACE did not arm the ambient tracer"
write_trace(tracer, os.environ["KEYSTONE_TRACE"])

trace = json.load(open(os.environ["KEYSTONE_TRACE"]))
assert trace["traceEvents"], "empty traceEvents"
counters = trace["keystone"]["metrics"]["counters"]
assert "dispatch.programs_compiled" in counters, sorted(counters)
line = compile_summary(trace)
assert line is not None, "trace carries no compile digest"
print(f"compile smoke: run1 {first['programs_compiled']} cold / "
      f"{first['compile_cache_hits']} hits; run2 +0 cold — {line} OK")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry "$COMPILE_TRACE" >/dev/null

echo "== megafusion smoke (1-program apply run; warm repeat stays 0-cold) =="
MEGA_CACHE="$(mktemp -d /tmp/keystone_mega_smoke.XXXXXX)"
MEGA_TRACE="$(mktemp /tmp/keystone_mega_smoke.XXXXXX.json)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP" "$DISPATCH_TRACE" "$COMPILE_TRACE" "$MEGA_TRACE"; rm -rf "$COMPILE_CACHE" "$MEGA_CACHE"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_MEGAFUSION=1 KEYSTONE_COMPILE_CACHE="$MEGA_CACHE" \
KEYSTONE_TRACE="$MEGA_TRACE" python - <<'PY'
# One example apply run TWICE under megafusion against a fresh
# persistent-cache dir with tracing armed: each apply run must execute
# exactly ONE program (the whole-plan scan-bodied megafused program),
# the warm second run must perform zero cold compiles, and the trace's
# dispatch digest must carry the per-plan breakdown row showing it.
import json, os
from keystone_tpu.dispatch_bench import measure_example
from keystone_tpu.telemetry import compiles_snapshot
from keystone_tpu.workflow.executor import drain_warmups

r1 = measure_example("MnistRandomFFT", "megafused")
assert r1["apply_run_programs"] == 1, r1["apply_run_programs"]
drain_warmups()  # background AOT compiles count against run 1
first = compiles_snapshot()
r2 = measure_example("MnistRandomFFT", "megafused")
assert r2["apply_run_programs"] == 1, r2["apply_run_programs"]
drain_warmups()
second = compiles_snapshot()
new_cold = second["programs_compiled"] - first["programs_compiled"]
assert new_cold == 0, (
    f"warm megafused run performed {new_cold} cold compile(s)")

import keystone_tpu.telemetry.spans as spans
from keystone_tpu.telemetry.export import (
    dispatch_plan_breakdown, dispatch_summary, write_trace)
tracer = spans.current_tracer()
assert tracer is not None, "KEYSTONE_TRACE did not arm the ambient tracer"
write_trace(tracer, os.environ["KEYSTONE_TRACE"])

trace = json.load(open(os.environ["KEYSTONE_TRACE"]))
rows = dispatch_plan_breakdown(trace)
assert rows and "megafused=1" in rows[0], rows
summary = dispatch_summary(trace)
assert summary is not None and "megafused" in summary, summary
print(f"megafusion smoke: {rows[0]}; run2 +0 cold OK")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry "$MEGA_TRACE" >/dev/null

echo "== ledger smoke (decision records match enforced plan tags; self-diff clean) =="
LEDGER_TRACE="$(mktemp /tmp/keystone_ledger_smoke.XXXXXX.json)"
LEDGER_FILE="$(mktemp /tmp/keystone_ledger_smoke.XXXXXX.jsonl)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP" "$DISPATCH_TRACE" "$COMPILE_TRACE" "$MEGA_TRACE" "$LEDGER_TRACE" "$LEDGER_FILE"; rm -rf "$COMPILE_CACHE" "$MEGA_CACHE"' EXIT
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
KEYSTONE_TRACE="$LEDGER_TRACE" KEYSTONE_LEDGER="$LEDGER_FILE" python - <<'PY'
# One example pipeline (the dispatch-bench MnistRandomFFT instance,
# full default stack: megafusion + sharding planner + precision with
# the floor dropped) run end-to-end with the trace AND the decision
# ledger armed. The gate: the JSONL ledger parses, EVERY enforced plan
# tag in the executed graphs (fused/megafused program operators,
# planned_out_spec placements, planned_precision policies) has a
# matching decision record of the right kind covering its vertex, and
# every record carries chosen + >=1 priced alternative + predicted cost.
import os
import numpy as np
from keystone_tpu import PipelineEnv
from keystone_tpu.dispatch_bench import EXAMPLES, _plan_context
from keystone_tpu.telemetry import ledger
from keystone_tpu.workflow.env import (
    config_override, dispatch_override, overlap_override)

optimizer, overlap_on, concurrent_on, overrides = _plan_context("precision")
PipelineEnv.reset()
PipelineEnv.get().set_optimizer(optimizer)
with overlap_override(overlap_on), dispatch_override(concurrent_on), \
        config_override(**overrides):
    predictor, train, test = EXAMPLES["MnistRandomFFT"]()
    fit_res = predictor(train)
    fit_res.get()
    apply_res = predictor(test)
    apply_res.get()

    run = ledger.read_ledger(os.environ["KEYSTONE_LEDGER"])
    assert run["header"]["ledger_version"] == ledger.LEDGER_VERSION
    assert run["header"]["config"]["megafusion"] is True, run["header"]
    decisions = run["decisions"]
    assert decisions, "armed run recorded no decisions"
    for d in decisions:
        assert d["enforced"], d
        assert d["chosen"] and len(d["alternatives"]) >= 1, d
        assert d["predicted"], d

    # every enforced plan tag has a matching decision record
    from keystone_tpu.nodes.util.fusion import FusedBatchTransformer
    from keystone_tpu.workflow.fusion_rule import (
        FusedChainOperator, MegafusedPlanOperator)
    by_kind = {}
    for d in decisions:
        for v in d["vertices"]:
            by_kind.setdefault(d["kind"], set()).add(int(v))
    checked = {"fusion": 0, "megafusion": 0, "placement": 0,
               "precision": 0}
    for res in (fit_res, apply_res):
        graph = res.executor.optimized_graph
        for vid, op in graph.operators.items():
            tags = []
            if isinstance(op, MegafusedPlanOperator):
                tags.append("megafusion")
            elif isinstance(op, (FusedChainOperator, FusedBatchTransformer)):
                tags.append("fusion")
            if getattr(op, "planned_out_spec", None) is not None:
                tags.append("placement")
            if getattr(op, "planned_precision", None) is not None:
                tags.append("precision")
            for kind in tags:
                vertices = by_kind.get(kind, set())
                assert vid.id in vertices, (
                    f"enforced {kind} tag on vertex {vid.id} "
                    f"({op.label}) has no matching decision record "
                    f"(recorded vertices: {sorted(vertices)})")
                checked[kind] += 1
    assert checked["fusion"] or checked["megafusion"], checked

    # flush the ambient trace so the CLI can join decisions with
    # observations on this same artifact
    import keystone_tpu.telemetry.spans as spans
    from keystone_tpu.telemetry.export import write_trace
    tracer = spans.current_tracer()
    assert tracer is not None, "KEYSTONE_TRACE did not arm the tracer"
    write_trace(tracer, os.environ["KEYSTONE_TRACE"])
PipelineEnv.reset()
print("ledger smoke: " + ", ".join(
    f"{k}={v}" for k, v in sorted(checked.items())) + " plan tags matched")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --ledger "$LEDGER_FILE" >/dev/null
# a run diffed against itself must report zero regressions (exit 0)
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --diff "$LEDGER_FILE" "$LEDGER_FILE"

echo "== live-telemetry smoke (tight SLO breaches on a real apply; flight dump + conformance record) =="
LIVE_LEDGER="$(mktemp /tmp/keystone_live_smoke.XXXXXX.jsonl)"
LIVE_FLIGHT="$(mktemp -d /tmp/keystone_live_smoke.XXXXXX)"
trap 'rm -f "$SHARDING_JSON" "$PLANNER_JSON" "$PRECISION_JSON" "$ROOFLINE_JSON" "$UNIFIED_JSON" "$SERVING_JSON" "$TRACE_TMP" "$DISPATCH_TRACE" "$COMPILE_TRACE" "$MEGA_TRACE" "$LEDGER_TRACE" "$LEDGER_FILE" "$LIVE_LEDGER"; rm -rf "$COMPILE_CACHE" "$MEGA_CACHE" "$LIVE_FLIGHT"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_LEDGER="$LIVE_LEDGER" \
KEYSTONE_FLIGHT_DIR="$LIVE_FLIGHT" python - <<'PY'
# Arm the conformance watchdog with an artificially tight certificate
# (1 ns bound at every ladder shape), run a real warm apply through
# `request_scope`, and assert the breach path end-to-end: the breach
# counter fires, the flight-ring dump the breach triggered parses as a
# Chrome trace, and the conformance ledger record names the certified
# bound the observed latency was compared against.
import numpy as np
from keystone_tpu import PipelineEnv
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.dispatch_bench import EXAMPLES
from keystone_tpu.telemetry import ledger, registry
from keystone_tpu.telemetry.export import load_trace
from keystone_tpu.telemetry.flight import ensure_flight, reset_flight
from keystone_tpu.telemetry.streaming import health, reset_live
from keystone_tpu.telemetry.watchdog import arm_watchdog, disarm_watchdog

TIGHT = 1e-9
PipelineEnv.reset()
predictor, train, test = EXAMPLES["MnistRandomFFT"]()
fitted = predictor.fit()
X = np.asarray(test.numpy())[:64]
np.asarray(fitted.apply(Dataset.from_numpy(X)).numpy())  # warm the shape

ensure_flight()
wd = arm_watchdog({
    "slo_seconds": TIGHT, "certified": True,
    "shapes": [{"batch": b, "predicted_seconds": TIGHT}
               for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                         1024, 2048, 4096)],
}, pipeline="MnistRandomFFT")
assert wd is not None, "watchdog did not arm from the tight certificate"
mark = ledger.session_mark()
np.asarray(fitted.apply(Dataset.from_numpy(X)).numpy())

assert wd.breaches >= 1, f"no breach under a {TIGHT}s bound: {wd.describe()}"
reg = registry()
assert reg.counter("serving.slo_breaches").value >= 1
assert reg.counter("serving.conformance_checks").value >= 1
recs = [d for d in ledger.session_since(mark) if d["kind"] == "conformance"]
assert recs, "breach emitted no conformance ledger record"
rec = recs[0]
assert rec["predicted"]["bound_seconds"] == TIGHT, rec["predicted"]
assert rec["chosen"]["observed_seconds"] > TIGHT
assert rec["alternatives"][0]["cost_seconds"] == TIGHT
dump = rec["chosen"]["flight_dump"]
assert dump, "breach did not dump the flight ring"
trace = load_trace(dump)  # the dump is a valid Chrome trace
assert trace.get("keystone", {}).get("flight", {}).get("capacity", 0) > 0
h = health()
assert h["counters"]["serving.slo_breaches"]["value"] >= 1, h["counters"]
assert any(r["count"] >= 1 for r in h["latency"]), h["latency"]
disarm_watchdog()
reset_live()
reset_flight()
PipelineEnv.reset()
print(f"live-telemetry smoke: {len(recs)} breach record(s), "
      f"dump {int(trace['keystone']['flight']['spans_held'])} span(s) OK")
PY
# the JSONL ledger the breach appended renders through the --ledger CLI,
# and the breach dump renders through the --flight CLI
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --ledger "$LIVE_LEDGER" >/dev/null
LIVE_DUMP="$(ls "$LIVE_FLIGHT"/keystone_flight_*.json | head -1)"
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --flight "$LIVE_DUMP" >/dev/null

echo "== serving-runtime smoke (certified micro-batching: ladder-only dispatch, 0 cold compiles, handoff record) =="
SERVING_SMOKE_LEDGER="$(mktemp /tmp/keystone_serving_rt_smoke.XXXXXX.jsonl)"
JAX_PLATFORMS=cpu KEYSTONE_LEDGER="$SERVING_SMOKE_LEDGER" python - <<'PY'
# Start the real certified serving runtime on MnistRandomFFT, fire
# concurrent requests through the coalescing path, and assert the
# start-sequence contract end-to-end: every dispatched batch shape sits
# on the certificate's warmed pad ladder (ragged coalesced counts pad
# onto a rung, never compile their own program), the warm window
# performs 0 cold compiles, the conformance watchdog records 0
# breaches, results equal direct FittedPipeline.apply, and the ledger
# carries the serving_handoff record binding certificate to runtime.
import threading

import numpy as np

from keystone_tpu import PipelineEnv
from keystone_tpu.analysis import ServingEnvelope
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.dispatch_bench import EXAMPLES
from keystone_tpu.serving import NdarrayIngress, ServingRuntime
from keystone_tpu.telemetry import ledger
from keystone_tpu.telemetry.streaming import reset_live
from keystone_tpu.telemetry.watchdog import active_watchdog, disarm_watchdog

PipelineEnv.reset()
reset_live()
predictor, train, test = EXAMPLES["MnistRandomFFT"]()
fitted = predictor.fit()
X = np.asarray(test.numpy())
ref = np.asarray(fitted.apply(Dataset.from_numpy(X)).numpy())

mark = ledger.session_mark()
rt = ServingRuntime(
    fitted, NdarrayIngress(X.shape[1:]),
    envelope=ServingEnvelope(max_batch=8, slo_seconds=1.0),
    name="MnistRandomFFT").start()
try:
    from jax._src import monitoring

    compiles = []

    def listener(name, **kw):
        if name == "/jax/compilation_cache/compile_requests_use_cache":
            compiles.append(name)

    monitoring.register_event_listener(listener)
    try:
        results, errors = {}, []

        def client(i):
            try:
                results[i] = rt.submit(X[i])
            except Exception as e:  # noqa: BLE001 - asserted below
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        try:
            monitoring._event_listeners.remove(listener)
        except ValueError:
            monitoring.clear_event_listeners()

    assert not errors, errors[:3]
    assert len(results) == 32
    for i, out in results.items():
        assert np.allclose(out, ref[i]), i
    stats = rt.stats()
    assert stats["dispatched_shapes"], "nothing dispatched"
    assert stats["dispatched_outside_ladder"] == [], (
        "a dispatch left the certified ladder: "
        f"{stats['dispatched_shapes']} vs {stats['ladder']}")
    assert not compiles, (
        f"{len(compiles)} cold compile(s) while serving on a warm "
        "runtime — the warmed-manifest claim is broken")
    wd = active_watchdog()
    assert wd is not None and wd.describe()["breaches"] == 0, (
        wd and wd.describe())
    checked = wd.describe()["checked"]
    handoffs = [d for d in ledger.session_since(mark)
                if d["kind"] == "serving_handoff"]
    assert handoffs, "runtime start emitted no serving_handoff record"
    h = handoffs[0]
    assert h["chosen"]["entry"] == "coalesced micro-batching", h["chosen"]
    assert h["chosen"]["ladder_shapes"] == stats["ladder"], h["chosen"]
    assert h["chosen"]["warmed_sites"] == rt.warmed_sites
finally:
    rt.stop()
disarm_watchdog()
reset_live()
PipelineEnv.reset()
print(f"serving-runtime smoke: 32 requests, shapes "
      f"{stats['dispatched_shapes']} on ladder {stats['ladder']}, "
      f"0 cold compiles, {checked} watchdog checks / 0 breaches, "
      f"{len(handoffs)} handoff record(s) OK")
PY
# the handoff record the start appended renders through the --ledger CLI
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --ledger "$SERVING_SMOKE_LEDGER" >/dev/null
rm -f "$SERVING_SMOKE_LEDGER"

echo "== out-of-core smoke (dataset 8x budget: windowed peak under budget, warm 0-cold, spill decision in ledger) =="
OOC_LEDGER="$(mktemp /tmp/keystone_ooc_smoke.XXXXXX.jsonl)"
OOC_CACHE="$(mktemp -d /tmp/keystone_ooc_cache.XXXXXX)"
JAX_PLATFORMS=cpu KEYSTONE_LEDGER="$OOC_LEDGER" \
KEYSTONE_COMPILE_CACHE="$OOC_CACHE" python - <<'PY'
# Two halves of the out-of-core contract. (1) Streaming: a synthetic
# dataset 8x a synthetic HBM budget streams through the windowed spill
# prefetcher into normal-equation accumulators — the warm second pass
# performs 0 cold compiles (every window pads onto an already-compiled
# ladder rung), observed live device bytes stay under the budget, and
# index coverage is exact. (2) Planning: the unified planner, given a
# budget every device cache busts, enforces a HOST-placed CacheMarker
# end-to-end and appends a kind="spill" ledger record whose
# alternatives price the infeasible device cache (INF) against the
# feasible host spill; the kill-switch arm enforces no host placement
# and keeps an empty spill set.
import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu import PipelineEnv
from keystone_tpu.data.dataset import Dataset
from keystone_tpu.loaders import synthetic_out_of_core
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
from keystone_tpu.nodes.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.nodes.util import ClassLabelIndicatorsFromInt, MaxClassifier
from keystone_tpu.telemetry import compiles_snapshot, ledger
from keystone_tpu.telemetry.compile_events import install_compile_listeners
from keystone_tpu.utils.batching import stream_spill_windows
from keystone_tpu.workflow.autocache import CacheMarker
from keystone_tpu.workflow.env import config_override
from keystone_tpu.workflow.executor import drain_warmups

PipelineEnv.reset()
install_compile_listeners()

# -- (1) windowed streaming under an 8x-too-small budget -----------------
n, dim, window = 32768, 64, 512
budget = n * dim * 4 // 8
source = synthetic_out_of_core(n, dim, shard_rows=4096)
W = jnp.asarray(np.random.default_rng(7)
                .standard_normal((dim, dim)).astype(np.float32) * 0.05)

@jax.jit
def accum(ata, xb):
    f = jnp.maximum(xb @ W, 0.0)
    return ata + f.T @ f

def windowed_pass(track_peak=False):
    ata = jnp.zeros((dim, dim), jnp.float32)
    seen, peak = [], 0
    for idxs, win in stream_spill_windows(source.row_loader, n,
                                          window=window):
        ata = accum(ata, win)
        seen.extend(int(i) for i in idxs)
        if track_peak:
            jax.block_until_ready(ata)
            peak = max(peak, sum(int(a.nbytes) for a in jax.live_arrays()))
    return ata, seen, peak

windowed_pass()          # cold pass: compiles the ladder rungs
drain_warmups()
first = compiles_snapshot()
ata, seen, peak = windowed_pass(track_peak=True)
drain_warmups()
second = compiles_snapshot()
new_cold = second["programs_compiled"] - first["programs_compiled"]
assert new_cold == 0, (
    f"warm windowed pass performed {new_cold} cold compile(s): "
    f"{first} -> {second}")
assert sorted(seen) == list(range(n)), (
    f"window index coverage broken: {len(seen)} indices for {n} rows")
assert peak <= budget, (
    f"windowed pass peaked at {peak} device bytes against a "
    f"{budget}-byte budget (dataset is {n * dim * 4})")

# -- (2) planner-enforced host spill + ledger record ---------------------
def predictor(data, labels_ds, fdim=64, classes=4):
    featurizer = (RandomSignNode(fdim).to_pipeline()
                  >> PaddedFFT() >> LinearRectifier(0.0))
    labels = ClassLabelIndicatorsFromInt(classes)(labels_ds)
    return featurizer.and_then(
        BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3),
        data, labels) >> MaxClassifier()

rng = np.random.default_rng(11)
X = rng.standard_normal((16384, 64)).astype(np.float32)
y = rng.integers(0, 4, size=16384).astype(np.int32)

def markers_under(spill_budget, **cfg):
    PipelineEnv.reset()
    with config_override(unified_min_savings_seconds=0.0,
                         hbm_budget_bytes=spill_budget, **cfg):
        applied = predictor(Dataset.from_numpy(X),
                            Dataset.from_numpy(y))(Dataset.from_numpy(X))
        g = applied.executor.optimized_graph
        return [(v.id, g.get_operator(v).placement) for v in g.operators
                if isinstance(g.get_operator(v), CacheMarker)]

mark = ledger.session_mark()
spill_markers = markers_under(64 << 10)
assert any(p == "host" for _, p in spill_markers), (
    f"64KiB budget enforced no host placement: {spill_markers}")
spills = [d for d in ledger.session_since(mark) if d["kind"] == "spill"]
assert spills, "spill enforcement appended no kind='spill' ledger record"
rec = spills[0]
assert rec["chosen"]["placement"] == "host", rec["chosen"]
assert rec["chosen"]["spills"][0]["reload_seconds"] > 0, rec["chosen"]
alts = rec["alternatives"]
assert any(a["entry"].startswith("cache_") and not a["feasible"]
           for a in alts), (
    "spill record prices no infeasible device-cache alternative", alts)
assert any(a["entry"].startswith("spill_") and a["feasible"]
           for a in alts), (
    "spill record prices no feasible spill alternative", alts)

kill_markers = markers_under(64 << 10, ooc_spill=False)
assert not any(p == "host" for _, p in kill_markers), (
    f"KEYSTONE_OOC_SPILL=0 arm still placed a host cache: {kill_markers}")

PipelineEnv.reset()
print(f"out-of-core smoke: {n * dim * 4 >> 20}MiB dataset / "
      f"{budget >> 10}KiB budget, peak {peak >> 10}KiB, warm +0 cold, "
      f"host marker {spill_markers} with {len(alts)} priced "
      f"alternative(s); kill switch clean OK")
PY
# the spill record the enforcement appended renders through --ledger
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry --ledger "$OOC_LEDGER" >/dev/null
rm -f "$OOC_LEDGER"; rm -rf "$OOC_CACHE"

echo "lint: OK"
