#!/usr/bin/env bash
# Fast pre-test lint gate: AST-level JAX lints + static validation of
# every example pipeline. Runs in seconds with no data and no devices
# beyond the CPU backend (the pipeline validator traces with
# jax.eval_shape only). Mirrored in tier-1 by the `lint` pytest marker
# (tests/test_jaxlint.py, tests/test_analysis.py).
#
#   scripts/lint.sh              # whole gate
#   scripts/lint.sh --list-rules # rule catalog
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--list-rules" ]]; then
    python scripts/jaxlint.py --list-rules
    JAX_PLATFORMS=cpu python -m keystone_tpu.analysis --list-rules
    exit 0
fi

echo "== jaxlint (AST rules) =="
python scripts/jaxlint.py keystone_tpu

echo "== pipeline validation (abstract specs) =="
JAX_PLATFORMS=cpu python -m keystone_tpu.analysis "$@"

echo "== telemetry smoke (trace a tiny pipeline, validate the JSON) =="
TRACE_TMP="$(mktemp /tmp/keystone_trace_smoke.XXXXXX.json)"
trap 'rm -f "$TRACE_TMP"' EXIT
JAX_PLATFORMS=cpu KEYSTONE_SMOKE_TRACE="$TRACE_TMP" python - <<'PY'
import json, os
import numpy as np
from keystone_tpu import Dataset, Transformer
from keystone_tpu.telemetry import trace_run

path = os.environ["KEYSTONE_SMOKE_TRACE"]
with trace_run(path):
    pipe = Transformer.from_function(lambda x: x * 2.0).to_pipeline()
    pipe(Dataset.from_numpy(np.ones((8, 4), np.float32))).get()
trace = json.load(open(path))
events = trace["traceEvents"]
assert isinstance(events, list) and events, "empty traceEvents"
for e in events:
    assert "ph" in e and "name" in e and "pid" in e, e
assert any(e.get("cat") == "node" for e in events), "no node-force spans"
assert "keystone" in trace and "metrics" in trace["keystone"]
print(f"telemetry smoke: {len(events)} events OK")
PY
JAX_PLATFORMS=cpu python -m keystone_tpu.telemetry "$TRACE_TMP" >/dev/null

echo "lint: OK"
