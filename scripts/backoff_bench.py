"""StupidBackoff at reference scale (VERDICT r4 #8).

Builds a ≥1M-distinct-ngram synthetic corpus (Zipf unigram distribution
over a 50k vocabulary — the shape of real text frequency tables), fits
`PackedStupidBackoffEstimator`, and scores every corpus trigram through
the iterative vectorized path. Prints one JSON line with fit time,
scores/sec, and the model's measured memory bound
(12 bytes/distinct-ngram + the unigram vector).

Host-side by design: the model is a lookup table — the reference scored
on the cluster's JVMs (StupidBackoff.scala:61-121, partition-local via
InitialBigramPartitioner:25-59); the packed layout reconstructs that
locality as a first-two-words-major sort order.

Usage: python scripts/backoff_bench.py [--tokens 3000000] [--vocab 50000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tokens", type=int, default=3_000_000)
    p.add_argument("--vocab", type=int, default=50_000)
    p.add_argument("--doc-len", type=int, default=200)
    p.add_argument("--out", default="-")
    args = p.parse_args()

    from keystone_tpu.data.dataset import HostDataset
    from keystone_tpu.nodes.nlp import PackedStupidBackoffEstimator

    rng = np.random.default_rng(0)
    n_docs = args.tokens // args.doc_len
    # Zipf(1.3) truncated to the vocabulary: heavy head, long tail —
    # yields >1M distinct 2/3-gram types at 3M tokens
    words = [f"w{i}" for i in range(args.vocab)]
    t0 = time.perf_counter()
    docs = []
    for _ in range(n_docs):
        ids = rng.zipf(1.3, size=args.doc_len) % args.vocab
        docs.append([words[j] for j in ids])
    gen_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    model = PackedStupidBackoffEstimator().fit(HostDataset(docs))
    fit_s = time.perf_counter() - t0
    n_types = len(model.keys)

    # score every corpus trigram (mix of seen/backed-off after dedup,
    # since repeated trigrams were counted once but queried many times)
    t0 = time.perf_counter()
    id_rows = []
    for doc in docs:
        ids = np.array([model.vocab[w] for w in doc], np.int64)
        tri = np.stack([ids[:-2], ids[1:-1], ids[2:]], axis=1)
        id_rows.append(tri)
    queries = np.concatenate(id_rows)
    prep_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    scores = model.score_ids(queries)
    score_s = time.perf_counter() - t0
    assert np.isfinite(scores).all() and (scores > 0).all()

    record = {
        "workload": "stupid-backoff reference-scale scoring (host)",
        "corpus_tokens": n_docs * args.doc_len,
        "vocab": args.vocab,
        "distinct_ngram_types_2_3": n_types,
        "fit_seconds": round(fit_s, 2),
        "queries": int(len(queries)),
        "score_seconds": round(score_s, 3),
        "scores_per_sec": round(len(queries) / score_s, 0),
        "query_prep_seconds": round(prep_s, 2),
        "corpus_gen_seconds": round(gen_s, 2),
        "model_bytes": int(model.nbytes),
        "bytes_per_type": round(model.nbytes / max(n_types, 1), 1),
        "memory_bound": "12 B/distinct 2-3gram (8 key + 4 count) + "
                        "8 B/vocab word; independent of corpus tokens",
        "mean_score": float(np.mean(scores)),
    }
    line = json.dumps(record)
    print(line)
    if args.out != "-":
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
