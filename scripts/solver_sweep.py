"""Solver-comparison sweep mirroring the reference's only published
performance table (scripts/solver-comparisons-final.csv, plotted by
constantEstimator.R — see BASELINE.md): Exact vs Block vs LS-LBFGS train
times on TIMIT-shaped dense and Amazon-shaped sparse workloads.

Reference hardware was 16× r3.4xlarge (Spark cluster); this sweep runs
each solver on ONE TPU chip at the same (n, d, k, sparsity) where the
arrays fit single-chip HBM, and at proportionally reduced n otherwise
(recorded per row as `n_scale`; the reference solves are all
O(n·d·B)-dominated, so time scales ~linearly in n and `scaled_time_ms`
= measured/n_scale estimates the full-n single-chip time).

Usage:  python scripts/solver_sweep.py [--out SOLVERS_BENCH.json]
        [--quick]    # tiny shapes, CPU smoke test

Timing follows the tunnel-safe pattern (memoizing transport, ~69 ms
RTT): jit once at fixed shapes, warm, then time a fresh-valued run and
force a host transfer of a scalar of the result.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

OOM_RC = 17  # child exit code: HBM exhausted at this n — parent shrinks

# allow `python scripts/solver_sweep.py` without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Reference rows (BASELINE.md / solver-comparisons-final.csv:1-27, times
# in ms on 16x r3.4xlarge). The reference has no Exact row at d=16384.
REFERENCE_MS = {
    ("timit", "exact", 1024): 7_323,
    ("timit", "block", 1024): 33_521,
    ("timit", "lbfgs", 1024): 70_396,
    ("timit", "exact", 2048): 17_949,
    ("timit", "block", 2048): 61_395,
    ("timit", "lbfgs", 2048): 98_834,
    ("timit", "exact", 4096): 76_562,
    ("timit", "block", 4096): 120_998,
    ("timit", "lbfgs", 4096): 259_498,
    ("timit", "exact", 8192): 315_183,
    ("timit", "block", 8192): 255_570,
    ("timit", "lbfgs", 8192): 810_286,
    ("timit", "block", 16384): 580_555,
    ("timit", "lbfgs", 16384): 1_589_308,
    ("amazon", "lbfgs", 1024): 33_704,
    ("amazon", "lbfgs", 2048): 33_643,
    ("amazon", "lbfgs", 4096): 40_606,
    ("amazon", "lbfgs", 8192): 45_407,
    ("amazon", "lbfgs", 16384): 52_290,
}

TIMIT_N, TIMIT_K = 2_200_000, 138  # constantEstimator.R:33-36
AMAZON_N, AMAZON_K, AMAZON_SPARSITY = 65_000_000, 2, 0.005


_PERTURB_RNG = np.random.default_rng()  # entropy-seeded on purpose


def _fit_once(est, data, labels):
    """Train-time of one fit with a host-transfer sync on the model.

    The input values are perturbed on-device by a fresh tiny scalar
    first: the axon transport memoizes byte-identical executions, so a
    repeat fit on the exact same values would return instantly and time
    nothing. The perturbation is one fused elementwise pass (no host
    round trip) and leaves the solve's arithmetic profile unchanged."""
    eps = float(_PERTURB_RNG.random()) * 1e-6
    if hasattr(data, "map_batches"):
        data = data.map_batches(lambda x: x * (1.0 + eps))
        # perturbation pass must not land inside the timed fit window
        # (dispatch is async, and block_until_ready does not actually
        # block through the axon tunnel — PERF.md methodology): fence
        # with a tiny value transfer, same as the post-fit sync
        np.asarray(data.array[:1, :1]).sum()
    elif hasattr(data, "idx") and hasattr(data, "val"):
        # device-resident padded sparse: perturb both orientations by the
        # same factor (they must describe the same matrix), fence before
        # the timed window
        from keystone_tpu.data.sparse import PaddedSparseDataset

        data = PaddedSparseDataset(
            data.idx, data.val * (1.0 + eps), data.dim, mesh=data.mesh,
            nnz=data.nnz, cidx=data.cidx,
            cval=None if data.cval is None else data.cval * (1.0 + eps))
        np.asarray(data.val[:1, :1]).sum()
    elif hasattr(data, "matrix"):  # sparse: fresh values keep the
        # on-device Gram L-BFGS iterations out of the transport memo too
        m = data.matrix.copy()
        m.data = m.data * (1.0 + eps)
        data = type(data)(m, mesh=data.mesh)
    t0 = time.perf_counter()
    model = est.fit(data, labels)
    np.asarray(model.W[:1, :1]).sum()  # device slice first: sync via a
    # scalar transfer, not a full-model pull through the tunnel
    return (time.perf_counter() - t0) * 1e3


def _amazon_route(d: int):
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    w = max(1, int(d * AMAZON_SPARSITY))
    est = SparseLBFGSwithL2(lam=1e-2, num_iters=20)
    return est._route(AMAZON_N, d, AMAZON_K, w), w


def _amazon_n_budget(d: int) -> int:
    """Largest row count the 16 GB chip can hold for an Amazon-shaped
    problem in the slot-major layout, by solver route. Gram route:
    idx+val at 8 sublane-padded slots (8·w8) + labels (4·k8) + the
    streamed dense block / G / C (amortized constant). Iterative route
    adds the column form (~8.4·w), residual + two transients (12·k8),
    mask, and the with_column_form sort transient (~16·w), whichever
    phase peaks."""
    from keystone_tpu.data.sparse import sublane_pad8

    route, w = _amazon_route(d)
    w8, k8 = sublane_pad8(w), sublane_pad8(AMAZON_K)
    if route == "gram":
        # 12·w8: idx+val plus the fresh-value perturbed copy of val
        # that _fit_once keeps live during the timed fit
        per_row = 12.0 * w8 + 4.0 * k8
        return int(12.0e9 / per_row)
    solve_peak = 8.0 * w8 + 8.4 * w + 16.0 * k8 + 4.0
    build_peak = 8.0 * w8 + 8.4 * w + 16.0 * w + 4.0 * k8
    return int(13.0e9 / max(solve_peak, build_peak))


def measure_amazon_row(d: int, n: int, n_full: int,
                       precision: str = "highest") -> dict:
    """Generate an Amazon-shaped problem slot-major ON DEVICE at row
    count n and time the cost-routed sparse L-BFGS fit (warm, fresh
    values). Runs in its own process under the sweep driver so an OOM
    cannot poison later attempts."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.data.sparse import PaddedSparseDataset
    from keystone_tpu.nodes.learning import SparseLBFGSwithL2

    w = max(1, int(d * AMAZON_SPARSITY))

    @jax.jit
    def make_sparse(key):
        ki, kv, ky = jax.random.split(key, 3)
        idxT = jax.random.randint(ki, (w, n), 0, d, jnp.int32)
        valT = jax.random.normal(kv, (w, n), jnp.float32)
        Yt = jax.random.normal(ky, (AMAZON_K, n), jnp.float32)
        return idxT, valT, Yt

    route, _ = _amazon_route(d)
    idxT, valT, Yt = make_sparse(jax.random.PRNGKey(d))
    sd = PaddedSparseDataset(idxT, valT, d, nnz=n * w)
    if route == "iterative":  # gram never touches the column form
        sd = sd.with_column_form()
    est = SparseLBFGSwithL2(lam=1e-2, num_iters=20,
                            gram_precision=precision)
    _fit_once(est, sd, Yt)
    ms = _fit_once(est, sd, Yt)
    n_scale = n / n_full
    ref = REFERENCE_MS.get(("amazon", "lbfgs", d))
    scaled = ms / max(n_scale, 1e-9)
    row = {
        "experiment": "amazon-shaped", "solver": f"sparse-lbfgs-{route}",
        "d": d, "n": n, "n_scale": round(n_scale, 6),
        "sparsity": AMAZON_SPARSITY,
        "time_ms": round(ms, 1),
        "scaled_time_ms": round(scaled, 1),
        "reference_ms_16xr3.4xlarge": ref,
        "speedup_vs_reference": round(ref / scaled, 2) if ref else None,
    }
    if precision != "highest":
        row["gram_precision"] = precision
    return row


def run_sweep(quick: bool = False, hbm_budget_bytes: float = 12e9,
              experiments: tuple = ("timit", "amazon")):
    import jax

    from keystone_tpu.data.dataset import Dataset
    from keystone_tpu.nodes.learning import (
        BlockLeastSquaresEstimator,
        DenseLBFGSwithL2,
        LinearMapEstimator,
        SparseLBFGSwithL2,
    )

    rows = []
    dims = (256,) if quick else (1024, 2048, 4096, 8192, 16384)
    n_full = 20_000 if quick else TIMIT_N
    k = TIMIT_K
    rng = np.random.default_rng(0)

    import jax.numpy as jnp

    def gen_problem(n, d, k, seed):
        """Generate the regression problem ON DEVICE (jitted PRNG +
        GEMM): host numpy generation + device_put of multi-GB arrays is
        both slow through the tunnel and, if the process dies
        mid-transfer, can wedge it (same rationale as bench._flagship_bcd)."""

        @jax.jit
        def make(key):
            kx, kw, ke = jax.random.split(key, 3)
            X = jax.random.normal(kx, (n, d), jnp.float32)
            W = jax.random.normal(kw, (d, k), jnp.float32) * 0.1
            Y = X @ W + 0.01 * jax.random.normal(ke, (n, k), jnp.float32)
            return X, Y

        X, Y = make(jax.random.PRNGKey(seed))
        return Dataset(X), Dataset(Y)

    for d in (dims if "timit" in experiments else ()):
        # fit (X, Y, residual copies ~3 n·d f32 buffers) in HBM
        n = min(n_full, int(hbm_budget_bytes / (3 * 4 * d)))
        n_scale = n / n_full
        data, labels = gen_problem(n, d, k, seed=d)
        solvers = {
            "exact": LinearMapEstimator(lam=1e-2),
            "block": BlockLeastSquaresEstimator(
                block_size=min(4096, d), num_iter=3, lam=1e-2
            ),
            "lbfgs": DenseLBFGSwithL2(lam=1e-2, num_iters=20),
        }
        for name, est in solvers.items():
            _fit_once(est, data, labels)  # warm (compile at these shapes)
            ms = _fit_once(est, data, labels)
            ref = REFERENCE_MS.get(("timit", name, d))
            scaled = ms / max(n_scale, 1e-9)
            rows.append({
                "experiment": "timit-shaped", "solver": name, "d": d,
                "n": n, "n_scale": round(n_scale, 4),
                "time_ms": round(ms, 1),
                "scaled_time_ms": round(scaled, 1),
                "reference_ms_16xr3.4xlarge": ref,
                "speedup_vs_reference": (
                    round(ref / scaled, 2) if ref else None
                ),
            })
            print(json.dumps(rows[-1]), flush=True)
        del data, labels

    # Amazon-shaped sparse: slot-major device-resident width-padded
    # rows, solver route picked by the measured cost model (gram =
    # one-hot densify + MXU for these d's; iterative gather matvecs
    # only for hashing-scale d — see SparseLBFGSwithL2._route and
    # scripts/sparse_microbench.py). The problem is GENERATED on device
    # (jitted PRNG); each row runs in a fresh subprocess at the largest
    # n the per-route HBM budget allows (full n=65e6 at d≤2048).
    amz_n_full = 20_000 if quick else AMAZON_N
    for d in (dims if "amazon" in experiments else ()):
        n = min(amz_n_full, 20_000 if quick else _amazon_n_budget(d))
        if quick:
            row = measure_amazon_row(d, n, amz_n_full)
        else:
            # one SUBPROCESS per attempt: an HBM OOM under the tunnel
            # poisons the arena for the rest of the process (observed:
            # after one ResourceExhausted every later allocation fails
            # down to n=1M), so shrink-and-retry must start from a
            # fresh device session each time
            row = None
            while row is None:
                r = subprocess.run(
                    [sys.executable, "-u", os.path.abspath(__file__),
                     "--one-amazon", str(d), "--n", str(n)],
                    capture_output=True, text=True,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))))
                if r.returncode == 0:
                    row = json.loads(r.stdout.strip().splitlines()[-1])
                elif r.returncode == OOM_RC:
                    n = int(n * 0.8)
                    print(json.dumps({"experiment": "amazon-shaped",
                                      "d": d, "oom_retry_n": n}), flush=True)
                    if n < 1_000_000:
                        raise RuntimeError(
                            f"amazon d={d}: OOM even at n<1e6")
                else:
                    raise RuntimeError(
                        f"amazon d={d} child failed rc={r.returncode}:\n"
                        f"{r.stderr[-2000:]}")
        rows.append(row)
        print(json.dumps(row), flush=True)

    return {
        "workload": "solver sweep (BASELINE.md / solver-comparisons-final.csv)",
        "platform": jax.devices()[0].platform,
        "chips": 1,
        "reference_hardware": "16x r3.4xlarge (Spark)",
        "rows": rows,
    }


def write_csv(result, path):
    """Emit the sweep in the reference table's column style
    (solver-comparisons-final.csv header + our scaling columns)."""
    import csv

    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow([
            "Experiment", "Solver", "Num Features", "n", "n_scale",
            "Time (ms)", "Scaled Time at ref n (ms)",
            "Reference (ms, 16x r3.4xlarge)", "Speedup vs reference",
        ])
        for r in result["rows"]:
            w.writerow([
                r["experiment"], r["solver"], r["d"], r["n"], r["n_scale"],
                r["time_ms"], r["scaled_time_ms"],
                r.get("reference_ms_16xr3.4xlarge") or "",
                r.get("speedup_vs_reference") or "",
            ])


def main():
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--out", default="SOLVERS_BENCH.json")
    p.add_argument("--csv", default="SOLVERS_SWEEP.csv")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--experiments", nargs="+", default=["timit", "amazon"],
                   choices=["timit", "amazon"],
                   help="subset to run (e.g. re-measure amazon alone)")
    p.add_argument("--one-amazon", type=int, default=None, metavar="D",
                   help="(internal) measure one amazon row at --n rows "
                        "in this process; prints the row JSON")
    p.add_argument("--n", type=int, default=None)
    p.add_argument("--precision", default="highest",
                   choices=["default", "high", "highest"],
                   help="(with --one-amazon) Gram GEMM precision")
    args = p.parse_args()
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":
        # programmatic forcing works where env-var platform selection
        # can hang under plugin site hooks (see keystone_tpu/__main__.py);
        # must run before the --one-amazon child branch too
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.one_amazon is not None:
        try:
            row = measure_amazon_row(args.one_amazon, args.n, AMAZON_N,
                                     precision=args.precision)
        except RuntimeError as e:
            if any(s in str(e) for s in ("exceed memory",
                                         "RESOURCE_EXHAUSTED", "Allocation")):
                print(str(e)[-500:], file=sys.stderr)
                sys.exit(OOM_RC)
            raise
        print(json.dumps(row), flush=True)
        return
    result = run_sweep(quick=args.quick,
                       experiments=tuple(args.experiments))
    if set(args.experiments) != {"timit", "amazon"} and os.path.exists(args.out):
        # subset re-measure: keep the other experiments' existing rows
        # (in their original order) instead of clobbering the artifact
        with open(args.out) as f:
            prev = json.load(f)
        fresh = {e.split("-")[0] for e in args.experiments}
        kept = [r for r in prev.get("rows", [])
                if r["experiment"].split("-")[0] not in fresh]
        result["rows"] = kept + result["rows"]
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    write_csv(result, args.csv)
    print(f"wrote {args.out} + {args.csv} ({len(result['rows'])} rows)")


if __name__ == "__main__":
    main()
