"""Measure candidate TPU sparse-matvec primitives head-to-head.

The iterative sparse L-BFGS spends its whole budget in two ops:
  Xv   (n rows, w slots; table lookup W[idx] then reduce over slots)
  XᵀR  (column form: table lookup R[:, cidx] then reduce over slots)
Which XLA lowering is fast on TPU is not derivable from first
principles (gather granularity, lane vs sublane axes, scatter
serialization are all compiler-dependent), so this script times each
candidate at Amazon-like shapes and prints one JSON line per cell.

Run:  python scripts/sparse_microbench.py [--n 8000000] [--d 1024]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("KEYSTONE_BACKEND") == "cpu":
    # programmatic forcing works where env-var platform selection is
    # ignored under plugin site hooks (see keystone_tpu/__main__.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def timeit(fn, *args, reps: int = 3):
    """Warm once, then time `reps` fresh-valued executions (the axon
    transport memoizes byte-identical executions)."""
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: np.asarray(x.ravel()[:1]).sum(), out)
    best = float("inf")
    for r in range(reps):
        bumped = [a * (1 + 1e-7 * (r + 1)) if jnp.issubdtype(a.dtype, jnp.floating)
                  else a for a in args]
        t0 = time.perf_counter()
        out = fn(*bumped)
        jax.tree_util.tree_map(
            lambda x: np.asarray(x.ravel()[:1]).sum(), out)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=8_000_000)
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--w", type=int, default=5)
    p.add_argument("--block", type=int, default=1 << 19)
    args = p.parse_args()
    n, d, k, w, b = args.n, args.d, args.k, args.w, args.block
    n = n // b * b
    nb = n // b

    key = jax.random.PRNGKey(0)
    ki, kv, kw = jax.random.split(key, 3)
    idxT = jax.random.randint(ki, (w, n), 0, d, jnp.int32)   # slot-major
    valT = jax.random.normal(kv, (w, n), jnp.float32)
    W = jax.random.normal(kw, (k, d), jnp.float32)           # model space
    nnz = n * w
    meta = {"n": n, "d": d, "k": k, "w": w, "block": b,
            "platform": jax.devices()[0].platform}
    print(json.dumps({"meta": meta}), flush=True)

    def report(name, sec, flops=None):
        row = {"candidate": name, "ms": round(sec * 1e3, 2),
               "gbytes_min": round(nnz * (8 + 4 * k) / 1e9, 2),
               "eff_gbs": round(nnz * (8 + 4 * k) / sec / 1e9, 1)}
        print(json.dumps(row), flush=True)

    # A. lane-axis gather: take(table (k,d+1), idx, axis=1) — current impl
    @jax.jit
    def cand_a(valT, W):
        table = jnp.concatenate([W, jnp.zeros((k, 1), W.dtype)], axis=1)

        def body(i, R):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            g = jnp.take(table, ib, axis=1)  # (k, w, b)
            rb = jnp.einsum("wb,kwb->kb", vb, g)
            return jax.lax.dynamic_update_slice(R, rb, (0, i * b))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, n), jnp.float32))

    report("A_lane_gather", timeit(cand_a, valT, W))

    # B. row gather of a (d+1, k) table from block-transposed indices
    @jax.jit
    def cand_b(valT, W):
        table = jnp.concatenate([W.T, jnp.zeros((1, k), W.dtype)], axis=0)

        def body(i, R):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1).T  # (b, w)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1).T
            g = jnp.take(table, ib, axis=0)  # (b, w, k)
            rb = jnp.einsum("bw,bwk->bk", vb, g).T
            return jax.lax.dynamic_update_slice(R, rb, (0, i * b))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, n), jnp.float32))

    report("B_row_gather", timeit(cand_b, valT, W))

    # C. per-k 1-D table gather (k unrolled in python, tiny k)
    @jax.jit
    def cand_c(valT, W):
        tables = [jnp.concatenate([W[c], jnp.zeros((1,), W.dtype)])
                  for c in range(k)]

        def body(i, R):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            rows = [jnp.sum(vb * tables[c][ib], axis=0) for c in range(k)]
            rb = jnp.stack(rows, axis=0)
            return jax.lax.dynamic_update_slice(R, rb, (0, i * b))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, n), jnp.float32))

    report("C_1d_gather", timeit(cand_c, valT, W))

    # D. one-hot densify on MXU: dense_b = onehot GEMM, then dense @ W.T
    #    (the embedding-as-matmul idiom; cost ~ 2·b·w·d one-hot ops +
    #    2·b·d·k MXU flops per block, bf16 one-hot pass)
    @jax.jit
    def cand_d(valT, W):
        iota = jnp.arange(d + 1, dtype=jnp.int32)

        def body(i, R):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            # (b, d+1) dense block built by compare-accumulate
            dense = jnp.zeros((b, d + 1), jnp.float32)
            for j in range(w):
                dense = dense + jnp.where(
                    ib[j][:, None] == iota[None, :], vb[j][:, None], 0.0)
            rb = (dense[:, :d] @ W.T).T  # (k, b)
            return jax.lax.dynamic_update_slice(R, rb, (0, i * b))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, n), jnp.float32))

    report("D_onehot_mxu", timeit(cand_d, valT, W))

    # E. scatter-densify + MXU (the Gram-accumulate idiom)
    @jax.jit
    def cand_e(valT, W):
        rows = jnp.broadcast_to(jnp.arange(b)[None, :], (w, b))

        def body(i, R):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            dense = (jnp.zeros((b, d + 1), jnp.float32)
                     .at[rows, ib].add(vb)[:, :d])
            rb = (dense @ W.T).T
            return jax.lax.dynamic_update_slice(R, rb, (0, i * b))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, n), jnp.float32))

    report("E_scatter_mxu", timeit(cand_e, valT, W))

    # F. sort-free segment-sum tmatvec probe: XᵀR via scatter into (k, d+1)
    R = jax.random.normal(jax.random.PRNGKey(9), (k, n), jnp.float32)

    @jax.jit
    def cand_f(valT, R):
        def body(i, acc):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            Rb = jax.lax.dynamic_slice_in_dim(R, i * b, b, 1)
            contrib = vb[None, :, :] * Rb[:, None, :]
            return acc.at[:, ib.reshape(-1)].add(contrib.reshape(k, -1))

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, d + 1), jnp.float32))

    report("F_tmat_scatter", timeit(cand_f, valT, R))

    # G. tmatvec by densify + MXU: dense_bᵀ @ R_bᵀ per block
    @jax.jit
    def cand_g(valT, R):
        rows = jnp.broadcast_to(jnp.arange(b)[None, :], (w, b))

        def body(i, acc):
            ib = jax.lax.dynamic_slice_in_dim(idxT, i * b, b, 1)
            vb = jax.lax.dynamic_slice_in_dim(valT, i * b, b, 1)
            Rb = jax.lax.dynamic_slice_in_dim(R, i * b, b, 1)  # (k, b)
            dense = (jnp.zeros((b, d + 1), jnp.float32)
                     .at[rows, ib].add(vb)[:, :d])
            return acc + Rb @ dense  # (k, d)

        return jax.lax.fori_loop(0, nb, body, jnp.zeros((k, d), jnp.float32))

    report("G_tmat_mxu", timeit(cand_g, valT, R))


if __name__ == "__main__":
    main()
