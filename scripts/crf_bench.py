"""Linear-chain CRF tagger throughput (VERDICT r4 #5 perf axis).

Trains the jitted CRF on the 50k-token synthetic grammar corpus and
measures batched Viterbi decode throughput (tokens/sec, warm) plus
training wall time — the TPU-native counterpart of the reference's
Epic CRF wrappers (POSTagger.scala:24-36). Prints one JSON line.

Usage: python scripts/crf_bench.py [--sentences 4500]
       KEYSTONE_BACKEND=cpu python scripts/crf_bench.py --sentences 600
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sentences", type=int, default=4500)
    p.add_argument("--max-iter", type=int, default=50)
    p.add_argument("--out", default="-")
    args = p.parse_args()
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from keystone_tpu.nodes.nlp import LinearChainCRFTagger, generate_pos_corpus

    corpus = generate_pos_corpus(args.sentences, seed=0)
    n_train = int(len(corpus) * 8 / 9)
    train, test = corpus[:n_train], corpus[n_train:]
    n_train_tok = sum(len(s) for s in train)

    t0 = time.perf_counter()
    crf = LinearChainCRFTagger(max_iter=args.max_iter).train(train)
    train_s = time.perf_counter() - t0

    toks = [[w for w, _ in s] for s in test]
    gold = [[t for _, t in s] for s in test]
    n_tok = sum(len(t) for t in toks)
    preds = crf.predict_batch(toks)  # warm/compile
    t0 = time.perf_counter()
    preds = crf.predict_batch(toks)
    decode_s = time.perf_counter() - t0

    correct = sum(p == g for pr, gl in zip(preds, gold)
                  for p, g in zip(pr, gl))
    record = {
        "workload": "linear-chain CRF tagger (hashed features, jitted "
                    "L-BFGS train + batched Viterbi decode)",
        "platform": jax.devices()[0].platform,
        "train_sentences": len(train),
        "train_tokens": n_train_tok,
        "train_seconds": round(train_s, 2),
        "decode_tokens": n_tok,
        "decode_seconds": round(decode_s, 4),
        "decode_tokens_per_sec": round(n_tok / decode_s, 0),
        "test_accuracy": round(correct / n_tok, 4),
    }
    line = json.dumps(record)
    print(line)
    if args.out != "-":
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
