"""Render the stage/roofline/flagship tables from a bench record
(BENCH_LAST_GOOD.json or a bench.py output line) as markdown for
PERF.md.

Usage: python scripts/perf_table.py [path=BENCH_LAST_GOOD.json]
       python scripts/perf_table.py --trace run.json [--top N]
       python scripts/perf_table.py --ledger run.ledger.jsonl

``--trace`` renders a Chrome trace (written via KEYSTONE_TRACE /
`trace_run`, e.g. the ``trace_artifact`` path a bench record carries) as
a markdown per-node self-time table, so bench rounds can diff span-level
detail across PRs (see OBSERVABILITY.md). When the trace embeds
optimizer decisions, the decision tables are appended automatically.

``--ledger`` renders a run's decision ledger (the ``ledger_artifact``
path a bench record carries, or a decision-carrying trace) as the
markdown predicted-vs-observed tables PERF.md rounds source their
decision columns from.
"""

import json
import sys


def trace_table(path, top=15):
    """Markdown per-node self-time table from a Chrome trace."""
    sys.path.insert(0, ".")
    from keystone_tpu.telemetry import aggregate_spans, load_trace

    trace = load_trace(path)
    print(f"Trace `{path}`:\n")
    for cat, title in (("node", "Node forces"), ("step", "Solver steps"),
                       ("chunk", "Stream chunks")):
        agg = aggregate_spans(trace, cat)
        if not agg:
            continue
        print(f"**{title}** (top {top} by self-time)\n")
        print("| Span | Self s | Total s | Count | MB |")
        print("|---|---|---|---|---|")
        for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["self_s"])[:top]:
            print(f"| {name} | {a['self_s']:.4f} | {a['total_s']:.4f} | "
                  f"{int(a['count'])} | {a['bytes'] / 1e6:.1f} |")
        print()
    from keystone_tpu.telemetry import compile_summary, dispatch_summary

    dispatch = dispatch_summary(trace)
    if dispatch:
        print(f"**Dispatch**: {dispatch} — serial-vs-concurrent runs "
              "diff on this line\n")
    # the per-plan breakdown the dispatch bench embeds: the 2→1
    # megafusion reduction per example, readable without opening the
    # raw trace (same metadata dispatch_plan_breakdown renders — the
    # table form tolerates partial rows/plans the same way)
    meta = trace.get("keystone", {}).get("dispatch_plans") or {}
    per = meta.get("apply_run_programs") or {}
    if per:
        plans = meta.get("plans") or sorted(
            {p for row in per.values() for p in row})
        print("| Example | " + " | ".join(plans) + " |")
        print("|---" * (1 + len(plans)) + "|")
        for example in sorted(per):
            row = per[example]
            cells = " | ".join(
                str(row[p]) if p in row else "—" for p in plans)
            print(f"| {example} | {cells} |")
        print()
    compiles = compile_summary(trace)
    if compiles:
        print(f"**Compiles**: {compiles} — a warm (persistent-cache / "
              "AOT-warmed) run holds the cold count at 0\n")
    hist = trace.get("keystone", {}).get("metrics", {}).get("histograms", {})
    stall = hist.get("prefetch.producer_stall_s")
    wait = hist.get("prefetch.consumer_wait_s")
    if stall or wait:
        print("**Overlap queue stalls**: "
              + "; ".join(
                  f"{label} {h['total']:.4f}s/{int(h['count'])}"
                  for label, h in (("producer", stall), ("consumer", wait))
                  if h))
    try:
        from keystone_tpu.analysis.reconcile import (
            format_reconciliation,
            reconcile_trace,
        )

        rec = reconcile_trace(trace)
        if rec["rows"]:
            print()
            print("```\n" + format_reconciliation(rec) + "\n```")
    except Exception:
        pass
    if trace.get("keystone", {}).get("decisions"):
        print()
        ledger_table(path)


def _fmt_kv(d):
    return "; ".join(
        f"{k}={int(v) if isinstance(v, float) and v == int(v) else v}"
        for k, v in sorted(d.items())
        if not isinstance(v, (dict, list))) or "—"


def ledger_table(path):
    """Markdown predicted-vs-observed tables from a run's decision
    ledger (a ``KEYSTONE_LEDGER`` JSONL file or a decision-carrying
    trace) — the PERF.md round-table source: one run-level row per
    reconciled quantity (programs executed/compiled, megafused
    programs, baked casts) and one row per decision with the chosen
    entry, the best-priced runner-up, and the observed/residual join
    when the run's trace is reachable."""
    sys.path.insert(0, ".")
    from keystone_tpu.telemetry.ledger import read_ledger, runner_up

    run = read_ledger(path)
    rec = None
    if run.get("trace") is not None:
        try:
            from keystone_tpu.analysis.reconcile import reconcile_decisions

            rec = reconcile_decisions(run)
        except Exception:
            rec = None
    print(f"**Optimizer decisions** ({len(run['decisions'])} recorded, "
          f"`{path}`):\n")
    if rec and (rec["run_predicted"] or rec["run_observed"]):
        print("| Run quantity | Predicted | Observed | Residual |")
        print("|---|---|---|---|")
        keys = sorted(set(rec["run_predicted"]) | set(rec["run_observed"]))
        for k in keys:
            p = rec["run_predicted"].get(k, "—")
            o = rec["run_observed"].get(k, "—")
            r = rec["residuals"].get(k, "—")
            print(f"| {k} | {p} | {o} | {r} |")
        print()
    obs_by_seq = {}
    if rec:
        obs_by_seq = {row["seq"]: row for row in rec["rows"]}
    print("| Kind | Decision | Chosen | Runner-up | Predicted | "
          "Observed | Residual |")
    print("|---|---|---|---|---|---|---|")
    for d in run["decisions"]:
        labels = d.get("labels") or ["?"]
        name = labels[0][:40] + (f" (+{len(labels) - 1})"
                                 if len(labels) > 1 else "")
        ru = runner_up(d)
        row = obs_by_seq.get(d.get("seq")) or {}
        print(f"| {d.get('kind')} | {name} "
              f"| {(d.get('chosen') or {}).get('entry', '—')} "
              f"| {(ru or {}).get('entry', '—')} "
              f"| {_fmt_kv(d.get('predicted') or {})} "
              f"| {_fmt_kv(row.get('observed') or {})} "
              f"| {_fmt_kv(row.get('residuals') or {})} |")
    print()


def main():
    if "--ledger" in sys.argv:
        return ledger_table(sys.argv[sys.argv.index("--ledger") + 1])
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        path = sys.argv[i + 1]
        top = (int(sys.argv[sys.argv.index("--top") + 1])
               if "--top" in sys.argv else 15)
        return trace_table(path, top)
    path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_LAST_GOOD.json"
    with open(path) as f:
        text = f.read().strip()
    if text.startswith("BENCH_DETAIL "):
        text = text[len("BENCH_DETAIL "):]
    rec = json.loads(text)
    d = rec.get("detail", rec)
    # Incomplete / stale / error records must not render as clean results
    flags = []
    if rec.get("partial"):
        flags.append(f"PARTIAL ({rec['partial']})")
    if d.get("stale"):
        flags.append("STALE carry-over")
    if rec.get("error"):
        flags.append(f"ERROR: {rec['error']}")
    if flags:
        print("**" + " | ".join(flags) + "**\n")
    value = rec.get("value", d.get("images_per_sec"))
    vsb = rec.get("vs_baseline")
    vsb = f"{vsb}x" if vsb is not None else "n/a"
    band = d.get("accuracy_band")
    band_s = f" in band {band}" if band is not None else ""
    print(f"Headline: {value} img/s ({d.get('train_seconds')} s e2e, "
          f"vs_baseline {vsb}); test_accuracy "
          f"{d.get('test_accuracy')}{band_s}\n")
    stages = d.get("stages_seconds")
    roofs = d.get("rooflines", {})
    if stages:
        print("| Stage | Seconds | GFLOP | GB | TFLOP/s | GB/s | %peak FLOP | %peak BW |")
        print("|---|---|---|---|---|---|---|---|")
        for name, secs in stages.items():
            r = roofs.get(name, {})
            print(f"| {name} | {secs} | {r.get('gflops','—')} | "
                  f"{r.get('gbytes','—')} | {r.get('attained_tflops','—')} | "
                  f"{r.get('attained_gbs','—')} | {r.get('pct_peak_flops','—')} | "
                  f"{r.get('pct_peak_bw','—')} |")
        print(f"| **sum** | **{d.get('stages_sum_seconds')}** | | | | | | |")
    fl = d.get("flagship_bcd_d8192")
    if fl:
        r = fl.get("roofline", {})
        print(f"\nFlagship BCD d={fl['d']} k={fl['k']} n={fl['n']} "
              f"({fl['num_iter']} epochs x {-(-fl['d']//fl['block_size'])} blocks): "
              f"{fl['fit_seconds']} s fit "
              f"({r.get('attained_tflops')} TFLOP/s, {r.get('attained_gbs')} GB/s); "
              f"n-scaled vs 16x r3.4xlarge reference: "
              f"{fl.get('speedup_vs_reference_n_scaled')}x faster")


if __name__ == "__main__":
    main()
