"""Render the stage/roofline/flagship tables from a bench record
(BENCH_LAST_GOOD.json or a bench.py output line) as markdown for
PERF.md.

Usage: python scripts/perf_table.py [path=BENCH_LAST_GOOD.json]
       python scripts/perf_table.py --trace run.json [--top N]
       python scripts/perf_table.py --ledger run.ledger.jsonl
       python scripts/perf_table.py --roofline [EXAMPLE ...]
       python scripts/perf_table.py --serving [EXAMPLE ...]

``--roofline`` runs the STATIC roofline analyzer
(keystone_tpu/analysis/roofline.py) over the named analyzable()
examples (default: the three bench examples) and renders the per-stage
markdown table PERF.md rounds source their intensity columns from —
flops, stage-at-a-time HBM bytes, arithmetic intensity, the
compute/bandwidth classification against the calibrated machine
balance, predicted seconds, and the KP801 Pallas-candidate chains.

``--trace`` renders a Chrome trace (written via KEYSTONE_TRACE /
`trace_run`, e.g. the ``trace_artifact`` path a bench record carries) as
a markdown per-node self-time table, so bench rounds can diff span-level
detail across PRs (see OBSERVABILITY.md). When the trace embeds
optimizer decisions, the decision tables are appended automatically.

``--ledger`` renders a run's decision ledger (the ``ledger_artifact``
path a bench record carries, or a decision-carrying trace) as the
markdown predicted-vs-observed tables PERF.md rounds source their
decision columns from.

``--serving`` runs the STATIC serving-readiness certifier
(keystone_tpu/analysis/serving.py — the KP9xx tier) over the named
analyzable() examples (default: every registered example) and renders
the per-example markdown verdict table: certified / uncertified (with
the NAMED suppressions for examples that genuinely cannot certify
yet), the worst-shape certified latency bound vs the SLO, and the
dominating stage. ``KEYSTONE_SLO_MS`` / ``KEYSTONE_SERVING_MAX_BATCH``
refine the envelope.
"""

import json
import sys


def trace_table(path, top=15):
    """Markdown per-node self-time table from a Chrome trace."""
    sys.path.insert(0, ".")
    from keystone_tpu.telemetry import aggregate_spans, load_trace

    trace = load_trace(path)
    print(f"Trace `{path}`:\n")
    for cat, title in (("node", "Node forces"), ("step", "Solver steps"),
                       ("chunk", "Stream chunks")):
        agg = aggregate_spans(trace, cat)
        if not agg:
            continue
        print(f"**{title}** (top {top} by self-time)\n")
        print("| Span | Self s | Total s | Count | MB |")
        print("|---|---|---|---|---|")
        for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["self_s"])[:top]:
            print(f"| {name} | {a['self_s']:.4f} | {a['total_s']:.4f} | "
                  f"{int(a['count'])} | {a['bytes'] / 1e6:.1f} |")
        print()
    from keystone_tpu.telemetry import compile_summary, dispatch_summary

    dispatch = dispatch_summary(trace)
    if dispatch:
        print(f"**Dispatch**: {dispatch} — serial-vs-concurrent runs "
              "diff on this line\n")
    # the per-plan breakdown the dispatch bench embeds: the 2→1
    # megafusion reduction per example, readable without opening the
    # raw trace (same metadata dispatch_plan_breakdown renders — the
    # table form tolerates partial rows/plans the same way)
    meta = trace.get("keystone", {}).get("dispatch_plans") or {}
    per = meta.get("apply_run_programs") or {}
    if per:
        plans = meta.get("plans") or sorted(
            {p for row in per.values() for p in row})
        print("| Example | " + " | ".join(plans) + " |")
        print("|---" * (1 + len(plans)) + "|")
        for example in sorted(per):
            row = per[example]
            cells = " | ".join(
                str(row[p]) if p in row else "—" for p in plans)
            print(f"| {example} | {cells} |")
        print()
    compiles = compile_summary(trace)
    if compiles:
        print(f"**Compiles**: {compiles} — a warm (persistent-cache / "
              "AOT-warmed) run holds the cold count at 0\n")
    hist = trace.get("keystone", {}).get("metrics", {}).get("histograms", {})
    stall = hist.get("prefetch.producer_stall_s")
    wait = hist.get("prefetch.consumer_wait_s")
    if stall or wait:
        print("**Overlap queue stalls**: "
              + "; ".join(
                  f"{label} {h['total']:.4f}s/{int(h['count'])}"
                  for label, h in (("producer", stall), ("consumer", wait))
                  if h))
    apply_h = hist.get("serving.apply_seconds")
    if apply_h and apply_h.get("count"):
        print("**Live serving latency**: "
              f"{int(apply_h['count'])} request(s), "
              f"p50 {apply_h.get('p50', 0.0) * 1e3:.1f} ms / "
              f"p99 {apply_h.get('p99', 0.0) * 1e3:.1f} ms "
              "(reservoir percentiles, `serving.apply_seconds`)")
    try:
        from keystone_tpu.analysis.reconcile import (
            format_reconciliation,
            reconcile_trace,
        )

        rec = reconcile_trace(trace)
        if rec["rows"]:
            print()
            print("```\n" + format_reconciliation(rec) + "\n```")
    except Exception:
        pass
    try:
        from keystone_tpu.analysis.reconcile import reconcile_roofline

        roof = reconcile_roofline(trace)
        if roof["stages_joined"]:
            print("\n**Roofline** (static predicted vs observed span "
                  "seconds)\n")
            print("| Stage | FLOPs | Bound | Predicted s | Observed s | "
                  "Residual s |")
            print("|---|---|---|---|---|---|")
            for r in roof["rows"]:
                if r["residual"] is None:
                    continue
                print(f"| {r['label'][:40]} | {r['flops']:.3g} | "
                      f"{r['bound'] or '—'} | "
                      f"{r['predicted_seconds']:.3e} | "
                      f"{r['observed_seconds']:.3e} | "
                      f"{r['residual']:+.3e} |")
            print(f"\nflops residual: predicted "
                  f"{roof['predicted_seconds']:.4f}s vs observed "
                  f"{roof['observed_seconds']:.4f}s over "
                  f"{roof['stages_joined']} joined stage(s)\n")
    except Exception:
        pass
    if trace.get("keystone", {}).get("decisions"):
        print()
        ledger_table(path)


def _fmt_kv(d):
    return "; ".join(
        f"{k}={int(v) if isinstance(v, float) and v == int(v) else v}"
        for k, v in sorted(d.items())
        if not isinstance(v, (dict, list))) or "—"


def ledger_table(path):
    """Markdown predicted-vs-observed tables from a run's decision
    ledger (a ``KEYSTONE_LEDGER`` JSONL file or a decision-carrying
    trace) — the PERF.md round-table source: one run-level row per
    reconciled quantity (programs executed/compiled, megafused
    programs, baked casts) and one row per decision with the chosen
    entry, the best-priced runner-up, and the observed/residual join
    when the run's trace is reachable."""
    sys.path.insert(0, ".")
    from keystone_tpu.telemetry.ledger import read_ledger, runner_up

    run = read_ledger(path)
    rec = None
    if run.get("trace") is not None:
        try:
            from keystone_tpu.analysis.reconcile import reconcile_decisions

            rec = reconcile_decisions(run)
        except Exception:
            rec = None
    print(f"**Optimizer decisions** ({len(run['decisions'])} recorded, "
          f"`{path}`):\n")
    if rec and (rec["run_predicted"] or rec["run_observed"]):
        print("| Run quantity | Predicted | Observed | Residual |")
        print("|---|---|---|---|")
        keys = sorted(set(rec["run_predicted"]) | set(rec["run_observed"]))
        for k in keys:
            p = rec["run_predicted"].get(k, "—")
            o = rec["run_observed"].get(k, "—")
            r = rec["residuals"].get(k, "—")
            print(f"| {k} | {p} | {o} | {r} |")
        print()
    obs_by_seq = {}
    if rec:
        obs_by_seq = {row["seq"]: row for row in rec["rows"]}
    print("| Kind | Decision | Chosen | Runner-up | Predicted | "
          "Observed | Residual |")
    print("|---|---|---|---|---|---|---|")
    for d in run["decisions"]:
        labels = d.get("labels") or ["?"]
        name = labels[0][:40] + (f" (+{len(labels) - 1})"
                                 if len(labels) > 1 else "")
        ru = runner_up(d)
        row = obs_by_seq.get(d.get("seq")) or {}
        print(f"| {d.get('kind')} | {name} "
              f"| {(d.get('chosen') or {}).get('entry', '—')} "
              f"| {(ru or {}).get('entry', '—')} "
              f"| {_fmt_kv(d.get('predicted') or {})} "
              f"| {_fmt_kv(row.get('observed') or {})} "
              f"| {_fmt_kv(row.get('residuals') or {})} |")
    print()


#: the bench examples whose roofline table PERF.md rounds carry.
_ROOFLINE_DEFAULT_EXAMPLES = (
    "MnistRandomFFT", "RandomPatchCifar", "TimitPipeline")


def roofline_table(examples=None):
    """Markdown per-stage roofline table from the STATIC analyzer (no
    run needed): the PERF.md round-table source for per-stage
    arithmetic intensity."""
    sys.path.insert(0, ".")
    from keystone_tpu.analysis import as_source_spec
    from keystone_tpu.analysis.examples import build_example
    from keystone_tpu.analysis.propagate import spec_pass
    from keystone_tpu.analysis.roofline import roofline_pass

    machine = None
    for name in examples or _ROOFLINE_DEFAULT_EXAMPLES:
        pipeline, source_spec = build_example(name)
        specs, _ = spec_pass(
            pipeline.graph, {pipeline.source: as_source_spec(source_spec)})
        est, _ = roofline_pass(pipeline.graph, specs)
        machine = est.machine
        print(f"**{name}** — ≈{est.plan_seconds:.3e}s predicted over "
              f"{len(est.stages)} priced stage(s), "
              f"{len(est.candidates)} pallas candidate(s)\n")
        rows = est.rows(pipeline.graph)
        if rows:
            print("| Stage | FLOPs | HBM bytes | FLOP/B | Bound | "
                  "Predicted s |")
            print("|---|---|---|---|---|---|")
            for r in rows:
                print(f"| {r['label'][:44]} | {r['flops']:.3g} | "
                      f"{int(r['hbm_bytes']):,} | {r['intensity']:.2f} | "
                      f"{r['bound']} | {r['predicted_seconds']:.3e} |")
            print()
        for c in est.candidates:
            print(f"- KP801 candidate ({c['kind']}): "
                  f"{' >> '.join(c['stages'])} — "
                  f"{c['boundary_bytes']:,} boundary bytes, "
                  f"≈{c['seconds_saved']:.2e}s saved")
        if est.candidates:
            print()
    if machine is not None:
        print(f"(machine balance {machine.balance:.1f} FLOP/B — peaks "
              f"{machine.peak_flops:.3g} FLOP/s, "
              f"{machine.peak_bw:.3g} B/s)")


def serving_table(examples=None):
    """Markdown per-example serving-certification table from the STATIC
    KP9xx certifier (no run needed): the ROADMAP serving runtime's
    pre-traffic readiness board."""
    sys.path.insert(0, ".")
    from keystone_tpu.analysis.examples import EXAMPLES
    from keystone_tpu.analysis.serving import (
        SERVING_SUPPRESSIONS,
        ServingEnvelope,
        certify_example,
        envelope_from_env,
    )

    envelope = envelope_from_env(require_slo=False)
    print(f"**Serving readiness** — envelope: batch "
          f"[{envelope.min_batch}, {envelope.max_batch}], SLO "
          f"{envelope.slo_seconds * 1e3:.0f} ms, "
          f"{envelope.tenants} tenant(s)\n")
    print("| Example | Verdict | Worst shape | Bound | SLO | "
          "Dominating stage | Notes |")
    print("|---|---|---|---|---|---|---|")
    for name in examples or sorted(EXAMPLES):
        try:
            cert, diags = certify_example(name, envelope)
        except Exception as e:
            print(f"| {name} | build error | — | — | — | — | "
                  f"{type(e).__name__}: {e} |")
            continue
        suppressed = sorted(
            {d.rule for d in diags if d.severity.name == "ERROR"
             and d.rule in SERVING_SUPPRESSIONS.get(name, {})})
        verdict = ("certified" if cert.certified else
                   f"uncertified (suppressed: {', '.join(suppressed)})"
                   if suppressed else "**UNCERTIFIED**")
        worst = cert.worst_shape
        notes = []
        if cert.ingress:
            notes.append(f"ingress at {cert.ingress['stage']}")
        if cert.unpriced_stages:
            notes.append(f"{cert.unpriced_stages} unpriced host stage(s)")
        if cert.exposed_stages:
            notes.append(f"{len(cert.exposed_stages)} recompile-exposed")
        print(f"| {name} | {verdict} "
              f"| {worst['batch'] if worst else '—'} "
              f"| {worst['predicted_seconds'] * 1e3:.1f} ms "
              f"| {envelope.slo_seconds * 1e3:.0f} ms "
              f"| {(cert.dominating_stage or '—')[:44]} "
              f"| {'; '.join(notes) or '—'} |")
    print()


def main():
    if "--serving" in sys.argv:
        names = [a for a in sys.argv[sys.argv.index("--serving") + 1:]
                 if not a.startswith("-")]
        return serving_table(names or None)
    if "--roofline" in sys.argv:
        names = [a for a in sys.argv[sys.argv.index("--roofline") + 1:]
                 if not a.startswith("-")]
        return roofline_table(names or None)
    if "--ledger" in sys.argv:
        return ledger_table(sys.argv[sys.argv.index("--ledger") + 1])
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        path = sys.argv[i + 1]
        top = (int(sys.argv[sys.argv.index("--top") + 1])
               if "--top" in sys.argv else 15)
        return trace_table(path, top)
    path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_LAST_GOOD.json"
    with open(path) as f:
        text = f.read().strip()
    if text.startswith("BENCH_DETAIL "):
        text = text[len("BENCH_DETAIL "):]
    rec = json.loads(text)
    d = rec.get("detail", rec)
    # Incomplete / stale / error records must not render as clean results
    flags = []
    if rec.get("partial"):
        flags.append(f"PARTIAL ({rec['partial']})")
    if d.get("stale"):
        flags.append("STALE carry-over")
    if rec.get("error"):
        flags.append(f"ERROR: {rec['error']}")
    if flags:
        print("**" + " | ".join(flags) + "**\n")
    value = rec.get("value", d.get("images_per_sec"))
    vsb = rec.get("vs_baseline")
    vsb = f"{vsb}x" if vsb is not None else "n/a"
    band = d.get("accuracy_band")
    band_s = f" in band {band}" if band is not None else ""
    print(f"Headline: {value} img/s ({d.get('train_seconds')} s e2e, "
          f"vs_baseline {vsb}); test_accuracy "
          f"{d.get('test_accuracy')}{band_s}\n")
    stages = d.get("stages_seconds")
    roofs = d.get("rooflines", {})
    if stages:
        print("| Stage | Seconds | GFLOP | GB | TFLOP/s | GB/s | %peak FLOP | %peak BW |")
        print("|---|---|---|---|---|---|---|---|")
        for name, secs in stages.items():
            r = roofs.get(name, {})
            print(f"| {name} | {secs} | {r.get('gflops','—')} | "
                  f"{r.get('gbytes','—')} | {r.get('attained_tflops','—')} | "
                  f"{r.get('attained_gbs','—')} | {r.get('pct_peak_flops','—')} | "
                  f"{r.get('pct_peak_bw','—')} |")
        print(f"| **sum** | **{d.get('stages_sum_seconds')}** | | | | | | |")
    fl = d.get("flagship_bcd_d8192")
    if fl:
        r = fl.get("roofline", {})
        print(f"\nFlagship BCD d={fl['d']} k={fl['k']} n={fl['n']} "
              f"({fl['num_iter']} epochs x {-(-fl['d']//fl['block_size'])} blocks): "
              f"{fl['fit_seconds']} s fit "
              f"({r.get('attained_tflops')} TFLOP/s, {r.get('attained_gbs')} GB/s); "
              f"n-scaled vs 16x r3.4xlarge reference: "
              f"{fl.get('speedup_vs_reference_n_scaled')}x faster")


if __name__ == "__main__":
    main()
