"""Single-datum serving latency (VERDICT r4 #7).

Measures warm `FittedPipeline.apply(datum)` p50/p90/p99 for the
RandomPatchCifar image pipeline and the Newsgroups text pipeline — the
reference's single-item hot loop (Operator.scala:77-100 single dispatch,
FittedPipeline.scala:38). Prints one JSON line; results land in PERF.md.

Usage: python scripts/serving_latency.py [--reps 200] [--out -]
       KEYSTONE_BACKEND=cpu python scripts/serving_latency.py --reps 20
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(samples):
    a = np.asarray(samples) * 1e3
    return {
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p90_ms": round(float(np.percentile(a, 90)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
        "mean_ms": round(float(a.mean()), 3),
        "reps": len(samples),
    }


def bench_cifar(reps: int):
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    config = RandomPatchCifarConfig(num_filters=256)
    train, _ = synthetic_cifar(2048, 64, config.num_classes, config.seed)
    fitted = build_pipeline(train, config).fit()
    images = np.asarray(train.data.numpy())[:reps + 8]

    int(fitted.apply(images[0]))  # warm the batch=1 programs
    int(fitted.apply(images[1]))
    samples = []
    for i in range(reps):
        x = images[2 + (i % (len(images) - 2))]
        t0 = time.perf_counter()
        out = int(fitted.apply(x))  # int() = host sync
        samples.append(time.perf_counter() - t0)
        assert 0 <= out < config.num_classes
    return _percentiles(samples)


def bench_newsgroups(reps: int):
    from keystone_tpu.pipelines.text_pipelines import (
        build_newsgroups_predictor,
        synthetic_corpus,
    )
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    labels, docs = synthetic_corpus(800, 4, seed=0)
    fitted = build_newsgroups_predictor(docs, labels, 4).fit()
    items = list(docs.items)

    int(fitted.apply(items[0]))  # warm
    int(fitted.apply(items[1]))
    samples = []
    for i in range(reps):
        t0 = time.perf_counter()
        out = int(fitted.apply(items[2 + (i % (len(items) - 2))]))
        samples.append(time.perf_counter() - t0)
        assert 0 <= out < 4
    return _percentiles(samples)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--reps", type=int, default=200)
    p.add_argument("--out", default="-")
    args = p.parse_args()
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    record = {
        "workload": "single-datum serving latency (warm, batch=1 jitted)",
        "platform": jax.devices()[0].platform,
        "random_patch_cifar": bench_cifar(args.reps),
        "newsgroups": bench_newsgroups(args.reps),
    }
    line = json.dumps(record)
    print(line)
    if args.out != "-":
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
