"""Serving latency — the observed half of the KP9xx serving-cert join.

Measures warm `FittedPipeline.apply` percentiles two ways:

  - the legacy single-datum records (VERDICT r4 #7): warm batch=1
    p50/p90/p99 for RandomPatchCifar and Newsgroups — PERF.md's
    serving rows, unchanged;
  - per-shape records over the serving envelope's pad ladder: for each
    request batch size, the batch coalesces onto PR-5's pow-2 ladder
    (`utils.batching._pad_target`), and the record carries the batch,
    the padded ``chunk_shape`` it dispatched at, the percentiles, and
    the ``trace`` path — exactly the observed side
    `analysis.reconcile.reconcile_serving` joins against the certified
    per-shape bounds.

Each covered example runs with the ambient tracer armed AND the
serving envelope armed (``KEYSTONE_SLO_MS`` — set by this script when
absent), so the apply-run executor embeds the KP9xx certificate
(``keystone.serving``) into the same trace this script embeds its
measurements into (``keystone.serving_observed``): ONE artifact
carries both sides of the join, and

    python -m keystone_tpu.telemetry <trace>   # serving reconciliation
    python scripts/perf_table.py --serving     # certified-vs-SLO table

render predicted-bound-vs-observed-p50 per shape. Coverage is every
example with a runnable synthetic instance: RandomPatchCifar,
NewsgroupsPipeline, MnistRandomFFT, TimitPipeline (the dispatch-bench
instances); VOC/ImageNet SIFT remain static-only until their loaders
grow synthetic fixtures.

A third pass (``--runtime``) drives the REAL server loop: a
`serving.ServingRuntime` is certified, warmed, and started per covered
example, ``--clients`` concurrent client threads fire requests through
`submit()`, and the observed side is read back from the streaming
sketches the coalesced dispatch path fed — so the
``keystone.serving_observed`` records in the runtime trace are
bound-vs-observed under real concurrency (queueing + coalescing
included), not a sequential-apply idealization.

Usage: python scripts/serving_latency.py [--reps 200] [--out -]
           [--max-batch 64] [--trace-dir /tmp] [--examples NAME ...]
           [--runtime] [--clients 8]
       KEYSTONE_BACKEND=cpu python scripts/serving_latency.py --reps 20
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(samples):
    a = np.asarray(samples) * 1e3
    return {
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p90_ms": round(float(np.percentile(a, 90)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
        "mean_ms": round(float(a.mean()), 3),
        "reps": len(samples),
    }


# ------------------------------------------------------- example builders
#
# Each builder returns ``(fitted, make_batch, sync)``: a fitted
# pipeline, a ``make_batch(b, i)`` closure yielding the i-th rotating
# request batch of size b, and a ``sync(out)`` host-synchronizer (the
# timed section must include device→host completion).


def _build_cifar():
    from keystone_tpu.loaders.cifar_loader import synthetic_cifar
    from keystone_tpu.pipelines.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_pipeline,
    )

    config = RandomPatchCifarConfig(num_filters=256)
    train, _ = synthetic_cifar(2048, 64, config.num_classes, config.seed)
    fitted = build_pipeline(train, config).fit()
    images = np.asarray(train.data.numpy())
    return fitted, images, config.num_classes


def _build_newsgroups():
    from keystone_tpu.pipelines.text_pipelines import (
        build_newsgroups_predictor,
        synthetic_corpus,
    )

    labels, docs = synthetic_corpus(800, 4, seed=0)
    fitted = build_newsgroups_predictor(docs, labels, 4).fit()
    return fitted, list(docs.items)


def _bench_example_builder(name):
    """A per-shape builder over the dispatch-bench synthetic instance of
    ``name`` — the same pipelines the lint.sh smokes run."""
    from keystone_tpu.dispatch_bench import EXAMPLES as BENCH

    def build():
        from keystone_tpu.data.dataset import Dataset

        predictor, train, test = BENCH[name]()
        fitted = predictor.fit()
        X = np.concatenate([np.asarray(test.numpy()),
                            np.asarray(train.numpy())])

        def make_batch(b, i):
            off = (i * b) % max(1, len(X) - b)
            return Dataset.from_numpy(np.ascontiguousarray(X[off:off + b]))

        def sync(out):
            return np.asarray(out.numpy())

        return fitted, make_batch, sync

    return build


def _make_array_batcher(images):
    from keystone_tpu.data.dataset import Dataset

    def make_batch(b, i):
        off = (i * b) % max(1, len(images) - b)
        return Dataset.from_numpy(np.ascontiguousarray(images[off:off + b]))

    def sync(out):
        return np.asarray(out.numpy())

    return make_batch, sync


def _make_host_batcher(items):
    from keystone_tpu.data.dataset import HostDataset

    def make_batch(b, i):
        off = (i * b) % max(1, len(items) - b)
        return HostDataset(items[off:off + b])

    def sync(out):
        return np.asarray(out.numpy())

    return make_batch, sync


#: covered examples (names match the analysis registry); each maps to a
#: builder returning ``(fitted, make_batch, sync)``.
def _builders():
    def cifar():
        fitted, images, _ = _build_cifar()
        return (fitted, *_make_array_batcher(images))

    def newsgroups():
        fitted, items = _build_newsgroups()
        return (fitted, *_make_host_batcher(items))

    return {
        "RandomPatchCifar": cifar,
        "NewsgroupsPipeline": newsgroups,
        "MnistRandomFFT": _bench_example_builder("MnistRandomFFT"),
        "TimitPipeline": _bench_example_builder("TimitPipeline"),
    }


# ----------------------------------------------------------- measurement


def bench_shapes(name, build, reps, batches, trace_path):
    """Per-shape percentile records for one example. Percentiles are
    measured UNTRACED (an armed tracer re-runs the static-estimate
    embed per request-bound executor — host work a serving process
    would not pay per request); then one warm apply per shape runs
    inside a `trace_run` so the apply executor embeds the KP9xx
    certificate, and the observed records are embedded alongside it —
    the written trace carries both sides of the `reconcile_serving`
    join.

    The traced applies also arm the live conformance watchdog (the
    executor hands its embedded certificate to
    `telemetry.watchdog.maybe_arm_from_certificate`), so every
    percentile apply below runs under live conformance checking; the
    returned ``live`` record carries the online story — checks,
    breaches, and the streaming sketches' per-shape percentiles, the
    fixed-memory twin of the sample-array percentiles measured here."""
    from keystone_tpu.analysis.memory import resolve_chunk_rows
    from keystone_tpu.telemetry import trace_run
    from keystone_tpu.telemetry.streaming import health, reset_live
    from keystone_tpu.telemetry.watchdog import (
        active_watchdog,
        disarm_watchdog,
    )
    from keystone_tpu.utils.batching import _pad_target
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.executor import drain_warmups

    PipelineEnv.reset()
    disarm_watchdog()
    reset_live()
    chunk = resolve_chunk_rows(None)
    records = []
    fitted, make_batch, sync = build()
    drain_warmups()  # AOT ladder warmup must not count against p99
    for b in batches:
        sync(fitted.apply(make_batch(b, 0)))  # warm this shape
        sync(fitted.apply(make_batch(b, 1)))
        samples = []
        for i in range(reps):
            x = make_batch(b, 2 + i)
            t0 = time.perf_counter()
            sync(fitted.apply(x))
            samples.append(time.perf_counter() - t0)
        rec = _percentiles(samples)
        rec["batch"] = int(b)
        rec["chunk_shape"] = int(_pad_target(b, chunk, b))
        rec["trace"] = trace_path
        records.append(rec)
    # the join artifact: one warm apply per shape under the tracer (the
    # executor embeds keystone.serving), plus the observed half
    with trace_run(trace_path) as tracer:
        for b in batches:
            sync(fitted.apply(make_batch(b, 0)))
        tracer.metadata["serving_observed"] = records
    # live pass: the traced applies above armed the conformance
    # watchdog from the certificate the executor embedded; replay a
    # few warm applies per shape under it and capture the online
    # story the plane saw — conformance checks, breaches, and the
    # sketches' percentiles
    live = {"armed": False}
    wd = active_watchdog()
    if wd is not None:
        live_reps = max(3, min(int(reps), 10))
        for b in batches:
            for i in range(live_reps):
                sync(fitted.apply(make_batch(b, i)))
        digest = wd.describe()
        live = {
            "armed": True,
            "pipeline": digest.get("pipeline"),
            "slo_seconds": digest.get("slo_seconds"),
            "checked": digest.get("checked", 0),
            "breaches": digest.get("breaches", 0),
            "shapes": digest.get("shapes", {}),
            "streaming": health().get("latency", []),
        }
    disarm_watchdog()
    reset_live()
    PipelineEnv.reset()
    return records, live


# ------------------------------------------------ runtime (real server)


def _runtime_builders():
    """Builders for the ``--runtime`` pass: each returns an UNSTARTED
    `ServingRuntime` plus the request payload pool its clients draw
    from. Coverage is the examples with a declarable ingress: the
    dispatch-bench ndarray instances submit raw element rows, and
    Newsgroups serves its device tail behind a `TextIngress`
    (`split_fitted_at` extracts the fitted host front-end)."""
    from keystone_tpu.serving import (
        NdarrayIngress,
        ServingRuntime,
        TextIngress,
        split_fitted_at,
    )

    def _bench_ndarray(name):
        def build():
            from keystone_tpu.dispatch_bench import EXAMPLES as BENCH

            predictor, train, test = BENCH[name]()
            fitted = predictor.fit()
            X = np.concatenate([np.asarray(test.numpy()),
                                np.asarray(train.numpy())])
            rt = ServingRuntime(
                fitted, NdarrayIngress(X.shape[1:]), name=name)
            return rt, [np.ascontiguousarray(X[i]) for i in range(len(X))]

        return build

    def newsgroups():
        fitted, items = _build_newsgroups()
        host_ops, tail = split_fitted_at(fitted, "NaiveBayesModel")
        ingress = TextIngress(host_ops)
        element = ingress.accept(items[0]).shape
        rt = ServingRuntime(tail, ingress, element_shape=element,
                            name="NewsgroupsPipeline")
        return rt, items

    return {
        "MnistRandomFFT": _bench_ndarray("MnistRandomFFT"),
        "TimitPipeline": _bench_ndarray("TimitPipeline"),
        "NewsgroupsPipeline": newsgroups,
    }


def bench_runtime(name, build, reps, clients, trace_path):
    """One example through the real serving loop: certify + warm + start
    the runtime, fire ``clients`` concurrent threads × ``reps`` requests
    each through `submit()`, and read the observed per-shape percentiles
    back from the streaming sketches the coalesced dispatch path fed
    (`request_scope` keys them by padded ladder shape). The written
    trace carries the runtime's OWN certificate as ``keystone.serving``
    and the sketch percentiles as ``keystone.serving_observed`` — the
    `reconcile_serving` join under real concurrency."""
    import threading

    from keystone_tpu.serving import CertificationError
    from keystone_tpu.telemetry import trace_run
    from keystone_tpu.telemetry.metrics import metrics_delta, registry
    from keystone_tpu.telemetry.streaming import latency_sketch, reset_live
    from keystone_tpu.telemetry.watchdog import (
        active_watchdog,
        disarm_watchdog,
    )
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    disarm_watchdog()
    reset_live()
    # fresh per-example coalescing histogram (the registry is
    # process-cumulative; the batcher re-creates the metric on start)
    registry().histograms.pop("serving.coalesced_batch", None)
    rt, payloads = build()
    result = {"trace": trace_path, "clients": int(clients),
              "requests": int(clients) * int(reps)}
    # the client load runs UNTRACED: an armed tracer re-runs the
    # static-estimate embed per request-bound executor (host work a
    # serving process would not pay per request) and its per-apply
    # re-arm resets the watchdog counters — the join artifact is
    # written separately below, from the runtime's own certificate
    try:
        rt.start()
    except CertificationError as e:
        disarm_watchdog()
        result["skipped"] = str(e)
        return result
    try:
        errors = []
        with metrics_delta() as delta:
            t0 = time.perf_counter()

            def client(cid):
                for i in range(reps):
                    try:
                        rt.submit(
                            payloads[(cid + clients * i) % len(payloads)])
                    except Exception as e:  # shed/failure: record, go on
                        errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True)
                       for c in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        wd = active_watchdog()
        digest = wd.describe() if wd is not None else {}
        stats = rt.stats()
        records = []
        for shape in stats["dispatched_shapes"]:
            sk = latency_sketch("fitted_pipeline", int(shape))
            if sk is None or sk.count == 0:
                continue
            records.append({
                "batch": int(shape),
                "chunk_shape": int(shape),
                "p50_ms": round(sk.quantile(0.50) * 1e3, 3),
                "p90_ms": round(sk.quantile(0.90) * 1e3, 3),
                "p99_ms": round(sk.quantile(0.99) * 1e3, 3),
                "mean_ms": round(sk.total / sk.count * 1e3, 3),
                "reps": int(sk.count),
                "trace": trace_path,
                "source": "runtime",
            })
        # the join artifact: the runtime's OWN certificate (issued at
        # the declared ingress element, priced at the worst ladder
        # count) as keystone.serving, the sketch percentiles as
        # keystone.serving_observed
        with trace_run(trace_path) as tracer:
            tracer.metadata["serving"] = rt.certificate.as_record()
            tracer.metadata["serving_observed"] = records
            tracer.metadata["serving_runtime"] = {
                "example": name,
                "clients": int(clients),
                "watchdog": digest,
            }
    finally:
        rt.stop()
    coalesced = registry().histograms.get("serving.coalesced_batch")
    result.update({
        "wall_seconds": round(wall, 3),
        "throughput_rps": (round(clients * reps / wall, 1)
                           if wall > 0 else None),
        "dispatches": int(delta.counter("serving.dispatches")),
        "shed": int(delta.counter("serving.shed_total")),
        "error_count": len(errors),
        "errors": errors[:5],
        "shapes": records,
        "coalesced_batch": coalesced.snapshot() if coalesced else None,
        "dispatched_outside_ladder": stats["dispatched_outside_ladder"],
        "watchdog": {
            "checked": digest.get("checked", 0),
            "breaches": digest.get("breaches", 0),
        },
    })
    reset_live()
    PipelineEnv.reset()
    return result


def bench_cifar(reps: int):
    """Legacy single-datum record (PERF.md serving row)."""
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    fitted, images, num_classes = _build_cifar()

    int(fitted.apply(images[0]))  # warm the batch=1 programs
    int(fitted.apply(images[1]))
    samples = []
    for i in range(reps):
        x = images[2 + (i % (len(images) - 2))]
        t0 = time.perf_counter()
        out = int(fitted.apply(x))  # int() = host sync
        samples.append(time.perf_counter() - t0)
        assert 0 <= out < num_classes
    return _percentiles(samples)


def bench_newsgroups(reps: int):
    """Legacy single-datum record (PERF.md serving row)."""
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()
    fitted, items = _build_newsgroups()

    int(fitted.apply(items[0]))  # warm
    int(fitted.apply(items[1]))
    samples = []
    for i in range(reps):
        t0 = time.perf_counter()
        out = int(fitted.apply(items[2 + (i % (len(items) - 2))]))
        samples.append(time.perf_counter() - t0)
        assert 0 <= out < 4
    return _percentiles(samples)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--reps", type=int, default=200)
    p.add_argument("--out", default="-")
    p.add_argument("--max-batch", type=int, default=64,
                   help="largest request batch measured; per-shape "
                        "batches walk the pow-2 ladder 1..max-batch "
                        "(the serving envelope's coalescing window)")
    p.add_argument("--trace-dir", default=None,
                   help="directory for per-example trace artifacts "
                        "(default: a fresh temp dir); each trace "
                        "carries keystone.serving AND "
                        "keystone.serving_observed — the reconcile_"
                        "serving join input")
    p.add_argument("--examples", nargs="*", default=None,
                   help="subset of covered examples (default: all)")
    p.add_argument("--skip-shapes", action="store_true",
                   help="legacy single-datum records only")
    p.add_argument("--runtime", action="store_true",
                   help="also drive the real serving loop "
                        "(serving.ServingRuntime) with concurrent "
                        "clients per covered example; the runtime trace "
                        "carries keystone.serving AND keystone."
                        "serving_observed from the coalesced path")
    p.add_argument("--clients", type=int, default=8,
                   help="concurrent client threads for --runtime")
    args = p.parse_args()
    if os.environ.get("KEYSTONE_BACKEND") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    # pop an inherited KEYSTONE_SLO_MS up front: the legacy
    # single-datum rows must run with the envelope DISARMED so their
    # methodology (and comparability with prior PERF.md rounds) is
    # untouched by the ladder AOT warmup an armed envelope triggers —
    # and a malformed value must degrade NOW, not crash after minutes
    # of measurement
    inherited = os.environ.pop("KEYSTONE_SLO_MS", None)
    try:
        slo_ms = float(inherited) if inherited else 1000.0
    except (TypeError, ValueError):
        slo_ms = 1000.0

    record = {
        "workload": "serving latency (warm apply; per-shape over the "
                    "pad ladder + legacy single-datum)",
        "platform": jax.devices()[0].platform,
        "random_patch_cifar": bench_cifar(args.reps),
        "newsgroups": bench_newsgroups(args.reps),
    }

    trace_dir = None
    if not args.skip_shapes or args.runtime:
        # arm the serving envelope for the per-shape and runtime
        # sections: the apply-run executor embeds the KP9xx certificate
        # into the trace this script measures into, and warmup widens
        # to the ladder (drained before timing). --max-batch is
        # explicit and must WIN over an inherited env var — otherwise
        # the measured shapes and the certified ladder desynchronize
        # and the excess shapes cold-compile inside the timed section
        os.environ["KEYSTONE_SLO_MS"] = str(slo_ms)
        os.environ["KEYSTONE_SERVING_MAX_BATCH"] = str(args.max_batch)
        record["slo_ms"] = slo_ms
        trace_dir = args.trace_dir or tempfile.mkdtemp(
            prefix="keystone_serving_")
        os.makedirs(trace_dir, exist_ok=True)

    if not args.skip_shapes:
        batches = []
        b = 1
        while b < args.max_batch:
            batches.append(b)
            b <<= 1
        batches.append(args.max_batch)
        builders = _builders()
        names = args.examples or sorted(builders)
        shapes = {}
        for name in names:
            if name not in builders:
                print(f"unknown example {name!r}; covered: "
                      f"{', '.join(sorted(builders))}", file=sys.stderr)
                return 2
            trace_path = os.path.join(trace_dir, f"{name}.trace.json")
            per_shape, live = bench_shapes(name, builders[name],
                                           args.reps, batches, trace_path)
            shapes[name] = {
                "trace": trace_path,
                "shapes": per_shape,
                "live": live,
            }
        record["examples"] = shapes

    if args.runtime:
        rbuilders = _runtime_builders()
        names = [n for n in (args.examples or sorted(rbuilders))
                 if n in rbuilders]
        runtime = {}
        for name in names:
            trace_path = os.path.join(trace_dir,
                                      f"{name}.runtime.trace.json")
            runtime[name] = bench_runtime(
                name, rbuilders[name], args.reps, args.clients, trace_path)
        record["runtime"] = runtime
        record["runtime_covered"] = sorted(rbuilders)

    line = json.dumps(record)
    print(line)
    if args.out != "-":
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
