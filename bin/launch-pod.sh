#!/usr/bin/env bash
# Pod launcher — the TPU-native analog of the reference's push-button
# cluster bring-up (bin/keystone-ec2.sh:1-14 + EC2.md:14-34: spark-ec2
# provisions master+slaves with KeystoneML preinstalled). Here the
# "cluster" is a Cloud TPU pod slice: `launch` provisions it (queued
# resource or direct VM create), `push` rsyncs this repo to every host,
# `run` starts one keystone_tpu process per host with the per-host
# coordinator/process-id flags consumed by `python -m keystone_tpu`
# (bin/run-pipeline.sh + keystone_tpu/__main__.py --coordinator/
# --num-processes/--process-id -> parallel.init_multihost), and
# `delete` tears it down.
#
#   ./bin/launch-pod.sh launch my-pod --accelerator v5litepod-16 \
#       --zone us-west4-a --project my-proj [--spot] [--queued]
#   ./bin/launch-pod.sh push   my-pod --zone ... --project ...
#   ./bin/launch-pod.sh run    my-pod --zone ... --project ... -- \
#       pipelines.images.cifar.RandomPatchCifar --num-filters 256
#   ./bin/launch-pod.sh delete my-pod --zone ... --project ...
#
# --dry-run (or KEYSTONE_POD_DRY_RUN=1) prints every command instead of
# executing — this is what the argument-assembly test drives; the gcloud
# path needs a configured gcloud, which CI does not have.
set -euo pipefail

REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

usage() { sed -n '2,22p' "$0" | sed 's/^# \{0,1\}//'; exit 1; }

[ $# -ge 2 ] || usage
ACTION="$1"; NAME="$2"; shift 2

ZONE=""; PROJECT=""; ACCEL="v5litepod-16"; VERSION="tpu-ubuntu2204-base"
SPOT=0; QUEUED=0; DRY=${KEYSTONE_POD_DRY_RUN:-0}; PORT=8476
REMOTE_DIR="/tmp/keystone_tpu"
APP_ARGS=()
while [ $# -gt 0 ]; do
  case "$1" in
    --zone) ZONE="$2"; shift 2 ;;
    --project) PROJECT="$2"; shift 2 ;;
    --accelerator) ACCEL="$2"; shift 2 ;;
    --version) VERSION="$2"; shift 2 ;;
    --port) PORT="$2"; shift 2 ;;
    --remote-dir) REMOTE_DIR="$2"; shift 2 ;;
    --spot) SPOT=1; shift ;;
    --queued) QUEUED=1; shift ;;
    --dry-run) DRY=1; shift ;;
    --) shift; APP_ARGS=("$@"); break ;;
    *) echo "unknown flag: $1" >&2; usage ;;
  esac
done

# chips from the accelerator suffix (v5litepod-16 -> 16); v5e packs 4
# chips per host VM, so a v5litepod-16 slice is 4 worker hosts.
CHIPS="${ACCEL##*-}"
case "$ACCEL" in
  v5litepod-*|v5e-*) CHIPS_PER_HOST=4 ;;
  v4-*) CHIPS_PER_HOST=8 ;;  # v4 counts suffix in TensorCores (2/chip)
  *) CHIPS_PER_HOST=4 ;;
esac
NUM_HOSTS=$(( (CHIPS + CHIPS_PER_HOST - 1) / CHIPS_PER_HOST ))
[ "$NUM_HOSTS" -ge 1 ] || NUM_HOSTS=1

run() {  # print in dry-run mode, execute otherwise
  if [ "$DRY" = 1 ]; then
    printf 'DRYRUN:'; printf ' %q' "$@"; printf '\n'
  else
    "$@"
  fi
}

GCLOUD_COMMON=(--zone "$ZONE")
[ -n "$PROJECT" ] && GCLOUD_COMMON+=(--project "$PROJECT")

case "$ACTION" in
  launch)
    if [ "$QUEUED" = 1 ]; then
      # queued resource: the way capacity is actually obtained for
      # larger slices (waits in queue until the slice is available)
      CMD=(gcloud compute tpus queued-resources create "$NAME"
           --node-id "$NAME" "${GCLOUD_COMMON[@]}"
           --accelerator-type "$ACCEL" --runtime-version "$VERSION")
      [ "$SPOT" = 1 ] && CMD+=(--spot)
    else
      CMD=(gcloud compute tpus tpu-vm create "$NAME" "${GCLOUD_COMMON[@]}"
           --accelerator-type "$ACCEL" --version "$VERSION")
      [ "$SPOT" = 1 ] && CMD+=(--spot)
    fi
    run "${CMD[@]}"
    echo "# next: $0 push $NAME --zone $ZONE ${PROJECT:+--project $PROJECT}"
    ;;
  push)
    # distribute the package to every worker host (≈ spark-ec2's rsync
    # of /root/keystone to the cluster, EC2.md:33-34)
    run gcloud compute tpus tpu-vm scp --recurse "${GCLOUD_COMMON[@]}" \
        --worker=all "$REPO_DIR" "$NAME":"$REMOTE_DIR"
    ;;
  run)
    [ ${#APP_ARGS[@]} -gt 0 ] || { echo "run needs '-- <pipeline> [flags]'" >&2; exit 1; }
    # TPU VM workers are NOT resolvable as "<tpu-name>-0" — internal DNS
    # uses auto-generated instance hostnames (t1v-n-…-w-0) — so resolve
    # worker 0's internal IP from the API and hand THAT to every process
    DESCRIBE=(gcloud compute tpus tpu-vm describe "$NAME" "${GCLOUD_COMMON[@]}"
              --format='value(networkEndpoints[0].ipAddress)')
    if [ "$DRY" = 1 ]; then
      run "${DESCRIBE[@]}"
      COORD_IP='${WORKER0_IP}'   # placeholder: dry-run cannot call gcloud
    else
      COORD_IP="$("${DESCRIBE[@]}")"
      [ -n "$COORD_IP" ] || { echo "could not resolve worker 0 internal IP for $NAME" >&2; exit 1; }
    fi
    COORD="${COORD_IP}:${PORT}"
    # shell-quote each app arg for the remote shell (spaces/metachars)
    APP_Q=""
    for a in "${APP_ARGS[@]}"; do APP_Q+=" $(printf '%q' "$a")"; done
    for i in $(seq 0 $((NUM_HOSTS - 1))); do
      REMOTE_CMD="cd $REMOTE_DIR && ./bin/run-pipeline.sh \
--coordinator $COORD --num-processes $NUM_HOSTS --process-id $i$APP_Q"
      if [ "$DRY" = 1 ]; then
        # sequential in dry-run: backgrounded printfs can interleave
        run gcloud compute tpus tpu-vm ssh "$NAME" "${GCLOUD_COMMON[@]}" \
            --worker="$i" --command "$REMOTE_CMD"
      else
        run gcloud compute tpus tpu-vm ssh "$NAME" "${GCLOUD_COMMON[@]}" \
            --worker="$i" --command "$REMOTE_CMD" &
      fi
    done
    if [ "$DRY" != 1 ]; then
      echo "# started $NUM_HOSTS processes (coordinator $COORD); waiting"
      wait
    fi
    ;;
  delete)
    run gcloud compute tpus tpu-vm delete "$NAME" "${GCLOUD_COMMON[@]}" --quiet
    ;;
  *) usage ;;
esac
