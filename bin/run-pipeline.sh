#!/usr/bin/env bash
# Pipeline launcher (mirrors the reference bin/run-pipeline.sh: class
# name + flags -> JVM/spark-submit; here -> python -m keystone_tpu).
#
#   ./bin/run-pipeline.sh pipelines.images.cifar.RandomPatchCifar --num-filters 256
#
# Env:
#   KEYSTONE_BACKEND=tpu|cpu   (default: whatever jax picks; cpu forces
#                               JAX_PLATFORMS=cpu)
#   KEYSTONE_CPU_DEVICES=N     (virtual device count when backend=cpu)
set -euo pipefail
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="${REPO_DIR}${PYTHONPATH:+:$PYTHONPATH}"

if [[ "${KEYSTONE_BACKEND:-}" == "cpu" ]]; then
  export JAX_PLATFORMS=cpu
  if [[ -n "${KEYSTONE_CPU_DEVICES:-}" ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${KEYSTONE_CPU_DEVICES}"
  fi
fi

exec python -m keystone_tpu "$@"
