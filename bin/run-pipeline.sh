#!/usr/bin/env bash
# Pipeline launcher (mirrors the reference bin/run-pipeline.sh: class
# name + flags -> JVM/spark-submit; here -> python -m keystone_tpu).
#
#   ./bin/run-pipeline.sh pipelines.images.cifar.RandomPatchCifar --num-filters 256
#
#   ./bin/run-pipeline.sh --backend=tpu pipelines.speech.TimitPipeline ...
#
# Flags:
#   --backend tpu|cpu          (anywhere on the line; also via env
#                               KEYSTONE_BACKEND)
# Env:
#   KEYSTONE_CPU_DEVICES=N     (virtual device count when backend=cpu)
set -euo pipefail
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="${REPO_DIR}${PYTHONPATH:+:$PYTHONPATH}"

# Backend forcing happens programmatically inside keystone_tpu.__main__
# (jax.config updates) — env-var-only forcing breaks under site hooks
# that snapshot/consume JAX_PLATFORMS/XLA_FLAGS. KEYSTONE_BACKEND and
# KEYSTONE_CPU_DEVICES are read there.

exec python -m keystone_tpu "$@"
